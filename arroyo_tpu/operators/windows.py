"""Windowed aggregation operators: tumbling, sliding (hop), session.

Capability parity with the reference's window operators
(/root/reference/crates/arroyo-worker/src/arrow/
{tumbling,sliding,session}_aggregating_window.rs): event-time bins advance
with the watermark; tumbling emits a bin when the watermark passes its end;
sliding maintains slide-granularity partials merged per emitted window;
session windows gap-merge per key and emit when the watermark passes
last-event + gap. Late rows (whose windows already emitted) are dropped.

TPU-native redesign: instead of one DataFusion partial-aggregation stream
per bin, all (bin, key) groups share flat device accumulator arrays
(ops/aggregates.py) updated by one jitted scatter-reduce per batch; the
host-side SlotDirectory owns group->slot assignment. Emission gathers slots
to host once per watermark advance. Output rows carry
_timestamp = window_end - 1ns (inside the window, reference behavior) and
optional window start/end columns.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import pyarrow as pa

from ..engine.construct import register_operator
from ..graph.logical import OperatorName
from ..ops.aggregates import AggSpec, make_accumulator
from ..ops.directory import SlotDirectory, unintern_value
from ..schema import StreamSchema, TIMESTAMP_FIELD
from ..types import WatermarkKind
from .base import Operator


def _specs_from_config(config: dict) -> List[AggSpec]:
    return [
        AggSpec(
            kind=a["kind"],
            col=a.get("col"),
            name=a["name"],
            is_float=a.get("is_float", False),
            udaf=a.get("udaf"),
            col2=a.get("col2"),
            param=a.get("param"),
            distinct=a.get("distinct", False),
            replay=a.get("replay", False),
        )
        for a in config["aggregates"]
    ]


class WindowOperatorBase(Operator):
    """Shared machinery: accumulator, directory, output batch building."""

    flow_class = "buffering"  # holds rows across barriers until windows fire

    def __init__(self, config: dict, name: str):
        super().__init__(name)
        self.specs = _specs_from_config(config)
        self.key_cols: List[int] = list(config.get("key_cols", []))
        self.out_schema: StreamSchema = config["schema"]
        self.window_start_field: Optional[str] = config.get("window_start_field")
        self.window_end_field: Optional[str] = config.get("window_end_field")
        self.window_field: Optional[str] = config.get("window_field")
        self.backend = config.get("backend")
        mesh_n = self._mesh_devices(config)
        # planner marks aggregates whose every grouping key is the
        # window itself (one group per bin): hash ownership would
        # starve most shards, so those run SALTED — rows spread
        # round-robin across all shards, folded at gather. Device
        # phys ops are all fold-able (add/min/max); host-state
        # aggregates (UDAF buffers / multisets) are keyed by GLOBAL
        # slot and folded host-side, so they ride along unchanged.
        salted = bool(config.get("mesh_salted"))
        if mesh_n >= 2 and salted and not self._salted_on_mesh(mesh_n):
            # window-global groupings have no key axis to shard: on a
            # VIRTUAL (forced host-platform) mesh the salted spread
            # costs S x serial scatter work for a handful of groups, so
            # the stage runs on the standard single-device tier instead
            # (state stays device-resident; the keyed stages around it
            # keep the mesh exchange). Real chip meshes keep salting —
            # there the spread buys S x scatter bandwidth.
            mesh_n = 0
            if self._offmesh_backend is not None:
                # session windows: imperative host bookkeeping dominates;
                # off the mesh they keep their numpy accumulator
                self.backend = self._offmesh_backend
        if mesh_n >= 2:
            from ..parallel import (
                MeshSlotDirectory,
                ShardedAccumulator,
                SharedMeshSlotDirectory,
                key_mesh,
            )

            from ..config import config as config_fn

            self.acc = ShardedAccumulator(
                self.specs,
                key_mesh(self._mesh_device_list(mesh_n)),
                rows_per_shard=config_fn().tpu.mesh_rows_per_shard,
                salted=salted,
                flush_rows=config_fn().tpu.mesh_flush_rows,
            )
            self.dir = (
                SharedMeshSlotDirectory(mesh_n) if salted
                else MeshSlotDirectory(mesh_n)
            )
        else:
            self.acc = make_accumulator(self.specs, backend=self.backend)
            self.dir = SlotDirectory()
        self._key_types: Optional[List[pa.DataType]] = None
        self._key_names: Optional[List[str]] = None
        # columnar chunks of (slots, bins, key columns) touched since the
        # last checkpoint; captured at assign time so delta building is
        # O(dirty), not O(live keys). Kept columnar (numpy) — building a
        # python tuple per touched slot dominated high-cardinality
        # workloads. Deduped by slot (keep-last) at delta-build time.
        self._dirty_chunks: List[tuple] = []
        # rows across _dirty_chunks and the size right after the last
        # coalesce: chunks are squashed (keep-last per slot) whenever the
        # row count doubles past the floor, bounding memory between
        # checkpoints at O(distinct dirty slots) even when a hot key is
        # touched every batch over a long checkpoint interval
        self._dirty_rows = 0
        self._dirty_base = 0
        # native flat-key layout: when a struct key is flattened into its
        # int64 child words for the native directory, _flat_widths[i] is
        # the word count of key column i and _flat_offsets the prefix sums
        self._flat_widths: Optional[List[int]] = None
        self._flat_offsets: Optional[List[int]] = None

    # operators that only use assign/take_bin/bin_entries/items can swap in
    # the C++ directory for single-integer keys (tumbling, sliding, and —
    # with the slot-valued peek_bin / keys_for_slots / remove surface —
    # updating aggregates)
    _native_ok = False
    # the DEVICE directory now serves the full native surface (round 5:
    # keys_for_slots, slots_for_keys, targeted remove, slot-valued
    # peek_bin); the gate remains per-operator because the swap is only
    # worthwhile where assignment is the hot path — session windows
    # allocate slots imperatively and never call assign()
    _device_ok = False
    # operators whose state protocol is slot-based end to end can run on
    # the mesh-sharded accumulator (tumbling, sliding; session bookkeeping
    # allocates slots imperatively and stays host-side)
    _mesh_ok = False
    # backend to fall back to when a salted stage is tiered OFF the mesh
    # (None = keep the configured backend; sessions force numpy)
    _offmesh_backend: Optional[str] = None

    def _mesh_devices(self, config: dict) -> int:
        if not self._mesh_ok or self.backend == "numpy":
            return 0
        return self._cfg_mesh_devices(config)

    @staticmethod
    def _cfg_mesh_devices(config: dict) -> int:
        from ..config import config as config_fn

        n = config.get("mesh_devices")
        if n is None:
            n = config_fn().tpu.mesh_devices
        # deliberately NOT gated on require_accelerator/device_tier_active:
        # mesh mode only engages on an explicit mesh_devices >= 2, and
        # running it over a virtual CPU mesh is a supported deployment
        # (the multichip dryrun and the mesh tests validate sharding
        # compilation without accelerator hardware)
        return int(n or 0) if config_fn().tpu.enabled else 0

    @staticmethod
    def _mesh_device_list(n: int):
        import jax

        devices = jax.devices()
        if len(devices) < n:
            raise ValueError(
                f"tpu.mesh_devices={n} but only {len(devices)} devices "
                "are visible"
            )
        return devices[:n]

    def _salted_on_mesh(self, mesh_n: int) -> bool:
        """Should a SALTED (window-global) aggregate shard across the
        mesh? tpu.mesh_salted_tier: 'mesh' / 'single' force it; 'auto'
        salts only real chip meshes (parallel/mesh.mesh_is_virtual)."""
        from ..config import config as config_fn
        from ..parallel import key_mesh
        from ..parallel.mesh import mesh_is_virtual

        tier = str(getattr(config_fn().tpu, "mesh_salted_tier", "auto")
                   or "auto")
        if tier not in ("auto", "mesh", "single"):
            raise ValueError(
                f"tpu.mesh_salted_tier must be auto|mesh|single, "
                f"got {tier!r}"
            )
        if tier != "auto":
            return tier == "mesh"
        return not mesh_is_virtual(key_mesh(self._mesh_device_list(mesh_n)))

    def _capture_key_meta(self, ctx):
        if self._key_types is None:
            in_schema = ctx.in_schemas[0].schema
            self._key_types = [in_schema.field(i).type for i in self.key_cols]
            self._key_names = [in_schema.field(i).name for i in self.key_cols]
            self._maybe_swap_mesh_native()
            if (
                self._native_ok
                and isinstance(self.dir, SlotDirectory)
                and self.dir.n_live == 0
            ):
                from ..config import config as config_fn
                from ..ops.native import (
                    NativeSlotDirectory,
                    flat_key_widths,
                    key_word_widths,
                    load_native,
                )

                from ..ops._jax import device_tier_active

                cfg = config_fn().tpu
                use_device = (self._device_ok and device_tier_active()
                              and cfg.device_directory)
                widths = (
                    key_word_widths(self._key_types) if use_device
                    else flat_key_widths(self._key_types)
                )
                if widths is not None:
                    # struct keys (window structs) flatten into their int64
                    # child words; everything rides the flat N-key table
                    self._set_flat_layout(widths)
                    if use_device:
                        from ..ops.device_directory import (
                            DeviceSlotDirectory,
                        )

                        self.dir = DeviceSlotDirectory(n_keys=sum(widths))
                    else:
                        self.dir = NativeSlotDirectory(
                            load_native(), n_keys=sum(widths)
                        )

    def _set_flat_layout(self, widths: List[int]):
        """Record the flat native key layout when struct keys flatten
        into int64 child words (shared by the single-process swap and
        the mesh per-shard swap — one definition, no drift)."""
        if any(pa.types.is_struct(t) for t in self._key_types):
            self._flat_widths = widths
            self._flat_offsets = [0]
            for w in widths:
                self._flat_offsets.append(self._flat_offsets[-1] + w)

    def _maybe_swap_mesh_native(self):
        """Mesh mode: swap the facade's PYTHON directories (per-shard,
        or the salted flat directory) to the native C++ table when the
        operator's keys flatten to int64 words — the round-5 mesh
        profile's largest host cost was the per-shard python assigns
        plus tuple-per-key emission; the round-6 profile's was the
        salted stage's per-row window-struct interning. Same eligibility
        gate as the single-process native swap."""
        from ..parallel.sharded_state import (
            MeshSlotDirectory,
            SharedMeshSlotDirectory,
        )

        if not (self._native_ok
                and isinstance(self.dir, (MeshSlotDirectory,
                                          SharedMeshSlotDirectory))
                and self.dir.n_live == 0):
            return
        from ..ops.native import flat_key_widths, load_native

        widths = flat_key_widths(self._key_types)
        if widths is None:
            return
        if not self.dir.swap_to_native(load_native(), sum(widths)):
            return
        self._set_flat_layout(widths)

    def _ensure_capacity(self):
        need = self.dir.required_capacity()
        if need > self.acc.capacity - 1:
            self.acc.grow(need + 1)

    # -- incremental checkpoints --------------------------------------------
    # Window state checkpoints write only the (bin, key) groups whose slots
    # changed since the previous epoch into an expiring_time_key table; the
    # cumulative file list rides in the manifest and retention (keyed to the
    # row's window-end timestamp) prunes emitted windows on restore.
    # Mirrors the reference's incremental ExpiringTimeKeyTable design
    # (/root/reference/crates/arroyo-state/src/tables/
    # expiring_time_key_map.rs:53, flush in table_manager.rs:368).

    def _mark_dirty(self, slots: np.ndarray, bins: np.ndarray,
                    key_cols: List[np.ndarray]):
        """Record (bin, portable key) per touched slot. A stale mapping
        (slot emitted+freed before the checkpoint) writes a row whose bin
        is already behind the watermark — pruned by retention on restore —
        so no directory scan is ever needed."""
        if not len(slots):
            return
        uniq, first = np.unique(slots, return_index=True)
        norm = []
        for c in key_cols:
            c = np.asarray(c)
            if c.dtype == np.uint64:
                c = c.view(np.int64)
            elif c.dtype.kind == "M":
                c = c.view("i8")
            norm.append(c[first])
        self._dirty_chunks.append(
            (uniq, np.asarray(bins)[first].astype(np.int64, copy=False),
             norm)
        )
        self._dirty_rows += len(uniq)
        # amortized O(1) per row: squash only once the count doubles
        # since the last squash (floor 64k rows)
        if self._dirty_rows > max(65536, 2 * self._dirty_base):
            self._dirty_chunks = [self._coalesce_dirty()]
            self._dirty_rows = self._dirty_base = len(
                self._dirty_chunks[0][0]
            )

    def _coalesce_dirty(self) -> tuple:
        """Concatenate all dirty chunks and keep the LAST mark per slot
        (a slot freed and reassigned must report its newest (bin, key))."""
        chunks = self._dirty_chunks
        slots = np.concatenate([c[0] for c in chunks])
        bins = np.concatenate([c[1] for c in chunks])
        n_kc = len(chunks[0][2])
        key_cols = [
            np.concatenate([c[2][i] for c in chunks]) for i in range(n_kc)
        ]
        _, idx_rev = np.unique(slots[::-1], return_index=True)
        keep = len(slots) - 1 - idx_rev
        return slots[keep], bins[keep], [c[keep] for c in key_cols]

    def _key_delta_cols(self, key_cols: List[np.ndarray]) -> List[pa.Array]:
        """Columnar variant of _key_delta_arrays: key columns arrive as the
        normalized numpy arrays _mark_dirty captured (object arrays for
        interned types, int64-viewable otherwise)."""
        out = []
        for i, kt in enumerate(self._key_types):
            c = key_cols[i]
            if _is_interned_type(kt):
                out.append(pa.array(c.tolist(), type=kt))
            else:
                out.append(pa.array(c.astype(np.int64, copy=False)))
        return out

    def _key_delta_arrays(self, key_rows: List[tuple]) -> List[pa.Array]:
        """Portable key tuples -> one arrow array per key column (interned
        types keep their values/types; the rest are int64 codes whose hash
        matches the shuffle's)."""
        out = []
        for i, kt in enumerate(self._key_types):
            vals = [k[i] for k in key_rows]
            if _is_interned_type(kt):
                out.append(pa.array(vals, type=kt))
            else:
                out.append(
                    pa.array(np.asarray(vals, dtype=np.int64))
                )
        return out

    def _decode_delta_keys(self, batch: pa.RecordBatch) -> List[np.ndarray]:
        """__k* columns -> numpy arrays in the form _restore_rows expects
        (object arrays for interned types, int64 codes otherwise)."""
        names = batch.schema.names
        out = []
        for i, kt in enumerate(self._key_types):
            col = batch.column(names.index(f"__k{i}"))
            if _is_interned_type(kt):
                out.append(np.array(col.to_pylist(), dtype=object))
            else:
                out.append(np.asarray(col.cast(pa.int64())))
        return out

    def _use_incremental(self) -> bool:
        """Struct keys (window structs) hash differently in the parquet
        snapshot than on the shuffle, and host-state aggregates (UDAF
        buffers, count_distinct multisets) are variable-length — both fall
        back to the full-snapshot global table."""
        if self._key_types is None:
            return False
        if any(s.host_state() is not None for s in self.specs):
            return False
        return not any(pa.types.is_struct(t) for t in self._key_types)

    def _delta_key_fields(self) -> tuple:
        return tuple(f"__k{i}" for i in range(len(self.key_cols)))

    def _build_delta_batch(self, bin_ts):
        """Delta thunk for dirty slots: keys/bins were captured at assign
        time (O(dirty)), the accumulator gather is *dispatched* now against
        the current device state, and the returned zero-arg callable
        materializes the RecordBatch (__ts = bin_ts(bin), __bin, __k*,
        __v*) on the flush path — so the device->host copy overlaps the
        next epoch's processing."""
        if not self._dirty_chunks:
            return None
        slots, bins, key_cols = self._coalesce_dirty()
        self._dirty_chunks = []
        self._dirty_rows = self._dirty_base = 0
        values = self.acc.snapshot(slots, materialize=False)

        def build() -> pa.RecordBatch:
            arrays = [pa.array(bin_ts(bins)), pa.array(bins)]
            names = ["__ts", "__bin"]
            for i, arr in enumerate(self._key_delta_cols(key_cols)):
                arrays.append(arr)
                names.append(f"__k{i}")
            for j, v in enumerate(values):
                arrays.append(pa.array(np.asarray(v)))
                names.append(f"__v{j}")
            return pa.RecordBatch.from_arrays(arrays, names=names)

        return build

    async def _checkpoint_window_state(self, ctx, inc_table: str,
                                       bin_ts) -> dict:
        """Stage the incremental delta (or legacy full snapshot when not
        eligible) and return the meta snap to extend + put."""
        if self._use_incremental():
            delta = self._build_delta_batch(bin_ts)
            if delta is not None:
                (await ctx.table(inc_table)).write_delta(delta)
            return {"bins": [], "keys": [], "values": []}
        return self._snapshot_rows()

    async def _restore_incremental(self, ctx, inc_table: str):
        """Rebuild directory+accumulator from incremental delta files.
        Later rows supersede earlier ones per (bin, key); the table manager
        already applied key-range and retention filters."""
        table = await ctx.table(inc_table)
        if self._key_types is None:
            return
        newest: Dict[tuple, list] = {}
        n_phys = len(self.acc.phys)
        for b in table.all_batches():
            names = b.schema.names
            bins = np.asarray(b.column(names.index("__bin")))
            key_cols = self._decode_delta_keys(b)
            vals = [
                np.asarray(b.column(names.index(f"__v{j}")))
                for j in range(n_phys)
            ]
            for r in range(b.num_rows):
                k = (int(bins[r]), tuple(c[r] for c in key_cols))
                newest[k] = [v[r] for v in vals]
        if not newest:
            return
        bins_l, keys_l = [], []
        cols: List[list] = [[] for _ in range(n_phys)]
        for (b_, key_t), vv in newest.items():
            bins_l.append(b_)
            keys_l.append(list(key_t))
            for j, v in enumerate(vv):
                cols[j].append(v)
        self._restore_rows(
            {"bins": bins_l, "keys": keys_l, "values": cols}, ctx
        )
        # conduit table: in-memory source of truth is the accumulator
        table.clear_batches()

    def _key_arrays(self, batch: pa.RecordBatch) -> List[np.ndarray]:
        out = []
        for i in self.key_cols:
            col = batch.column(i)
            if pa.types.is_struct(col.type) and self._flat_widths is not None:
                # native flat layout: struct children ride as separate
                # int64 key words — no python tuple per row
                for j in range(col.type.num_fields):
                    out.append(
                        np.asarray(col.field(j).cast(pa.int64()))
                    )
                continue
            if pa.types.is_struct(col.type):
                # struct keys (window structs) become tuples of child values;
                # tuples are built per UNIQUE row (batches share few windows)
                children = [
                    np.asarray(col.field(j).cast(pa.int64()))
                    if _is_temporal_or_int(col.type.field(j).type)
                    else np.array(col.field(j).to_pylist(), dtype=object)
                    for j in range(col.type.num_fields)
                ]
                if all(c.dtype != object for c in children):
                    mat = np.stack(children, axis=1)
                    uniq, inverse = np.unique(mat, axis=0, return_inverse=True)
                    tuples = np.empty(len(uniq), dtype=object)
                    tuples[:] = [tuple(int(x) for x in row) for row in uniq]
                    out.append(tuples[inverse.ravel()])
                else:
                    out.append(
                        np.fromiter(
                            (tuple(int(c[r]) if isinstance(c[r], np.integer)
                                   else c[r] for c in children)
                             for r in range(batch.num_rows)),
                            dtype=object,
                            count=batch.num_rows,
                        )
                    )
                continue
            try:
                out.append(col.to_numpy(zero_copy_only=False))
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
                out.append(np.array(col.to_pylist(), dtype=object))
        return out

    def _agg_input_cols(self, batch: pa.RecordBatch) -> Dict:
        """Column arrays for the accumulator. Numeric (device-phys) specs
        that actually read their column ('col'-sourced phys ops — count's
        phys reads the constant 1, never the column) claim plain keys with
        the cast the reduction needs; host-state specs (UDAF buffers,
        count_distinct multisets) always get the raw uncast values under
        ('raw', col) so strings survive and BIGINTs shared with a float
        spec don't collapse above 2^53."""
        cols: Dict = {}

        def claim(c: int):
            # cast by COLUMN type: float columns stay float64, everything
            # else (ints, bools, timestamps) flattens to int64 bit-friendly
            # values; derived sources (sq/prod) re-cast to float64 at use
            if c in cols:
                return
            arr = batch.column(c)
            if pa.types.is_floating(arr.type):
                cols[c] = np.asarray(
                    arr.to_numpy(zero_copy_only=False), dtype=np.float64
                )
            else:
                cols[c] = np.asarray(
                    arr.cast(pa.int64()).to_numpy(zero_copy_only=False)
                )

        for spec in self.specs:
            if spec.host_state() is not None:
                continue
            for _, _, src in spec.phys():
                if src in ("col", "sq", "prod"):
                    claim(spec.col)
                if src in ("col2", "sq2", "prod"):
                    claim(spec.col2)
        for spec in self.specs:
            if spec.col is None or spec.host_state() is None:
                continue
            for c in (spec.col, spec.col2):
                if c is not None and ("raw", c) not in cols:
                    cols[("raw", c)] = np.asarray(
                        batch.column(c).to_numpy(zero_copy_only=False)
                    )
        return cols

    def _build_output(
        self,
        keys: List[tuple],
        agg_cols: List[np.ndarray],
        start: int,
        end: int,
        ts_value: Optional[int] = None,
        key_arrays: Optional[List[np.ndarray]] = None,
        serve_stage: bool = True,
    ) -> pa.RecordBatch:
        """Build an output batch for one window [start, end). `key_arrays`
        (one int64 array per key column, raw directory bit-patterns) is the
        vectorized fast path used by the native-directory emit — no python
        tuple per key. start/end/ts_value may be scalars (one window) or
        per-row arrays (batched session emission)."""
        n = len(key_arrays[0]) if key_arrays is not None else len(keys)

        def const_or_arr(v):
            if isinstance(v, np.ndarray):
                return v.astype(np.int64, copy=False)
            return np.full(n, v, dtype=np.int64)

        window_field = getattr(self, "window_field", None)
        arrays = []
        for f in self.out_schema.schema:
            if f.name == TIMESTAMP_FIELD:
                ts = ts_value if ts_value is not None else end - 1
                arrays.append(
                    pa.array(const_or_arr(ts)).cast(f.type)
                )
            elif f.name == window_field and pa.types.is_struct(f.type):
                s = pa.array(const_or_arr(start)).cast(f.type.field(0).type)
                e = pa.array(const_or_arr(end)).cast(f.type.field(1).type)
                arrays.append(
                    pa.StructArray.from_arrays(
                        [s, e], names=[f.type.field(0).name,
                                       f.type.field(1).name]
                    )
                )
            elif f.name == self.window_start_field:
                arrays.append(
                    pa.array(const_or_arr(start)).cast(f.type)
                )
            elif f.name == self.window_end_field:
                arrays.append(
                    pa.array(const_or_arr(end)).cast(f.type)
                )
            elif f.name in (self._key_names or []):
                ki = self._key_names.index(f.name)
                kt = self._key_types[ki]
                if key_arrays is not None:
                    off = (self._flat_offsets[ki]
                           if self._flat_offsets is not None else ki)
                    if pa.types.is_struct(kt):
                        # flat layout: regroup the struct's child words
                        children = [
                            pa.array(key_arrays[off + j]).cast(
                                kt.field(j).type
                            )
                            for j in range(kt.num_fields)
                        ]
                        arrays.append(
                            pa.StructArray.from_arrays(
                                children,
                                names=[kt.field(j).name
                                       for j in range(kt.num_fields)],
                            )
                        )
                    elif pa.types.is_unsigned_integer(kt):
                        arrays.append(
                            pa.array(key_arrays[off].view(np.uint64),
                                     type=kt)
                        )
                    else:  # signed ints and timestamps cast directly
                        arrays.append(pa.array(key_arrays[off]).cast(kt))
                    continue
                vals = [_to_py(k[ki]) for k in keys]
                if pa.types.is_struct(kt):
                    tuples = [unintern_value(v) for v in vals]
                    children = [
                        pa.array(
                            [t[j] for t in tuples], type=pa.int64()
                        ).cast(kt.field(j).type)
                        if _is_temporal_or_int(kt.field(j).type)
                        else pa.array([t[j] for t in tuples],
                                      type=kt.field(j).type)
                        for j in range(kt.num_fields)
                    ]
                    arrays.append(
                        pa.StructArray.from_arrays(
                            children,
                            names=[kt.field(j).name
                                   for j in range(kt.num_fields)],
                        )
                    )
                elif _is_interned_type(kt):
                    arrays.append(
                        pa.array([unintern_value(v) for v in vals], type=kt)
                    )
                elif pa.types.is_unsigned_integer(kt):
                    # directory codes are bit-preserving int64; normalize back
                    arrays.append(
                        pa.array([v % (1 << 64) for v in vals], type=kt)
                    )
                elif pa.types.is_timestamp(kt):
                    arrays.append(pa.array(vals, type=pa.int64()).cast(kt))
                else:
                    arrays.append(pa.array(vals, type=kt))
            else:
                ai = next(
                    j for j, s in enumerate(self.specs) if s.name == f.name
                )
                col = agg_cols[ai]
                if pa.types.is_floating(f.type):
                    arrays.append(pa.array(col.astype(np.float64), type=f.type))
                elif pa.types.is_boolean(f.type):
                    arrays.append(pa.array(col.astype(bool)))
                elif pa.types.is_list(f.type):
                    arrays.append(pa.array(
                        [[_to_py(x) for x in v] for v in col], type=f.type
                    ))
                else:
                    arrays.append(pa.array(col.astype(np.int64), type=f.type))
        out = pa.RecordBatch.from_arrays(arrays, schema=self.out_schema.schema)
        if serve_stage and self._serve_view is not None:
            # StateServe: mirror the emitted window results into the
            # serve view's stage buffer (sealed at the next checkpoint
            # capture; reads see them once that epoch publishes).
            # serve_stage=False is the session-partial snapshot path,
            # which stages its batch itself with the partial flag set.
            from ..serve import stage_batch

            stage_batch(self._serve_view, out)
        return out

    # -- checkpoint form ----------------------------------------------------

    def _key_tuple_to_values(self, key: tuple) -> list:
        """Directory key tuple (codes) -> portable key values."""
        if self._flat_widths is not None:
            # native flat layout: struct child words regroup into the
            # portable tuple form (plain ints — nothing is interned here)
            out = []
            off = 0
            for ki, w in enumerate(self._flat_widths):
                if pa.types.is_struct(self._key_types[ki]):
                    out.append(tuple(int(x) for x in key[off:off + w]))
                else:
                    out.append(_to_py(key[off]))
                off += w
            return out
        out = []
        for ki, k in enumerate(key):
            if _is_interned_type(self._key_types[ki]):
                out.append(unintern_value(_to_py(k)))
            else:
                out.append(_to_py(k))
        return out

    def _snapshot_rows(self) -> dict:
        """Directory + accumulator values as plain lists (checkpoint form).
        Interned key codes are resolved to their values: codes are
        process-local and must never leave the process."""
        bins, keys, slots = [], [], []
        for b, key, slot in self.dir.items():
            bins.append(int(b))
            keys.append(self._key_tuple_to_values(key))
            slots.append(int(slot))
        slots_arr = np.asarray(slots, dtype=np.int64)
        values = self.acc.snapshot(slots_arr) if len(slots) else []
        return {"bins": bins, "keys": keys, "values": [v.tolist() for v in values]}

    def _restore_rows(self, snap: dict, ctx=None):
        """Rebuild directory+accumulator from a snapshot. Snapshots from ALL
        pre-restart subtasks are replayed; rows outside this subtask's key
        range are skipped, which makes rescaling a restore-time re-read
        (reference: key-range sharding, arroyo-types lib.rs:640)."""
        bins = snap["bins"]
        if not bins:
            return
        keys = snap["keys"]
        mask = self._range_mask(keys, ctx)
        if mask is not None:
            bins = [b for b, m in zip(bins, mask) if m]
            keys = [k for k, m in zip(keys, mask) if m]
            if not bins:
                return
        n_keycols = len(keys[0]) if keys else 0
        key_cols = []
        for i in range(n_keycols):
            vals = [k[i] for k in keys]
            kt = self._key_types[i]
            if self._flat_widths is not None and pa.types.is_struct(kt):
                # flat native layout: portable struct tuples -> child words
                mat = np.asarray([list(v) for v in vals], dtype=np.int64)
                key_cols.extend(
                    mat[:, j] for j in range(self._flat_widths[i])
                )
            elif _is_interned_type(kt):
                # dtype=object routes through the interning path in assign()
                key_cols.append(np.asarray(vals, dtype=object))
            else:
                key_cols.append(np.asarray(vals, dtype=np.int64))
        bins_arr = np.asarray(bins, dtype=np.int64)
        slots = self.dir.assign(bins_arr, key_cols)
        self._ensure_capacity()
        # trailing host-state columns (UDAF buffers / count-distinct
        # multisets) are per-slot variable-length lists: force 1-d object
        # arrays — np.asarray on ragged nested lists raises, and on
        # same-length lists it would silently build a 2-d numeric array
        n_phys = len(self.acc.phys)
        values = []
        for j, v in enumerate(snap["values"]):
            if j < n_phys:
                values.append(np.asarray(v))
            else:
                arr = np.empty(len(v), dtype=object)
                arr[:] = v
                values.append(arr)
        if mask is not None:
            marr = np.asarray(mask)
            values = [v[marr] for v in values]
        self.acc.restore(slots, values)
        # rows restored from a legacy full snapshot have no delta files;
        # mark them dirty so the first incremental checkpoint after restore
        # persists them (otherwise a later crash would lose every group not
        # touched since the format upgrade). Non-incremental operators
        # snapshot the whole directory anyway — marking would only grow
        # chunks nothing ever drains.
        if self._use_incremental():
            self._mark_dirty(slots, bins_arr, key_cols)

    def _range_mask(self, keys: List[list], ctx) -> Optional[List[bool]]:
        """True per row iff the key hashes into this subtask's range."""
        if ctx is None or ctx.task_info.parallelism <= 1 or not keys:
            return None
        if not self.key_cols:
            return None
        from ..types import hash_arrays, hash_column, server_for_hash_array

        cols = []
        for i in range(len(keys[0])):
            vals = [k[i] for k in keys]
            kt = self._key_types[i]
            # dtype must match what the shuffle hashed (schema.hash_keys)
            if pa.types.is_struct(kt):
                # shuffle hashes struct children in order. Portable
                # snapshot values are the tuples themselves (msgpack may
                # hand them back as lists); in-process session bookkeeping
                # passes interned codes
                tuples = [
                    unintern_value(v) if isinstance(v, (int, np.integer))
                    else tuple(v)
                    for v in (_to_py(v) for v in vals)
                ]
                for j in range(kt.num_fields):
                    cols.append(hash_column(
                        np.asarray([t[j] for t in tuples], dtype=np.int64)
                    ))
                continue
            if pa.types.is_floating(kt):
                arr = np.asarray(vals, dtype=np.float64)
            elif _is_interned_type(kt):
                arr = np.asarray(vals, dtype=object)
            else:
                arr = np.asarray(vals, dtype=np.int64)
            cols.append(hash_column(arr))
        owners = server_for_hash_array(
            hash_arrays(cols), ctx.task_info.parallelism
        )
        return list(owners == ctx.task_info.task_index)


def _to_py(v):
    return v.item() if isinstance(v, np.generic) else v


def _is_temporal_or_int(t: pa.DataType) -> bool:
    return pa.types.is_integer(t) or pa.types.is_timestamp(t)


def _snaps_for_me(table, ctx, keyed: bool):
    """Snapshots this subtask should replay: keyed state replays every
    subtask's snapshot (rows are filtered by key range inside
    _restore_rows); unkeyed state maps old subtask i onto new subtask
    i % parallelism so exactly one new subtask owns each old snapshot."""
    p = ctx.task_info.parallelism
    for snap in table.all_values():
        if snap is None:
            continue
        if keyed or snap.get("subtask", 0) % p == ctx.task_info.task_index:
            yield snap


def _is_interned_type(t: pa.DataType) -> bool:
    return not (
        pa.types.is_integer(t)
        or pa.types.is_boolean(t)
        or pa.types.is_timestamp(t)
    )


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


class TumblingWindowOperator(WindowOperatorBase):
    _native_ok = True
    _device_ok = True
    _mesh_ok = True

    """Fixed-width windows: bin = ts // width; emit at watermark >= end
    (reference tumbling_aggregating_window.rs:66-321).

    width_nanos == 0 is *instant* mode: rows group by their exact
    _timestamp — used to aggregate already-windowed streams
    (GROUP BY window), where every row of a window shares one timestamp."""

    def __init__(self, config: dict):
        super().__init__(config, "tumbling_window")
        self.width = int(config.get("width_nanos", 0))
        self.emitted_up_to: Optional[int] = None  # last emitted bin END

    def tables(self):
        from ..state.table_config import global_table, time_key_table

        # retention ties the delta rows' __ts (= bin end - 1, or the raw
        # instant timestamp) to the watermark: rows whose window already
        # emitted at the checkpointed watermark are pruned on restore.
        # Instant mode (width 0) emits at wm >= ts, hence retention -1
        # keeps exactly ts > wm.
        return {
            "t": global_table("t"),
            "ti": time_key_table(
                "ti",
                retention_nanos=0 if self.width else -1,
                timestamp_field="__ts",
                key_fields=self._delta_key_fields(),
            ),
        }

    def _delta_ts(self, bins: np.ndarray) -> np.ndarray:
        return (bins + 1) * self.width - 1 if self.width else bins

    async def on_start(self, ctx):
        self._capture_key_meta(ctx)
        if ctx.table_manager is not None:
            table = await ctx.table("t")
            for snap in _snaps_for_me(table, ctx, bool(self.key_cols)):
                if snap.get("emitted_up_to") is not None:
                    self.emitted_up_to = max(
                        self.emitted_up_to or 0, snap["emitted_up_to"]
                    )
                self._restore_rows(snap, ctx)
            await self._restore_incremental(ctx, "ti")

    async def handle_checkpoint(self, barrier, ctx, collector):
        if ctx.table_manager is not None:
            table = await ctx.table("t")
            snap = await self._checkpoint_window_state(
                ctx, "ti", self._delta_ts
            )
            snap["emitted_up_to"] = self.emitted_up_to
            snap["subtask"] = ctx.task_info.task_index
            table.put(ctx.task_info.task_index, snap)

    def _bin_of(self, ts: np.ndarray) -> np.ndarray:
        return ts // self.width if self.width else ts

    def _bin_end(self, b: int) -> int:
        return (b + 1) * self.width if self.width else b

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        self._capture_key_meta(ctx)
        ts = ctx.in_schemas[0].timestamps(batch)
        bins = self._bin_of(ts)
        if self.emitted_up_to is not None:
            if self.width:
                live = (bins + 1) * self.width > self.emitted_up_to
            else:
                live = bins > self.emitted_up_to
            if not live.all():
                if not live.any():
                    return
                batch = batch.filter(pa.array(live))
                bins = bins[live]
        keys = self._key_arrays(batch)
        slots = self.dir.assign(bins, keys)
        self._ensure_capacity()
        if ctx.table_manager is not None and self._use_incremental():
            self._mark_dirty(slots, bins, keys)
        self.acc.update(slots, self._agg_input_cols(batch))

    async def handle_watermark(self, watermark, ctx, collector):
        if watermark.kind != WatermarkKind.EVENT_TIME:
            return watermark
        t = watermark.timestamp
        limit = _ceil_div(t, self.width) if self.width else t + 1
        take_arrays = getattr(self.dir, "take_bin_arrays", None)
        # mesh accumulators fuse gather+reset into one device program
        # (halves the per-wave emission dispatches); host-state drops
        # then happen after finalize has read the stores
        fused = getattr(self.acc, "gather_and_reset", None)
        # ONE device drain for the whole wave: per-bin slot sets of the
        # same watermark advance concatenate into a single gather/take
        # dispatch (the old per-bin loop launched one device program per
        # bin — ~30 near-empty mesh.take dispatches per wave on the q5
        # per-window-max stage), then outputs slice back out per bin
        wave = []  # (bin, end, keys, key_arrays, slots)
        for b in self.dir.bins_up_to(limit):
            end = self._bin_end(b)
            if end > t:
                continue
            if take_arrays is not None:
                # native fast path: key columns stay numpy end-to-end
                key_arrays, slots = take_arrays(b)
                keys: List[tuple] = []
            else:
                keys, slots = self.dir.take_bin(b)
                key_arrays = None
            wave.append((b, end, keys, key_arrays, slots))
        if not wave:
            return watermark
        all_slots = (
            wave[0][4] if len(wave) == 1
            else np.concatenate([w[4] for w in wave])
        )
        gathered = (
            fused(all_slots) if fused is not None
            else self.acc.gather(all_slots)
        )
        agg_cols = self.acc.finalize(gathered)
        if fused is not None:
            self.acc.drop_host_state(all_slots)
        else:
            self.acc.reset_slots(all_slots)
        off = 0
        for b, end, keys, key_arrays, slots in wave:
            n = len(slots)
            cols_b = [c[off:off + n] for c in agg_cols]
            off += n
            if self.width:
                out = self._build_output(keys, cols_b, b * self.width, end,
                                         key_arrays=key_arrays)
            else:
                # instant mode: preserve the window's timestamp exactly
                out = self._build_output(keys, cols_b, b, b, ts_value=b,
                                         key_arrays=key_arrays)
            await collector.collect(out)
            self.emitted_up_to = max(self.emitted_up_to or 0, end)
        return watermark


class SlidingWindowOperator(WindowOperatorBase):
    """Hop windows: slide-granularity partial bins; each emitted window
    merges width/slide bins (reference sliding_aggregating_window.rs:64-753).
    Requires width % slide == 0."""

    _native_ok = True
    _device_ok = True
    _mesh_ok = True

    def __init__(self, config: dict):
        super().__init__(config, "sliding_window")
        self.width = int(config["width_nanos"])
        self.slide = int(config["slide_nanos"])
        assert self.slide > 0 and self.width % self.slide == 0, (
            "window width must be a positive multiple of slide"
        )
        self.k = self.width // self.slide
        self.next_emit: Optional[int] = None
        self.last_freed_bin: Optional[int] = None

    def tables(self):
        from ..state.table_config import global_table, time_key_table

        # a slide-granularity bin stays live until it exits its last
        # window: freed <=> bin_end <= wm - width + slide, so retention
        # width - slide over __ts = bin_end - 1 prunes exactly freed bins
        return {
            "s": global_table("s"),
            "si": time_key_table(
                "si",
                retention_nanos=self.width - self.slide,
                timestamp_field="__ts",
                key_fields=self._delta_key_fields(),
            ),
        }

    def _delta_ts(self, bins: np.ndarray) -> np.ndarray:
        return (bins + 1) * self.slide - 1

    async def on_start(self, ctx):
        self._capture_key_meta(ctx)
        if ctx.table_manager is not None:
            table = await ctx.table("s")
            for snap in _snaps_for_me(table, ctx, bool(self.key_cols)):
                if snap.get("next_emit") is not None:
                    self.next_emit = (
                        snap["next_emit"] if self.next_emit is None
                        else min(self.next_emit, snap["next_emit"])
                    )
                if snap.get("last_freed_bin") is not None:
                    self.last_freed_bin = (
                        snap["last_freed_bin"] if self.last_freed_bin is None
                        else min(self.last_freed_bin, snap["last_freed_bin"])
                    )
                self._restore_rows(snap, ctx)
            await self._restore_incremental(ctx, "si")

    async def handle_checkpoint(self, barrier, ctx, collector):
        if ctx.table_manager is not None:
            table = await ctx.table("s")
            snap = await self._checkpoint_window_state(
                ctx, "si", self._delta_ts
            )
            snap["next_emit"] = self.next_emit
            snap["last_freed_bin"] = self.last_freed_bin
            snap["subtask"] = ctx.task_info.task_index
            table.put(ctx.task_info.task_index, snap)

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        self._capture_key_meta(ctx)
        ts = ctx.in_schemas[0].timestamps(batch)
        bins = ts // self.slide
        if self.last_freed_bin is not None:
            live = bins > self.last_freed_bin
            if not live.all():
                if not live.any():
                    return
                batch = batch.filter(pa.array(live))
                bins = bins[live]
        if self.next_emit is None and len(bins):
            self.next_emit = (int(bins.min()) + 1) * self.slide
        keys = self._key_arrays(batch)
        slots = self.dir.assign(bins, keys)
        self._ensure_capacity()
        if ctx.table_manager is not None and self._use_incremental():
            self._mark_dirty(slots, bins, keys)
        self.acc.update(slots, self._agg_input_cols(batch))

    async def handle_watermark(self, watermark, ctx, collector):
        if watermark.kind != WatermarkKind.EVENT_TIME:
            return watermark
        t = watermark.timestamp
        while self.next_emit is not None and self.next_emit <= t:
            await self._emit_window(self.next_emit, collector)
            if not self.dir.by_bin:
                self.next_emit = None  # drained; restart at next data
            else:
                self.next_emit += self.slide
        return watermark

    async def _emit_window(self, end: int, collector):
        end_bin = end // self.slide  # window covers bins [end_bin-k, end_bin)
        lo_bin = end_bin - self.k
        # merge per-key across participating bins (host merge: runs once per
        # slide period; the per-event scatter stays on device).
        # The bin exiting the window (lo_bin) is TAKEN from the directory
        # up front so its entries lead the union: the accumulator can then
        # gather the union and reset the freed bin in ONE fused device
        # dispatch (combine_for_segments_and_free) instead of a gather
        # followed by a separate reset program launch per wave.
        key_chunks = []
        slot_chunks = []
        take_arrays = getattr(self.dir, "take_bin_arrays", None)
        if take_arrays is not None:
            fk_cols, freed = take_arrays(lo_bin)
            if len(freed):
                key_chunks.append(np.stack(fk_cols, axis=1))
                slot_chunks.append(freed)
        else:
            fk, freed = self.dir.take_bin(lo_bin)
            if len(freed):
                key_chunks.append(fk)
                slot_chunks.append(freed)
        multi = getattr(self.dir, "bin_entries_multi", None)
        if multi is not None:
            # native directories: ONE batched crossing covering every
            # participating bin (the merge unions keys across bins, so
            # per-bin identity is irrelevant) instead of k get_bin calls
            # — k x shards calls on the mesh facade
            kmat, slots_m = multi(
                np.arange(lo_bin + 1, end_bin, dtype=np.int64)
            )
            if len(slots_m):
                key_chunks.append(kmat)
                slot_chunks.append(slots_m)
        else:
            for b in range(lo_bin + 1, end_bin):
                keys_b, slots_b = self.dir.bin_entries(b)
                if len(slots_b):
                    key_chunks.append(keys_b)
                    slot_chunks.append(slots_b)
        if slot_chunks:
            all_slots = np.concatenate(slot_chunks)
            key_arrays = None
            if isinstance(key_chunks[0], np.ndarray):
                # native path: vectorized key-union over int64 key matrices
                # (count, n_keycols); keys stay numpy end-to-end (no python
                # tuple per key)
                all_keys = np.concatenate(key_chunks)
                if all_keys.shape[1] == 1:
                    # 1-D unique is markedly faster than axis=0
                    u1, seg_ids = np.unique(
                        all_keys[:, 0], return_inverse=True
                    )
                    uniq = u1[:, None]
                else:
                    uniq, seg_ids = np.unique(
                        all_keys, axis=0, return_inverse=True
                    )
                seg_ids = np.asarray(seg_ids).ravel()
                if self.key_cols:
                    out_keys = []
                    # one column per flat key word (struct children ride
                    # as separate words under the flat layout)
                    key_arrays = [
                        uniq[:, j] for j in range(uniq.shape[1])
                    ]
                else:
                    out_keys = [() for _ in range(len(uniq))]
                n_keys = len(uniq)
            else:
                index: Dict[tuple, int] = {}
                seg = np.empty(len(all_slots), dtype=np.int64)
                i = 0
                for chunk in key_chunks:
                    for key in chunk:
                        seg[i] = index.setdefault(key, len(index))
                        i += 1
                seg_ids = seg
                out_keys = list(index.keys())
                n_keys = len(index)
            combined = self.acc.combine_for_segments_and_free(
                all_slots, seg_ids, n_keys, free_n=len(freed)
            )
            agg_cols = self.acc.finalize(combined)
            out_batch = self._build_output(
                out_keys, agg_cols, end - self.width, end,
                key_arrays=key_arrays,
            )
            await collector.collect(out_batch)
        self.last_freed_bin = max(self.last_freed_bin or lo_bin, lo_bin)


def _tolist(col) -> list:
    """Portable list view of one snapshot column slice (numpy scalar
    arrays or ragged host-state object arrays)."""
    if isinstance(col, np.ndarray):
        return col.tolist()
    return list(col)


def _batch_group_codes(key_cols: List[np.ndarray], n: int) -> np.ndarray:
    """Per-row group code over the key columns, local to ONE batch:
    non-integer columns factorize via pandas (no entry in the process-
    wide intern table — session keys expire, interning them would leak)."""
    if not key_cols:
        return np.zeros(n, dtype=np.int64)
    import pandas as pd

    norm = []
    for c in key_cols:
        c = np.asarray(c)
        if c.dtype.kind == "M":
            c = c.view("i8")
        elif c.dtype == np.uint64:
            c = c.view(np.int64)
        if c.dtype.kind not in "iub":
            c = pd.factorize(c)[0].astype(np.int64)
        norm.append(c.astype(np.int64, copy=False))
    if len(norm) == 1:
        _, inverse = np.unique(norm[0], return_inverse=True)
        return inverse.ravel()
    _, inverse = np.unique(np.stack(norm, axis=1), axis=0,
                           return_inverse=True)
    return inverse.ravel()


class SessionWindowOperator(WindowOperatorBase):
    """Per-key gap-merged sessions
    (reference session_aggregating_window.rs:51-942). Session bookkeeping is
    inherently scalar and stays host-side; the accumulator arithmetic runs
    on the numpy backend single-device (a lone jax device wins nothing over
    the bookkeeping) but shards across the device mesh in mesh mode —
    slots are allocated round-robin across shards and every accumulator
    update/gather rides the sharded all_to_all path like tumbling/sliding
    (reference treats all window types uniformly)."""

    _mesh_ok = True
    _offmesh_backend = "numpy"

    def __init__(self, config: dict):
        config = dict(config)
        if self._cfg_mesh_devices(config) < 2:
            config["backend"] = "numpy"
        super().__init__(config, "session_window")
        self.gap = int(config["gap_nanos"])
        assert self.gap > 0
        # key -> list of [start, last_ts, slot], sorted by start
        self.sessions: Dict[tuple, List[List]] = {}
        # incremental checkpointing (ROADMAP item 4): keys whose sessions
        # or accumulators changed since the last epoch, and keys whose
        # last session closed (tombstoned in the sess table) — capture
        # cost is O(touched sessions), not O(live sessions)
        self._ckpt_dirty: set = set()
        self._ckpt_dead: set = set()
        # serve partial staging (ISSUE 20) follows the same delta
        # discipline: only keys whose sessions changed since the last
        # capture are re-staged as partials (unchanged partials persist
        # in the cumulative view/mirror), so the capture span stays
        # O(touched sessions) under growing live-session counts
        self._serve_dirty: set = set()
        self._serve_dead: set = set()
        self._serve_partial_keys: set = set()
        self._next_shard = 0
        # block-refilled slot pool: one vectorized alloc_slots call per
        # _POOL_BLOCK sessions instead of one Python directory call per
        # session (the mesh facade deals the block round-robin across
        # shards, preserving placement balance)
        self._slot_pool: List[int] = []

    _POOL_BLOCK = 64

    def _alloc_slot(self) -> int:
        if not self._slot_pool:
            self._slot_pool = self.dir.alloc_slots(
                self._POOL_BLOCK, self._next_shard
            ).tolist()
            self._next_shard += self._POOL_BLOCK
        return self._slot_pool.pop()

    def _free_slot(self, slot: int):
        self.dir.free_slot(int(slot))

    def _return_pool(self):
        """Return unused pooled slots to the directory free lists. Left
        in the pool across a checkpoint they are allocated-but-unused:
        required_capacity (and the accumulator grow threshold) carries
        up to _POOL_BLOCK-1 idle slots, and a restore from that
        checkpoint strands them entirely (ADVICE round 5)."""
        if self._slot_pool:
            self.dir.free_slots(
                np.asarray(self._slot_pool, dtype=np.int64)
            )
            self._slot_pool = []

    def tables(self):
        from ..state.table_config import global_table

        return {"sess": global_table("sess")}

    async def on_start(self, ctx):
        self._capture_key_meta(ctx)
        if ctx.table_manager is None:
            return
        table = await ctx.table("sess")
        if not self.key_cols:
            # unkeyed (window-global) sessions keep the legacy
            # per-subtask snapshot — there is no key to partition by
            for snap in _snaps_for_me(table, ctx, False):
                self._restore_sessions(snap, ctx)
            self._serve_dirty.update(self.sessions)
            return
        legacy, per_key = [], []
        for k, v in table.items():
            if isinstance(k, tuple) and k and k[0] == "sk":
                per_key.append((k, v))
            elif isinstance(v, dict) and "sessions" in v:
                legacy.append(v)
        for snap in legacy:
            self._restore_sessions(snap, ctx)
        kept = self._restore_per_key(per_key, ctx)
        # each subtask's chain carries ONLY its own keys from here on:
        # out-of-range entries (and replayed legacy snaps) are owned and
        # re-persisted by their own subtasks this same epoch, so they are
        # pruned without tombstones — which keeps the cross-subtask union
        # free of stale replicated copies and lets rebase drop tombstones
        table.retain(lambda k: isinstance(k, tuple) and k and k[0] == "sk"
                     and k in kept)
        # everything restored re-persists at the first post-restore epoch
        # (covers legacy-format upgrades and the pruned replicas)
        self._ckpt_dirty.update(self.sessions)
        self._serve_dirty.update(self.sessions)

    async def handle_checkpoint(self, barrier, ctx, collector):
        self._return_pool()
        if self._serve_view is None:
            # no attached view consumes the serve delta sets; keep them
            # bounded on unviewed jobs
            self._serve_dirty.clear()
            self._serve_dead.clear()
        if ctx.table_manager is None:
            return
        table = await ctx.table("sess")
        if not self.key_cols:
            snap = self._snapshot_sessions()
            snap["subtask"] = ctx.task_info.task_index
            table.put(ctx.task_info.task_index, snap)
            return
        for key in self._ckpt_dead:
            table.delete(self._sess_key(key))
        self._ckpt_dead.clear()
        dirty = [k for k in self._ckpt_dirty if k in self.sessions]
        self._ckpt_dirty.clear()
        if dirty:
            # one batched accumulator gather for every dirty session
            slots = [s[2] for k in dirty for s in self.sessions[k]]
            values = self.acc.snapshot(
                np.asarray(slots, dtype=np.int64)
            ) if slots else []
            idx = 0
            for k in dirty:
                sess = self.sessions[k]
                n = len(sess)
                table.put(self._sess_key(k), {
                    "s": [[int(x) for x in s[:2]] + [int(s[2])]
                          for s in sess],
                    "v": [_tolist(col[idx:idx + n]) for col in values],
                })
                idx += n

    def _sess_key(self, key: tuple) -> tuple:
        """Portable per-session-key table key ("sk", *values) — msgpack
        round-trips it as a list, GlobalTable re-tuples on load."""
        return ("sk", *self._key_tuple_to_values(key))

    def _restore_per_key(self, items: list, ctx) -> set:
        """Replay per-key entries owned by this subtask; returns the set
        of table keys kept (for the retain() prune)."""
        if not items:
            return set()
        key_rows = [list(k[1:]) for k, _v in items]
        mask = self._range_mask(key_rows, ctx)
        kept = set()
        sessions, slots, cols = [], [], None
        idx = 0
        for i, (k, v) in enumerate(items):
            if mask is not None and not mask[i]:
                continue
            kept.add(k)
            sess_list = []
            for s in v["s"]:
                sess_list.append([s[0], s[1], idx])
                slots.append(idx)
                idx += 1
            sessions.append([list(k[1:]), sess_list])
            if cols is None:
                cols = [[] for _ in v["v"]]
            for c, col in zip(cols, v["v"]):
                c.extend(col)
        if sessions:
            self._restore_sessions(
                {"sessions": sessions, "slots": slots, "values": cols or []},
                ctx,
            )
        return kept

    def _snapshot_sessions(self) -> dict:
        slots = [s[2] for v in self.sessions.values() for s in v]
        slots_arr = np.asarray(slots, dtype=np.int64)
        values = self.acc.snapshot(slots_arr) if slots else []
        return {
            "sessions": [
                [self._key_tuple_to_values(key), [[int(x) for x in s] for s in v]]
                for key, v in self.sessions.items()
            ],
            "slots": [int(s) for s in slots],
            "values": [v.tolist() for v in values],
        }

    def _restore_sessions(self, snap: dict, ctx=None):
        """Replay one pre-restart subtask's sessions, remapping slots (old
        slot ids collide across subtasks) and skipping keys outside this
        subtask's range."""
        from ..ops.directory import intern_value

        def to_key(vals: list) -> tuple:
            return tuple(
                intern_value(v) if _is_interned_type(self._key_types[i]) else v
                for i, v in enumerate(vals)
            )

        slot_pos = {s: i for i, s in enumerate(snap["slots"])}
        # trailing host-state columns are ragged per-slot lists (same
        # object-array discipline as _restore_rows)
        n_phys = len(self.acc.phys)
        values = []
        for j, v in enumerate(snap["values"]):
            if j < n_phys:
                values.append(np.asarray(v))
            else:
                arr = np.empty(len(v), dtype=object)
                arr[:] = v
                values.append(arr)
        key_rows = [key_vals for key_vals, _ in snap["sessions"]]
        mask = self._range_mask(key_rows, ctx) if key_rows else None
        new_slots: List[int] = []
        positions: List[int] = []
        for si, (key_vals, sess_list) in enumerate(snap["sessions"]):
            if mask is not None and not mask[si]:
                continue
            key = to_key(key_vals)
            cur = self.sessions.setdefault(key, [])
            for s in sess_list:
                new_slot = self._alloc_slot()
                new_slots.append(new_slot)
                positions.append(slot_pos[s[2]])
                cur.append([s[0], s[1], new_slot])
            cur.sort(key=lambda x: x[0])
        if new_slots:
            # one batched restore (a single scatter dispatch in mesh mode)
            self._ensure_capacity()
            pos = np.asarray(positions, dtype=np.int64)
            self.acc.restore(
                np.asarray(new_slots, dtype=np.int64),
                [v[pos] for v in values],
            )

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        self._capture_key_meta(ctx)
        ts = ctx.in_schemas[0].timestamps(batch)
        wm = ctx.watermarks.current_nanos()
        keys = self._key_arrays(batch)
        cols = self._agg_input_cols(batch)
        n = len(ts)
        row_slots = np.full(n, -1, dtype=np.int64)
        live = (
            np.ones(n, dtype=bool) if wm is None
            else ts + self.gap > wm  # else fully late: already emitted
        )
        li = np.nonzero(live)[0]
        if len(li):
            # vectorized segmentation: group rows by key, split each
            # key's time-sorted rows where the gap is exceeded, then do
            # the scalar bookkeeping ONCE PER SEGMENT (high-rate session
            # streams have many rows per segment; the old per-row
            # _place loop was the session operator's host ceiling)
            lts = ts[li]
            lk = [np.asarray(k)[li] for k in keys]
            inverse = _batch_group_codes(lk, len(li))
            order = np.lexsort((lts, inverse))
            so_key = inverse[order]
            so_ts = lts[order]
            new_seg = np.ones(len(order), dtype=bool)
            if len(order) > 1:
                new_seg[1:] = (so_key[1:] != so_key[:-1]) | (
                    so_ts[1:] - so_ts[:-1] >= self.gap
                )
            seg_id = np.cumsum(new_seg) - 1
            starts = np.nonzero(new_seg)[0]
            ends = np.r_[starts[1:], len(order)] - 1
            seg_slots = np.empty(len(starts), dtype=np.int64)
            for g in range(len(starts)):
                first = int(order[starts[g]])
                key = tuple(_to_py(c[first]) for c in lk)
                seg_slots[g] = self._place_segment(
                    key, int(so_ts[starts[g]]), int(so_ts[ends[g]])
                )
                self._ckpt_dirty.add(key)
                self._ckpt_dead.discard(key)
                self._serve_dirty.add(key)
                self._serve_dead.discard(key)
            row_slots[li[order]] = seg_slots[seg_id]
        keep = row_slots >= 0
        if keep.any():
            self._ensure_capacity()
            self.acc.update(
                row_slots[keep], {c: v[keep] for c, v in cols.items()}
            )

    def _place_segment(self, key: tuple, lo: int, hi: int) -> int:
        """Find/extend/merge the session covering [lo, hi] (all rows of
        one batch segment share it); returns its slot. Interval union
        with gap is order-independent, so segment-level placement yields
        the same final sessions as the old per-row placement."""
        sess = self.sessions.setdefault(key, [])
        hit = None
        for s in sess:
            if s[0] - self.gap < hi and lo < s[1] + self.gap:
                hit = s
                break
        if hit is None:
            slot = self._alloc_slot()
            self._ensure_capacity()
            sess.append([lo, hi, slot])
            sess.sort(key=lambda s: s[0])
            return slot
        hit[0] = min(hit[0], lo)
        hit[1] = max(hit[1], hi)
        # the extension may bridge adjacent sessions: merge while
        # overlapping. When the HIT side is the one folded away (it
        # bridged backwards into an earlier session), the survivor
        # becomes the hit — returning the folded slot would scatter the
        # segment's rows into a freed (reusable) slot.
        sess.sort(key=lambda s: s[0])
        i = 0
        while i < len(sess) - 1:
            a, b = sess[i], sess[i + 1]
            if b[0] < a[1] + self.gap:
                self._merge_slots(a, b)
                sess.pop(i + 1)
                if b is hit:
                    hit = a
            else:
                i += 1
        return hit[2]

    def _merge_slots(self, a: List, b: List):
        """Fold session b's accumulator into a's; free b's slot."""
        self.acc.merge_slot_into(a[2], b[2])
        ga = self.acc.gather(np.asarray([a[2], b[2]], dtype=np.int64))
        combined = []
        for (op, dt, _, _), vals in zip(self.acc.phys, ga):
            if op == "add":
                combined.append(np.asarray([vals[0] + vals[1]]))
            elif op == "min":
                combined.append(np.asarray([min(vals[0], vals[1])]))
            else:
                combined.append(np.asarray([max(vals[0], vals[1])]))
        self.acc.restore(np.asarray([a[2]], dtype=np.int64), combined)
        self.acc.reset_slots(np.asarray([b[2]], dtype=np.int64))
        self._free_slot(b[2])
        a[0] = min(a[0], b[0])
        a[1] = max(a[1], b[1])

    def serve_stage_snapshot(self, view) -> None:
        """Serve OPEN sessions as partials (ISSUE 20 satellite). Called
        by seal_op inside the checkpoint capture span. Delta-staged:
        only keys whose sessions changed since the last capture — new
        events, merges, expiries, tracked in `_serve_dirty` beside the
        incremental-checkpoint sets — are re-gathered and re-staged
        flagged `partial: True` (end is the would-be close `last_ts +
        gap`), so point reads — worker- and follower-side alike — see
        in-flight sessions at the published epoch instead of a 404
        until the gap closes. Unchanged partials persist in the
        cumulative view/mirror, keeping capture cost O(touched
        sessions) rather than O(live sessions) — the state-bloat
        flatness gate depends on this. Requires a side-effect-free
        `gather`; mesh-fused accumulators expose only gather_and_reset,
        so they skip partials (a documented known limit — finals are
        unaffected). A key whose sessions all closed since the last
        capture is tombstoned ONLY if no final landed in this barrier
        interval, so partials never clobber a just-emitted final."""
        gather = getattr(self.acc, "gather", None)
        prev = getattr(self, "_serve_partial_keys", set())
        if gather is None:
            return
        dirty = getattr(self, "_serve_dirty", None)
        delta = dirty is not None
        if not delta:
            # stub operators (tests) without the delta sets: stage the
            # full open set and diff against prev for tombs
            dirty = set(self.sessions)
        dead = getattr(self, "_serve_dead", set())
        keys: List[tuple] = []
        starts: List[int] = []
        ends: List[int] = []
        slots: List[int] = []
        for key in dirty:
            for s in self.sessions.get(key, ()):
                # one row per session; staging overwrites per key, so a
                # multi-session key serves its latest (max-start) session
                keys.append(key)
                starts.append(s[0])
                ends.append(s[1] + self.gap)
                slots.append(s[2])
        staged: set = set()
        if keys:
            from ..serve import stage_batch

            slot_arr = np.asarray(slots, dtype=np.int64)
            agg_cols = self.acc.finalize(gather(slot_arr))
            out = self._build_output(
                keys, agg_cols,
                np.asarray(starts, dtype=np.int64),
                np.asarray(ends, dtype=np.int64),
                serve_stage=False,
            )
            staged = set(stage_batch(view, out, partial=True))
        gone = (prev & dead) if delta else (prev - staged)
        for k in gone:
            if view.has_staged(k):
                continue  # a final landed this interval; keep it
            if view.live_mode:
                v = view.served.get(k)
                if not (isinstance(v, dict) and v.get("partial")):
                    continue
            view.stage_tomb(k)
        # gone keys leave the partial set either way: tombed, or their
        # staged row this interval is a final, no longer a partial
        self._serve_partial_keys = (prev - gone) | staged
        if delta:
            dirty.clear()
            dead.clear()

    async def handle_watermark(self, watermark, ctx, collector):
        if watermark.kind != WatermarkKind.EVENT_TIME:
            return watermark
        t = watermark.timestamp
        # collect every expired session first: one batched gather +
        # finalize + reset per watermark (2 device dispatches in mesh
        # mode), one output batch with per-row window bounds
        exp_keys: List[tuple] = []
        exp_starts: List[int] = []
        exp_ends: List[int] = []
        exp_slots: List[int] = []
        for key in list(self.sessions):
            remaining = []
            expired_any = False
            for s in self.sessions[key]:
                if s[1] + self.gap <= t:
                    expired_any = True
                    exp_keys.append(key)
                    exp_starts.append(s[0])
                    exp_ends.append(s[1] + self.gap)
                    exp_slots.append(s[2])
                else:
                    remaining.append(s)
            if remaining:
                self.sessions[key] = remaining
                if expired_any:
                    self._ckpt_dirty.add(key)
                    # the expiry final overwrote the key's served
                    # partial; re-stage the still-open session
                    self._serve_dirty.add(key)
            else:
                del self.sessions[key]
                if expired_any:
                    self._ckpt_dead.add(key)
                    self._ckpt_dirty.discard(key)
                    self._serve_dead.add(key)
                    self._serve_dirty.discard(key)
        if exp_slots:
            slot_arr = np.asarray(exp_slots, dtype=np.int64)
            fused = getattr(self.acc, "gather_and_reset", None)
            if fused is not None:
                # mesh: one fused device program per expiry wave
                agg_cols = self.acc.finalize(fused(slot_arr))
                self.acc.drop_host_state(slot_arr)
            else:
                agg_cols = self.acc.finalize(self.acc.gather(slot_arr))
                self.acc.reset_slots(slot_arr)
            self.dir.free_slots(slot_arr)  # batch: one extend per shard
            out = self._build_output(
                exp_keys, agg_cols,
                np.asarray(exp_starts, dtype=np.int64),
                np.asarray(exp_ends, dtype=np.int64),
            )
            await collector.collect(out)
        return watermark


@register_operator(OperatorName.TUMBLING_WINDOW_AGGREGATE)
def _make_tumbling(config: dict) -> Operator:
    return TumblingWindowOperator(config)


@register_operator(OperatorName.SLIDING_WINDOW_AGGREGATE)
def _make_sliding(config: dict) -> Operator:
    return SlidingWindowOperator(config)


@register_operator(OperatorName.SESSION_WINDOW_AGGREGATE)
def _make_session(config: dict) -> Operator:
    return SessionWindowOperator(config)
