"""The composed protocol model: controller x N workers x storage x channels.

Explicit-state transition system over hashable NamedTuple states. Worker 0
is the source-only role; the rest are transactional sinks sealing
per-epoch commit data (the kafka-exactly-once shape, the hardest 2PC
case). The controller machine's legal JobState moves come from the
EXTRACTED TRANSITIONS table (`extract.job_state_machine`) — an illegal
move is itself a reported violation, never a crash.

Modeled from the dispatch code (each transition cites its handlers via
TRANSITION_HANDLERS; the bijection check ties those to @protocol_effect
annotations on the real functions):

  * pipelined checkpoint cadence: up to `inflight` epochs fanned out
    before the first publishes; manifests publish strictly in epoch
    order; an epoch whose report set can no longer complete is abandoned
    on deadline, and a LATER epoch may still publish — sound only
    because per-worker flushes are epoch-ordered, which is exactly what
    the V_ATOMIC chain check verifies at every publish;
  * worker capture/flush split with `inflight` admission, strictly
    epoch-ordered flushes, fail-fast flush errors (TaskFailedResp);
  * 2PC: sinks seal a transaction per captured epoch, the controller
    CAS-claims the commit record after the manifest publishes and fans
    CommitMsg to committing workers only; commit application is
    cumulative (epoch <= msg epoch), sinks hold a committing state at
    stop, and a restore idempotently replays every claimed epoch's
    commit from its manifest (the connectors' sealed-state replay);
  * generation fencing: recovery claims a fresh generation; a superseded
    generation's publish is fenced; data paths are generation-stamped so
    a fenced zombie's late upload lands beside, never over, a live blob;
  * RESCALING: drain -> stop checkpoint -> apply overrides -> teardown ->
    fresh generation -> reschedule, with the documented failure windows
    (pre-publish failures recover at the old parallelism, post-publish at
    the new one).

Timeouts (epoch deadline abandons) are modeled as "fair": enabled only
when the awaited report set provably cannot complete — the wall-clock
deadline never beats sub-second progress in the real system, and an
always-enabled timeout would flood the space with unreal runs. The
V_STALL invariant then asks that detection of a dead worker never
REQUIRES a timeout (the PR 2 mid-barrier-death bug class).

Fault events (first-class transitions, budgeted by `cfg.faults`): worker
kill, heartbeat blackout (presumed-dead zombie), barrier loss (a
data-plane connection drop surfacing as a task failure), barrier
duplication (dedupe safety), cross-channel reorder (commit vs barrier),
manifest CAS race, zombie fencing at publish, flush failure, rescale
reschedule failure. Zombie late-writes are free consequences of a
blackout teardown.

Mutants (mutants.py) are named flags consulted here — every read is a
`cfg.mutant == "..."` comparison so the modeled-bug diff is greppable.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Set, Tuple

# effect name -> (file suffix, function name): the handler bindings the
# bijection check enforces against @protocol_effect annotations. Every
# entry must be referenced by >=1 transition in TRANSITION_HANDLERS.
HANDLER_BINDINGS: Dict[str, Tuple[str, str]] = {
    "ctrl.run_cadence": ("controller/controller.py", "_run"),
    "ctrl.checkpoint_start": ("controller/controller.py", "_checkpoint_start"),
    "ctrl.checkpoint_reap": ("controller/controller.py", "_checkpoint_reap"),
    "ctrl.drain_pending": ("controller/controller.py", "_drain_pending_epochs"),
    "ctrl.stop_checkpoint": ("controller/controller.py", "_checkpoint_inner"),
    "ctrl.publish_epoch": ("controller/controller.py", "_publish_epoch"),
    "ctrl.rescale": ("controller/controller.py", "_rescale"),
    "ctrl.overlap_prepare": ("controller/controller.py", "_overlap_prepare"),
    "ctrl.overlap_activate": ("controller/controller.py",
                              "_overlap_activate"),
    "ctrl.recover": ("controller/controller.py", "_recover"),
    "ctrl.schedule": ("controller/controller.py", "_schedule_inner"),
    "ctrl.failover_promote": ("controller/controller.py",
                              "_failover_promote"),
    "failover.arm": ("failover/manager.py", "_arm"),
    "failover.tail": ("failover/manager.py", "_tail"),
    "failover.promote": ("failover/manager.py", "_promote"),
    "replica.subscribe": ("replica/follower.py", "_subscribe"),
    "replica.tail": ("replica/follower.py", "_tail"),
    "replica.serve": ("replica/follower.py", "read"),
    "replica.detach": ("replica/manager.py", "detach"),
    "state.tail_chains": ("state/table_manager.py", "tail_chains"),
    "worker.capture": ("operators/runner.py", "_checkpoint_chain"),
    "worker.admit_flush": ("operators/runner.py", "_admit_flush"),
    "worker.flush": ("operators/runner.py", "_flush_and_report"),
    "worker.drain_flushes": ("operators/runner.py", "_await_pending_flush"),
    "worker.commit": ("operators/runner.py", "_handle_commit"),
    "worker.await_commit": ("operators/runner.py", "_await_commit"),
    "state.capture_tables": ("state/table_manager.py", "capture"),
    "state.flush_tables": ("state/table_manager.py", "flush_captured"),
    "serve.read": ("serve/store.py", "read"),
    "storage.new_generation": ("state/protocol.py", "initialize_generation"),
    "storage.check_fence": ("state/protocol.py", "check_current"),
    "storage.publish_manifest": ("state/protocol.py", "publish_checkpoint"),
    "storage.prepare_commit": ("state/protocol.py", "prepare_commit"),
    "storage.claim_commit": ("state/protocol.py", "claim_commit"),
}

_PUBLISH_EFFECTS = (
    "ctrl.publish_epoch", "storage.check_fence", "storage.publish_manifest",
    "storage.prepare_commit", "storage.claim_commit",
)

# transition label -> handler effects it exercises (drives the "every
# binding used" direction of the bijection; cited in counterexamples)
TRANSITION_HANDLERS: Dict[str, Tuple[str, ...]] = {
    "ctrl.schedule_init": ("ctrl.schedule",),
    "ck.start": ("ctrl.run_cadence", "ctrl.checkpoint_start"),
    "ck.reap": ("ctrl.checkpoint_reap",) + _PUBLISH_EFFECTS,
    "ck.abandon": ("ctrl.checkpoint_reap",),
    "ctrl.detect_death": ("ctrl.run_cadence", "ctrl.drain_pending"),
    "ctrl.recover": ("ctrl.recover", "storage.new_generation"),
    "ctrl.fail": ("ctrl.recover",),
    "ctrl.schedule": ("ctrl.schedule",),
    "ctrl.recv": ("ctrl.run_cadence",),
    "stop.request": ("ctrl.run_cadence",),
    "stop.begin": ("ctrl.run_cadence", "ctrl.drain_pending"),
    "stop.barrier": ("ctrl.stop_checkpoint",),
    "stop.publish": ("ctrl.stop_checkpoint",) + _PUBLISH_EFFECTS,
    "stop.abandon": ("ctrl.stop_checkpoint",),
    "stop.finish": ("ctrl.run_cadence",),
    "rescale.request": ("ctrl.rescale",),
    "rescale.begin": ("ctrl.rescale",),
    "rescale.barrier": ("ctrl.rescale", "ctrl.stop_checkpoint"),
    "rescale.reschedule": ("ctrl.rescale", "storage.new_generation"),
    # generation-overlap rescale (ISSUE 15): the new incarnation is
    # PREPARED (workers acquired, program built, state restored
    # read-only) while the old incarnation drains its final epoch, then
    # ACTIVATED — claiming the fresh generation and resuming from the
    # durable rescale checkpoint — once that epoch published and the old
    # generation settled. RESCALING -> RUNNING, never through SCHEDULING.
    "overlap.prepare": ("ctrl.rescale", "ctrl.overlap_prepare"),
    "overlap.activate": ("ctrl.overlap_activate", "storage.new_generation"),
    # hot-standby failover (ISSUE 17): a warm standby incarnation is
    # ARMED beside the live generation (staged restore, sources parked),
    # TAILED forward on every published epoch's delta chain, and
    # PROMOTED in place on heartbeat loss — RUNNING stays RUNNING, no
    # SCHEDULING pass. Promotion claims a fresh generation, which is
    # what fences a merely-slow primary.
    "standby.arm": ("failover.arm",),
    "standby.tail": ("failover.tail", "state.tail_chains"),
    "failover.promote": ("ctrl.failover_promote", "failover.promote",
                         "storage.new_generation"),
    # follower read replicas (ISSUE 20): a follower is structurally a
    # standby that SERVES instead of waiting to promote — it subscribes
    # with a read-only restore at the last published manifest, tails
    # each newly published epoch's delta chain, and answers reads at its
    # own tailed epoch (never past what storage made durable). Follower
    # death is non-fatal: the gateway falls back worker-ward, and a
    # reattach re-resolves latest.json from scratch.
    "follower.subscribe": ("replica.subscribe",),
    "follower.tail": ("replica.tail", "state.tail_chains"),
    "follower.serve": ("replica.serve",),
    "fault.follower_die": ("replica.detach",),
    "w.capture": ("worker.capture", "worker.admit_flush",
                  "state.capture_tables"),
    "w.flush": ("worker.flush", "state.flush_tables"),
    "w.commit": ("worker.commit",),
    "w.finish": ("worker.drain_flushes", "worker.await_commit"),
    "fault.kill": ("ctrl.run_cadence",),
    "fault.blackout": ("ctrl.run_cadence",),
    "fault.drop_barrier": ("worker.capture",),
    "fault.dup_barrier": ("worker.capture",),
    "fault.reorder_inbox": ("worker.capture", "worker.commit"),
    "fault.cas_race": ("storage.publish_manifest",),
    "fault.fence": ("storage.check_fence",),
    "fault.flush_fail": ("worker.flush",),
    "fault.zombie_write": ("state.flush_tables",),
    "fault.reschedule_fail": ("ctrl.rescale",),
    # StateServe reader actor (ISSUE 12): reads at the last PUBLISHED
    # epoch; the serve_reads_unpublished_epoch mutant reads at the last
    # ISSUED epoch instead
    "serve.read": ("serve.read",),
}

USED_EFFECTS: Set[str] = {
    e for effs in TRANSITION_HANDLERS.values() for e in effs
}

FAULT_KINDS = (
    "fault.kill", "fault.blackout", "fault.drop_barrier",
    "fault.dup_barrier", "fault.reorder_inbox", "fault.cas_race",
    "fault.fence", "fault.flush_fail", "fault.reschedule_fail",
    "fault.follower_die",
)
# modeled wall-clock deadlines; V_STALL asks that dead-worker detection
# never REQUIRES one of these
TIMEOUT_KINDS = ("ck.abandon", "stop.abandon")


class ModelConfig(NamedTuple):
    workers: int = 2          # >= 2; worker 0 is the source-only role
    epochs: int = 3           # cadence epochs per incarnation
    inflight: int = 2         # state.max_inflight_flushes analog
    faults: int = 1           # total fault-event budget
    restarts: int = 2         # controller max_restarts analog
    rescales: int = 0         # rescale-request budget (0 or 1)
    overlap: int = 0          # 1 = rescales use the generation-overlap path
    reads: int = 0            # StateServe reader-actor event budget
    standby: int = 0          # 1 = a hot-standby incarnation may be armed
    followers: int = 0        # 1 = a read replica may subscribe (ISSUE 20)
    fault_kinds: Tuple[str, ...] = FAULT_KINDS
    mutant: str = ""          # mutants.py flag (empty == faithful model)


class WorkerS(NamedTuple):
    alive: bool = True
    blackout: bool = False    # presumed dead by the controller, still running
    gen: int = 1
    inbox: Tuple = ()         # FIFO: ("b", epoch, then_stop) | ("c", epoch)
    seen_barrier: int = 0     # highest barrier epoch captured (dedupe)
    captured: Tuple = ()      # epochs captured, flush pending (ordered)
    flushed: int = 0          # highest flushed epoch this incarnation
    flush_failed: bool = False
    stopping: bool = False    # captured a then_stop barrier
    sealed: Tuple = ()        # ((epoch, gen), ...) txs awaiting commit
    finished: bool = False    # local work done; rpc server closed


class CtrlS(NamedTuple):
    js: str = "CREATED"
    gen: int = 1
    epoch: int = 0            # last issued epoch
    epoch_budget: int = 0     # cadence epochs left this incarnation
    pending: Tuple = ()       # fanned-out, unpublished epochs
    reports: Tuple = ()       # ((epoch, widx), ...) completions received
    finished: Tuple = ()      # widx whose TaskFinished arrived
    restarts: int = 0
    stop: int = 0             # 0 none, 1 requested, 2 stop barrier in flight
    stop_epoch: int = 0
    rescale: int = 0          # 0 none, 1 requested, 2 stop barrier in flight
    rescaled: bool = False    # overrides applied (survives recovery)
    # generation-overlap rescale: 1 = the new incarnation is prepared
    # (restored read-only at prep_epoch) while the old one drains
    overlap: int = 0
    prep_epoch: int = -1      # published epoch the prepared restore used
    # hot-standby failover: 0 = none, 1 = armed (staged restore parked
    # beside the live generation); standby_epoch is the published epoch
    # its tailed restore has reached
    standby: int = 0
    standby_epoch: int = -1
    # follower read replica (ISSUE 20): 0 = none, 1 = subscribed;
    # follower_epoch is the published epoch its tailed restore has
    # reached (-1 = detached). A follower SURVIVES job recovery — it
    # tails published manifests, which outlive any one incarnation —
    # so _fail does not reset it. follower_deaths counts fault-driven
    # detaches (a reattach must re-resolve latest.json from scratch).
    follower: int = 0
    follower_epoch: int = -1
    follower_deaths: int = 0
    failure: str = ""         # latest failure reason (trace readability)


class StoreS(NamedTuple):
    gen: int = 1              # current-generation.json
    manifests: Tuple = ()     # ((epoch, gen), ...) in publish order
    latest: int = 0           # latest.json
    claimed: Tuple = ()       # epochs with a commit_done record
    blobs: Tuple = ()         # sorted ((epoch, widx, gen), ...) data files
    gen_base: Tuple = ()      # ((gen, restore_epoch), ...) chain bases


class Sys(NamedTuple):
    ctrl: CtrlS
    workers: Tuple[WorkerS, ...]
    store: StoreS
    finalized: Tuple = ()     # ((epoch, gen), ...) visible committed txs
    zombies: Tuple = ()       # ((widx, epoch, gen), ...) pending late writes
    faults: int = 0           # fault budget spent
    reads: int = 0            # serve-read budget spent


class Step(NamedTuple):
    label: str                # TRANSITION_HANDLERS key
    arg: Tuple                # discriminating payload
    nxt: Optional[Sys]        # successor (None when the step violates)
    violation: str = ""       # non-empty == invariant broken BY this step


def initial_state(cfg: ModelConfig) -> Sys:
    return Sys(
        ctrl=CtrlS(js="CREATED", epoch_budget=cfg.epochs),
        workers=tuple(WorkerS() for _ in range(cfg.workers)),
        store=StoreS(),
    )


def is_sink(widx: int) -> bool:
    return widx != 0


class _V:
    """Violation labels (stable ids for traces, SARIF, tests)."""

    ILLEGAL_MOVE = "illegal-jobstate-move"
    ORDER = "manifest-publish-order"
    ATOMIC = "epoch-half-committed"
    FENCE = "zombie-generation-published"
    OVERWRITE = "fenced-write-clobbered-live-blob"
    DOUBLE_COMMIT = "transaction-committed-twice"
    STRANDED = "sealed-transaction-stranded-at-stop"
    FAILED_NO_FAULT = "failed-without-fault"
    STALL = "dead-worker-undetected-stall"
    DEADLOCK = "deadlock"
    STUCK = "non-terminal-state-cannot-terminate"
    SERVE = "serve-read-inconsistent"
    # follower read replicas (ISSUE 20): a follower answered a read at
    # an epoch no published manifest has made durable — the replica
    # tier's one invariant (it may LAG the published epoch, never lead)
    REPLICA = "follower-served-unpublished-epoch"
    # generation-overlap rescale: a sink sealed an epoch another
    # generation already made visible — the new incarnation resumed
    # behind the durable rescale checkpoint and re-emitted its output
    OVERLAP_EMIT = "epoch-emitted-by-both-generations"


VIOLATIONS = _V


# -- tuple helpers -----------------------------------------------------------


def _sorted_add(t: Tuple, item) -> Tuple:
    return t if item in t else tuple(sorted(t + (item,)))


def _replace_worker(s: Sys, widx: int, w: WorkerS) -> Sys:
    ws = list(s.workers)
    ws[widx] = w
    return s._replace(workers=tuple(ws))


def _dead_unfinished(s: Sys) -> List[int]:
    """Workers the controller's liveness view sees as dead (killed or
    heartbeat-blacked-out) that never reported finished."""
    return [
        i for i, w in enumerate(s.workers)
        if (not w.alive or w.blackout) and i not in s.ctrl.finished
    ]


class Model:
    """Enumerates enabled transitions of the composed system. `transitions`
    is the EXTRACTED JobState table, `terminals` the extracted terminal
    set. A Step with `violation` set is a counterexample endpoint."""

    def __init__(self, cfg: ModelConfig,
                 transitions: Dict[str, Set[str]],
                 terminals: Set[str]):
        self.cfg = cfg
        self.transitions = {k: set(v) for k, v in transitions.items()}
        self.terminals = set(terminals)
        if cfg.mutant == "transitions_missing_recovering":
            # state-machine mutant: delete the CHECKPOINT_STOPPING ->
            # RECOVERING edge (PR 2's "retry the stop after a failed stop
            # checkpoint" fix)
            self.transitions.get("CHECKPOINT_STOPPING", set()).discard(
                "RECOVERING"
            )

    def done(self, s: Sys) -> bool:
        return s.ctrl.js in self.terminals

    # -- js moves through the extracted table --------------------------------

    def _move(self, s: Sys, label: str, nxt_js: str, **updates) -> Step:
        cur = s.ctrl.js
        if nxt_js not in self.transitions.get(cur, set()):
            return Step(label, (cur, nxt_js), None,
                        f"{_V.ILLEGAL_MOVE}: {cur} -> {nxt_js}")
        return Step(label, (cur, nxt_js),
                    s._replace(ctrl=s.ctrl._replace(js=nxt_js, **updates)))

    def _fail(self, s: Sys, label: str, reason: str) -> Step:
        """The job.failure -> RECOVERING route every handler shares. A
        stop request survives recovery (the stop is retried); a rescale
        request is consumed (the autoscaler re-decides)."""
        st = self._move(
            s, label, "RECOVERING",
            failure=reason, stop=(1 if s.ctrl.stop else 0), rescale=0,
            stop_epoch=0, pending=(), reports=(),
            # a failed overlap discards the prepared incarnation: it
            # restored read-only and claimed nothing durable — the same
            # holds for an armed standby (it re-arms after recovery)
            overlap=0, prep_epoch=-1, standby=0, standby_epoch=-1,
        )
        return Step(label, (reason,), st.nxt, st.violation)

    # -- report bookkeeping --------------------------------------------------

    def _reports_complete(self, s: Sys, epoch: int) -> bool:
        got = {w for (e, w) in s.ctrl.reports if e == epoch}
        return all(i in got or i in s.ctrl.finished
                   for i in range(len(s.workers)))

    def _cannot_complete(self, s: Sys, epoch: int) -> bool:
        """True when some missing report for `epoch` can never arrive —
        the fair-timeout gate for deadline abandons."""
        got = {w for (e, w) in s.ctrl.reports if e == epoch}
        for i, w in enumerate(s.workers):
            if i in got or i in s.ctrl.finished:
                continue
            if not w.alive or w.flush_failed:
                return True
            will_capture = (
                epoch in w.captured
                or w.seen_barrier >= epoch
                or any(m[0] == "b" and m[1] == epoch for m in w.inbox)
            )
            if not will_capture:
                return True
            if w.flushed >= epoch and (epoch, i) not in s.ctrl.reports:
                return True  # report lost forever (not modeled, safety net)
        return False

    def _chain_epochs(self, s: Sys, upto: int) -> List[int]:
        """Epochs whose blobs a manifest at `upto` references under the
        current generation: everything since the generation's restore
        base (the incremental base+delta chain)."""
        base = dict(s.store.gen_base).get(s.ctrl.gen, 0)
        return list(range(base + 1, upto + 1))

    # -- publish (shared by reap / stop / rescale) ---------------------------

    def _publish(self, s: Sys, label: str, epoch: int,
                 cas_race: bool = False) -> Step:
        cfg = self.cfg
        ctrl, store = s.ctrl, s.store
        fenced = store.gen != ctrl.gen
        if fenced and cfg.mutant == "no_fence_check":
            return Step(label, (epoch,), None,
                        f"{_V.FENCE}: gen {ctrl.gen} published epoch "
                        f"{epoch} while gen {store.gen} is current")
        if fenced:
            # storage.check_fence: a superseded generation must not publish
            return self._fail(s, label, "fenced")
        if cas_race:
            # storage.cas_conflict without key creation: the publish reads
            # nothing back and raises Fenced -> failure -> recovery
            return self._fail(s, label, "manifest-cas-race")
        if (not self._reports_complete(s, epoch)
                and cfg.mutant != "publish_without_reports"):
            return Step(label, (epoch,), None,
                        "publish guard broken: incomplete report set")
        if store.manifests and epoch <= max(e for (e, _g) in store.manifests):
            return Step(label, (epoch,), None,
                        f"{_V.ORDER}: epoch {epoch} published after epoch "
                        f"{max(e for (e, _g) in store.manifests)}")
        # V_ATOMIC: the manifest references every worker's blob chain; all
        # chain epochs must be durably flushed. Epoch-ordered flushes are
        # what make an abandoned epoch's successor safe to publish.
        blob_keys = {(e, w) for (e, w, g) in store.blobs if g == ctrl.gen}
        for widx in range(len(s.workers)):
            for e in self._chain_epochs(s, epoch):
                if (e, widx) not in blob_keys:
                    return Step(
                        label, (epoch,), None,
                        f"{_V.ATOMIC}: manifest {epoch} references "
                        f"unflushed blob (epoch {e}, worker {widx})",
                    )
        new = s._replace(
            store=store._replace(
                manifests=store.manifests + ((epoch, ctrl.gen),),
                latest=epoch,
            ),
            ctrl=ctrl._replace(
                pending=tuple(e for e in ctrl.pending if e != epoch),
                reports=tuple((e, w) for (e, w) in ctrl.reports if e != epoch),
            ),
        )
        # 2PC phase 2: CAS-claim the commit record, then fan CommitMsg to
        # committing (sink) workers only. A closed target's rpc raises ->
        # failure -> recovery (claim + manifest stay durable; the restore
        # replays the commit).
        if epoch not in new.store.claimed:
            new = new._replace(store=new.store._replace(
                claimed=_sorted_add(new.store.claimed, epoch)
            ))
            targets = (range(len(s.workers))
                       if cfg.mutant == "commit_fanout_all_workers"
                       else [w for w in range(len(s.workers)) if is_sink(w)])
            for widx in targets:
                w = new.workers[widx]
                if w.finished or not w.alive:
                    if cfg.mutant == "stop_strands_commit":
                        continue  # the bug: drop the commit silently
                    return self._fail(
                        new, label, f"commit-rpc-to-closed-worker-{widx}"
                    )
                new = _replace_worker(
                    new, widx, w._replace(inbox=w.inbox + (("c", epoch),))
                )
        return Step(label, (epoch,), new)

    # -- enumeration ---------------------------------------------------------

    def enabled(self, s: Sys) -> List[Step]:
        cfg = self.cfg
        ctrl = s.ctrl
        if self.done(s):
            return []
        # lifecycle states are atomic handler bodies in the code: model
        # them as single steps (faults/zombies interleave before or after)
        if ctrl.js == "CREATED":
            return [self._move(s, "ctrl.schedule_init", "SCHEDULING")]
        if ctrl.js == "RECOVERING":
            return [self._recover(s)]
        if ctrl.js == "SCHEDULING":
            return [self._schedule(s)]

        out: List[Step] = []
        dead = _dead_unfinished(s)
        if dead and not self._liveness_masked(s):
            # failover (ISSUE 17): with a standby armed the controller
            # may promote it in place instead of recovering. Both moves
            # stay enabled — promotion can fail in the real system and
            # fall back to the cold path, so the model verifies both.
            if ctrl.js == "RUNNING" and ctrl.standby == 1:
                out.append(self._failover_promote(s))
            out.append(self._fail(s, "ctrl.detect_death",
                                  f"heartbeat-timeout-w{dead[0]}"))

        if ctrl.js == "RUNNING":
            if (ctrl.stop == 0 and ctrl.rescale == 0
                    and ctrl.epoch_budget > 0
                    and len(ctrl.pending) < cfg.inflight):
                out.append(self._ck_start(s))
            if ctrl.pending:
                out.extend(self._reap_steps(s))
            if ctrl.stop == 0 and ctrl.rescale == 0:
                out.append(Step("stop.request", (),
                                s._replace(ctrl=ctrl._replace(stop=1))))
                if cfg.rescales > 0 and not ctrl.rescaled:
                    out.append(Step(
                        "rescale.request", (),
                        s._replace(ctrl=ctrl._replace(rescale=1)),
                    ))
            if ctrl.stop == 1:
                out.append(self._move(s, "stop.begin", "CHECKPOINT_STOPPING"))
            if ctrl.rescale == 1:
                out.append(self._move(s, "rescale.begin", "RESCALING"))
            if cfg.standby:
                if ctrl.standby == 0:
                    # arm: stage a read-only restore at the last
                    # PUBLISHED manifest beside the live generation
                    # (sources parked on the release gate — claims
                    # nothing durable)
                    out.append(Step(
                        "standby.arm", (s.store.latest,),
                        s._replace(ctrl=ctrl._replace(
                            standby=1, standby_epoch=s.store.latest,
                        )),
                    ))
                elif ctrl.standby_epoch < s.store.latest:
                    # tail: replay the newly published epoch's delta
                    # chain onto the standby's tables
                    out.append(Step(
                        "standby.tail", (s.store.latest,),
                        s._replace(ctrl=ctrl._replace(
                            standby_epoch=s.store.latest,
                        )),
                    ))
            if cfg.followers:
                if ctrl.follower == 0:
                    out.append(self._follower_subscribe(s))
                elif ctrl.follower_epoch < s.store.latest:
                    # tail: replay the newly published epoch's delta
                    # chain onto the follower's serve tables (the same
                    # tail_chains suffix replay the standby uses)
                    out.append(Step(
                        "follower.tail", (s.store.latest,),
                        s._replace(ctrl=ctrl._replace(
                            follower_epoch=s.store.latest,
                        )),
                    ))

        if ctrl.js == "CHECKPOINT_STOPPING":
            if ctrl.stop != 2 and ctrl.pending:
                out.extend(self._reap_steps(s))
            elif ctrl.stop == 1:
                out.append(self._barrier(s, "stop.barrier", stop=2))
            elif ctrl.stop == 2:
                out.extend(self._stop_wait_steps(s))

        if ctrl.js == "RESCALING":
            if ctrl.rescale != 2 and ctrl.pending:
                out.extend(self._reap_steps(s))
            elif ctrl.rescale == 1:
                out.append(self._barrier(s, "rescale.barrier", rescale=2))
            elif ctrl.rescale == 2:
                if cfg.overlap and ctrl.overlap == 0:
                    # overlap window: prepare the new incarnation (acquire
                    # workers, build, restore read-only from the last
                    # PUBLISHED manifest) while the old one drains the
                    # stop epoch. Claims nothing durable — a failure
                    # anywhere discards it for free.
                    out.append(Step(
                        "overlap.prepare", (s.store.latest,),
                        s._replace(ctrl=ctrl._replace(
                            overlap=1, prep_epoch=s.store.latest,
                        )),
                    ))
                out.extend(self._rescale_wait_steps(s))

        for widx, w in enumerate(s.workers):
            if w.alive and not w.finished:
                out.extend(self._worker_steps(s, widx, w))
            if (w.alive and w.finished and widx not in ctrl.finished):
                out.append(Step(
                    "ctrl.recv", (widx,),
                    s._replace(ctrl=ctrl._replace(
                        finished=_sorted_add(ctrl.finished, widx)
                    )),
                ))

        if (s.reads < cfg.reads
                and ctrl.js in ("RUNNING", "CHECKPOINT_STOPPING",
                                "RESCALING")):
            out.append(self._serve_read(s))
            if ctrl.follower == 1:
                # a subscribed follower keeps serving through stop and
                # rescale windows — its view is pinned to published
                # manifests, not to any live incarnation
                out.append(self._follower_serve(s))

        out.extend(self._fault_steps(s))
        for z in s.zombies:
            out.append(self._zombie_write(s, z))
        return out

    # -- StateServe reader actor (ISSUE 12) ----------------------------------

    def _serve_read(self, s: Sys) -> Step:
        """One queryable-state read. Faithful model: the read resolves at
        the last PUBLISHED epoch (store.latest) and its blobs under that
        manifest's generation — the invariant is that no read observes a
        partially-published epoch or a fenced generation's blob. The
        `serve_reads_unpublished_epoch` mutant reads at the controller's
        last ISSUED epoch instead (a fanned-out-but-unpublished
        checkpoint), which is exactly the half-captured view the real
        read path's published-epoch fold forbids."""
        ctrl, store = s.ctrl, s.store
        epoch = (ctrl.epoch
                 if self.cfg.mutant == "serve_reads_unpublished_epoch"
                 else store.latest)
        nxt = s._replace(reads=s.reads + 1)
        if epoch <= 0:
            return Step("serve.read", (epoch,), nxt)  # empty view: fine
        gen = dict(store.manifests).get(epoch)
        if gen is None:
            return Step(
                "serve.read", (epoch,), None,
                f"{_V.SERVE}: read observed epoch {epoch} with no "
                f"published manifest (last published {store.latest})",
            )
        base = dict(store.gen_base).get(gen, 0)
        blob_keys = set(store.blobs)
        for widx in range(len(s.workers)):
            for e in range(base + 1, epoch + 1):
                if (e, widx, gen) not in blob_keys:
                    return Step(
                        "serve.read", (epoch,), None,
                        f"{_V.SERVE}: read resolved a missing/fenced "
                        f"blob (epoch {e}, worker {widx}, gen {gen})",
                    )
        return Step("serve.read", (epoch,), nxt)

    # -- follower read replica (ISSUE 20) ------------------------------------

    def _follower_subscribe(self, s: Sys) -> Step:
        """Subscribe (or reattach): the follower resolves the LAST
        PUBLISHED manifest from storage (latest.json) and restores
        read-only at it. The `follower_serves_unpublished_epoch` mutant
        reattaches a died follower from the controller's in-memory
        issued-epoch counter instead of re-resolving latest.json — a
        fanned-out-but-unpublished checkpoint nobody made durable."""
        ctrl = s.ctrl
        epoch = (ctrl.epoch
                 if (self.cfg.mutant == "follower_serves_unpublished_epoch"
                     and ctrl.follower_deaths > 0)
                 else s.store.latest)
        return Step(
            "follower.subscribe", (epoch,),
            s._replace(ctrl=ctrl._replace(follower=1, follower_epoch=epoch)),
        )

    def _follower_serve(self, s: Sys) -> Step:
        """One follower-routed read at the follower's OWN tailed epoch.
        Faithful model: follower_epoch only ever advances to
        store.latest, so the served epoch always has a published
        manifest and a complete blob chain — the invariant is that a
        follower may lag the published epoch but never lead it."""
        ctrl, store = s.ctrl, s.store
        epoch = ctrl.follower_epoch
        nxt = s._replace(reads=s.reads + 1)
        if epoch <= 0:
            return Step("follower.serve", (epoch,), nxt)  # empty view: fine
        gen = dict(store.manifests).get(epoch)
        if gen is None:
            return Step(
                "follower.serve", (epoch,), None,
                f"{_V.REPLICA}: follower served epoch {epoch} with no "
                f"published manifest (last published {store.latest})",
            )
        base = dict(store.gen_base).get(gen, 0)
        blob_keys = set(store.blobs)
        for widx in range(len(s.workers)):
            for e in range(base + 1, epoch + 1):
                if (e, widx, gen) not in blob_keys:
                    return Step(
                        "follower.serve", (epoch,), None,
                        f"{_V.REPLICA}: follower resolved a missing/"
                        f"fenced blob (epoch {e}, worker {widx}, "
                        f"gen {gen})",
                    )
        return Step("follower.serve", (epoch,), nxt)

    def _liveness_masked(self, s: Sys) -> bool:
        if self.cfg.mutant == "no_liveness_in_stop_wait":
            # the PR 2 bug class: the stop/checkpoint wait loops did not
            # check worker liveness, so a mid-barrier death stalled the
            # wait until the 60s deadline
            return s.ctrl.js == "CHECKPOINT_STOPPING"
        return False

    # -- controller steps ----------------------------------------------------

    def _ck_start(self, s: Sys) -> Step:
        ctrl = s.ctrl
        epoch = ctrl.epoch + 1
        new = s._replace(ctrl=ctrl._replace(
            epoch=epoch, epoch_budget=ctrl.epoch_budget - 1,
            pending=ctrl.pending + (epoch,),
        ))
        return Step("ck.start", (epoch,), self._fanout(new, epoch, False))

    def _barrier(self, s: Sys, label: str, **flags) -> Step:
        """Stop/rescale barrier: one then_stop epoch fanned to all."""
        ctrl = s.ctrl
        epoch = ctrl.epoch + 1
        new = s._replace(ctrl=ctrl._replace(
            epoch=epoch, stop_epoch=epoch,
            pending=ctrl.pending + (epoch,), **flags,
        ))
        return Step(label, (epoch,), self._fanout(new, epoch, True))

    @staticmethod
    def _fanout(s: Sys, epoch: int, then_stop: bool) -> Sys:
        new = s
        for widx, w in enumerate(new.workers):
            if w.alive and not w.finished:
                new = _replace_worker(new, widx, w._replace(
                    inbox=w.inbox + (("b", epoch, then_stop),)
                ))
        return new

    def _reap_steps(self, s: Sys) -> List[Step]:
        """_checkpoint_reap: publish the LOWEST pending epoch once its
        report set completes; abandon (deadline, fair-gated) an epoch
        that can no longer complete. The order mutant publishes the
        HIGHEST complete epoch instead."""
        out: List[Step] = []
        pending = sorted(s.ctrl.pending)
        candidates = (sorted(pending, reverse=True)
                      if self.cfg.mutant == "publish_any_complete"
                      else pending[:1])
        for e in candidates:
            if (self._reports_complete(s, e)
                    or self.cfg.mutant == "publish_without_reports"):
                out.append(self._publish(s, "ck.reap", e))
                break
        e0 = pending[0]
        if (not self._reports_complete(s, e0)
                and self._cannot_complete(s, e0)):
            out.append(Step(
                "ck.abandon", (e0,),
                s._replace(ctrl=s.ctrl._replace(
                    pending=tuple(x for x in s.ctrl.pending if x != e0),
                    reports=tuple((e, w) for (e, w) in s.ctrl.reports
                                  if e != e0),
                )),
            ))
        return out

    def _stop_wait_steps(self, s: Sys) -> List[Step]:
        e = s.ctrl.stop_epoch
        if e in s.ctrl.pending:
            if (self._reports_complete(s, e)
                    or self.cfg.mutant == "publish_without_reports"):
                return [self._publish(s, "stop.publish", e)]
            if self._cannot_complete(s, e):
                # the fixed code: an incomplete stopping checkpoint is a
                # FAILURE (recover, retry the stop) — never a silent stop
                return [self._fail(s, "stop.abandon",
                                   "stop-checkpoint-incomplete")]
            return []
        if all(i in s.ctrl.finished for i in range(len(s.workers))):
            return [self._move(s, "stop.finish", "STOPPED", stop=0)]
        return []

    def _rescale_wait_steps(self, s: Sys) -> List[Step]:
        out: List[Step] = []
        e = s.ctrl.stop_epoch
        if e in s.ctrl.pending:
            if (self._reports_complete(s, e)
                    or self.cfg.mutant == "publish_without_reports"):
                return [self._publish(s, "stop.publish", e)]
            if self._cannot_complete(s, e):
                return [self._fail(s, "stop.abandon",
                                   "rescale-stop-checkpoint-incomplete")]
            return []
        # durable stop published: a dead worker is safe here (teardown is
        # imminent; the restore replays the claimed commit)
        if all(i in s.ctrl.finished or not w.alive
               for i, w in enumerate(s.workers)):
            applied = s._replace(ctrl=s.ctrl._replace(rescaled=True))
            if (s.faults < self.cfg.faults
                    and "fault.reschedule_fail" in self.cfg.fault_kinds):
                out.append(self._fail(
                    applied._replace(faults=applied.faults + 1),
                    "fault.reschedule_fail", "rescale-reschedule-fail",
                ))
            if applied.ctrl.overlap == 1:
                out.append(self._overlap_activate(applied))
                return out
            torn = self._teardown(applied)
            newgen = torn.store.gen + 1
            torn = torn._replace(
                store=torn.store._replace(
                    gen=newgen,
                    gen_base=torn.store.gen_base
                    + ((newgen, torn.store.latest),),
                ),
                ctrl=torn.ctrl._replace(gen=newgen, rescale=0, stop_epoch=0,
                                        pending=(), reports=(), finished=()),
            )
            out.append(self._move(torn, "rescale.reschedule", "SCHEDULING"))
        return out

    def _overlap_activate(self, s: Sys) -> Step:
        """Generation-overlap activation: the prepared incarnation claims
        the fresh generation and resumes FROM THE DURABLE RESCALE
        CHECKPOINT (store.latest — the stop epoch it watched publish),
        promoting RESCALING -> RUNNING without a SCHEDULING pass. Like a
        restore, it idempotently replays every claimed epoch's commit
        from its manifest (the old incarnation's sealed sinks may have
        died post-publish, pre-commit). The `overlap_double_emission`
        mutant activates at the PREPARED epoch instead — skipping the
        stop epoch's chain replay — so its sources rewind behind output
        the old generation already made visible."""
        base = (s.ctrl.prep_epoch
                if self.cfg.mutant == "overlap_double_emission"
                else s.store.latest)
        torn = self._teardown(s)
        newgen = torn.store.gen + 1
        # restore-time commit replay (same rule as ctrl.schedule): every
        # claimed epoch's manifest commit becomes visible exactly once
        finalized = torn.finalized
        mgens = dict(torn.store.manifests)
        for e in torn.store.claimed:
            g = mgens.get(e)
            if g is None:
                continue
            clash = [g2 for (e2, g2) in finalized if e2 == e and g2 != g]
            if clash:
                return Step("overlap.activate", (), None,
                            f"{_V.DOUBLE_COMMIT}: overlap restore replayed "
                            f"epoch {e} under gen {g} over gen {clash[0]}")
            finalized = _sorted_add(finalized, (e, g))
        torn = torn._replace(
            finalized=finalized,
            workers=tuple(WorkerS(gen=newgen)
                          for _ in range(len(s.workers))),
            store=torn.store._replace(
                gen=newgen,
                gen_base=torn.store.gen_base + ((newgen, base),),
            ),
            ctrl=torn.ctrl._replace(
                gen=newgen, rescale=0, stop_epoch=0, overlap=0,
                prep_epoch=-1, epoch=base, epoch_budget=self.cfg.epochs,
                pending=(), reports=(), finished=(), failure="",
            ),
        )
        return self._move(torn, "overlap.activate", "RUNNING")

    def _failover_promote(self, s: Sys) -> Step:
        """Hot-standby promotion (ISSUE 17): on heartbeat loss the armed
        standby claims a fresh generation and takes over IN PLACE —
        RUNNING stays RUNNING, no SCHEDULING pass. Promotion re-resolves
        the LATEST published manifest at claim time (the standby's
        tailed restore may be an epoch behind) and, like any restore,
        idempotently replays every claimed epoch's commit. The fresh
        generation is the fence: a merely-slow (heartbeat-blacked-out)
        primary keeps running, but its publishes fence and its late
        uploads land beside, never over, live blobs. The
        `promote_while_primary_alive` mutant promotes at the standby's
        TAILED epoch without re-resolving latest — resuming behind
        output the still-alive primary already made visible, so the
        promoted generation re-emits a committed epoch (the
        overlap_double_emission invariant generalized to failover)."""
        base = (s.ctrl.standby_epoch
                if self.cfg.mutant == "promote_while_primary_alive"
                else s.store.latest)
        torn = self._teardown(s)
        newgen = torn.store.gen + 1
        # restore-time commit replay (same rule as ctrl.schedule /
        # overlap.activate): every claimed epoch's manifest commit
        # becomes visible exactly once
        finalized = torn.finalized
        mgens = dict(torn.store.manifests)
        for e in torn.store.claimed:
            g = mgens.get(e)
            if g is None:
                continue
            clash = [g2 for (e2, g2) in finalized if e2 == e and g2 != g]
            if clash:
                return Step("failover.promote", (), None,
                            f"{_V.DOUBLE_COMMIT}: promoted restore "
                            f"replayed epoch {e} under gen {g} over gen "
                            f"{clash[0]}")
            finalized = _sorted_add(finalized, (e, g))
        nxt = torn._replace(
            finalized=finalized,
            workers=tuple(WorkerS(gen=newgen)
                          for _ in range(len(s.workers))),
            store=torn.store._replace(
                gen=newgen,
                gen_base=torn.store.gen_base + ((newgen, base),),
            ),
            ctrl=torn.ctrl._replace(
                gen=newgen, stop=(1 if s.ctrl.stop else 0), rescale=0,
                stop_epoch=0, standby=0, standby_epoch=-1,
                epoch=base, epoch_budget=self.cfg.epochs,
                pending=(), reports=(), finished=(), failure="",
            ),
        )
        return Step("failover.promote", (base,), nxt)

    def _teardown(self, s: Sys) -> Sys:
        """Force-stop every worker. A blacked-out (presumed-dead but
        running) worker's unflushed captures become zombie late-writes
        under its old generation."""
        zombies = s.zombies
        new = s
        for widx, w in enumerate(s.workers):
            if w.blackout and w.alive:
                for e in w.captured:
                    zombies = zombies + ((widx, e, w.gen),)
            new = _replace_worker(new, widx, WorkerS(alive=False))
        return new._replace(zombies=zombies)

    def _recover(self, s: Sys) -> Step:
        ctrl = s.ctrl
        if ctrl.restarts >= self.cfg.restarts:
            return self._move(s, "ctrl.fail", "FAILED")
        torn = self._teardown(s)
        newgen = torn.store.gen + 1
        torn = torn._replace(
            store=torn.store._replace(
                gen=newgen,
                gen_base=torn.store.gen_base + ((newgen, torn.store.latest),),
            ),
            ctrl=torn.ctrl._replace(
                gen=newgen, restarts=ctrl.restarts + 1,
                pending=(), reports=(), finished=(), rescale=0, stop_epoch=0,
                overlap=0, prep_epoch=-1, standby=0, standby_epoch=-1,
            ),
        )
        return self._move(torn, "ctrl.recover", "SCHEDULING")

    def _schedule(self, s: Sys) -> Step:
        """Spawn fresh workers under the current generation; restore from
        the latest manifest. Restored sinks idempotently replay every
        claimed epoch's commit from its manifest (the connectors'
        sealed-state replay) — clashing generations are a violation."""
        ctrl, store = s.ctrl, s.store
        finalized = s.finalized
        mgens = dict(store.manifests)
        for e in store.claimed:
            g = mgens.get(e)
            if g is None:
                continue
            clash = [g2 for (e2, g2) in finalized if e2 == e and g2 != g]
            if clash:
                return Step("ctrl.schedule", (), None,
                            f"{_V.DOUBLE_COMMIT}: restore replayed epoch "
                            f"{e} under gen {g} over gen {clash[0]}")
            finalized = _sorted_add(finalized, (e, g))
        new = s._replace(
            workers=tuple(WorkerS(gen=ctrl.gen)
                          for _ in range(len(s.workers))),
            finalized=finalized,
            ctrl=ctrl._replace(
                epoch=store.latest, epoch_budget=self.cfg.epochs,
                pending=(), reports=(), finished=(), failure="",
            ),
        )
        return self._move(new, "ctrl.schedule", "RUNNING")

    # -- worker steps --------------------------------------------------------

    def _worker_steps(self, s: Sys, widx: int, w: WorkerS) -> List[Step]:
        cfg = self.cfg
        out: List[Step] = []
        if w.inbox:
            msg = w.inbox[0]
            if msg[0] == "b":
                _tag, epoch, then_stop = msg
                if epoch <= w.seen_barrier:
                    # stale/duplicated barrier: alignment dedupes by epoch
                    out.append(Step(
                        "w.capture", (widx, epoch, "dup"),
                        _replace_worker(s, widx,
                                        w._replace(inbox=w.inbox[1:])),
                    ))
                elif len(w.captured) < cfg.inflight:
                    emitted_by_other_gen = [
                        g for (e2, g) in s.finalized
                        if e2 == epoch and g != w.gen
                    ]
                    if is_sink(widx) and emitted_by_other_gen:
                        # generation-overlap invariant (ISSUE 15): a sink
                        # sealing an epoch ANOTHER generation already made
                        # visible means the incarnation resumed behind the
                        # durable rescale checkpoint and is re-emitting
                        # committed output
                        out.append(Step(
                            "w.capture", (widx, epoch), None,
                            f"{_V.OVERLAP_EMIT}: gen {w.gen} sealed epoch "
                            f"{epoch} already visible under gen "
                            f"{emitted_by_other_gen[0]}",
                        ))
                        return out
                    nw = w._replace(
                        inbox=w.inbox[1:],
                        seen_barrier=epoch,
                        captured=w.captured + (epoch,),
                        stopping=w.stopping or then_stop,
                        sealed=(w.sealed + ((epoch, w.gen),)
                                if is_sink(widx) else w.sealed),
                    )
                    out.append(Step("w.capture", (widx, epoch),
                                    _replace_worker(s, widx, nw)))
                # else: admission full — the barrier blocks until a flush
                # frees a slot (the flush step below is the way forward)
            elif msg[0] == "c":
                out.append(self._apply_commit(s, widx, w, msg[1]))
        if w.captured and not w.flush_failed:
            out.append(self._flush(s, widx, w))
        if (w.stopping and not w.captured and not w.flush_failed
                and not w.finished):
            # committing state: a sink holds until its sealed txs commit
            if (not w.sealed or not is_sink(widx)
                    or cfg.mutant == "stop_strands_commit"):
                out.append(Step(
                    "w.finish", (widx,),
                    _replace_worker(s, widx, w._replace(finished=True)),
                ))
        return out

    def _flush(self, s: Sys, widx: int, w: WorkerS) -> Step:
        # strictly epoch-ordered per subtask; the mutant flushes LIFO
        if self.cfg.mutant == "unordered_flush" and len(w.captured) > 1:
            e, rest = w.captured[-1], w.captured[:-1]
        else:
            e, rest = w.captured[0], w.captured[1:]
        nw = w._replace(captured=rest, flushed=max(w.flushed, e))
        new = _replace_worker(s, widx, nw)._replace(
            store=s.store._replace(
                blobs=_sorted_add(s.store.blobs, (e, widx, w.gen))
            ),
        )
        # the completion report rides an awaited rpc: reliable, ordered
        new = new._replace(ctrl=new.ctrl._replace(
            reports=_sorted_add(new.ctrl.reports, (e, widx))
        ))
        return Step("w.flush", (widx, e), new)

    def _apply_commit(self, s: Sys, widx: int, w: WorkerS,
                      epoch: int) -> Step:
        """Cumulative commit application (epochs <= msg epoch), matching
        _handle_commit's `msg.epoch >= awaited` clearing and the sinks'
        sealed-state semantics."""
        finalized = s.finalized
        for (e, g) in w.sealed:
            if e > epoch:
                continue
            clash = [g2 for (e2, g2) in finalized if e2 == e and g2 != g]
            if clash:
                return Step("w.commit", (widx, epoch), None,
                            f"{_V.DOUBLE_COMMIT}: epoch {e} visible under "
                            f"gens {clash[0]} and {g}")
            finalized = _sorted_add(finalized, (e, g))
        nw = w._replace(
            inbox=w.inbox[1:],
            sealed=tuple((e, g) for (e, g) in w.sealed if e > epoch),
        )
        return Step("w.commit", (widx, epoch),
                    _replace_worker(s, widx, nw)._replace(
                        finalized=finalized))

    # -- faults --------------------------------------------------------------

    def _fault_steps(self, s: Sys) -> List[Step]:
        cfg = self.cfg
        if s.faults >= cfg.faults:
            return []
        out: List[Step] = []
        spend = s.faults + 1
        for widx, w in enumerate(s.workers):
            if not w.alive or w.finished:
                continue
            if "fault.kill" in cfg.fault_kinds:
                # SIGKILL: the process and its in-flight uploads die
                out.append(Step(
                    "fault.kill", (widx,),
                    _replace_worker(s, widx, WorkerS(alive=False))
                    ._replace(faults=spend),
                ))
            if "fault.blackout" in cfg.fault_kinds and not w.blackout:
                # heartbeats stop; the process (and its uploads) do not
                out.append(Step(
                    "fault.blackout", (widx,),
                    _replace_worker(s, widx, w._replace(blackout=True))
                    ._replace(faults=spend),
                ))
            if w.inbox and w.inbox[0][0] == "b":
                if "fault.drop_barrier" in cfg.fault_kinds:
                    # a data-plane connection drop: the barrier frame is
                    # lost AND the failure surfaces as a task error
                    dropped = _replace_worker(
                        s, widx, w._replace(inbox=w.inbox[1:])
                    )._replace(faults=spend)
                    out.append(self._fail(dropped, "fault.drop_barrier",
                                          f"connection-drop-w{widx}"))
                if "fault.dup_barrier" in cfg.fault_kinds:
                    out.append(Step(
                        "fault.dup_barrier", (widx,),
                        _replace_worker(
                            s, widx,
                            w._replace(inbox=(w.inbox[0],) + w.inbox),
                        )._replace(faults=spend),
                    ))
            if (len(w.inbox) > 1 and w.inbox[0][0] != w.inbox[1][0]
                    and "fault.reorder_inbox" in cfg.fault_kinds):
                # cross-channel race: a CommitMsg (control queue) passing
                # a barrier (data plane) or vice versa
                swapped = (w.inbox[1], w.inbox[0]) + w.inbox[2:]
                out.append(Step(
                    "fault.reorder_inbox", (widx,),
                    _replace_worker(s, widx, w._replace(inbox=swapped))
                    ._replace(faults=spend),
                ))
            if (w.captured and not w.flush_failed
                    and "fault.flush_fail" in cfg.fault_kinds):
                failed = _replace_worker(
                    s, widx, w._replace(flush_failed=True)
                )._replace(faults=spend)
                # TaskFailedResp is reliable: the controller reacts
                out.append(self._fail(failed, "fault.flush_fail",
                                      f"flush-failed-w{widx}"))
        if (s.ctrl.follower == 1
                and "fault.follower_die" in cfg.fault_kinds):
            # follower death is NON-FATAL: the gateway falls back
            # worker-ward; the job never notices. The budget spend keeps
            # the die/reattach cycle finite.
            out.append(Step(
                "fault.follower_die", (),
                s._replace(
                    faults=spend,
                    ctrl=s.ctrl._replace(
                        follower=0, follower_epoch=-1,
                        follower_deaths=s.ctrl.follower_deaths + 1,
                    ),
                ),
            ))
        pend = sorted(s.ctrl.pending)
        if (pend and self._reports_complete(s, pend[0])
                and s.ctrl.js in ("RUNNING", "CHECKPOINT_STOPPING",
                                  "RESCALING")):
            if "fault.cas_race" in cfg.fault_kinds:
                out.append(self._publish(
                    s._replace(faults=spend), "fault.cas_race", pend[0],
                    cas_race=True,
                ))
        if (s.ctrl.js in ("RUNNING", "CHECKPOINT_STOPPING", "RESCALING")
                and "fault.fence" in cfg.fault_kinds
                and s.store.gen == s.ctrl.gen):
            # zombie resurrect: another controller claims a newer
            # generation out from under this one — every later publish by
            # the current generation must fence
            out.append(Step(
                "fault.fence", (),
                s._replace(
                    faults=spend,
                    store=s.store._replace(gen=s.store.gen + 1),
                ),
            ))
        return out

    def _zombie_write(self, s: Sys, z: Tuple) -> Step:
        """A fenced incarnation's late upload finally lands. Generation-
        stamped paths make it land beside the live blob; the
        `unstamped_data_paths` mutant collapses the key to (epoch, worker)
        and clobbers whatever is there."""
        widx, epoch, gen = z
        zombies = tuple(x for x in s.zombies if x != z)
        if self.cfg.mutant == "unstamped_data_paths":
            clobbered = [
                (e, w, g) for (e, w, g) in s.store.blobs
                if e == epoch and w == widx and g != gen
            ]
            if clobbered:
                return Step(
                    "fault.zombie_write", (widx, epoch), None,
                    f"{_V.OVERWRITE}: gen {gen} late write over epoch "
                    f"{epoch} worker {widx} blob of gen {clobbered[0][2]}",
                )
        return Step(
            "fault.zombie_write", (widx, epoch),
            s._replace(
                zombies=zombies,
                store=s.store._replace(
                    blobs=_sorted_add(s.store.blobs, (epoch, widx, gen))
                ),
            ),
        )

    # -- state invariants (checked by the explorer on every state) -----------

    def check_state(self, s: Sys, enabled: List[Step]) -> Optional[str]:
        ctrl = s.ctrl
        if ctrl.js == "STOPPED":
            stranded = [
                (widx, w.sealed) for widx, w in enumerate(s.workers)
                if w.sealed
            ]
            invisible = [
                e for e in s.store.claimed
                if not any(fe == e for (fe, _g) in s.finalized)
            ]
            if stranded or invisible:
                return (f"{_V.STRANDED}: stopped with sealed={stranded} "
                        f"claimed-but-invisible={invisible}")
        if ctrl.js == "FAILED" and s.faults == 0:
            return (f"{_V.FAILED_NO_FAULT}: last failure "
                    f"{ctrl.failure or 'unknown'!r}")
        if not self.done(s):
            if not enabled:
                return f"{_V.DEADLOCK}: no enabled transitions in {ctrl.js}"
            dead = _dead_unfinished(s)
            waiting = ctrl.js in ("CHECKPOINT_STOPPING", "RESCALING")
            if dead and waiting:
                progress = {
                    st.label for st in enabled
                    if st.label not in TIMEOUT_KINDS
                    and not st.label.startswith("fault.")
                    and st.label not in ("serve.read", "follower.serve")
                    # reads never unstick a dead-worker wait
                }
                if not progress:
                    return (f"{_V.STALL}: worker(s) {dead} dead in "
                            f"{ctrl.js}, only deadline timeouts enabled")
        return None
