"""arroyolint — project-specific static analysis for the arroyo_tpu tree.

The reference engine leans on rustc + clippy to keep its concurrency-heavy
exactly-once protocol honest; this package is the Python reproduction's
equivalent guardrail: a self-contained AST rule engine with project-aware
rules spanning three hazard layers (SURVEY §2.8; ISSUE 3):

  asyncio   — dangling ``create_task`` results, blocking calls inside
              ``async def``, ``await`` under a held sync lock, swallowed
              ``CancelledError`` on barrier/commit paths
  protocol  — exhaustive ControlMsg handling in the runner select loop,
              state-machine transitions declared legal, chaos fault-point
              registry/call-site bijection
  jax+config— host syncs inside jitted bodies, jit-captured mutable Python
              state, dotted config keys resolving to declared defaults

Since ISSUE 9 the package also hosts the protocol MODEL CHECKER
(``analysis/model/``): explicit-state exploration of the checkpoint/2PC/
rescale machines extracted from this same tree (``tools/model_check.py``),
with counterexamples that replay as seeded chaos drills — and lint rule
PRO004 ties the dispatch code's epoch bookkeeping to the model's
``@protocol_effect`` handler annotations. Reporters gained SARIF 2.1.0
(``tools/lint.py --sarif``) so CI annotates PRs with findings.

Run it via ``python tools/lint.py`` (``--strict`` is the CI/tier-1 mode);
``tests/test_lint.py`` executes the full tree inside the tier-1 suite.
Inline suppressions: ``# arroyolint: disable=RULE`` on the offending line,
``# arroyolint: disable-file=RULE`` near the top of a file. Grandfathered
findings live in ``LINT_BASELINE.json`` (each entry must carry a
justification; the committed baseline is empty — fix, don't baseline).
"""

from .core import (  # noqa: F401 - public surface
    Finding,
    FileContext,
    Project,
    Rule,
    all_rules,
    get_rule,
    register,
)
from .baseline import Baseline  # noqa: F401
from .engine import LintResult, collect_files, run_lint  # noqa: F401

# importing the rule modules registers every rule
from . import rules_asyncio  # noqa: F401,E402
from . import rules_protocol  # noqa: F401,E402
from . import rules_jax_config  # noqa: F401,E402
from . import rules_segments  # noqa: F401,E402
from .races import rules_races  # noqa: F401,E402 - RACE00x (ISSUE 18)
