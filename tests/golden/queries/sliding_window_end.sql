CREATE TABLE impulse (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE out (
  start TIMESTAMP, end TIMESTAMP, cnt BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO out
SELECT window.start, window.end, cnt FROM (
  SELECT hop(interval '5 second', interval '15 second') as window,
         count(*) as cnt
  FROM impulse
  GROUP BY 1
);
