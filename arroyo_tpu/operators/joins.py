"""Placeholder: joins operators land with the window/join milestone."""
