"""Observability ("flight recorder") public surface.

Usage — sync extent (context manager attaches the trace context):

    with obs.span("checkpoint", trace=obs.new_trace(job_id, "ck-3"),
                  cat="controller", epoch=3) as sp:
        ...                      # nested obs.span(...) calls become children

Async hop (explicit start/finish across awaits or threads):

    sp = obs.start_span("checkpoint.flush", trace=tid, parent=pid,
                        cat="runner")
    tok = sp.attach()            # storage spans nest under it
    try: ...
    finally:
        sp.detach(tok); sp.finish()

`obs.span(...)` with neither an explicit trace nor an ambient context
returns an inert NULL span, so instrumentation never needs None checks.
Config: `obs.enabled` gates everything; `obs.trace_buffer_spans` sizes
the per-process ring buffer; `obs.frame_sample_every` rates data-plane
frame tracing. Export: `/debug/trace` on the admin server,
`/api/v1/jobs/{id}/traces` on the REST API, `tools/trace_report.py` for
multi-process merges.
"""

from __future__ import annotations

import os
from typing import Optional

from .trace import (  # noqa: F401 - public surface
    NULL_SPAN,
    Span,
    TraceRecorder,
    attach,
    chrome_trace,
    current,
    detach,
    new_span_id,
    new_trace,
    perfetto_trace,
)

_RECORDER: Optional[TraceRecorder] = None
_ROLE: str = ""


def set_role(role: str) -> None:
    """Name this process's track in trace exports ('controller',
    'worker-2000', ...). Takes effect for spans recorded afterwards."""
    global _ROLE
    _ROLE = role
    if _RECORDER is not None:
        _RECORDER.role = role


def enabled() -> bool:
    from ..config import config

    return bool(config().obs.enabled)


def frame_sample_every() -> int:
    from ..config import config

    return int(config().obs.frame_sample_every)


def latency_marker_interval() -> float:
    from ..config import config

    return float(config().obs.latency_marker_interval)


def recorder() -> TraceRecorder:
    """The process-wide ring buffer (lazily sized from
    obs.trace_buffer_spans)."""
    global _RECORDER
    if _RECORDER is None:
        from ..config import config

        _RECORDER = TraceRecorder(
            config().obs.trace_buffer_spans,
            role=_ROLE or f"proc-{os.getpid()}",
        )
    return _RECORDER


def reset(capacity: Optional[int] = None) -> TraceRecorder:
    """Drop the recorder and rebuild (tests; capacity override). Also
    clears the fleet-observatory side state (phase ledger, attribution
    accounting) so tests start from a clean observatory."""
    global _RECORDER
    timeline.clear()
    attribution.ACCOUNTING.reset()
    history.HISTORY.reset()
    audit.reset()
    if capacity is None:
        _RECORDER = None
        return recorder()
    _RECORDER = TraceRecorder(capacity, role=_ROLE or f"proc-{os.getpid()}")
    return _RECORDER


def expunge_job(job_id: str) -> None:
    """Job-scoped observatory GC, wired into the same paths as the
    metrics cardinality GC (Registry.drop_job): drops the job's spans
    from the trace ring, its phase instants from the timeline ledger,
    and its attribution accumulator state. The arroyo_job_attributed_*
    series themselves carry a `job` label and are dropped by
    Registry.drop_job."""
    if _RECORDER is not None:
        _RECORDER.expunge_job(job_id)
    timeline.expunge_job(job_id)
    attribution.ACCOUNTING.drop_job(job_id)
    history.HISTORY.drop_job(job_id)
    # conservation ledger: the job's reconciler goes with it (the
    # process-wide breach ring deliberately survives — drills assert
    # audit silence after the embedded controller tears the job down)
    audit.expunge_job(job_id)


def span(name: str, *, trace: Optional[str] = None,
         parent: Optional[str] = None, cat: str = "obs", **attrs):
    """Create a span. With `trace` (+ optional `parent`) it anchors
    explicitly; without, it becomes a child of the ambient context — or a
    NULL span when there is none (un-traced code paths stay silent)."""
    if not enabled():
        return NULL_SPAN
    if trace is None:
        ctx = current()
        if ctx is None:
            return NULL_SPAN
        trace = ctx[0]
        if parent is None:
            parent = ctx[1]
    elif parent is None:
        ctx = current()
        if ctx is not None and ctx[0] == trace:
            parent = ctx[1]
    return Span(trace, new_span_id(), parent, name, cat, attrs)


def start_span(name: str, *, trace: Optional[str] = None,
               parent: Optional[str] = None, cat: str = "obs", **attrs):
    """Alias of span() for call sites that finish() explicitly (async
    hops); reads as intent."""
    return span(name, trace=trace, parent=parent, cat=cat, **attrs)


def event(name: str, *, cat: str = "event", **attrs) -> None:
    """Record an instant event. Attaches to the ambient span when one is
    active; otherwise lands as a standalone instant under a per-process
    trace so it still shows up in dumps (chaos fires use this)."""
    if not enabled():
        return
    import time

    ctx = current()
    recorder().record({
        "trace_id": ctx[0] if ctx else f"proc/{os.getpid()}",
        "span_id": new_span_id(),
        "parent_id": ctx[1] if ctx else None,
        "name": name,
        "cat": cat,
        "ts": time.time() * 1e6,
        "dur": 0.0,
        "instant": True,
        "attrs": dict(attrs),
        "events": [],
        "pid": os.getpid(),
        "tid": 0,
    })


def headers() -> Optional[dict]:
    """The ambient context as a wire header ({'t': trace, 's': span}), or
    None — RPC clients attach this under the '__trace__' message key."""
    ctx = current()
    if ctx is None:
        return None
    return {"t": ctx[0], "s": ctx[1]}


def latency_report(job_id: Optional[str] = None) -> dict:
    """The device-tier observatory's structured latency surface: per-task
    latency-marker quantiles (transit source→operator), end-to-end
    quantiles at terminal subtasks, and the XLA compile/dispatch summary.
    Shared by `GET /api/v1/jobs/{id}/latency`, the admin server's
    `/debug/latency`, and tools/trace_report.py."""
    from ..metrics import REGISTRY, hist_quantiles

    snap = REGISTRY.snapshot()

    def series(name: str) -> list:
        out = []
        for labels, h in snap.get(name, []):
            if job_id is not None and labels.get("job") != job_id:
                continue
            count = h.get("count", 0)
            entry = {
                "job": labels.get("job"),
                "task": labels.get("task"),
                "samples": int(count),
                "mean_ms": round(1e3 * h.get("sum", 0.0) / count, 3)
                if count else 0.0,
            }
            entry.update({
                f"{q}_ms": round(v * 1e3, 3)
                for q, v in hist_quantiles(h).items()
            })
            out.append(entry)
        out.sort(key=lambda e: (e["job"] or "", e["task"] or ""))
        return out

    return {
        "operators": series("arroyo_worker_latency_marker_seconds"),
        "end_to_end": series("arroyo_worker_e2e_latency_seconds"),
        "device": device.summary(),
    }


# watchtower (ISSUE 13): the retained metric-history tier — imported
# first: attribution's pump samples it, the doctor reads windowed
# rates from it
from . import history  # noqa: E402 - public surface

# fleet observatory (ISSUE 11): per-job attribution, the batch-phase
# timeline ledger, and the bottleneck doctor — imported before device
# (InstrumentedJit notes per-job device seconds through attribution)
from . import attribution, timeline  # noqa: F401,E402 - public surface

# device-tier observatory (XLA compile/dispatch telemetry) — imported
# last: device.py pulls in the metric families and the trace primitives
from . import device  # noqa: F401,E402 - public surface
from . import doctor  # noqa: F401,E402 - public surface

# conservation ledger (ISSUE 19): per-edge epoch attestations + the
# controller-resident reconciler — imports nothing heavier than the
# metric families, so it can ride at the tail of the package
from . import audit  # noqa: F401,E402 - public surface
