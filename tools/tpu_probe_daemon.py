#!/usr/bin/env python
"""TPU relay grant-capture daemon.

The axon relay that fronts the single real TPU chip is intermittently
wedged: most `jax.devices()` calls hang forever inside the PJRT claim
path, but occasionally a grant lands (round 2: exactly once, 13:49 UTC).
Round-2 evidence shows the fatal pattern: the probe that captured the
grant exited, and the *next* process (the bench) wedged re-claiming.

Therefore this daemon's probe child converts a grant into benchmark
numbers IN-PROCESS, while it still holds the claim:

  parent loop (this file, no jax import):
    spawn child --probe
      child: watchdog thread hard-exits (os._exit) if jax.devices()
             hasn't returned within PROBE_GRACE seconds
      child: on grant, prints GRANTED and immediately runs the nexmark
             device benches (q5/q1/q7/q8) in-process via bench.child()
    parent: 150 s deadline to see GRANTED, else kill -> log "wedged";
            after GRANTED, generous deadline for compiles through the
            relay (~20-40 s per XLA program).
    on success: write TPU_GRANT.json (bench.py consumes it at round end
            if the live device child wedges) and append to probe log.
    sleep ~15 min (+/- jitter), repeat for the whole round.

Run:  python tools/tpu_probe_daemon.py            # daemon
      python tools/tpu_probe_daemon.py --probe    # one probe child
      python tools/tpu_probe_daemon.py --once     # single parent cycle

Log:  tools/tpu_probe.log   (one line per probe: ts outcome detail)
Out:  TPU_GRANT.json at repo root on first successful device bench.
"""

import json
import os
import random
import signal
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "tools", "tpu_probe.log")
GRANT_JSON = os.path.join(REPO, "TPU_GRANT.json")
PROBE_GRACE = 100.0     # child self-kill if no grant within this
PARENT_PROBE_DEADLINE = 150.0   # parent kills child if no GRANTED line
BENCH_DEADLINE = 3600.0         # after GRANTED: compiles are slow
SLEEP_BASE = 900.0              # 15 min between probes while wedged
SLEEP_AFTER_GRANT = 3600.0      # once numbers exist, probe hourly
MAX_RUNTIME = 11.5 * 3600

# (query, events) — q5 is the headline; sizes keep post-compile runtime
# in seconds while being large enough for a credible rate.
BENCH_PLAN = [("q5", 500_000), ("q1", 200_000), ("q7", 200_000),
              ("q8", 200_000)]


def log_line(msg: str) -> None:
    ts = time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime())
    line = f"{ts} {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def probe_child() -> None:
    """Claim the device; on grant run the benches while holding it."""
    granted = threading.Event()

    def watchdog():
        if not granted.wait(PROBE_GRACE):
            # jax.devices() is stuck in C inside the axon claim path —
            # no exception can unwind it; hard-exit so the parent sees a
            # clean death instead of a zombie holding half a claim.
            print("WEDGED probe watchdog fired", flush=True)
            os._exit(3)

    threading.Thread(target=watchdog, daemon=True).start()
    t0 = time.monotonic()
    import jax  # noqa: deferred heavy import
    devs = jax.devices()
    granted.set()
    kinds = ",".join(sorted({d.platform for d in devs}))
    if not any(d.platform == "tpu" for d in devs):
        print(f"NOTTPU {kinds}", flush=True)
        os._exit(4)
    print(f"GRANTED {kinds} in {time.monotonic() - t0:.1f}s", flush=True)

    sys.path.insert(0, REPO)
    import bench
    for query, events in BENCH_PLAN:
        print(f"BENCHQ {query} {events}", flush=True)
        try:
            bench.child(events, "jax", query)   # prints RESULT eps rows dt
        except BaseException as e:  # keep going; later queries may pass
            print(f"BENCHFAIL {query} {type(e).__name__}: {e}", flush=True)
    print("DONE", flush=True)
    os._exit(0)


def run_one_probe() -> bool:
    """One parent cycle. Returns True if a grant produced numbers."""
    import queue

    cmd = [sys.executable, os.path.abspath(__file__), "--probe"]
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True,
                            stderr=subprocess.STDOUT, cwd=REPO)
    q: "queue.Queue" = queue.Queue()

    def reader():
        for ln in proc.stdout:
            q.put(ln)
        q.put(None)  # EOF

    threading.Thread(target=reader, daemon=True).start()
    deadline = time.monotonic() + PARENT_PROBE_DEADLINE
    granted = False
    results = {}
    cur_q = None
    lines = []
    try:
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError
            try:
                line = q.get(timeout=min(remaining, 5.0))
            except queue.Empty:
                continue
            if line is None:
                # child exited; if it never printed a recognized marker
                # (e.g. import jax blew up), still leave a trail
                if not granted and not any(
                        ln.startswith(("WEDGED", "NOTTPU")) for ln in lines):
                    tail = "; ".join(lines[-3:]) or "<no output>"
                    log_line(f"probe exited rc={proc.poll()} "
                             f"without grant; tail=[{tail}]")
                break
            line = line.strip()
            if not line:
                continue
            lines.append(line)
            if line.startswith("GRANTED"):
                granted = True
                deadline = time.monotonic() + BENCH_DEADLINE
                log_line(f"probe GRANTED ({line})")
            elif line.startswith("BENCHQ"):
                cur_q = line.split()[1]
            elif line.startswith("RESULT") and cur_q:
                parts = line.split()
                results[cur_q] = {"eps": float(parts[1]),
                                  "rows": int(parts[2]),
                                  "secs": float(parts[3])}
            elif line.startswith(("WEDGED", "NOTTPU", "BENCHFAIL")):
                log_line(f"probe: {line}")
            elif line.startswith("DONE"):
                break
    except TimeoutError:
        _kill(proc)
        tail = "; ".join(lines[-3:])
        if granted:
            log_line(f"probe granted but bench DEADLINED; partial={list(results)} tail=[{tail}]")
        else:
            log_line("probe wedged (no grant within "
                     f"{PARENT_PROBE_DEADLINE:.0f}s)")
    finally:
        _kill(proc)

    if granted and "q5" in results:
        payload = {
            "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "source": "tools/tpu_probe_daemon.py in-process capture",
            "events": dict(BENCH_PLAN),
            **{f"{q}_eps": round(r["eps"], 1) for q, r in results.items()},
            "q5_rows": results["q5"]["rows"],
        }
        tmp = GRANT_JSON + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, GRANT_JSON)  # atomic: bench.py may read anytime
        log_line(f"GRANT CAPTURED -> TPU_GRANT.json {payload}")
        return True
    if granted and results:
        log_line(f"grant produced partial results (no q5): {results}")
    return False


def _kill(proc):
    if proc.poll() is None:
        try:
            proc.send_signal(signal.SIGKILL)
            proc.wait(10)
        except Exception:
            pass


def main():
    if "--probe" in sys.argv:
        probe_child()
        return
    once = "--once" in sys.argv
    start = time.monotonic()
    log_line(f"daemon start pid={os.getpid()} (round 3)")
    have_grant = os.path.exists(GRANT_JSON)
    while True:
        try:
            got = run_one_probe()
            have_grant = have_grant or got
        except Exception as e:
            log_line(f"daemon cycle error {type(e).__name__}: {e}")
        if once:
            break
        if time.monotonic() - start > MAX_RUNTIME:
            log_line("daemon max runtime reached; exiting")
            break
        base = SLEEP_AFTER_GRANT if have_grant else SLEEP_BASE
        time.sleep(base + random.uniform(-60, 60))


if __name__ == "__main__":
    main()
