"""Must NOT fire ASY001: every spawned task is retained or awaited."""
import asyncio

TASKS = set()


async def work():
    pass


async def go(tg):
    t = asyncio.create_task(work())
    TASKS.add(t)
    t.add_done_callback(TASKS.discard)
    await t
    kept = asyncio.ensure_future(work())
    await kept
    tg.create_task(work())  # TaskGroup retains its children
