"""Structured logging with console/json/logfmt formats.

Capability parity with the reference's init_logging
(/root/reference/crates/arroyo-server-common/src/lib.rs:57-190).
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Optional


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)),
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        return json.dumps(out)


class _LogfmtFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        msg = (record.getMessage()
               .replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n"))
        return (
            f'ts={time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created))} '
            f'level={record.levelname.lower()} target={record.name} msg="{msg}"'
        )


def init_logging(
    fmt: str = "console", level: str = "INFO", file: Optional[str] = None
) -> None:
    root = logging.getLogger("arroyo")
    root.setLevel(level.upper())
    root.handlers.clear()
    handler = logging.FileHandler(file) if file else logging.StreamHandler(sys.stderr)
    if fmt == "json":
        handler.setFormatter(_JsonFormatter())
    elif fmt == "logfmt":
        handler.setFormatter(_LogfmtFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)-5s %(name)s: %(message)s")
        )
    root.addHandler(handler)
    root.propagate = False


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(f"arroyo.{name}")
