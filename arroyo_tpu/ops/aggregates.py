"""Keyed aggregate accumulators: the device-resident window state.

This is the TPU-native replacement for the reference's per-bin DataFusion
partial-aggregation streams (/root/reference/crates/arroyo-worker/src/arrow/
tumbling_aggregating_window.rs:66-110): instead of running a CPU physical
plan per bin, ALL (bin, key) groups share flat device arrays of accumulator
slots, updated with one jitted scatter-reduce per batch and drained with one
gather per watermark. Slot assignment (the "hash table") stays host-side in
round 1 — a python dict over unique (bin, key) pairs, O(unique) per batch —
while the O(rows) arithmetic runs on device.

Shape discipline: `slots`/value arrays are padded to bucket sizes
(config.tpu.shape_buckets) so XLA compiles O(buckets × capacities) programs,
not one per batch size. Padded rows scatter neutral elements into a
reserved scratch slot.

Supported aggregate kinds: count, sum, min, max, avg (each decomposes into
"physical" accumulators: add/min/max over a column or the constant 1).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config import config

# jax import deferred so host-only deployments can import the module tree
_jax = None


def _get_jax():
    global _jax
    if _jax is None:
        import jax

        jax.config.update("jax_enable_x64", True)
        _jax = jax
    return _jax


INT_MIN = np.iinfo(np.int64).min
INT_MAX = np.iinfo(np.int64).max


@dataclasses.dataclass(frozen=True)
class AggSpec:
    kind: str  # count | sum | min | max | avg | count_distinct | udaf
    col: Optional[int]  # input column index (None for count(*))
    name: str  # output field name
    is_float: bool = False  # input/output numeric class
    udaf: Optional[str] = None  # registered UDAF name when kind == "udaf"

    def host_state(self) -> Optional[str]:
        """Host-resident per-slot state flavor, or None when the aggregate
        decomposes fully onto device phys arrays. 'buffer' = raw value
        chunks (UDAFs; order-insensitive, append-only). 'multiset' = value
        -> signed count (count_distinct; retractable, mergeable)."""
        if self.kind == "udaf":
            return "buffer"
        if self.kind == "count_distinct":
            return "multiset"
        return None

    def phys(self) -> List[Tuple[str, str, str]]:
        """[(op, dtype, source)]: op in add|min|max, dtype i8|f8,
        source col|one."""
        if self.host_state() is not None:
            # host-state aggregates keep raw values host-side (the
            # reference hands all values to its UDAFs too, udafs.rs;
            # count_distinct is a DataFusion grouped-distinct there)
            return []
        if self.kind == "count":
            return [("add", "i8", "one")]
        d = "f8" if self.is_float else "i8"
        if self.kind == "sum":
            return [("add", d, "col")]
        if self.kind == "min":
            return [("min", d, "col")]
        if self.kind == "max":
            return [("max", d, "col")]
        if self.kind == "avg":
            return [("add", "f8", "col"), ("add", "i8", "one")]
        raise ValueError(f"unknown aggregate {self.kind}")


def _not_null_mask(vals: np.ndarray) -> np.ndarray:
    """True per row where the value is non-null (None or NaN)."""
    if vals.dtype == object:
        return np.fromiter(
            (v is not None and v == v for v in vals),
            dtype=bool, count=len(vals),
        )
    if vals.dtype.kind == "f":
        return ~np.isnan(vals)
    if vals.dtype.kind == "M":
        return ~np.isnat(vals)
    return np.ones(len(vals), dtype=bool)


def _neutral(op: str, dtype: str):
    if op == "add":
        return 0
    if op == "min":
        return np.inf if dtype == "f8" else INT_MAX
    return -np.inf if dtype == "f8" else INT_MIN


def _np_dtype(d: str):
    return np.float64 if d == "f8" else np.int64


def _bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return int(2 ** np.ceil(np.log2(max(n, 1))))


class Accumulator:
    """Flat slot-indexed accumulator state shared by all (bin, key) groups of
    one window-operator subtask. Backend 'jax' (device) or 'numpy' (host)."""

    def __init__(self, specs: List[AggSpec], capacity: int = 4096,
                 backend: str = "jax"):
        self.specs = specs
        self.backend = backend
        self.capacity = capacity  # last slot is scratch for padded rows
        self.phys: List[Tuple[str, str, str, int]] = []  # op,dtype,src,spec_idx
        for si, spec in enumerate(specs):
            for op, dtype, src in spec.phys():
                self.phys.append((op, dtype, src, si))
        self._buckets = tuple(config().tpu.shape_buckets)
        # host-side per-slot state: spec idx -> slot -> chunks ('buffer',
        # UDAFs) or value->count dict ('multiset', count_distinct)
        self.host_kinds: Dict[int, str] = {
            i: s.host_state() for i, s in enumerate(specs)
            if s.host_state() is not None
        }
        self.udaf_idx = [
            i for i, k in self.host_kinds.items() if k == "buffer"
        ]
        self.multiset_idx = [
            i for i, k in self.host_kinds.items() if k == "multiset"
        ]
        self.udaf_store: Dict[int, Dict[int, list]] = {
            i: {} for i in self.udaf_idx
        }
        self.multiset_store: Dict[int, Dict[int, dict]] = {
            i: {} for i in self.multiset_idx
        }
        self._gather_slots: Optional[np.ndarray] = None
        self._segment_udaf: Optional[Dict[int, list]] = None
        self._segment_multiset: Optional[Dict[int, list]] = None
        if backend == "jax":
            jnp = _get_jax().numpy
            self.state = [
                jnp.full(capacity, _neutral(op, dt), dtype=_np_dtype(dt))
                for op, dt, _, _ in self.phys
            ]
            self._update_fn = self._make_update_fn()
            self._gather_fn = self._make_gather_fn()
        else:
            self.state = [
                np.full(capacity, _neutral(op, dt), dtype=_np_dtype(dt))
                for op, dt, _, _ in self.phys
            ]

    # -- capacity -----------------------------------------------------------

    def grow(self, min_capacity: int):
        # 4x steps (not 2x): every growth re-specializes the jitted
        # update/gather/reset programs for the new state shape, so fewer,
        # larger jumps bound recompilation churn at high cardinality
        new_cap = self.capacity
        while new_cap < min_capacity:
            new_cap *= 4
        if new_cap == self.capacity:
            return
        # the old scratch slot (capacity-1) absorbed padded-row scatters;
        # it becomes an allocatable slot after growth and must restart
        # from neutral
        if self.backend == "jax":
            jnp = _get_jax().numpy
            self.state = [
                jnp.concatenate(
                    [s, jnp.full(new_cap - self.capacity,
                                 _neutral(op, dt), dtype=_np_dtype(dt))]
                ).at[self.capacity - 1].set(_neutral(op, dt))
                for s, (op, dt, _, _) in zip(self.state, self.phys)
            ]
        else:
            self.state = [
                np.concatenate(
                    [s, np.full(new_cap - self.capacity,
                                _neutral(op, dt), dtype=_np_dtype(dt))]
                )
                for s, (op, dt, _, _) in zip(self.state, self.phys)
            ]
            for (op, dt, _, _), s in zip(self.phys, self.state):
                s[self.capacity - 1] = _neutral(op, dt)
        self.capacity = new_cap

    # -- update (hot path) --------------------------------------------------

    def update(self, slots: np.ndarray, cols: Dict[int, np.ndarray],
               signs: Optional[np.ndarray] = None):
        """Scatter-reduce a batch. slots[i] = accumulator slot of row i
        (must be < capacity-1; capacity-1 is scratch). cols maps input column
        index -> numpy array of row values. `signs` (+1 append / -1 retract
        per row) makes the update invertible for retraction-consuming
        aggregates; only add-reductions (count/sum/avg) support it."""
        n = len(slots)
        if n == 0:
            return
        self._check_signed(signs)
        self._update_host(slots, cols, signs)
        if not self.phys:
            return
        if self.backend == "numpy":
            self._np_update(slots, cols, signs)
            return
        jnp = _get_jax().numpy
        padded = _bucket(n, self._buckets)
        slots_p = np.full(padded, self.capacity - 1, dtype=np.int64)
        slots_p[:n] = slots
        valid = np.zeros(padded, dtype=np.int64)
        valid[:n] = 1 if signs is None else signs
        inputs = []
        for op, dt, src, si in self.phys:
            spec = self.specs[si]
            if src == "one":
                vals = valid
            else:
                vals = np.zeros(padded, dtype=_np_dtype(dt))
                vals[:n] = (
                    cols[spec.col] if signs is None
                    else cols[spec.col] * signs
                )
                if op != "add":
                    vals[n:] = _neutral(op, dt)
            inputs.append(jnp.asarray(vals))
        self.state = self._update_fn(self.state, jnp.asarray(slots_p), *inputs)

    def _check_signed(self, signs: Optional[np.ndarray]):
        if signs is not None and (
            self.udaf_idx or any(op != "add" for op, _, _, _ in self.phys)
        ):
            raise ValueError(
                "signed (retractable) update requires invertible aggregates "
                "(count/sum/avg/count_distinct)"
            )

    def _update_host(self, slots: np.ndarray, cols: Dict[int, np.ndarray],
                     signs: Optional[np.ndarray] = None):
        """Fold a batch into the host-side per-slot states: value chunks
        for 'buffer' specs, signed value counts for 'multiset' specs."""
        if not self.host_kinds:
            return
        n = len(slots)
        order = np.argsort(slots, kind="stable")
        s_sorted = slots[order]
        bounds = np.nonzero(np.diff(s_sorted))[0] + 1
        starts = np.concatenate([[0], bounds])
        ends = np.concatenate([bounds, [n]])
        sg_sorted = signs[order] if signs is not None else None
        for si in self.udaf_idx:
            vals = self._host_vals(si, cols)[order]
            store = self.udaf_store[si]
            for lo, hi in zip(starts, ends):
                store.setdefault(int(s_sorted[lo]), []).append(vals[lo:hi])
        for si in self.multiset_idx:
            # SQL count(DISTINCT x) excludes NULLs; raw columns carry them
            # as None (object dtype) or NaN (float)
            vals = self._host_vals(si, cols)[order]
            valid = _not_null_mask(vals)
            store = self.multiset_store[si]
            for lo, hi in zip(starts, ends):
                d = store.setdefault(int(s_sorted[lo]), {})
                gv = valid[lo:hi]
                group = vals[lo:hi][gv]
                if sg_sorted is None:
                    uniq, counts = np.unique(group, return_counts=True)
                    for v, c in zip(uniq.tolist(), counts.tolist()):
                        d[v] = d.get(v, 0) + c
                else:
                    for v, sg in zip(group.tolist(),
                                     sg_sorted[lo:hi][gv].tolist()):
                        nc = d.get(v, 0) + int(sg)
                        if nc <= 0:
                            d.pop(v, None)
                        else:
                            d[v] = nc

    def _host_vals(self, si: int, cols: Dict) -> np.ndarray:
        """Host-state specs read the raw (uncast) representation when the
        operator provided one under ('raw', col) — a column shared with a
        float-cast numeric spec would otherwise lose integer precision
        above 2^53 in the multiset keys."""
        c = self.specs[si].col
        return cols[("raw", c)] if ("raw", c) in cols else cols[c]

    def _make_update_fn(self):
        jax = _get_jax()
        phys = list(self.phys)

        @partial(jax.jit, donate_argnums=(0,))
        def update(state, slots, *vals):
            out = []
            for (op, dt, src, si), s, v in zip(phys, state, vals):
                if op == "add":
                    out.append(s.at[slots].add(v))
                elif op == "min":
                    out.append(s.at[slots].min(v))
                else:
                    out.append(s.at[slots].max(v))
            return out

        return update

    def _np_update(self, slots, cols, signs=None):
        for (op, dt, src, si), s in zip(self.phys, self.state):
            spec = self.specs[si]
            if src == "one":
                vals = (
                    np.ones(len(slots), dtype=np.int64)
                    if signs is None else signs.astype(np.int64)
                )
            else:
                vals = cols[spec.col].astype(_np_dtype(dt), copy=False)
                if signs is not None:
                    vals = vals * signs
            if op == "add":
                np.add.at(s, slots, vals)
            elif op == "min":
                np.minimum.at(s, slots, vals)
            else:
                np.maximum.at(s, slots, vals)

    # -- drain --------------------------------------------------------------

    def gather(self, slots: np.ndarray,
               materialize: bool = True) -> List[np.ndarray]:
        """Read accumulator values for `slots` (emission); returns one numpy
        array per physical accumulator. The slots are remembered so
        finalize() can resolve UDAF value buffers for the same emission.
        With materialize=False the jax device->host copy is only
        *dispatched*: the returned arrays are device arrays whose
        np.asarray completes later (async snapshot overlap)."""
        self._gather_slots = np.asarray(slots)
        self._segment_udaf = None
        self._segment_multiset = None
        if len(slots) == 0:
            return [np.empty(0, dtype=s.dtype) for s in
                    (self.state if self.backend == "numpy" else self.state)]
        if self.backend == "numpy":
            return [s[slots] for s in self.state]
        jnp = _get_jax().numpy
        padded = _bucket(len(slots), self._buckets)
        slots_p = np.full(padded, self.capacity - 1, dtype=np.int64)
        slots_p[: len(slots)] = slots
        outs = self._gather_fn(self.state, jnp.asarray(slots_p))
        if not materialize:
            return [o[: len(slots)] for o in outs]
        return [np.asarray(o)[: len(slots)] for o in outs]

    def _make_gather_fn(self):
        jax = _get_jax()

        @jax.jit
        def gather(state, slots):
            return [s[slots] for s in state]

        return gather

    def _drop_udaf_slots(self, slots: np.ndarray):
        for si in self.udaf_idx:
            store = self.udaf_store[si]
            for s in slots:
                store.pop(int(s), None)
        for si in self.multiset_idx:
            store = self.multiset_store[si]
            for s in slots:
                store.pop(int(s), None)

    def reset_slots(self, slots: np.ndarray):
        """Return emitted slots to neutral so they can be reused."""
        self._drop_udaf_slots(slots)
        if len(slots) == 0 or not self.phys:
            return
        if self.backend == "numpy":
            for (op, dt, _, _), s in zip(self.phys, self.state):
                s[slots] = _neutral(op, dt)
            return
        jnp = _get_jax().numpy
        padded = _bucket(len(slots), self._buckets)
        slots_p = np.full(padded, self.capacity - 1, dtype=np.int64)
        slots_p[: len(slots)] = slots
        if not hasattr(self, "_reset_fn"):
            jax = _get_jax()
            phys = list(self.phys)

            @partial(jax.jit, donate_argnums=(0,))
            def reset(state, s_idx):
                out = []
                for (op, dt, _, _), s in zip(phys, state):
                    out.append(s.at[s_idx].set(_neutral(op, dt)))
                return out

            self._reset_fn = reset
        self.state = self._reset_fn(self.state, jnp.asarray(slots_p))

    # -- finalize -----------------------------------------------------------

    def finalize(self, gathered: List[np.ndarray]) -> List[np.ndarray]:
        """Physical accumulator values -> one output column per spec.
        Host-state specs resolve from the per-slot stores of the slots from
        the preceding gather()/combine_for_segments()."""
        out = []
        pi = 0
        for si, spec in enumerate(self.specs):
            if spec.kind == "udaf":
                out.append(self._finalize_udaf(si))
                continue
            if spec.kind == "count_distinct":
                out.append(self._finalize_multiset(si))
                continue
            n_phys = len(spec.phys())
            vals = gathered[pi: pi + n_phys]
            pi += n_phys
            if spec.kind == "avg":
                with np.errstate(invalid="ignore", divide="ignore"):
                    out.append(vals[0] / np.maximum(vals[1], 1))
            else:
                out.append(vals[0])
        return out

    def _finalize_multiset(self, si: int) -> np.ndarray:
        if self._segment_multiset is not None:
            sets = self._segment_multiset.get(si, [])
            return np.asarray([len(s) for s in sets], dtype=np.int64)
        store = self.multiset_store[si]
        return np.asarray(
            [len(store.get(int(s), ())) for s in self._gather_slots],
            dtype=np.int64,
        )

    def _finalize_udaf(self, si: int) -> np.ndarray:
        from ..udf.registry import get_udaf

        spec = self.specs[si]
        u = get_udaf(spec.udaf)
        if u is None:
            raise ValueError(f"unknown UDAF {spec.udaf!r}")
        if self._segment_udaf is not None:
            groups = self._segment_udaf.get(si, [])
        else:
            store = self.udaf_store[si]
            groups = [
                np.concatenate(store.get(int(s), [np.empty(0)]))
                for s in self._gather_slots
            ]
        return np.asarray([u.fn(g) for g in groups])

    def combine_for_segments(
        self, slots: np.ndarray, seg_ids: np.ndarray, n_segments: int
    ) -> List[np.ndarray]:
        """Merge per-slot accumulators into per-segment values (sliding
        window emission): device phys arrays segment-reduce on host; UDAF
        buffers concatenate per segment for the subsequent finalize()."""
        gathered = self.gather(slots)
        combined = []
        for (op, dt, _, _), vals in zip(self.phys, gathered):
            outv = np.full(n_segments, _neutral(op, dt), dtype=_np_dtype(dt))
            if op == "add":
                np.add.at(outv, seg_ids, vals)
            elif op == "min":
                np.minimum.at(outv, seg_ids, vals)
            else:
                np.maximum.at(outv, seg_ids, vals)
            combined.append(outv)
        if self.udaf_idx:
            seg_map: Dict[int, list] = {}
            for si in self.udaf_idx:
                store = self.udaf_store[si]
                groups = [[] for _ in range(n_segments)]
                for s, seg in zip(slots, seg_ids):
                    groups[int(seg)].extend(store.get(int(s), []))
                seg_map[si] = [
                    np.concatenate(g) if g else np.empty(0) for g in groups
                ]
            self._segment_udaf = seg_map
        if self.multiset_idx:
            mseg: Dict[int, list] = {}
            for si in self.multiset_idx:
                store = self.multiset_store[si]
                sets: List[set] = [set() for _ in range(n_segments)]
                for s, seg in zip(slots, seg_ids):
                    sets[int(seg)].update(store.get(int(s), ()))
                mseg[si] = sets
            self._segment_multiset = mseg
        return combined

    def merge_slot_into(self, dst: int, src: int):
        """Fold slot src into dst (session merges): device phys via
        gather/restore is handled by the caller; host state moves here."""
        for si in self.udaf_idx:
            store = self.udaf_store[si]
            if src in store:
                store.setdefault(dst, []).extend(store.pop(src))
        for si in self.multiset_idx:
            store = self.multiset_store[si]
            if src in store:
                d = store.setdefault(dst, {})
                for v, c in store.pop(src).items():
                    d[v] = d.get(v, 0) + c

    # -- checkpoint ---------------------------------------------------------

    def snapshot(self, slots: np.ndarray,
                 materialize: bool = True) -> List[np.ndarray]:
        """Device->host copy of live slots for checkpointing; host state
        rides along as one list-valued column per host-state spec (value
        chunks for buffers, [value, count] pairs for multisets), ordered
        buffers-then-multisets by spec index."""
        out = self.gather(slots, materialize=materialize)
        for si in self.udaf_idx:
            store = self.udaf_store[si]
            out.append(np.asarray(
                [np.concatenate(store.get(int(s), [np.empty(0)])).tolist()
                 for s in slots],
                dtype=object,
            ))
        for si in self.multiset_idx:
            store = self.multiset_store[si]
            out.append(np.asarray(
                [[[v, c] for v, c in store.get(int(s), {}).items()]
                 for s in slots],
                dtype=object,
            ))
        return out

    def _restore_udaf_cols(
        self, slots: np.ndarray, values: List[np.ndarray]
    ) -> List[np.ndarray]:
        """Consume trailing host-state columns; returns the physical
        accumulator columns."""
        if not self.host_kinds:
            return values
        n_phys = len(self.phys)
        host_cols = values[n_phys:]
        values = values[:n_phys]
        n_buf = len(self.udaf_idx)
        for si, col in zip(self.udaf_idx, host_cols[:n_buf]):
            store = self.udaf_store[si]
            for s, vals in zip(slots, col):
                arr = np.asarray(list(vals))
                if len(arr):
                    store.setdefault(int(s), []).append(arr)
        for si, col in zip(self.multiset_idx, host_cols[n_buf:]):
            store = self.multiset_store[si]
            for s, pairs in zip(slots, col):
                if len(pairs):
                    d = store.setdefault(int(s), {})
                    for v, c in pairs:
                        d[v] = d.get(v, 0) + int(c)
        return values

    def restore(self, slots: np.ndarray, values: List[np.ndarray]):
        """Write physical accumulator values back into `slots` (the tail
        columns are host-state buffers when such specs exist)."""
        values = self._restore_udaf_cols(slots, values)
        if len(slots) == 0 or not self.phys:
            return
        if self.backend == "numpy":
            for s, v in zip(self.state, values):
                s[slots] = v
            return
        jnp = _get_jax().numpy
        self.state = [
            s.at[jnp.asarray(slots)].set(jnp.asarray(v))
            for s, v in zip(self.state, values)
        ]

    def block_until_ready(self):
        if self.backend != "numpy":
            for s in self.state:
                s.block_until_ready()


def make_accumulator(specs: List[AggSpec], capacity: int = 4096,
                     backend: Optional[str] = None) -> Accumulator:
    if backend is None:
        backend = "jax" if config().tpu.enabled else "numpy"
    return Accumulator(specs, capacity, backend)
