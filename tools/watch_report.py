#!/usr/bin/env python3
"""Offline watchtower report renderer (ISSUE 13).

Renders a watch-drill report (tools/fleet_harness.py --watch --out) or
a captured diagnostic bundle as a human-readable alert timeline +
bundle summary — the artifact a responder reads when only the CI
uploads survived the incident.

Usage:
  python tools/watch_report.py WATCH_r01.json          # drill report
  python tools/watch_report.py --bundle bundle-*.json  # one bundle
  python tools/watch_report.py report.json --bundle b.json
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional


def _fmt_ts(ts: float) -> str:
    return time.strftime("%H:%M:%S", time.localtime(ts)) + (
        ".%01d" % int((ts % 1) * 10)
    )


def render_timeline(events: List[dict], out=sys.stdout) -> None:
    """The alert ledger as a timeline: one line per firing/cleared
    event, ordered by wall time."""
    events = sorted(events or [], key=lambda e: e.get("ts", 0))
    if not events:
        print("  (no alert events)", file=out)
        return
    t0 = events[0].get("ts", 0)
    for e in events:
        flag = "!" if e.get("event") == "firing" else "+"
        val = e.get("value")
        val_s = f"{val:.3g}" if isinstance(val, (int, float)) else "?"
        extra = ""
        if e.get("sustained_s") is not None:
            extra = f" after {e['sustained_s']}s sustained"
        if e.get("fired_for_s") is not None:
            extra = f" (fired for {e['fired_for_s']}s)"
        print(
            f"{flag} {_fmt_ts(e.get('ts', 0))} "
            f"(+{e.get('ts', 0) - t0:6.1f}s) "
            f"{e.get('event', '?').upper():<8} "
            f"job={e.get('job', '?')} rule={e.get('rule', '?')} "
            f"value={val_s}{e.get('unit', '')} "
            f"(threshold {e.get('threshold')}){extra}",
            file=out,
        )


def bundle_summary(bundle: dict, out=sys.stdout) -> None:
    """One diagnostic bundle, summarized: what fired, what the doctor
    said, and what evidence the bundle carries."""
    print(f"bundle #{bundle.get('n')} — job {bundle.get('job')} "
          f"(tenant {bundle.get('tenant')}) rule {bundle.get('rule')}",
          file=out)
    cap = bundle.get("captured_at")
    if cap:
        print(f"  captured {_fmt_ts(cap)}", file=out)
    alert = bundle.get("alert") or {}
    print(f"  breach: value={alert.get('value')}{alert.get('unit', '')} "
          f"threshold={alert.get('threshold')}", file=out)
    verdict = (bundle.get("doctor") or {}).get("verdict") or {}
    if verdict:
        line = (f"  doctor: {verdict.get('cause')} "
                f"(operator {verdict.get('operator')}, "
                f"confidence {verdict.get('confidence')})")
        if verdict.get("suspect"):
            line += f" suspect={verdict['suspect']}"
        print(line, file=out)
    spans = bundle.get("flight_recorder") or []
    perf = (bundle.get("perfetto") or {}).get("traceEvents") or []
    print(f"  flight recording: {len(spans)} spans, "
          f"{len(perf)} perfetto events", file=out)
    hist = bundle.get("history") or []
    print(f"  history: {len(hist)} series", file=out)
    for s in hist:
        if s.get("max") or s.get("rate") or s.get("quantiles"):
            stats = []
            if s.get("max") is not None:
                stats.append(f"max={s['max']:.3g}")
            if s.get("rate") is not None:
                stats.append(f"rate={s['rate']:.3g}/s")
            for q, v in (s.get("quantiles") or {}).items():
                stats.append(f"{q}={v:.3g}")
            labels = ",".join(f"{k}={v}" for k, v in
                              sorted((s.get("labels") or {}).items()))
            print(f"    {s['name']}{{{labels}}} "
                  f"{' '.join(stats)} ({len(s.get('samples', []))} "
                  "samples)", file=out)
    cause = bundle.get("cause") or []
    if cause:
        print(f"  cause series: "
              f"{', '.join(sorted({c['name'] for c in cause}))}",
              file=out)


def render_report(report: dict, out=sys.stdout) -> int:
    """A --watch drill report: verdicts, then the alert timeline, then
    the bundle index. Returns a shell rc (0 = drill passed)."""
    print("watchtower drill report", file=out)
    print(f"  victim: {report.get('watch_victim')} "
          f"(+{report.get('watch_healthy_observed', '?')} healthy "
          "co-tenants)", file=out)
    checks = [
        ("alert fired", bool(report.get("watch_fired"))),
        ("bundle captured + covers breach window",
         bool(report.get("watch_bundle_ok"))),
        ("cleared after recovery",
         bool(report.get("watch_cleared_ok"))),
        ("zero false positives",
         report.get("watch_false_positive_count", 1) == 0),
    ]
    for name, ok in checks:
        print(f"  [{'ok' if ok else 'FAIL'}] {name}", file=out)
    if report.get("watch_fire_s") is not None:
        print(f"  time to fire: {report['watch_fire_s']}s "
              f"(rules: {report.get('watch_victim_rules')})", file=out)
    print("\nalert timeline:", file=out)
    render_timeline(report.get("watch_ledger") or [], out=out)
    if report.get("watch_bundle_file"):
        print(f"\nbundle file: {report['watch_bundle_file']}", file=out)
    return 0 if all(ok for _n, ok in checks) else 1


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("report", nargs="?",
                    help="watch drill report JSON (--watch --out)")
    ap.add_argument("--bundle", action="append", default=[],
                    help="diagnostic bundle JSON file (repeatable)")
    args = ap.parse_args(argv)
    if not args.report and not args.bundle:
        ap.error("give a report and/or --bundle")
    rc = 0
    if args.report:
        try:
            with open(args.report) as f:
                report = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"watch_report: {e}", file=sys.stderr)
            return 2
        rc = render_report(report)
    for path in args.bundle:
        try:
            with open(path) as f:
                bundle = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"watch_report: {e}", file=sys.stderr)
            return 2
        print("", file=sys.stdout)
        bundle_summary(bundle)
    return rc


if __name__ == "__main__":
    sys.exit(main())
