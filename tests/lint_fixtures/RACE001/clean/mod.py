"""Must NOT fire RACE001: both escape hatches. `counter` is written from
two roots but always under the same lock; `epoch` is written from two
roots but declares ``multi_writer`` — an explicit, reviewable policy."""
import asyncio

from arroyo_tpu.analysis.races import shared_state


@shared_state("counter", "epoch", multi_writer=("epoch",))
class Job:
    def __init__(self):
        self.counter = 0
        self.epoch = 0
        self._lock = None


class Engine:
    async def drive(self, job):
        with job._lock:
            job.counter = 1
        job.epoch = 1

    async def checkpoint(self, job):
        with job._lock:
            job.counter = 2
        job.epoch = 2

    def start(self, job):
        asyncio.ensure_future(self.drive(job))
        asyncio.ensure_future(self.checkpoint(job))
