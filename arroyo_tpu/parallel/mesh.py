"""Device mesh helpers.

The engine's multi-chip axis is the KEY dimension of the keyed stream
(SURVEY.md §5.7/§5.8): hash-range key shards map onto devices of a 1-D
mesh, so the keyed shuffle becomes an on-device all-to-all over ICI inside
a slice, while the host data plane (engine/network.py) carries batches
across slices and to connectors.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

_MESH_CACHE: Dict[Tuple, object] = {}


def _get_jnp():
    """jax.numpy with x64 enabled (routes through ops.aggregates so the
    enable-x64 flag is set exactly once, before any tracing)."""
    from ..ops.aggregates import _get_jax

    return _get_jax().numpy


def key_mesh(devices: Optional[Sequence] = None, axis: str = "keys"):
    """The 1-D key mesh over `devices`. Cached per (device ids, axis):
    every operator over the same device set shares ONE Mesh instance, so
    the process-level jitted-program cache in sharded_state.py (keyed by
    mesh identity among other things) actually hits across operators —
    distinct Mesh objects would re-trace identical programs per stage."""
    import jax

    if devices is None:
        devices = jax.devices()
    key = (tuple(d.id for d in devices), axis)
    mesh = _MESH_CACHE.get(key)
    if mesh is None:
        from jax.sharding import Mesh

        import numpy as np

        mesh = _MESH_CACHE.setdefault(key, Mesh(np.array(devices), (axis,)))
    return mesh


def mesh_is_virtual(mesh) -> bool:
    """True when the mesh's "devices" are host-platform (CPU) devices of
    ONE process — the `--xla_force_host_platform_device_count` dryrun/CI
    configuration. There is no ICI underneath such a mesh: collectives
    are memcpys between buffers of the same host and every shard's
    compute shares the same cores, which inverts the cost model the
    device-routed exchange is built for (sharded_state.py picks the
    host-fed exchange and the single-device salted tier here)."""
    devs = list(mesh.devices.flat)
    return all(d.platform == "cpu" for d in devs) and len(
        {d.process_index for d in devs}
    ) == 1
