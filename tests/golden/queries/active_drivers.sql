--pk=drivers
CREATE TABLE cars (
  timestamp TIMESTAMP,
  driver_id BIGINT,
  event_type TEXT,
  location TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/cars.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE active_drivers (
  drivers BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'debezium_json',
  type = 'sink'
);
INSERT INTO active_drivers
SELECT count(*) FROM (
  SELECT driver_id, count(*) FROM cars GROUP BY driver_id
  HAVING count(*) > 50
);
