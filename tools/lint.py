#!/usr/bin/env python3
"""arroyolint CLI — project-specific static analysis for arroyo_tpu.

Usage:
    python tools/lint.py                  # lint arroyo_tpu/, tools/, bench.py
    python tools/lint.py --strict         # CI mode: findings OR a stale /
                                          #   unjustified baseline fail (exit 1)
    python tools/lint.py --changed-only   # only files touched vs git HEAD
    python tools/lint.py --json           # machine-readable findings
    python tools/lint.py --list-rules     # registered rules + descriptions
    python tools/lint.py --config-table   # resolved config key/default table
    python tools/lint.py --call-graph     # RACE rules' async call graph as
                                          #   JSON (roots, locksets, accesses)
    python tools/lint.py --update-baseline  # grandfather current findings
                                            # (each entry then needs a
                                            #  human-written justification)

Suppressions: `# arroyolint: disable=RULE` on the offending line,
`# arroyolint: disable-file=RULE` within the first 10 lines of a file.
Exit codes: 0 clean, 1 findings (or strict-mode baseline problems),
2 internal error.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO_ROOT))

from arroyo_tpu.analysis import Baseline, all_rules, run_lint  # noqa: E402
from arroyo_tpu.analysis.baseline import DEFAULT_BASELINE  # noqa: E402
from arroyo_tpu.analysis.engine import DEFAULT_ROOTS  # noqa: E402
from arroyo_tpu.analysis.reporters import (  # noqa: E402
    report_json,
    report_sarif,
    report_text,
)
from arroyo_tpu.analysis.rules_jax_config import config_key_table  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="lint.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("paths", nargs="*",
                    help=f"roots to lint (default: {', '.join(DEFAULT_ROOTS)})")
    ap.add_argument("--root", default=str(REPO_ROOT),
                    help="project root the paths are relative to")
    ap.add_argument("--strict", action="store_true",
                    help="fail on findings, stale baseline entries, and "
                         "unjustified baseline entries")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="JSON report on stdout")
    ap.add_argument("--sarif", metavar="FILE", default=None,
                    help="also write a SARIF 2.1.0 report (use '-' for "
                         "stdout); CI uploads it so findings annotate PRs")
    ap.add_argument("--verbose", "-v", action="store_true",
                    help="include rule descriptions under each finding")
    ap.add_argument("--changed-only", action="store_true",
                    help="report findings only for files changed vs git HEAD")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: <root>/{DEFAULT_BASELINE})")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write all current findings into the baseline")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--config-table", action="store_true",
                    help="print the declared config key/default table")
    ap.add_argument("--call-graph", action="store_true",
                    help="dump the async call graph the RACE rules analyze "
                         "as JSON: task roots -> reachable functions -> "
                         "shared-field accesses with locksets")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id}  {rule.name}")
            print(f"      {rule.description}")
        return 0

    root = Path(args.root)
    roots = tuple(args.paths) or DEFAULT_ROOTS
    baseline_path = Path(args.baseline) if args.baseline else root / DEFAULT_BASELINE

    if args.config_table:
        from arroyo_tpu.analysis.engine import collect_files, parse_project

        project = parse_project(root, collect_files(root, roots))
        table = config_key_table(project)
        width = max((len(k) for k, _ in table), default=0)
        for key, default in table:
            print(f"{key:<{width}}  {default}")
        print(f"{len(table)} declared config keys")
        return 0

    if args.call_graph:
        import json as _json

        from arroyo_tpu.analysis.engine import collect_files, parse_project
        from arroyo_tpu.analysis.races import callgraph

        project = parse_project(root, collect_files(root, roots))
        _json.dump(callgraph.build(project).to_debug_json(), sys.stdout,
                   indent=1, sort_keys=True)
        print()
        return 0

    rules = None
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
        rules = [r for r in all_rules() if r.id in wanted or r.name in wanted]
        missing = wanted - {r.id for r in rules} - {r.name for r in rules}
        if missing:
            print(f"unknown rule(s): {', '.join(sorted(missing))}", file=sys.stderr)
            return 2

    baseline = Baseline.load(baseline_path)
    try:
        result = run_lint(
            root,
            rules=rules,
            roots=roots,
            baseline=baseline,
            changed_only=args.changed_only,
        )
    except Exception as e:  # noqa: BLE001 - CLI boundary
        print(f"arroyolint internal error: {e!r}", file=sys.stderr)
        return 2

    if args.update_baseline:
        merged = Baseline.from_findings(result.findings)
        # keep still-matching grandfathered entries (and their justifications)
        matched = {(f.rule, f.path, f.message) for f in result.grandfathered}
        merged.entries.extend(
            e for e in baseline.entries
            if (e["rule"], e["path"], e["message"]) in matched
        )
        merged.save(baseline_path)
        print(f"baseline updated: {len(merged.entries)} entries -> "
              f"{baseline_path}")
        print("every new entry needs a human-written `justification` before "
              "--strict accepts it")
        return 0

    if args.sarif:
        if args.sarif == "-":
            report_sarif(result, sys.stdout)
        else:
            with open(args.sarif, "w") as f:
                report_sarif(result, f)
            print(f"sarif report written to {args.sarif}", file=sys.stderr)

    if args.as_json:
        report_json(result, sys.stdout)
    elif args.sarif != "-":  # '-' owns stdout: SARIF must stay parseable
        report_text(result, sys.stdout, verbose=args.verbose)

    if args.strict:
        if baseline.unjustified():
            print(f"--strict: {len(baseline.unjustified())} baseline "
                  "entry(ies) lack a justification", file=sys.stderr)
        return 0 if result.strict_ok(baseline) else 1
    return 0 if result.clean else 1


if __name__ == "__main__":
    sys.exit(main())
