"""Device execution tiers under faults (VERDICT r5 item 2, scoped slice):
a representative golden subset — ≥6 queries including one session window
and one updating query — with `tpu.require_accelerator` forced OFF (device
kernels engage on the CPU-jax backend) and the device directory on, plus
one checkpoint/kill/restore cycle through the device-tier paths.

Gated behind ARROYO_DEVICE_TIER_FAULTS=1 (or `-m device_tier` after
setting it): the XLA compiles make this subset too heavy for tier-1, and
the device tiers are exercised compile-free elsewhere in the suite.

    ARROYO_DEVICE_TIER_FAULTS=1 python -m pytest tests/test_device_tier_faults.py -q
"""

import asyncio
import os

import pytest

from arroyo_tpu import chaos
from arroyo_tpu.chaos import drill
from arroyo_tpu.config import update
from arroyo_tpu.engine import Engine
from arroyo_tpu.sql import plan_query

pytestmark = [
    pytest.mark.device_tier,
    pytest.mark.skipif(
        not os.environ.get("ARROYO_DEVICE_TIER_FAULTS"),
        reason="set ARROYO_DEVICE_TIER_FAULTS=1 to run device-tier fault "
        "coverage (XLA-compile heavy)",
    ),
]

# ≥6 goldens: windowed aggregates (tumble/hop), one SESSION window, one
# UPDATING query, a join, and a distinct aggregate — the surfaces the
# device kernels (scatter-reduce accumulators, device directory, device
# join probe) actually specialize
DEVICE_TIER_QUERIES = (
    "hourly_by_event_type",    # tumbling window aggregate
    "sliding_window_end",      # hopping window
    "session_window",          # session window (required by the issue)
    "updating_aggregate",      # updating query (required by the issue)
    "offset_impulse_join",     # windowed join
    "distinct_aggregates",     # distinct accumulator path
    "grouped_aggregates",      # updating debezium aggregate
)

DEVICE_TIER_CONFIG = {
    "enabled": True,
    "require_accelerator": False,  # engage device kernels on CPU-jax
    "device_directory": True,
    "device_directory_audit": True,  # catch 64-bit hash merges loudly
}


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    chaos.clear()
    yield
    chaos.clear()


def _golden(name):
    return os.path.join(drill.DEFAULT_GOLDEN_DIR, "queries", f"{name}.sql")


@pytest.mark.parametrize("name", DEVICE_TIER_QUERIES)
def test_device_tier_golden(name, tmp_path):
    """Each golden must match its committed output with the device tiers
    forced on — identical semantics to the host paths."""
    query_path = _golden(name)
    headers = drill.query_headers(query_path)
    drill.register_query_udfs(headers, drill.DEFAULT_GOLDEN_DIR)
    out = str(tmp_path / "out.json")
    sql = drill.load_query(query_path, out, drill.DEFAULT_GOLDEN_DIR)

    async def go():
        eng = Engine(plan_query(sql, parallelism=2).graph).start()
        await eng.join(120)

    with update(tpu=DEVICE_TIER_CONFIG):
        asyncio.run(go())
    got = drill.canonicalize_output(out, sql, headers)
    golden_file = os.path.join(
        drill.DEFAULT_GOLDEN_DIR, "golden_outputs", f"{name}.json"
    )
    want = [line.strip() for line in open(golden_file)]
    assert got == want, f"{name}: device-tier output diverged from golden"


def test_device_tier_checkpoint_kill_restore(tmp_path):
    """One checkpoint/kill/restore cycle with the device tiers on: a
    worker SIGKILL mid-window through the embedded cluster, restore from
    the durable checkpoint, output identical to the fault-free run —
    device accumulator state must round-trip through checkpoints."""

    def kill_plan(seed):
        from arroyo_tpu.chaos import FaultPlan

        return FaultPlan(seed).add("worker.kill", at_hits=(10,))

    with update(tpu=DEVICE_TIER_CONFIG):
        res = drill.run_drill(
            "hourly_by_event_type", seed=99, workdir=str(tmp_path),
            plan_factory=kill_plan,
        )
    assert res.passed, res.error
    assert res.restarts >= 1
