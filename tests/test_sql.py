"""SQL frontend: parse, plan, and execute queries end-to-end."""

import asyncio

import pyarrow as pa
import pytest

from arroyo_tpu.config import update
from arroyo_tpu.engine import Engine
from arroyo_tpu.sql import plan_query
from arroyo_tpu.sql.lexer import SqlError
from arroyo_tpu.sql.parser import parse_statements
from arroyo_tpu.sql.ast import CreateTable, CreateView, Insert

MS = 1_000_000

IMPULSE_DDL = """
CREATE TABLE impulse (
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'impulse',
  event_rate = '1000000',
  message_count = '10000',
  start_time = '0'
);
"""


def run_sql(sql, parallelism=1, timeout=60.0):
    results = []
    plan = plan_query(sql, parallelism=parallelism, preview_results=results)

    async def go():
        eng = Engine(plan.graph).start()
        await eng.join(timeout)

    asyncio.run(go())
    return results


# -- parser -----------------------------------------------------------------


def test_parse_nexmark_q5():
    # the committed fixture mirrors the reference's
    # arroyo-sql-testing/src/test/queries/nexmark_q5.sql; prefer the
    # reference checkout when present, else resolve our own copy so the
    # test doesn't depend on a path outside the repo
    import os

    candidates = [
        "/root/reference/crates/arroyo-sql-testing/src/test/queries/"
        "nexmark_q5.sql",
        os.path.join(os.path.dirname(__file__), "golden", "queries",
                     "nexmark_q5.sql"),
    ]
    path = next((p for p in candidates if os.path.exists(p)), None)
    if path is None:
        import pytest

        pytest.skip("nexmark_q5.sql fixture not found")
    sql = open(path).read()
    stmts = parse_statements(sql)
    assert len(stmts) == 3
    assert isinstance(stmts[0], CreateTable)
    assert stmts[0].options["connector"] == "single_file"
    assert isinstance(stmts[2], Insert)


def test_parse_views_and_intervals():
    stmts = parse_statements(
        """
        CREATE VIEW v AS (SELECT * FROM t WHERE x == 1);
        SELECT tumble(interval '1' HOUR) as w, count(*) FROM v GROUP BY 1;
        """
    )
    assert isinstance(stmts[0], CreateView)
    sel = stmts[1]
    assert sel.group_by and len(sel.items) == 2


def test_parse_error_has_position():
    with pytest.raises(SqlError, match="offset"):
        parse_statements("SELECT FROM WHERE")


# -- execution --------------------------------------------------------------


def test_select_projection_filter():
    rows = run_sql(
        IMPULSE_DDL
        + "SELECT counter * 2 AS double, counter FROM impulse WHERE counter < 5;"
    )
    assert sorted(r["double"] for r in rows) == [0, 2, 4, 6, 8]
    assert all(r["double"] == 2 * r["counter"] for r in rows)


def test_tumbling_aggregate_with_window_access():
    rows = run_sql(
        IMPULSE_DDL
        + """
        SELECT window.start as s, window.end as e, cnt, total FROM (
          SELECT tumble(interval '1 millisecond') as window,
                 count(*) as cnt, sum(counter) as total
          FROM impulse
          GROUP BY 1
        );
        """
    )
    assert len(rows) == 10
    rows.sort(key=lambda r: r["s"])
    for i, r in enumerate(rows):
        assert r["cnt"] == 1000
        lo = i * 1000
        assert r["total"] == sum(range(lo, lo + 1000))
        assert (r["e"] - r["s"]).total_seconds() == 0.001


def test_grouped_aggregate_parallel():
    with update(pipeline={"source_batch_size": 256}):
        rows = run_sql(
            IMPULSE_DDL
            + """
            SELECT counter % 4 as k, tumble(interval '2 millisecond') as w,
                   count(*) as cnt, min(counter) as lo, max(counter) as hi,
                   avg(counter) as mean
            FROM impulse
            GROUP BY 1, 2;
            """,
            parallelism=2,
        )
    # 10ms data / 2ms windows = 5 windows x 4 keys
    assert len(rows) == 20
    for r in rows:
        assert r["cnt"] == 500
        assert r["lo"] % 4 == r["k"] and r["hi"] % 4 == r["k"]
        assert r["mean"] == pytest.approx((r["lo"] + r["hi"]) / 2)


def test_having_filters_groups():
    rows = run_sql(
        IMPULSE_DDL
        + """
        SELECT counter % 3 as k, tumble(interval '10 millisecond') as w,
               count(*) as cnt
        FROM impulse
        GROUP BY 1, 2
        HAVING count(*) > 3333;
        """
    )
    assert len(rows) == 1  # counts: k=0 -> 3334, k=1/k=2 -> 3333
    assert rows[0]["k"] == 0 and rows[0]["cnt"] == 3334


def test_windowed_join_with_residual():
    """nexmark-q5 shape: windowed counts joined with windowed max."""
    rows = run_sql(
        IMPULSE_DDL
        + """
        SELECT AuctionBids.k, AuctionBids.num
        FROM (
          SELECT counter % 4 as k, count(*) AS num,
                 hop(interval '2 millisecond', interval '4 millisecond') as window
          FROM impulse
          GROUP BY 1, window
        ) AS AuctionBids
        JOIN (
          SELECT max(CountBids.num) AS maxn, CountBids.window
          FROM (
            SELECT counter % 4 as k, count(*) AS num,
                   hop(interval '2 millisecond', interval '4 millisecond') as window
            FROM impulse
            GROUP BY 1, window
          ) AS CountBids
          GROUP BY CountBids.window
        ) AS MaxBids
        ON AuctionBids.window = MaxBids.window
           AND AuctionBids.num >= MaxBids.maxn;
        """
    )
    # every window: 4 keys with equal counts -> all rows are max
    assert len(rows) > 0
    # windows: hop windows over 10ms of data with 2ms slide
    # all keys tie for max in each window, so count % 4 == 0
    assert len(rows) % 4 == 0


def test_windowed_left_join_residual_null_pads():
    """LEFT JOIN residuals carry ON-clause semantics: a left row whose
    matches all fail the residual emits null-padded instead of being
    dropped, and null-padded rows survive a null-valued residual."""
    rows = run_sql(
        IMPULSE_DDL
        + """
        SELECT A.k as k, B.num as bnum
        FROM (
          SELECT counter % 4 as k, count(*) as num,
                 tumble(interval '10 millisecond') as w
          FROM impulse GROUP BY 1, w
        ) A
        LEFT JOIN (
          SELECT counter % 4 as k, count(*) as num,
                 tumble(interval '10 millisecond') as w
          FROM impulse GROUP BY 1, w
        ) B
        ON A.w = B.w AND A.k = B.k AND B.k < 2;
        """
    )
    matched = sorted(r["k"] for r in rows if r["bnum"] is not None)
    padded = sorted(r["k"] for r in rows if r["bnum"] is None)
    assert matched and set(matched) == {0, 1}
    assert padded and set(padded) == {2, 3}
    assert len(matched) == len(padded)


def test_windowed_full_join_residual_null_pads_both_sides():
    """FULL JOIN with an always-false residual emits every row of both
    sides null-padded (previously: emitted nothing)."""
    rows = run_sql(
        IMPULSE_DDL
        + """
        SELECT A.num as anum, B.num as bnum
        FROM (
          SELECT counter % 2 as k, count(*) as num,
                 tumble(interval '10 millisecond') as w
          FROM impulse GROUP BY 1, w
        ) A
        FULL JOIN (
          SELECT counter % 4 as k, count(*) as num,
                 tumble(interval '10 millisecond') as w
          FROM impulse GROUP BY 1, w
        ) B
        ON A.w = B.w AND A.k = B.k AND A.num < 0;
        """
    )
    assert rows
    left_only = [r for r in rows if r["bnum"] is None and r["anum"] is not None]
    right_only = [r for r in rows if r["anum"] is None and r["bnum"] is not None]
    assert not [r for r in rows if r["anum"] is not None and r["bnum"] is not None]
    # per window: A has 2 groups, B has 4 groups, all preserved unmatched
    assert len(left_only) * 2 == len(right_only)


def test_union_all():
    rows = run_sql(
        IMPULSE_DDL
        + """
        SELECT counter FROM impulse WHERE counter < 3
        UNION ALL
        SELECT counter FROM impulse WHERE counter >= 9997;
        """
    )
    assert sorted(r["counter"] for r in rows) == [0, 1, 2, 9997, 9998, 9999]


def test_view_and_cte():
    rows = run_sql(
        IMPULSE_DDL
        + """
        CREATE VIEW odd AS SELECT * FROM impulse WHERE counter % 2 == 1;
        WITH small AS (SELECT * FROM odd WHERE counter < 10)
        SELECT counter FROM small;
        """
    )
    assert sorted(r["counter"] for r in rows) == [1, 3, 5, 7, 9]


def test_count_distinct_two_stage():
    rows = run_sql(
        IMPULSE_DDL
        + """
        SELECT tumble(interval '5 millisecond') as w,
               count(distinct counter % 10) as dk
        FROM impulse
        GROUP BY 1;
        """
    )
    assert len(rows) == 2
    assert all(r["dk"] == 10 for r in rows)


def test_count_distinct_mixed_with_aggregates():
    """count(DISTINCT) alongside regular aggregates: two-branch rewrite
    joined on (window, keys), including expressions over both."""
    rows = run_sql(
        IMPULSE_DDL
        + """
        SELECT counter % 2 as k, count(distinct counter % 100) as d,
               count(*) as c, sum(counter % 10) as s
        FROM impulse GROUP BY 1, tumble(interval '5 millisecond');
        """
    )
    got = sorted((r["k"], r["d"], r["c"], r["s"]) for r in rows)
    assert got == [(0, 50, 2500, 10000), (0, 50, 2500, 10000),
                   (1, 50, 2500, 12500), (1, 50, 2500, 12500)]
    rows = run_sql(
        IMPULSE_DDL
        + """
        SELECT counter % 4 as k,
               count(distinct counter % 8) * 1000 / count(*) as ratio,
               max(counter) as mx
        FROM impulse GROUP BY 1, tumble(interval '10 millisecond')
        HAVING max(counter) > 9995;
        """
    )
    got = sorted((r["k"], r["ratio"], r["mx"]) for r in rows)
    assert got == [(0, 0, 9996), (1, 0, 9997), (2, 0, 9998), (3, 0, 9999)]


def test_case_and_scalar_functions():
    rows = run_sql(
        IMPULSE_DDL
        + """
        SELECT counter,
               CASE WHEN counter % 2 = 0 THEN 'even' ELSE 'odd' END as parity,
               abs(counter - 5) as dist
        FROM impulse WHERE counter < 4;
        """
    )
    rows.sort(key=lambda r: r["counter"])
    assert [r["parity"] for r in rows] == ["even", "odd", "even", "odd"]
    assert [r["dist"] for r in rows] == [5, 4, 3, 2]


def test_python_udf():
    from arroyo_tpu.udf import udf

    @udf(pa.int64(), [pa.int64()])
    def triple(xs):
        return xs * 3

    rows = run_sql(
        IMPULSE_DDL + "SELECT triple(counter) as t FROM impulse WHERE counter < 3;"
    )
    assert sorted(r["t"] for r in rows) == [0, 3, 6]


def test_unknown_column_error():
    with pytest.raises(SqlError, match="unknown column nope"):
        plan_query(IMPULSE_DDL + "SELECT nope FROM impulse;")


def test_unknown_table_error():
    with pytest.raises(SqlError, match="unknown table ghost"):
        plan_query("SELECT x FROM ghost;")


def test_subplan_cache_invalidated_on_catalog_change():
    """Common-subplan cache must not survive a catalog mutation: a later
    statement redefining a table name would otherwise reuse a plan bound
    to the old definition (advisor round-2 finding)."""
    from types import SimpleNamespace

    from arroyo_tpu.sql.planner import Planner, SchemaProvider

    p = Planner(SchemaProvider())
    calls = []

    def fake_plan_select(sel):
        calls.append(sel)
        return object()

    p.plan_select = fake_plan_select

    class Sel:
        def __repr__(self):
            return "SELECT x FROM t"

    sel = Sel()
    out1 = p._plan_select_shared(sel)
    assert p._plan_select_shared(sel) is out1 and len(calls) == 1
    p.provider.add_table(SimpleNamespace(name="t"))
    out2 = p._plan_select_shared(sel)
    assert out2 is not out1 and len(calls) == 2
    p.provider.add_view("v", sel)
    assert p._plan_select_shared(sel) is not out2 and len(calls) == 3


def test_source_cache_invalidated_on_catalog_change():
    """The bare-table-name source cache must also drop on a catalog epoch
    bump: a Planner driven statement-by-statement across an add_table
    redefining a name would otherwise reuse the stale source plan
    (advisor round-3 finding)."""
    from types import SimpleNamespace

    from arroyo_tpu.sql.planner import Planner, SchemaProvider

    p = Planner(SchemaProvider())
    p._source_cache["t"] = object()

    class Sel:
        def __repr__(self):
            return "SELECT 1"

    p.plan_select = lambda sel: object()
    p._plan_select_shared(Sel())          # same epoch: cache survives
    assert "t" in p._source_cache
    p.provider.add_table(SimpleNamespace(name="t"))
    p._plan_select_shared(Sel())          # epoch bump: cache dropped
    assert "t" not in p._source_cache
