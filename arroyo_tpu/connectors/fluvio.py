"""Placeholder: fluvio connector lands with the connector milestone."""
