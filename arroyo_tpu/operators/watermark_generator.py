"""Watermark generator operator.

Capability parity with the reference's watermark_generator.rs
(/root/reference/crates/arroyo-worker/src/arrow/watermark_generator.rs):
watermark = max(_timestamp seen) - allowed_lateness interval, emitted as the
data flows; idleness detection emits Watermark::Idle after `idle_time`
without data so an empty partition doesn't hold back the pipeline; the
end-of-time watermark is emitted on EndOfData so all windows flush; the max
watermark is persisted per-subtask in global state and restored.
"""

from __future__ import annotations

import time
from typing import Optional

from ..graph.logical import OperatorName
from ..engine.construct import register_operator
from ..types import Watermark, WATERMARK_END
from .base import Operator


class WatermarkGenerator(Operator):
    # conservation ledger: every data batch passes through unchanged —
    # watermarks travel out-of-band via the runner's signal chain
    flow_class = "exact"

    def __init__(
        self,
        interval_nanos: int = 0,
        idle_time: Optional[float] = None,
        period_nanos: int = 0,
    ):
        super().__init__("watermark")
        self.interval = interval_nanos  # lateness allowance subtracted
        self.idle_time = idle_time
        self.period = period_nanos  # min watermark advance between emissions
        self.max_ts: Optional[int] = None
        self.last_emitted: Optional[int] = None
        self.last_data_at = time.monotonic()
        self.idle = False

    def tables(self):
        from ..state.table_config import global_table

        return {"w": global_table("w")}

    async def on_start(self, ctx):
        if ctx.table_manager is not None:
            table = await ctx.table(("w"))
            stored = table.get(ctx.task_info.task_index)
            if stored is not None:
                self.max_ts = stored
                self.last_emitted = None  # re-emit after restore

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        # locate _timestamp in the batch itself: chained upstream ops may
        # have reshaped the schema relative to the node's in-edge
        import pyarrow as pa

        from ..schema import TIMESTAMP_FIELD

        if TIMESTAMP_FIELD not in batch.schema.names or batch.num_rows == 0:
            await collector.collect(batch)
            return
        col = batch.column(batch.schema.names.index(TIMESTAMP_FIELD))
        m = int(pa.compute.max(col.cast(pa.int64())).as_py())
        if self.max_ts is None or m > self.max_ts:
            self.max_ts = m
        self.last_data_at = time.monotonic()
        self.idle = False
        await collector.collect(batch)
        wm = self.max_ts - self.interval
        if self.last_emitted is None or wm - self.last_emitted >= self.period:
            self.last_emitted = wm
            await self._emit(ctx, Watermark.event_time(wm))

    async def _emit(self, ctx, wm: Watermark):
        # inject into the chain *after* this operator and broadcast
        runner = _runner_of(ctx)
        if runner is not None:
            idx = runner.ops.index(self)
            await runner._chain_watermark(idx + 1, wm)

    def tick_interval(self) -> Optional[float]:
        return self.idle_time / 2 if self.idle_time else None

    async def handle_tick(self, tick, ctx, collector):
        if (
            self.idle_time
            and not self.idle
            and time.monotonic() - self.last_data_at > self.idle_time
        ):
            self.idle = True
            await self._emit(ctx, Watermark.idle())

    async def handle_checkpoint(self, barrier, ctx, collector):
        if ctx.table_manager is not None and self.max_ts is not None:
            table = await ctx.table("w")
            table.put(ctx.task_info.task_index, self.max_ts)

    async def on_close(self, ctx, collector, is_eod: bool):
        if is_eod:
            return Watermark.event_time(WATERMARK_END)
        return None


def _runner_of(ctx):
    # the runner stashes itself on source contexts; for mid-chain watermark
    # generators we find it via the context's back-reference set at build
    return getattr(ctx, "_runner", None)


@register_operator(OperatorName.EXPRESSION_WATERMARK)
def _make_watermark(config: dict) -> Operator:
    return WatermarkGenerator(
        interval_nanos=int(config.get("interval_nanos", 0)),
        idle_time=config.get("idle_time"),
        period_nanos=int(config.get("period_nanos", 0)),
    )
