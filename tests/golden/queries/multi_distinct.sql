CREATE TABLE cars (
  timestamp TIMESTAMP,
  driver_id BIGINT,
  event_type TEXT,
  location TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/cars.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE out (
  minute TIMESTAMP,
  drivers BIGINT,
  locations BIGINT,
  events BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO out
SELECT window.start, drivers, locations, events FROM (
  SELECT tumble(interval '1 minute') as window,
         count(DISTINCT driver_id) as drivers,
         count(DISTINCT location) as locations,
         count(*) as events
  FROM cars
  GROUP BY 1
);
