"""State table implementations.

Capability parity with the reference's table kinds
(/root/reference/crates/arroyo-state/src/tables/):
  * GlobalKeyedTable (global_keyed_map.rs:47): small KV, each subtask writes
    its entries; on restore every subtask sees the union (replication), so
    rescaled operators can filter by key range themselves.
  * ExpiringTimeKeyTable (expiring_time_key_map.rs:53): RecordBatch rows
    bucketed by event time, retention-pruned, key-range filtered on restore;
    checkpoints are incremental (only rows added since the last epoch are
    written; the cumulative live-file list rides in the metadata).
Values are msgpack-encoded (the reference uses bincode).

State-at-scale extensions (ROADMAP item 4):
  * GlobalTable checkpoints are incremental: put/delete mark dirty keys and
    tombstones, serialize_delta emits only the changed entries, and the
    manifest carries a blob *chain* (base + deltas) per (table, subtask)
    that restore replays in epoch order. Entries are epoch-stamped so the
    cross-subtask union is deterministic: replication re-persists every
    subtask's view, and without stamps a STALE copy of key k (written by a
    peer that restored it long ago) could win the restore merge over the
    owner's fresh value depending on blob load order.
  * TimeKeyTable has a disk spill tier: once in-memory batches exceed
    `state.memory_budget_bytes`, the coldest batches (lowest max event
    time) are spooled to local Arrow-IPC spill files and memory-mapped
    back only when expiry/emission/restore needs them. Spilled rows are
    checkpoint-free — the cumulative live-file list already persisted
    them — so spill bounds RAM without touching the durability story.
"""

from __future__ import annotations

import os
import tempfile
import uuid
from typing import Any, Dict, List, Optional, Tuple

import msgpack
import numpy as np
import pyarrow as pa

from ..types import server_for_hash_array
from ..utils.logging import get_logger
from .table_config import TableConfig

logger = get_logger("state.tables")

_DEAD = object()  # merge-time tombstone marker


class GlobalTable:
    """KV map; put/get are synchronous in-memory, persistence happens at
    checkpoint via incremental delta blobs (serialize_delta)."""

    def __init__(self, config: TableConfig):
        self.config = config
        self.data: Dict[Any, Any] = {}
        self.restored: Dict[Any, Any] = {}  # union of all subtasks' entries
        # epoch each key's entry last changed (loaded from blobs; dirty
        # keys are stamped at capture) — the restore-merge tie breaker
        self._stamps: Dict[Any, int] = {}
        # keys whose restore-merge candidate is currently a tombstone
        self._restore_tombs: Dict[Any, int] = {}
        # keys present at load time: a delete of one of these needs its
        # tombstone carried in the next BASE too (a peer's base may still
        # hold a stale copy); keys born and deleted within this
        # incarnation never left this process, so their tombstones can be
        # dropped once the chain rebases
        self._restored_keys: set = set()
        self._dirty: set = set()
        self._dead: Dict[Any, Optional[int]] = {}  # key -> tombstone epoch
        self._has_base = False
        self._approx_bytes = 0  # last serialized size (obs)

    def get(self, key, default=None):
        if key in self.data:
            return self.data[key]
        return self.restored.get(key, default)

    def put(self, key, value):
        self.data[key] = value
        self._dirty.add(key)
        self._dead.pop(key, None)

    def delete(self, key):
        existed = key in self.data or key in self.restored
        self.data.pop(key, None)
        self.restored.pop(key, None)
        self._dirty.discard(key)
        if existed:
            self._dead[key] = None  # stamped at the next capture

    def retain(self, pred):
        """Drop every key where pred(key) is false, WITHOUT tombstones:
        the caller asserts those keys are owned (and re-persisted) by
        other subtasks — rescale-aware keyed operators call this after
        restore so each subtask's chain only carries its own key range
        (which also lets rebase drop tombstones for churned keys)."""
        for k in [k for k in self.data if not pred(k)]:
            del self.data[k]
            self._dirty.discard(k)
        for k in [k for k in self.restored if not pred(k)]:
            del self.restored[k]
            self._stamps.pop(k, None)
            self._restored_keys.discard(k)

    def all_values(self) -> List[Any]:
        """Union view (restored entries from every subtask + local writes);
        used by rescale-aware operators to re-filter by key range."""
        merged = dict(self.restored)
        merged.update(self.data)
        return list(merged.values())

    def items(self):
        merged = dict(self.restored)
        merged.update(self.data)
        return merged.items()

    def state_size(self) -> Tuple[int, int]:
        """(approx bytes as of the last serialization, live entries)."""
        return self._approx_bytes, len(self.restored | self.data)

    # -- persistence --------------------------------------------------------

    def serialize(self) -> bytes:
        """Full-snapshot view (legacy/debug; does NOT clear dirty state)."""
        merged = dict(self.restored)
        merged.update(self.data)
        return msgpack.packb(
            {"v": 2, "b": True,
             "e": [[k, v, self._stamps.get(k, 0)] for k, v in merged.items()],
             "t": []},
            use_bin_type=True,
        )

    def serialize_delta(self, epoch: int,
                        force_base: bool = False) -> Tuple[Optional[bytes], bool]:
        """Capture this epoch's blob: (blob, is_base).

        The first capture of an incarnation (or a rebase) emits a base —
        the full merged map; afterwards only dirty entries + tombstones
        ride, so capture cost is O(dirty), not O(total). Returns
        (None, False) when nothing changed (the chain is reused as-is).
        Clears the dirty/tombstone sets: the caller owns flushing the
        blob (a failed flush fails the task, and recovery restores from
        the last published manifest)."""
        for k in self._dirty:
            self._stamps[k] = epoch
        for k, st in self._dead.items():
            if st is None:
                self._dead[k] = epoch
        if force_base or not self._has_base:
            merged = dict(self.restored)
            merged.update(self.data)
            # tombstones survive a rebase only for keys that predate this
            # incarnation (a peer's stale copy may still carry them)
            tombs = [
                [k, st] for k, st in self._dead.items()
                if k in self._restored_keys
            ]
            blob = msgpack.packb(
                {"v": 2, "b": True,
                 "e": [[k, v, self._stamps.get(k, epoch)]
                       for k, v in merged.items()],
                 "t": tombs},
                use_bin_type=True,
            )
            self._dirty.clear()
            self._dead.clear()
            self._has_base = True
            self._approx_bytes = len(blob)
            return blob, True
        if not self._dirty and not self._dead:
            return None, False
        entries = []
        for k in self._dirty:
            if k in self.data:
                entries.append([k, self.data[k], self._stamps[k]])
            elif k in self.restored:
                entries.append([k, self.restored[k], self._stamps[k]])
        tombs = [[k, st] for k, st in self._dead.items()]
        blob = msgpack.packb(
            {"v": 2, "b": False, "e": entries, "t": tombs},
            use_bin_type=True,
        )
        self._dirty.clear()
        self._dead.clear()
        return blob, False

    def load(self, blobs: List[bytes]):
        """Legacy entry: one flat list of blobs (treated as one chain)."""
        self.load_chain(blobs)

    def load_chain(self, blobs: List[bytes]):
        """Replay ONE subtask's blob chain in epoch order, merging into
        the union view. Cross-chain conflicts (replicated stale copies)
        resolve by entry stamp: the highest stamp wins; a tombstone kills
        entries up to its stamp. Call once per subtask chain."""
        for blob in blobs:
            obj = msgpack.unpackb(blob, raw=False, strict_map_key=False)
            if isinstance(obj, list):
                # pre-chain format: [[k, v], ...] full snapshot, stamp 0
                for k, v in obj:
                    self._merge_entry(_hashable(k), v, 0)
                continue
            for ent in obj.get("e", ()):
                k, v, stamp = ent[0], ent[1], ent[2] if len(ent) > 2 else 0
                self._merge_entry(_hashable(k), v, stamp)
            for k, stamp in obj.get("t", ()):
                self._merge_tomb(_hashable(k), stamp)
        self._restored_keys = set(self.restored)

    def _merge_entry(self, k, v, stamp: int):
        if self._restore_tombs.get(k, -1) > stamp:
            return  # deleted later than this entry was written
        if k in self.restored and self._stamps.get(k, 0) > stamp:
            return  # a fresher replica already merged
        self._restore_tombs.pop(k, None)
        self.restored[k] = v
        self._stamps[k] = stamp

    def _merge_tomb(self, k, stamp: int):
        if k in self.restored and self._stamps.get(k, 0) > stamp:
            return  # entry re-written after the delete
        self.restored.pop(k, None)
        self._stamps.pop(k, None)
        if stamp > self._restore_tombs.get(k, -1):
            self._restore_tombs[k] = stamp


def _hashable(k):
    return tuple(_hashable(x) for x in k) if isinstance(k, list) else k


# -- time-key spill tier ------------------------------------------------------


_SPILL_DIR: Optional[str] = None


def _spill_dir() -> str:
    """Per-process spill scratch directory (state.spill_dir or tempdir)."""
    global _SPILL_DIR
    if _SPILL_DIR is None:
        from ..config import config

        base = config().state.spill_dir or os.path.join(
            tempfile.gettempdir(), "arroyo-tpu-spill"
        )
        _SPILL_DIR = os.path.join(base, f"pid{os.getpid()}")
        os.makedirs(_SPILL_DIR, exist_ok=True)
    return _SPILL_DIR


def _batch_nbytes(batch: pa.RecordBatch) -> int:
    try:
        return batch.nbytes
    except Exception:  # noqa: BLE001 - exotic buffers
        return batch.num_rows * 64


class _Entry:
    """One buffered batch + its event-time metadata. `batch` is None once
    spilled; `path` points at the Arrow-IPC spill file then."""

    __slots__ = ("batch", "path", "min_ts", "max_ts", "rows", "nbytes")

    def __init__(self, batch: pa.RecordBatch, min_ts: int, max_ts: int):
        self.batch: Optional[pa.RecordBatch] = batch
        self.path: Optional[str] = None
        self.min_ts = min_ts
        self.max_ts = max_ts
        self.rows = batch.num_rows
        self.nbytes = _batch_nbytes(batch)

    @property
    def spilled(self) -> bool:
        return self.batch is None

    def spill(self) -> int:
        """Write the batch to an Arrow-IPC file and drop the in-memory
        reference. Returns the bytes released."""
        if self.batch is None:
            return 0
        path = os.path.join(_spill_dir(), f"spill-{uuid.uuid4().hex}.arrow")
        with pa.OSFile(path, "wb") as f:
            with pa.ipc.new_file(f, self.batch.schema) as w:
                w.write_batch(self.batch)
        self.path = path
        self.batch = None
        return self.nbytes

    def load(self) -> pa.RecordBatch:
        """Materialize: memory-map the spill file (zero-copy; the OS pages
        rows in on demand) — spilled entries stay spilled (reading for an
        expiry scan or checkpoint must not re-inflate the budget)."""
        if self.batch is not None:
            return self.batch
        with pa.memory_map(self.path, "rb") as src:
            reader = pa.ipc.open_file(src)
            batches = [reader.get_batch(i) for i in range(reader.num_record_batches)]
        if len(batches) == 1:
            return batches[0]
        return pa.Table.from_batches(batches).combine_chunks().to_batches()[0]

    def unspill(self, batch: pa.RecordBatch):
        """Bring the entry back in-memory (post-restore rebuffering)."""
        self.batch = batch
        self.drop_file()

    def drop_file(self):
        if self.path is not None:
            try:
                os.unlink(self.path)
            except OSError:
                pass
            self.path = None

    def __del__(self):  # best-effort scratch cleanup
        self.drop_file()


class TimeKeyTable:
    """Event-time bucketed RecordBatch store with retention.

    In-memory view is the source of truth while running; checkpoints write
    the *delta* since the previous epoch as parquet and carry the cumulative
    file list forward, dropping files whose max_ts fell behind
    watermark - retention. Batches beyond `state.memory_budget_bytes`
    spill coldest-first to local Arrow-IPC files (see module docstring).
    """

    def __init__(self, config: TableConfig, stream_schema=None):
        from ..config import config as get_config

        self.config = config
        self.schema: Optional[pa.Schema] = None
        self._entries: List[_Entry] = []
        self._dirty: List[pa.RecordBatch] = []
        # carried checkpoint file metadata: [{"path", "min_ts", "max_ts"}]
        self.files: List[dict] = []
        st = get_config().state
        self._budget = int(st.memory_budget_bytes)
        self._compact_fraction = float(st.expire_compact_fraction)
        self._mem_bytes = 0
        self._spilled_bytes = 0

    # -- ingestion ----------------------------------------------------------

    def insert(self, batch: pa.RecordBatch, stage_dirty: bool = True):
        """Buffer a batch in the in-memory view (spilling cold state past
        the budget); by default also stage it for the next checkpoint
        delta. stage_dirty=False re-buffers rows that are already durable
        (restore, operator-internal moves)."""
        if batch.num_rows == 0:
            return
        if self.schema is None:
            self.schema = batch.schema
        ts = self._ts(batch)
        entry = _Entry(batch, int(ts.min()), int(ts.max()))
        self._entries.append(entry)
        self._mem_bytes += entry.nbytes
        if stage_dirty:
            self._dirty.append(batch)
        self._maybe_spill()

    def write_delta(self, batch):
        """Conduit write: stage a delta for the next checkpoint WITHOUT
        keeping it in the in-memory view. Operators whose in-memory source
        of truth lives elsewhere (accumulator slots, join buffers) use this
        so state isn't held twice. `batch` may be a RecordBatch or a
        zero-arg callable returning one — a thunk defers materialization
        (e.g. a dispatched device->host gather) to the flush phase."""
        if not callable(batch) and self.schema is None:
            self.schema = batch.schema
        self._dirty.append(batch)

    def prune_dirty(self, pred):
        """Drop staged (non-thunk) deltas failing pred(batch) — operators
        use it to skip persisting rows already emitted this epoch."""
        self._dirty = [
            b for b in self._dirty if callable(b) or pred(b)
        ]

    # -- views --------------------------------------------------------------

    def all_batches(self) -> List[pa.RecordBatch]:
        return [e.load() for e in self._entries]

    def entry_stats(self) -> Tuple[int, int, int, int]:
        """(in-memory bytes, spilled bytes, rows, batches) for obs."""
        rows = sum(e.rows for e in self._entries)
        return self._mem_bytes, self._spilled_bytes, rows, len(self._entries)

    def clear_batches(self):
        """Drop the in-memory view (conduit operators own the rows after
        restore); releases spill scratch files."""
        for e in self._entries:
            e.drop_file()
        self._entries = []
        self._mem_bytes = 0
        self._spilled_bytes = 0

    def take_bins_upto(self, cutoff: int) -> List[Tuple[int, pa.RecordBatch]]:
        """Pop every row with timestamp <= cutoff, returned as (ts, batch)
        bins sorted by ts (spilled entries are memory-mapped back only
        here — exactly when emission needs them). Rows above the cutoff
        stay buffered; entries wholly above it are never materialized."""
        out: List[Tuple[int, pa.RecordBatch]] = []
        keep: List[_Entry] = []
        for e in self._entries:
            if e.min_ts > cutoff:
                keep.append(e)
                continue
            batch = e.load()
            if e.spilled:
                self._spilled_bytes -= e.nbytes
            else:
                self._mem_bytes -= e.nbytes
            e.drop_file()
            ts = self._ts(batch)
            if e.max_ts > cutoff:
                live = ts > cutoff
                rest = batch.filter(pa.array(live))
                if rest.num_rows:
                    rts = ts[live]
                    e2 = _Entry(rest, int(rts.min()), int(rts.max()))
                    self._mem_bytes += e2.nbytes
                    keep.append(e2)
                batch = batch.filter(pa.array(~live))
                ts = ts[~live]
            out.extend(_split_by_ts(batch, ts))
        self._entries = keep
        self._maybe_spill()
        out.sort(key=lambda p: p[0])
        return out

    # -- retention ----------------------------------------------------------

    def expire(self, watermark_nanos: Optional[int]):
        """Drop whole batches whose max timestamp fell out of retention;
        batches mostly-dead but pinned by a live max timestamp are
        compacted row-level once their expired fraction exceeds
        `state.expire_compact_fraction` (long-retention skew otherwise
        keeps dead rows in RAM indefinitely)."""
        if watermark_nanos is None or self.config.retention_nanos is None:
            return
        cutoff = watermark_nanos - self.config.retention_nanos
        keep: List[_Entry] = []
        for e in self._entries:
            if e.max_ts < cutoff:
                # fully expired: drop without materializing
                if e.spilled:
                    self._spilled_bytes -= e.nbytes
                else:
                    self._mem_bytes -= e.nbytes
                e.drop_file()
                continue
            if (
                not e.spilled
                and e.min_ts < cutoff
                and self._compact_fraction <= 1.0
                and e.rows
            ):
                ts = self._ts(e.batch)
                mask = ts >= cutoff
                dead_frac = 1.0 - (mask.sum() / e.rows)
                if dead_frac > self._compact_fraction:
                    self._mem_bytes -= e.nbytes
                    filtered = e.batch.filter(pa.array(mask))
                    e2 = _Entry(filtered, int(ts[mask].min()),
                                e.max_ts)
                    self._mem_bytes += e2.nbytes
                    keep.append(e2)
                    continue
            keep.append(e)
        self._entries = keep

    def filter_expired(self, watermark_nanos: Optional[int]):
        """Row-level expiry (used on restore)."""
        if watermark_nanos is None or self.config.retention_nanos is None:
            return
        cutoff = watermark_nanos - self.config.retention_nanos
        out: List[_Entry] = []
        for e in self._entries:
            if e.min_ts >= cutoff:
                out.append(e)
                continue
            if e.max_ts < cutoff:
                if e.spilled:
                    self._spilled_bytes -= e.nbytes
                else:
                    self._mem_bytes -= e.nbytes
                e.drop_file()
                continue
            batch = e.load()
            ts = self._ts(batch)
            mask = ts >= cutoff
            if e.spilled:
                self._spilled_bytes -= e.nbytes
            else:
                self._mem_bytes -= e.nbytes
            e.drop_file()
            if mask.any():
                filtered = batch.filter(pa.array(mask))
                e2 = _Entry(filtered, int(ts[mask].min()), e.max_ts)
                self._mem_bytes += e2.nbytes
                out.append(e2)
        self._entries = out
        self._maybe_spill()

    def _maybe_spill(self):
        if not self._budget or self._mem_bytes <= self._budget:
            return
        # spill coldest-first (lowest max event time): expiry/emission
        # touches cold bins last... actually FIRST at drain time, but a
        # drain materializes them exactly once via mmap; the hot tail
        # (still being appended/probed) stays in RAM
        hot = sorted(
            (e for e in self._entries if not e.spilled),
            key=lambda e: e.max_ts,
        )
        for e in hot:
            if self._mem_bytes <= self._budget:
                break
            released = e.spill()
            self._mem_bytes -= released
            self._spilled_bytes += released
            logger.debug("spilled %d bytes (table %s)", released,
                         self.config.name)

    def _ts(self, batch: pa.RecordBatch) -> np.ndarray:
        idx = batch.schema.names.index(self.config.timestamp_field)
        return np.asarray(batch.column(idx).cast(pa.int64()))

    # -- persistence --------------------------------------------------------

    def take_dirty(self) -> Optional[pa.Table]:
        return self.resolve_staged(self.take_dirty_staged())

    def take_dirty_staged(self) -> list:
        """Detach the staged deltas without resolving thunks (capture
        phase; resolution — e.g. a pending device->host copy — happens in
        resolve_staged on the flush path)."""
        staged = self._dirty
        self._dirty = []
        return staged

    @staticmethod
    def resolve_staged(staged: list) -> Optional[pa.Table]:
        batches = []
        for b in staged:
            if callable(b):
                b = b()
            if b is not None and b.num_rows:
                batches.append(b)
        if not batches:
            return None
        return pa.Table.from_batches(batches)

    def live_files(self, watermark_nanos: Optional[int]) -> List[dict]:
        if watermark_nanos is None or self.config.retention_nanos is None:
            return list(self.files)
        cutoff = watermark_nanos - self.config.retention_nanos
        return [f for f in self.files if f["max_ts"] >= cutoff]

    def load_batches(self, batches: List[pa.RecordBatch], key_range=None,
                     key_indices: Optional[List[int]] = None,
                     parallelism: int = 1, task_index: int = 0):
        """Restore: ingest batches, filtering rows to this subtask's key
        range when key columns are declared (rescale support). Batches
        beyond the memory budget spill like live inserts."""
        from ..types import hash_arrays, hash_column

        for b in batches:
            if b.num_rows == 0:
                continue
            if self.config.key_fields and parallelism > 1:
                cols = []
                for name in self.config.key_fields:
                    i = b.schema.names.index(name)
                    col = b.column(i)
                    cols.append(hash_column(
                        col.to_numpy(zero_copy_only=False)))
                hashes = hash_arrays(cols)
                owners = server_for_hash_array(hashes, parallelism)
                mask = owners == task_index
                if not mask.any():
                    continue
                if not mask.all():
                    b = b.filter(pa.array(mask))
            if self.schema is None:
                self.schema = b.schema
            self.insert(b, stage_dirty=False)


def _split_by_ts(batch: pa.RecordBatch,
                 ts: np.ndarray) -> List[Tuple[int, pa.RecordBatch]]:
    """Split one batch into per-timestamp bins (stable order)."""
    if batch.num_rows == 0:
        return []
    uniq = np.unique(ts)
    if len(uniq) == 1:
        return [(int(uniq[0]), batch)]
    order = np.argsort(ts, kind="stable")
    sb = batch.take(pa.array(order))
    sts = ts[order]
    bounds = np.searchsorted(sts, uniq, side="left").tolist()
    bounds.append(len(sts))
    return [
        (int(t), sb.slice(bounds[i], bounds[i + 1] - bounds[i]))
        for i, t in enumerate(uniq)
    ]
