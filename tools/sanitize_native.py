#!/usr/bin/env python
"""ASan/UBSan build-and-run for the native slot directory.

SURVEY.md §5.2: the reference relies on Rust's ownership model for
memory safety; our host C++ (native/slotdir.cpp — hand-rolled open
addressing + manual refcounts on the hot path of every window operator)
gets sanitizers instead. This script:

  1. compiles slotdir.cpp with -fsanitize=address,undefined into a
     scratch directory,
  2. runs an exercise workload (random assign/take/get/entries cycles,
     single- and multi-key, growth past the initial capacity, freed-slot
     reuse) in a child python under LD_PRELOAD=libasan, verifying
     results against the pure-python SlotDirectory,
  3. exits nonzero on any sanitizer report or mismatch.

Wired into the suite as tests/test_native_sanitizer.py; run manually:
    python tools/sanitize_native.py
"""

import os
import subprocess
import sys
import sysconfig
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "native", "slotdir.cpp")

EXERCISE = r"""
import numpy as np

import arroyo_native  # the sanitized build (scratch dir is first on path)

from arroyo_tpu.ops.directory import SlotDirectory
from arroyo_tpu.ops.native import NativeSlotDirectory

rng = np.random.default_rng(17)
for n_keys in (1, 3):
    nat = NativeSlotDirectory(arroyo_native, n_keys=n_keys)
    ref = SlotDirectory()
    for step in range(60):
        n = int(rng.integers(1, 700))
        bins = rng.integers(0, 6, n)
        keys = [rng.integers(-5000, 5000, n) for _ in range(n_keys)]
        s_nat = nat.assign(bins, keys)
        s_ref = ref.assign(bins, keys)
        # same grouping structure (slot numbering may differ)
        import numpy as _np
        _, inv_a = _np.unique(s_nat, return_inverse=True)
        _, inv_b = _np.unique(s_ref, return_inverse=True)
        pairs = set(zip(inv_a.tolist(), inv_b.tolist()))
        assert len(pairs) == len({a for a, _ in pairs}) == len(
            {b for _, b in pairs}
        ), f"grouping diverged at step {step}"
        if step % 13 == 7:
            # reverse index resolves to the EXACT (bin, key) of the
            # input rows (a stale slot_owner after entry recycling is
            # the bug class this structure can have)
            kk = nat.keys_for_slots(s_nat[:50])
            for i, entry in enumerate(kk):
                assert entry is not None, f"live slot unresolved at {step}"
                got_bin, got_key = entry
                assert got_bin == int(bins[i]), f"wrong bin at {step}"
                assert got_key == tuple(
                    int(c[i]) for c in keys
                ), f"wrong key at {step}"
            # targeted removal; freed slots must then resolve to None
            b = int(rng.integers(0, 6))
            pk = ref.peek_bin(b) or {}
            victims = list(pk.keys())[:20]
            nat_map = nat.slots_for_keys(b, victims)
            assert set(nat_map) == set(victims), f"lookup at {step}"
            f_nat = nat.remove(b, victims)
            f_ref = ref.remove(b, victims)
            assert len(f_nat) == len(f_ref), f"remove at {step}"
            assert sorted(int(s) for s in f_nat) == sorted(
                nat_map.values()
            ), f"freed slots disagree with lookup at {step}"
            gone = nat.keys_for_slots(np.asarray(f_nat))
            assert all(g is None for g in gone), (
                f"freed slot still resolves at {step}"
            )
        if step % 7 == 3:
            b = int(rng.integers(0, 6))
            ka, sa = nat.take_bin(b)
            kb, sb = ref.take_bin(b)
            assert sorted(ka) == sorted(kb), f"take_bin keys at {step}"
        if step % 11 == 5:
            b = int(rng.integers(0, 6))
            ents = nat.bin_entries(b)
            pk = ref.peek_bin(b) or {}
            assert len(ents[1]) == len(pk), f"bin_entries at {step}"
        assert nat.n_live == ref.n_live, f"n_live at {step}"
    list(nat.items())  # exercise entries() buffers
print("SANITIZED-OK")
"""


def main() -> int:
    include = sysconfig.get_paths()["include"]
    libasan = subprocess.run(
        ["g++", "-print-file-name=libasan.so"], capture_output=True,
        text=True,
    ).stdout.strip()
    if not libasan or not os.path.exists(libasan):
        print("libasan not found; skipping", file=sys.stderr)
        return 2
    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(
            td, f"arroyo_native{sysconfig.get_config_var('EXT_SUFFIX')}"
        )
        cmd = [
            "g++", "-O1", "-g", "-std=c++17", "-shared", "-fPIC",
            "-fsanitize=address,undefined", "-fno-omit-frame-pointer",
            f"-I{include}", SRC, "-o", out,
        ]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        env = dict(os.environ)
        env["LD_PRELOAD"] = libasan
        # CPython leaks deliberately at exit; halt hard on real errors
        env["ASAN_OPTIONS"] = "detect_leaks=0:abort_on_error=1"
        env["UBSAN_OPTIONS"] = "halt_on_error=1:print_stacktrace=1"
        env["JAX_PLATFORMS"] = "cpu"
        for var in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
                    "PYTHONPATH"):
            env.pop(var, None)
        # td inserted LAST so the sanitized build shadows any repo-level
        # arroyo_native on the path
        script = (
            f"REPO = {REPO!r}\n"
            f"import sys; sys.path.insert(0, REPO); "
            f"sys.path.insert(0, {td!r})\n" + EXERCISE
        )
        proc = subprocess.run(
            [sys.executable, "-c", script], env=env, capture_output=True,
            text=True, timeout=300,
        )
        sys.stdout.write(proc.stdout)
        sys.stderr.write(proc.stderr)
        if proc.returncode != 0 or "SANITIZED-OK" not in proc.stdout:
            print(f"sanitizer run failed rc={proc.returncode}",
                  file=sys.stderr)
            return 1
    print("native sanitizer run clean (ASan+UBSan)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
