"""Shared deferred-jax bootstrap.

jax is imported lazily so host-only deployments can import the module
tree without pulling in the accelerator stack; every device-path module
must see the same config (x64 enabled — the engine's timestamps, keys
and integer accumulators are 64-bit)."""

from __future__ import annotations

_jax = None


def get_jax():
    global _jax
    if _jax is None:
        import jax

        jax.config.update("jax_enable_x64", True)
        _jax = jax
    return _jax
