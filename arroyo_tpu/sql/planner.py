"""SQL logical planner: statements -> LogicalGraph.

Capability parity with the reference's planner pipeline
(/root/reference/crates/arroyo-planner/src/lib.rs:789
parse_and_get_arrow_program + src/rewriters.rs + src/plan/*): CREATE TABLE
connector tables, views/CTEs, INSERT INTO sinks, source rewriting (event
time + watermark injection), projection/filter planning, window-TVF
aggregate detection (tumble/hop/session in GROUP BY, ordinals and aliases
resolved), window struct columns with .start/.end access, windowed
(instant) joins with residual predicates, expiring non-windowed joins,
unions, and sink wiring. Unsupported constructs raise SqlError with the
reference feature named, so gaps are visible rather than silent.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import pyarrow as pa

from ..graph.logical import (
    ChainedOp,
    EdgeType,
    LogicalGraph,
    LogicalNode,
    OperatorName,
)
from ..schema import StreamSchema, TIMESTAMP_FIELD, add_timestamp_field
from .ast import (
    BinaryOp,
    Column,
    CreateTable,
    CreateView,
    Expr,
    FieldAccess,
    FuncCall,
    Insert,
    Interval,
    Join,
    Literal,
    Relation,
    Select,
    SelectItem,
    Star,
    SubqueryRef,
    TableRef,
    Unnest,
)
from .expressions import BoundExpr, CompiledProjection, Scope, bind
from .lexer import SqlError
from .parser import parse_statements
from .types import WINDOW_TYPE, sql_type_to_arrow

AGG_FUNCS = {
    "count", "sum", "min", "max", "avg", "mean",
    # variance family (one argument)
    "var", "var_samp", "var_pop", "variance", "stddev", "stddev_samp",
    "stddev_pop",
    # regression/covariance family: two arguments (y, x)
    "covar", "covar_pop", "covar_samp", "corr", "regr_slope",
    "regr_intercept", "regr_r2", "regr_avgx", "regr_avgy", "regr_count",
    "regr_sxx", "regr_syy", "regr_sxy",
    # boolean reductions
    "bool_and", "bool_or",
    # buffered builtins
    "median", "approx_median", "approx_distinct", "approx_percentile_cont",
    "approx_percentile_cont_with_weight", "bit_and", "bit_or", "bit_xor",
    "array_agg",
}
# canonical kind per alias (the rest map to themselves)
AGG_ALIASES = {"mean": "avg", "variance": "var", "covar": "covar_samp"}
# the variance/regression families decompose to pure add-reductions
# (Σx, Σx², Σxy, n), so they invert under retraction like count/sum/avg
from ..ops.aggregates import (  # noqa: E402
    REGR_KINDS as REGR_KINDS_SQL,
    VAR_KINDS as VAR_KINDS_SQL,
)
# two-argument aggregates: (y, x) / (value, weight)
TWO_ARG_AGGS = {
    "covar", "covar_pop", "covar_samp", "corr", "regr_slope",
    "regr_intercept", "regr_r2", "regr_avgx", "regr_avgy", "regr_count",
    "regr_sxx", "regr_syy", "regr_sxy",
    "approx_percentile_cont_with_weight",
}
# trailing literal parameters (not column inputs)
PARAM_AGGS = {"approx_percentile_cont": 1,
              "approx_percentile_cont_with_weight": 1}
WINDOW_TVFS = {"tumble", "hop", "session"}
DEFAULT_WATERMARK_DELAY = 1_000_000_000  # 1s, reference default


# ---------------------------------------------------------------------------
# Catalog
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class TableDef:
    name: str
    fields: List[pa.Field]
    options: Dict[str, str]
    # col name -> connector metadata key (DDL `METADATA FROM 'key'`,
    # reference MetadataDef / SourceMetadataVisitor)
    metadata_fields: Dict[str, str] = dataclasses.field(default_factory=dict)
    # col name -> virtual-column expression (`GENERATED ALWAYS AS (expr)`)
    generated: Dict[str, Expr] = dataclasses.field(default_factory=dict)

    @property
    def connector(self) -> str:
        c = self.options.get("connector")
        if not c:
            raise SqlError(f"table {self.name} has no connector option")
        return c

    @property
    def is_memory(self) -> bool:
        """CREATE TABLE with no connector: an in-graph pass-through —
        INSERT INTO it defines the stream, reading it consumes that
        dataflow (reference memory/'virtual' tables, tables.rs)."""
        return "connector" not in self.options

    @property
    def table_type(self) -> str:
        # source | sink (some connectors imply one)
        return self.options.get("type", "")

    def schema(self) -> pa.Schema:
        return pa.schema(self.fields)


class SchemaProvider:
    """Table/view/UDF catalog (reference: ArroyoSchemaProvider, lib.rs:112)."""

    def __init__(self):
        self.tables: Dict[str, TableDef] = {}
        self.views: Dict[str, Select] = {}
        # bumped on every catalog mutation: cached subplans are keyed on
        # it so a multi-statement script redefining a table/view name
        # never reuses a plan bound to the old definition
        self.epoch = 0

    def add_table(self, t: TableDef):
        self.tables[t.name.lower()] = t
        self.epoch += 1

    def add_view(self, name: str, q: Select):
        self.views[name.lower()] = q
        self.epoch += 1

    def get_table(self, name: str) -> Optional[TableDef]:
        return self.tables.get(name.lower())

    def get_view(self, name: str) -> Optional[Select]:
        return self.views.get(name.lower())


# ---------------------------------------------------------------------------
# Window specs
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class WindowSpec:
    kind: str  # tumbling | sliding | session
    width: int = 0  # nanos (tumbling/sliding)
    slide: int = 0
    gap: int = 0

    @staticmethod
    def from_call(call: FuncCall) -> "WindowSpec":
        def iv(e: Expr) -> int:
            if not isinstance(e, Interval):
                raise SqlError(
                    f"{call.name}() arguments must be INTERVAL literals"
                )
            return e.nanos

        if call.name == "tumble":
            if len(call.args) != 1:
                raise SqlError("tumble(width) takes one interval")
            return WindowSpec("tumbling", width=iv(call.args[0]))
        if call.name == "hop":
            if len(call.args) != 2:
                raise SqlError("hop(slide, width) takes two intervals")
            return WindowSpec(
                "sliding", slide=iv(call.args[0]), width=iv(call.args[1])
            )
        if len(call.args) != 1:
            raise SqlError("session(gap) takes one interval")
        return WindowSpec("session", gap=iv(call.args[0]))


# ---------------------------------------------------------------------------
# Relation plans
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class RelOutput:
    node_id: int
    schema: StreamSchema  # includes _timestamp
    scope: Scope  # qualifier-aware name resolution over schema
    window: Optional[WindowSpec] = None  # set when rows are window outputs
    window_field: Optional[str] = None  # name of the window struct column
    updating: bool = False


class Planner:
    def __init__(self, provider: SchemaProvider, parallelism: int = 1):
        self.provider = provider
        self.graph = LogicalGraph()
        self.parallelism = parallelism
        self._source_cache: Dict[str, RelOutput] = {}
        self._select_plan_cache: Dict[tuple, RelOutput] = {}
        self._cache_epoch = getattr(provider, "epoch", 0)
        self._sink_nodes: Dict[str, dict] = {}
        self._memory_tables: Dict[str, RelOutput] = {}
        self._cte_stack: List[Dict[str, Select]] = []
        self._counter = 0

    # -- helpers ------------------------------------------------------------

    def _next_id(self) -> int:
        return self.graph.next_id()

    def _fresh(self, prefix: str) -> str:
        self._counter += 1
        return f"__{prefix}_{self._counter}"

    def _edge(self, src_node_id: int, dst_parallelism: int) -> EdgeType:
        """Forward when parallelism matches (chainable); otherwise an
        unkeyed shuffle (round-robin)."""
        if self.graph.nodes[src_node_id].parallelism == dst_parallelism:
            return EdgeType.FORWARD
        return EdgeType.SHUFFLE

    def _add_value_node(
        self,
        upstream: RelOutput,
        exprs: List[BoundExpr],
        names: List[str],
        predicate: Optional[BoundExpr],
        description: str,
        keep_timestamp_from: Optional[BoundExpr] = None,
    ) -> RelOutput:
        """Append a projection/filter node fed by `upstream` via a forward
        edge. `exprs` excludes _timestamp, which is passed through (or
        computed by keep_timestamp_from)."""
        # updating streams must keep retract/append ordering: the projection
        # runs at the upstream node's parallelism so the edge stays FORWARD
        # (an unkeyed shuffle would round-robin a flush's retract batch and
        # append batch onto different subtasks)
        node_par = (
            self.graph.nodes[upstream.node_id].parallelism
            if upstream.updating
            else self.parallelism
        )
        out_fields = [pa.field(n, e.dtype) for n, e in zip(names, exprs)]
        out_schema = StreamSchema(add_timestamp_field(pa.schema(out_fields)))
        ts_idx = upstream.schema.timestamp_index

        from .expressions import _jx_col

        ts_expr = keep_timestamp_from or BoundExpr(
            lambda b: b.column(ts_idx), pa.timestamp("ns"), TIMESTAMP_FIELD,
            # device mirror: the timestamp passthrough is a plain column
            # ref, so it must not block whole-segment jax lowering
            jax=_jx_col(ts_idx, pa.timestamp("ns")),
        )
        # updating streams carry __updating_meta through every projection
        from ..schema import UPDATING_META_FIELD, UPDATING_META_TYPE

        meta_idx = (
            upstream.schema.schema.names.index(UPDATING_META_FIELD)
            if UPDATING_META_FIELD in upstream.schema.schema.names
            else None
        )
        if meta_idx is not None and UPDATING_META_FIELD not in names:
            exprs = exprs + [
                BoundExpr(
                    (lambda j: lambda b: b.column(j))(meta_idx),
                    UPDATING_META_TYPE,
                    UPDATING_META_FIELD,
                )
            ]
            names = names + [UPDATING_META_FIELD]
            out_fields = [pa.field(n, e.dtype) for n, e in zip(names, exprs)]
            out_schema = StreamSchema(
                add_timestamp_field(pa.schema(out_fields))
            )
        prog = CompiledProjection(
            exprs + [ts_expr], out_schema.schema, predicate
        )
        node = self.graph.add_node(
            LogicalNode.single(
                self._next_id(),
                OperatorName.ARROW_VALUE,
                {"py_fn": prog, "schema": out_schema, "name": description},
                description,
                parallelism=node_par,
            )
        )
        self.graph.add_edge(
            upstream.node_id, node.node_id,
            self._edge(upstream.node_id, node_par), upstream.schema,
        )
        return RelOutput(
            node.node_id,
            out_schema,
            Scope.from_schema(out_schema.schema),
            window=upstream.window,
            window_field=_passthrough_window_field(upstream, names),
            updating=upstream.updating,
        )

    # -- entry points -------------------------------------------------------

    def plan_source_table(self, t: TableDef, alias: Optional[str]) -> RelOutput:
        cache_key = t.name.lower()
        if t.is_memory:
            rel = self._memory_tables.get(cache_key)
            if rel is None:
                raise SqlError(
                    f"memory table {t.name} is read before any INSERT INTO "
                    "it (statements plan in script order)"
                )
            return RelOutput(
                rel.node_id,
                rel.schema,
                Scope.from_schema(rel.schema.schema, alias or t.name),
                rel.window,
                rel.window_field,
                rel.updating,
            )
        if cache_key in self._source_cache:
            cached = self._source_cache[cache_key]
            return RelOutput(
                cached.node_id,
                cached.schema,
                Scope.from_schema(cached.schema.schema, alias or t.name),
                cached.window,
                cached.window_field,
                cached.updating,
            )
        from ..connectors import get_connector

        conn = get_connector(t.connector)
        options = conn.validate_options(
            {k: v for k, v in t.options.items()
             if k not in ("connector", "type", "format")},
            None,
        )
        event_time_field = t.options.get("event_time_field")
        watermark_delay = DEFAULT_WATERMARK_DELAY
        if "watermark_delay" in t.options:
            from .parser import parse_expr_text

            wd = parse_expr_text(f"interval '{t.options['watermark_delay']}'")
            watermark_delay = wd.nanos  # type: ignore[union-attr]
        elif "watermark_delay_nanos" in t.options:
            # set by the WATERMARK FOR column-DDL clause
            watermark_delay = int(t.options["watermark_delay_nanos"])

        if t.fields:
            source_schema = StreamSchema(
                add_timestamp_field(pa.schema(list(t.fields)))
            )
        else:
            # column-less CREATE TABLE: the connector defines the schema
            # (impulse, nexmark)
            fixed = conn.table_schema()
            if fixed is None:
                raise SqlError(
                    f"table {t.name} must declare columns (connector "
                    f"{t.connector} has no fixed schema)"
                )
            source_schema = fixed
        config = {
            "connector": t.connector,
            "schema": source_schema,
            "format": t.options.get("format"),
            "bad_data": t.options.get("bad_data", "fail"),
            "event_time_field": event_time_field,
            "proto_descriptor": _proto_descriptor(t),
            **options,
        }
        if t.metadata_fields:
            allowed = getattr(conn, "metadata_keys", ())
            for col, key in t.metadata_fields.items():
                if key not in allowed:
                    raise SqlError(
                        f"connector {t.connector} has no metadata key "
                        f"{key!r} (column {col}); available: "
                        f"{list(allowed) or 'none'}"
                    )
            config["metadata_fields"] = dict(t.metadata_fields)
        chain = [ChainedOp(OperatorName.CONNECTOR_SOURCE, config, t.name)]
        # virtual columns (GENERATED ALWAYS AS): computed right after
        # deserialization so event-time/watermark can reference them
        if t.generated:
            for col, gexpr in t.generated.items():
                for other in t.generated:
                    if _expr_references(gexpr, other):
                        what = (
                            "itself" if other == col
                            else f"generated column {other}"
                        )
                        raise SqlError(
                            f"generated column {col} references {what}; "
                            "generated columns may only reference payload "
                            "columns"
                        )
            scope = Scope.from_schema(source_schema.schema)
            gen_exprs: List[BoundExpr] = []
            for i, f in enumerate(source_schema.schema):
                if f.name == TIMESTAMP_FIELD:
                    continue
                if f.name in t.generated:
                    gen_exprs.append(bind(t.generated[f.name], scope))
                else:
                    gen_exprs.append(
                        BoundExpr(
                            (lambda j: lambda b: b.column(j))(i),
                            f.type, f.name,
                        )
                    )
            ts_i = source_schema.timestamp_index
            gen_exprs.append(
                BoundExpr(
                    (lambda j: lambda b: b.column(j))(ts_i),
                    pa.timestamp("ns"), TIMESTAMP_FIELD,
                )
            )
            chain.append(
                ChainedOp(
                    OperatorName.PROJECTION,
                    {
                        "py_fn": CompiledProjection(
                            gen_exprs, source_schema.schema, None
                        ),
                        "schema": source_schema,
                    },
                    "generated_columns",
                )
            )
        # event-time rewrite: _timestamp = event_time_field (reference
        # SourceRewriter, rewriters.rs)
        if event_time_field:
            scope = Scope.from_schema(source_schema.schema)
            et = bind(Column(event_time_field), scope)
            if not pa.types.is_timestamp(et.dtype):
                raise SqlError(
                    f"event_time_field {event_time_field} must be TIMESTAMP"
                )
            idxs = list(range(len(source_schema.schema) - 1))
            exprs = [
                BoundExpr(
                    (lambda i: lambda b: b.column(i))(i),
                    source_schema.schema.field(i).type,
                    source_schema.schema.field(i).name,
                )
                for i in idxs
            ]
            prog = CompiledProjection(exprs + [et], source_schema.schema, None)
            chain.append(
                ChainedOp(
                    OperatorName.PROJECTION,
                    {"py_fn": prog, "schema": source_schema},
                    "event_time",
                )
            )
        chain.append(
            ChainedOp(
                OperatorName.EXPRESSION_WATERMARK,
                {"interval_nanos": watermark_delay,
                 "idle_time": _opt_float(t.options.get("idle_time"))},
                "watermark",
            )
        )
        node = self.graph.add_node(
            LogicalNode(self._next_id(), t.name, chain, parallelism=1)
        )
        out = RelOutput(
            node.node_id,
            source_schema,
            Scope.from_schema(source_schema.schema, alias or t.name),
        )
        self._source_cache[cache_key] = out
        return out

    # -- relations ----------------------------------------------------------

    def plan_relation(self, rel: Relation) -> RelOutput:
        if isinstance(rel, TableRef):
            view = self._resolve_view(rel.name)
            if view is not None:
                out = self._plan_select_shared(view)
                return _requalify(out, rel.alias or rel.name)
            t = self.provider.get_table(rel.name)
            if t is None:
                raise SqlError(f"unknown table {rel.name}")
            return self.plan_source_table(t, rel.alias)
        if isinstance(rel, SubqueryRef):
            out = self._plan_select_shared(rel.query)
            return _requalify(out, rel.alias)
        if isinstance(rel, Join):
            return self.plan_join(rel)
        if isinstance(rel, Unnest):
            raise SqlError(
                "bare UNNEST in FROM has no input stream; use "
                "`FROM tbl CROSS JOIN UNNEST(tbl.col) AS x` or unnest(col) "
                "as a SELECT item"
            )
        raise SqlError(f"unsupported relation {rel!r}")

    def _plan_select_shared(self, sel: Select) -> RelOutput:
        """Common-subplan elimination: structurally identical subqueries,
        views and CTE bodies plan ONCE and fan out (nexmark q5's two hop
        branches share one aggregation instead of maintaining duplicate
        window state; the reference gets the same effect from DataFusion's
        CSE + its SourceRewriter source cache). AST dataclasses repr
        structurally, so the repr is the cache key; the CTE stack rides
        along since the same text can resolve differently per scope."""
        # the key must capture WHAT names resolve to, not just nesting
        # depth: same-text subqueries under different same-depth CTE
        # scopes (or across statements redefining a CTE) are different
        # plans
        # catalog epoch: a later statement redefining a table/view must
        # not reuse a plan bound to the old definition. Clearing (rather
        # than keying on epoch) also drops the now-unreachable entries.
        ep = getattr(self.provider, "epoch", 0)
        if ep != self._cache_epoch:
            self._select_plan_cache.clear()
            # source plans are keyed by bare table name: a redefined
            # table must re-plan, not reuse the stale source. Memory
            # tables stay — they are plan-local entities (INSERT INTO
            # targets created by earlier statements of THIS plan), not
            # catalog-backed, and dropping them would orphan references
            # from statements planned after a DDL epoch bump.
            self._source_cache.clear()
            self._cache_epoch = ep
        key = (
            repr(sel),
            tuple(
                tuple(sorted((n, repr(q)) for n, q in scope.items()))
                for scope in self._cte_stack
            ),
        )
        hit = self._select_plan_cache.get(key)
        if hit is not None:
            return hit
        out = self.plan_select(sel)
        self._select_plan_cache[key] = out
        return out

    def _resolve_view(self, name: str) -> Optional[Select]:
        for scope in reversed(self._cte_stack):
            if name.lower() in scope:
                return scope[name.lower()]
        return self.provider.get_view(name)

    # -- select -------------------------------------------------------------

    def plan_select(self, sel: Select) -> RelOutput:
        ctes = getattr(sel, "ctes", [])
        if ctes:
            self._cte_stack.append({n.lower(): q for n, q in ctes})
        try:
            out = self._plan_select_body(sel)
            for u in sel.unions:
                out = self._plan_union(out, self._plan_select_body(u))
            if sel.order_by or sel.limit is not None:
                raise SqlError(
                    "ORDER BY/LIMIT on unbounded streams is not supported "
                    "(use window functions for top-N)"
                )
            return out
        finally:
            if ctes:
                self._cte_stack.pop()

    def _plan_select_body(self, sel: Select) -> RelOutput:
        out = self._plan_select_body_inner(sel)
        if sel.distinct:
            out = self._add_distinct_node(out)
        return out

    def _add_distinct_node(self, out: RelOutput) -> RelOutput:
        """SELECT DISTINCT: a zero-aggregate updating aggregate keyed by
        every output column (the reference plans DISTINCT as an aggregation
        over all select items; the emitted stream is updating). Duplicate
        rows produce no state change, so only first occurrences emit; over
        an updating input the per-key live count retracts rows whose every
        contributing input was retracted."""
        from ..schema import UPDATING_META_FIELD, UPDATING_META_TYPE

        in_names = out.schema.schema.names
        key_cols = [
            i for i, n in enumerate(in_names)
            if n not in (TIMESTAMP_FIELD, UPDATING_META_FIELD)
        ]
        key_names = [in_names[i] for i in key_cols]
        for i in key_cols:
            t = out.schema.schema.field(i).type
            if pa.types.is_list(t) or pa.types.is_map(t):
                raise SqlError(
                    f"SELECT DISTINCT over {t} column "
                    f"{in_names[i]!r} is not supported (list/map values "
                    "cannot be grouping keys)"
                )
        out_fields = [
            pa.field(in_names[i], out.schema.schema.field(i).type)
            for i in key_cols
        ]
        out_fields.append(pa.field(UPDATING_META_FIELD, UPDATING_META_TYPE))
        schema = StreamSchema(add_timestamp_field(pa.schema(out_fields)))
        cfg: Dict = {"aggregates": [], "key_cols": key_cols,
                     "schema": schema}
        if out.updating:
            cfg["retractable"] = True
            cfg["meta_col"] = in_names.index(UPDATING_META_FIELD)
        node = self.graph.add_node(
            LogicalNode.single(
                self._next_id(),
                OperatorName.UPDATING_AGGREGATE,
                cfg,
                "distinct",
                parallelism=self.parallelism,
            )
        )
        self.graph.add_edge(
            out.node_id, node.node_id, EdgeType.SHUFFLE,
            out.schema.with_keys(key_names),
        )
        return RelOutput(
            node.node_id, schema, Scope.from_schema(schema.schema),
            updating=True,
        )

    def _plan_select_body_inner(self, sel: Select) -> RelOutput:
        if sel.from_ is None:
            raise SqlError("SELECT without FROM is not supported")
        upstream = self.plan_relation(sel.from_)
        where = bind(sel.where, upstream.scope) if sel.where is not None else None

        items = self._expand_stars(sel.items, upstream)
        has_window_fn = any(
            isinstance(it.expr, FuncCall) and it.expr.over is not None
            for it in items
        )
        if has_window_fn:
            return self._plan_window_function(sel, items, upstream, where)
        from ..udf import registry as udf_registry

        async_items = [
            it for it in items
            if isinstance(it.expr, FuncCall)
            and (u := udf_registry.get(it.expr.name)) is not None
            and u.is_async
        ]
        if async_items:
            return self._plan_async_udf(sel, items, async_items, upstream,
                                        where)
        unnest_items = [
            it for it in items
            if isinstance(it.expr, FuncCall) and it.expr.name == "unnest"
        ]
        if unnest_items or any(_contains_unnest(it.expr) for it in items):
            if not unnest_items:
                raise SqlError(
                    "unnest() must be a top-level SELECT item (wrap other "
                    "expressions around it in an outer query)"
                )
            return self._plan_unnest(sel, items, unnest_items, upstream,
                                     where)
        if sel.group_by or self._has_aggregate(items):
            return self._plan_aggregate(sel, items, upstream, where)
        # plain projection/filter
        exprs, names = self._bind_items(items, upstream.scope)
        return self._add_value_node(
            upstream, exprs, names, where, _describe_items(names)
        )

    def _expand_stars(
        self, items: List[SelectItem], upstream: RelOutput
    ) -> List[SelectItem]:
        out: List[SelectItem] = []
        for it in items:
            if isinstance(it.expr, Star):
                for c in upstream.scope.cols:
                    if c.name == TIMESTAMP_FIELD or c.name.startswith("__"):
                        continue
                    if it.expr.table and c.qualifier != it.expr.table:
                        continue
                    out.append(
                        SelectItem(Column(c.name, table=c.qualifier), c.name)
                    )
            else:
                out.append(it)
        return out

    def _bind_items(
        self, items: List[SelectItem], scope: Scope
    ) -> Tuple[List[BoundExpr], List[str]]:
        exprs, names = [], []
        for it in items:
            e = bind(it.expr, scope)
            exprs.append(e)
            names.append(it.alias or _default_name(it.expr, e))
        return exprs, _dedup(names)

    @staticmethod
    def _has_aggregate(items: List[SelectItem]) -> bool:
        return any(_find_aggregates(it.expr) for it in items)

    # -- aggregates ---------------------------------------------------------

    def _plan_aggregate(
        self,
        sel: Select,
        items: List[SelectItem],
        upstream: RelOutput,
        where: Optional[BoundExpr],
    ) -> RelOutput:
        # resolve group-by entries: ordinals and select-alias references
        group_exprs: List[Expr] = []
        window_spec: Optional[WindowSpec] = None
        window_alias: Optional[str] = None
        for g in sel.group_by:
            g = self._resolve_group_ref(g, items)
            if isinstance(g, FuncCall) and g.name in WINDOW_TVFS:
                if window_spec is not None:
                    raise SqlError("only one window function per GROUP BY")
                window_spec = WindowSpec.from_call(g)
                continue
            if isinstance(g, Column):
                # group by an alias of the window TVF select item
                hit = _find_item_by_alias(items, g.name)
                if hit is not None and isinstance(hit.expr, FuncCall) and (
                    hit.expr.name in WINDOW_TVFS
                ):
                    window_spec = WindowSpec.from_call(hit.expr)
                    window_alias = hit.alias
                    continue
            group_exprs.append(g)

        # select items referencing the window TVF directly
        for it in items:
            if isinstance(it.expr, FuncCall) and it.expr.name in WINDOW_TVFS:
                spec = WindowSpec.from_call(it.expr)
                if window_spec is None:
                    window_spec = spec
                elif spec != window_spec:
                    raise SqlError("conflicting window specifications")
                window_alias = it.alias or "window"

        # GROUP BY over a window struct COLUMN (aggregating an already-
        # windowed stream, e.g. nexmark q5's MaxBids): instant mode — rows
        # of one window share a _timestamp, so bins are exact timestamps
        key_bound = [bind(g, upstream.scope) for g in group_exprs]
        instant = window_spec is None and any(
            pa.types.is_struct(b.dtype) for b in key_bound
        )
        if window_spec is None and not instant:
            return self._plan_updating_aggregate(
                sel, items, upstream, where, group_exprs, key_bound
            )
        if upstream.updating:
            raise SqlError(
                "windowed aggregation over an updating (retracting) input "
                "is not yet supported"
            )

        key_names = _dedup([_default_name(g, b) for g, b in
                            zip(group_exprs, key_bound)])
        agg_calls, agg_inputs = _collect_aggregates(items, upstream.scope)
        wfield = None if instant else (window_alias or "window")
        agg_out, agg_out_names = self._windowed_agg_node(
            upstream, where, window_spec, key_bound, key_names,
            agg_calls, agg_inputs, wfield, instant,
        )
        out, _ = self._agg_post_projection(
            sel, items, agg_out, key_names, group_exprs, agg_calls,
            agg_out_names, wfield,
        )
        return out

    def _windowed_agg_node(
        self, upstream, where, window_spec, key_bound, key_names,
        agg_calls, agg_inputs, wfield: Optional[str], instant: bool,
    ) -> Tuple[RelOutput, List[str]]:
        """Pre-projection + window-aggregate node for one aggregate branch
        (shared by the plain windowed path and the mixed-distinct regular
        branch). Output schema: [keys..., agg outs..., wfield?]."""
        pre_exprs = list(key_bound)
        pre_names = list(key_names)
        agg_col_idx: List[List[int]] = []
        for bs in agg_inputs:
            idxs = []
            for b in bs:
                pre_exprs.append(b)
                idxs.append(len(pre_exprs) - 1)
                pre_names.append(self._fresh("agg_in"))
            agg_col_idx.append(idxs)
        pre = self._add_value_node(
            upstream, pre_exprs, pre_names, where, "agg_input"
        )

        # aggregate specs
        specs = []
        agg_out_names = []
        for call, col_idx in zip(agg_calls, agg_col_idx):
            specs.append(
                _make_spec(call, col_idx, pre_exprs, self._fresh("agg_out"))
            )
            agg_out_names.append(specs[-1]["name"])

        # window operator output schema: keys + aggs + window struct
        out_fields = [
            pa.field(n, pre.schema.schema.field(i).type)
            for i, n in enumerate(key_names)
        ]
        for spec, call in zip(specs, agg_calls):
            out_fields.append(pa.field(spec["name"], _agg_output_type(
                spec, call, pre.schema.schema)))
        if not instant:
            out_fields.append(pa.field(wfield, WINDOW_TYPE))
        agg_out_schema = StreamSchema(
            add_timestamp_field(pa.schema(out_fields))
        )

        window_config: Dict = {
            "aggregates": specs,
            "key_cols": list(range(len(key_names))),
            "schema": agg_out_schema,
        }
        # one group per bin: every grouping key IS a window struct (q5's
        # MAX-per-window stage) or there are none. Mesh hash ownership
        # would land each window's rows on one shard, so the window
        # operators run these salted (rows spread across shards, folded
        # at gather — parallel/sharded_state.SharedMeshSlotDirectory)
        if not key_bound or all(
            b.dtype == WINDOW_TYPE for b in key_bound
        ):
            window_config["mesh_salted"] = True
        if instant:
            op_name = OperatorName.TUMBLING_WINDOW_AGGREGATE
            window_config["width_nanos"] = 0
            description = "instant_window"
        else:
            op_name = {
                "tumbling": OperatorName.TUMBLING_WINDOW_AGGREGATE,
                "sliding": OperatorName.SLIDING_WINDOW_AGGREGATE,
                "session": OperatorName.SESSION_WINDOW_AGGREGATE,
            }[window_spec.kind]
            window_config["window_field"] = wfield
            description = f"{window_spec.kind}_window"
            if window_spec.kind == "tumbling":
                window_config["width_nanos"] = window_spec.width
            elif window_spec.kind == "sliding":
                window_config["width_nanos"] = window_spec.width
                window_config["slide_nanos"] = window_spec.slide
            else:
                window_config["gap_nanos"] = window_spec.gap

        # global (unkeyed) aggregates cannot shard: all rows of a window
        # must meet in one accumulator, so the node runs at parallelism 1
        # (keyed aggregates shard by group key)
        agg_par = self.parallelism if key_names else 1
        agg_node = self.graph.add_node(
            LogicalNode.single(
                self._next_id(),
                op_name,
                window_config,
                description,
                parallelism=agg_par,
            )
        )
        shuffle_schema = pre.schema.with_keys(key_names) if key_names else pre.schema
        self.graph.add_edge(
            pre.node_id, agg_node.node_id, EdgeType.SHUFFLE, shuffle_schema
        )
        out_window_field = wfield
        if instant:
            # the window struct key column carries the window downstream
            for i, b in enumerate(key_bound):
                if pa.types.is_struct(b.dtype):
                    out_window_field = key_names[i]
                    break
        agg_out = RelOutput(
            agg_node.node_id,
            agg_out_schema,
            Scope.from_schema(agg_out_schema.schema),
            window=window_spec if not instant else upstream.window,
            window_field=out_window_field,
        )
        return agg_out, agg_out_names

    def _agg_post_projection(
        self, sel, items, agg_out, key_names, group_exprs, agg_calls,
        call_names, wcol: Optional[str],
    ) -> Tuple[RelOutput, List[str]]:
        """Select-item/HAVING projection over an aggregate (or joined
        aggregate) output: aggregate calls map to their output columns,
        group expressions to key columns, window TVF refs to `wcol`
        (shared by the windowed, count-distinct and mixed-distinct paths)."""
        post_scope = _agg_post_scope(
            agg_out, key_names, group_exprs, agg_calls, call_names
        )
        having = (
            bind(
                _rewrite_group_refs(
                    _rewrite_aggregates(sel.having, agg_calls, call_names),
                    group_exprs, key_names,
                ),
                post_scope,
            )
            if sel.having is not None
            else None
        )
        post_exprs: List[BoundExpr] = []
        post_names: List[str] = []
        for it in items:
            rewritten = _rewrite_aggregates(it.expr, agg_calls, call_names)
            rewritten = _rewrite_group_refs(rewritten, group_exprs, key_names)
            if (
                isinstance(rewritten, FuncCall)
                and rewritten.name in WINDOW_TVFS
                and wcol is not None
            ):
                rewritten = Column(wcol)
            e = bind(rewritten, post_scope)
            post_exprs.append(e)
            post_names.append(it.alias or _default_name(it.expr, e))
        out = self._add_value_node(
            agg_out, post_exprs, _dedup(post_names), having,
            _describe_items(post_names),
        )
        return out, post_names

    def _restore_select_order(
        self, out: RelOutput, items, special_item, out_name: str,
        plain_items, plain_names, description: str,
        final_name: Optional[str] = None,
    ) -> RelOutput:
        """Final projection restoring the SELECT item order after an
        operator that appends one computed column (window fn / async udf /
        unnest). `out_name` is the (fresh, collision-free) internal column;
        `final_name` the user-facing name it takes in the output."""
        final_exprs, final_names = [], []
        for it in items:
            if it is special_item:
                final_exprs.append(bind(Column(out_name), out.scope))
                final_names.append(final_name or out_name)
            else:
                idx = plain_items.index(it)
                final_exprs.append(bind(Column(plain_names[idx]), out.scope))
                final_names.append(it.alias or plain_names[idx])
        return self._add_value_node(
            out, final_exprs, _dedup(final_names), None, description
        )

    def _plan_unnest(
        self, sel, items, unnest_items, upstream: RelOutput, where
    ) -> RelOutput:
        """unnest(list_col) explodes each row into one row per element
        (reference UnnestRewriter, rewriters.rs); other select items
        replicate across the exploded rows."""
        if len(unnest_items) != 1:
            raise SqlError("one unnest() per SELECT is supported")
        if upstream.updating:
            raise SqlError(
                "unnest() over an updating (retracting) input is not yet "
                "supported"
            )
        if sel.group_by or self._has_aggregate(items):
            raise SqlError(
                "unnest() cannot be combined with GROUP BY or aggregates "
                "in one SELECT; unnest in a subquery first"
            )
        for it in items:
            if it is unnest_items[0]:
                continue
            if _contains_unnest(it.expr):
                raise SqlError(
                    "unnest() must be a top-level SELECT item (wrap other "
                    "expressions around it in an outer query)"
                )
        call = unnest_items[0].expr
        if len(call.args) != 1:
            raise SqlError("unnest() takes one list-typed argument")
        list_expr = bind(call.args[0], upstream.scope)
        if not pa.types.is_list(list_expr.dtype):
            raise SqlError(
                f"unnest() requires a list argument, got {list_expr.dtype}"
            )
        display_name = unnest_items[0].alias or "unnest"
        # fresh internal name: a plain item aliased to the same name (e.g.
        # `SELECT id AS unnest, unnest(tags)`) must not collide in src_idx
        out_name = self._fresh("unnest")
        plain_items = [it for it in items if it is not unnest_items[0]]
        exprs, names = self._bind_items(plain_items, upstream.scope)
        exprs = exprs + [list_expr]
        names = _dedup(names + [self._fresh("list")])
        pre = self._add_value_node(
            upstream, exprs, names, where, "unnest_input"
        )
        list_idx = len(names) - 1
        value_type = list_expr.dtype.value_type
        out_fields = [
            pa.field(n, f.type)
            for n, f in zip(names[:-1], pre.schema.schema)
        ] + [pa.field(out_name, value_type)]
        out_schema = StreamSchema(add_timestamp_field(pa.schema(out_fields)))
        ts_idx = pre.schema.timestamp_index
        # static plan-time mapping: output field -> source column index
        # (-1 = the flattened values, -2 = timestamp)
        src_idx = [
            -1 if f.name == out_name
            else (-2 if f.name == TIMESTAMP_FIELD
                  else pre.schema.schema.names.index(f.name))
            for f in out_schema.schema
        ]

        def explode(batch):
            import pyarrow.compute as pc

            col = batch.column(list_idx)
            parents = pc.list_parent_indices(col)
            flat = pc.list_flatten(col)
            if len(flat) == 0:
                return None
            taken = batch.take(parents)
            arrays = [
                flat if i == -1
                else taken.column(ts_idx if i == -2 else i)
                for i in src_idx
            ]
            return pa.RecordBatch.from_arrays(
                arrays, schema=out_schema.schema
            )

        node = self.graph.add_node(
            LogicalNode.single(
                self._next_id(),
                OperatorName.ARROW_VALUE,
                {"py_fn": explode, "schema": out_schema},
                "unnest",
                parallelism=self.parallelism,
            )
        )
        self.graph.add_edge(
            pre.node_id, node.node_id,
            self._edge(pre.node_id, self.parallelism), pre.schema,
        )
        out = RelOutput(
            node.node_id, out_schema, Scope.from_schema(out_schema.schema),
            window=upstream.window,
        )
        return self._restore_select_order(
            out, items, unnest_items[0], out_name, plain_items, names[:-1],
            "unnest_select", final_name=display_name,
        )

    def _plan_lateral_unnest(
        self, left: RelOutput, un: Unnest
    ) -> RelOutput:
        """FROM t CROSS JOIN UNNEST(t.col) AS x: one output row per list
        element, every left column replicated across the exploded rows."""
        if left.updating:
            raise SqlError(
                "UNNEST over an updating (retracting) input is not yet "
                "supported"
            )
        list_expr = bind(un.expr, left.scope)
        if not pa.types.is_list(list_expr.dtype):
            raise SqlError(
                f"UNNEST requires a list argument, got {list_expr.dtype}"
            )
        out_name = un.alias or "unnest"
        exprs, names = self._passthrough_exprs(left)
        exprs.append(list_expr)
        names = _dedup(names + [self._fresh("list")])
        pre = self._add_value_node(left, exprs, names, None, "unnest_input")
        list_idx = len(names) - 1
        value_type = list_expr.dtype.value_type
        out_fields = [
            pa.field(n, f.type)
            for n, f in zip(names[:-1], pre.schema.schema)
        ] + [pa.field(out_name, value_type)]
        out_schema = StreamSchema(add_timestamp_field(pa.schema(out_fields)))
        ts_idx = pre.schema.timestamp_index
        # positional mapping: passthrough cols, then the flattened values
        # (-1), then _timestamp (-2, appended last by add_timestamp_field)
        src_idx = list(range(list_idx)) + [-1, -2]

        def explode(batch):
            import pyarrow.compute as pc

            col = batch.column(list_idx)
            parents = pc.list_parent_indices(col)
            flat = pc.list_flatten(col)
            if len(flat) == 0:
                return None
            taken = batch.take(parents)
            arrays = [
                flat if i == -1
                else taken.column(ts_idx if i == -2 else i)
                for i in src_idx
            ]
            return pa.RecordBatch.from_arrays(
                arrays, schema=out_schema.schema
            )

        node = self.graph.add_node(
            LogicalNode.single(
                self._next_id(),
                OperatorName.ARROW_VALUE,
                {"py_fn": explode, "schema": out_schema},
                "unnest",
                parallelism=self.parallelism,
            )
        )
        self.graph.add_edge(
            pre.node_id, node.node_id,
            self._edge(pre.node_id, self.parallelism), pre.schema,
        )
        return RelOutput(
            node.node_id, out_schema,
            self._requalified_scope(out_schema, left), window=left.window,
            window_field=_passthrough_window_field(left, names[:-1]),
        )

    def _plan_async_udf(
        self, sel, items, async_items, upstream: RelOutput, where
    ) -> RelOutput:
        """Async UDF select items plan as an AsyncUdf operator
        (reference async_udf.rs + planner AsyncUdf node): pre-project the
        plain items + the UDF's argument columns, run the async operator
        (which appends the result column), then restore SELECT order."""
        from ..udf import registry as udf_registry

        if len(async_items) != 1:
            raise SqlError("one async UDF per SELECT is supported")
        call = async_items[0].expr
        u = udf_registry.get(call.name)
        display_name = async_items[0].alias or call.name
        out_name = self._fresh("audf")  # internal; no alias collisions
        plain_items = [it for it in items if it is not async_items[0]]
        exprs, names = self._bind_items(plain_items, upstream.scope)
        arg_cols = []
        for a in call.args:
            exprs.append(bind(a, upstream.scope))
            names.append(self._fresh("aarg"))
            arg_cols.append(len(exprs) - 1)
        names = _dedup(names)
        pre = self._add_value_node(
            upstream, exprs, names, where, "async_udf_input"
        )
        out_fields = [
            pa.field(n, f.type)
            for n, f in zip(names, pre.schema.schema)
            if n != TIMESTAMP_FIELD
        ] + [pa.field(out_name, u.return_type)]
        out_schema = StreamSchema(add_timestamp_field(pa.schema(out_fields)))
        node = self.graph.add_node(
            LogicalNode.single(
                self._next_id(),
                OperatorName.ASYNC_UDF,
                {
                    "udf": call.name,
                    "arg_cols": arg_cols,
                    "out_field": out_name,
                    "schema": out_schema,
                    "ordered": True,
                },
                f"async_{call.name}",
                parallelism=self.parallelism,
            )
        )
        self.graph.add_edge(
            pre.node_id, node.node_id,
            self._edge(pre.node_id, self.parallelism), pre.schema,
        )
        out = RelOutput(
            node.node_id, out_schema, Scope.from_schema(out_schema.schema),
            window=upstream.window,
        )
        return self._restore_select_order(
            out, items, async_items[0], out_name, plain_items, names,
            "async_udf_select", final_name=display_name,
        )

    def _plan_window_function(
        self, sel, items, upstream: RelOutput, where
    ) -> RelOutput:
        """SQL window functions (ROW_NUMBER/RANK/DENSE_RANK OVER
        (PARTITION BY ... ORDER BY ...)) evaluated per event-time window
        (reference plan/window_fn.rs + arrow/window_fn.rs)."""
        over_items = [
            it for it in items
            if isinstance(it.expr, FuncCall) and it.expr.over is not None
        ]
        if upstream.window is None:
            raise SqlError(
                "window functions require a windowed input (aggregate with "
                "tumble()/hop()/session() first)"
            )
        for it in over_items:
            if it.expr.name not in ("row_number", "rank", "dense_rank"):
                raise SqlError(
                    f"unsupported window function {it.expr.name}()"
                )
        # one WINDOW_FUNCTION operator per OVER item, chained; each stage
        # passes every upstream column through and appends its result
        # column, so later stages' PARTITION BY/ORDER BY still bind
        out = upstream
        pending_where = where
        out_cols: List[str] = []
        for it in over_items:
            out_name = self._fresh("wfn")  # internal; no alias collisions
            out = self._add_window_fn_stage(
                out, it.expr, pending_where, out_name
            )
            pending_where = None  # WHERE applies once, before the first
            out_cols.append(out_name)
        # final projection restoring SELECT item order
        final_exprs: List[BoundExpr] = []
        final_names: List[str] = []
        for it in items:
            hit = next(
                (i for i, o in enumerate(over_items) if o is it), None
            )
            if hit is not None:
                final_exprs.append(bind(Column(out_cols[hit]), out.scope))
                final_names.append(it.alias or it.expr.name)
            else:
                e = bind(it.expr, out.scope)
                final_exprs.append(e)
                final_names.append(it.alias or _default_name(it.expr, e))
        return self._add_value_node(
            out, final_exprs, _dedup(final_names), None, "window_fn_select"
        )

    def _passthrough_exprs(
        self, upstream: RelOutput
    ) -> Tuple[List[BoundExpr], List[str]]:
        """Pass every non-timestamp upstream column through by index
        (indices are stable, so qualified names stay remappable)."""
        exprs: List[BoundExpr] = []
        names: List[str] = []
        for i, f in enumerate(upstream.schema.schema):
            if f.name == TIMESTAMP_FIELD:
                continue
            exprs.append(
                BoundExpr(
                    (lambda j: lambda b: b.column(j))(i), f.type, f.name
                )
            )
            names.append(f.name)
        return exprs, names

    def _requalified_scope(
        self, schema: StreamSchema, upstream: RelOutput
    ) -> Scope:
        """Scope over `schema` that also resolves the upstream's qualified
        names — valid when `schema` starts with a pass-through of the
        upstream's non-timestamp columns in order."""
        ts_idx = upstream.schema.timestamp_index
        scope = Scope.from_schema(schema.schema)
        for c in upstream.scope.cols:
            if c.qualifier is not None and c.index != ts_idx:
                new_idx = c.index if c.index < ts_idx else c.index - 1
                scope.add(c.qualifier, c.name, new_idx, c.dtype)
        return scope

    def _add_window_fn_stage(
        self, upstream: RelOutput, call: FuncCall,
        where: Optional[BoundExpr], out_name: str,
    ) -> RelOutput:
        """One window-function operator: pass-through pre-projection (+
        fresh PARTITION BY/ORDER BY columns), then the WINDOW_FUNCTION
        node appending `out_name`."""
        exprs, names = self._passthrough_exprs(upstream)
        part_idx: List[int] = []
        for p in call.over.partition_by:
            # the window column partitions implicitly (rows bin by their
            # window's timestamp), so drop it from PARTITION BY
            b = bind(p, upstream.scope)
            if pa.types.is_struct(b.dtype):
                continue
            exprs.append(b)
            names.append(self._fresh("part"))
            part_idx.append(len(exprs) - 1)
        order_by: List[tuple] = []
        for o, desc in call.over.order_by:
            b = bind(o, upstream.scope)
            exprs.append(b)
            names.append(self._fresh("ord"))
            order_by.append((len(exprs) - 1, desc))
        names = _dedup(names)
        pre = self._add_value_node(
            upstream, exprs, names, where, "window_fn_input"
        )
        out_fields = [
            pa.field(n, f.type)
            for n, f in zip(names, pre.schema.schema)
            if n != TIMESTAMP_FIELD
        ] + [pa.field(out_name, pa.int64())]
        out_schema = StreamSchema(add_timestamp_field(pa.schema(out_fields)))
        node = self.graph.add_node(
            LogicalNode.single(
                self._next_id(),
                OperatorName.WINDOW_FUNCTION,
                {
                    "fn": call.name,
                    "partition_cols": part_idx,
                    "order_by": [list(o) for o in order_by],
                    "schema": out_schema,
                    "out_field": out_name,
                },
                f"{call.name}_over",
                parallelism=1,  # bins must see all partitions' rows
            )
        )
        self.graph.add_edge(
            pre.node_id, node.node_id, self._edge(pre.node_id, 1), pre.schema
        )
        return RelOutput(
            node.node_id, out_schema,
            self._requalified_scope(out_schema, upstream),
            window=upstream.window, window_field=upstream.window_field
            if upstream.window_field in out_schema.names else None,
        )

    def _plan_updating_aggregate(
        self, sel, items, upstream, where, group_exprs, key_bound
    ) -> RelOutput:
        """Non-windowed GROUP BY: updating aggregate emitting retract/append
        pairs (reference incremental_aggregator.rs / plan/aggregate.rs
        UpdatingAggregateExtension)."""
        from ..schema import UPDATING_META_FIELD, UPDATING_META_TYPE

        key_names = _dedup(
            [_default_name(g, b) for g, b in zip(group_exprs, key_bound)]
        )
        agg_calls, agg_inputs = _collect_aggregates(items, upstream.scope)
        pre_exprs = list(key_bound)
        pre_names = list(key_names)
        agg_col_idx: List[List[int]] = []
        for bs in agg_inputs:
            idxs = []
            for b in bs:
                pre_exprs.append(b)
                pre_names.append(self._fresh("agg_in"))
                idxs.append(len(pre_exprs) - 1)
            agg_col_idx.append(idxs)
        pre = self._add_value_node(
            upstream, pre_exprs, pre_names, where, "agg_input"
        )
        specs = []
        agg_out_names = []
        for call, col_idx in zip(agg_calls, agg_col_idx):
            specs.append(
                _make_spec(call, col_idx, pre_exprs, self._fresh("agg_out"))
            )
            agg_out_names.append(specs[-1]["name"])
        if upstream.updating:
            # retraction-consuming aggregation: invertible aggregates
            # (add-reductions and multisets) apply retract rows with sign
            # -1; everything else (min/max/median/UDAF/...) switches to
            # raw-value replay through the signed multiset (reference
            # incremental_aggregator.rs raw-value replay, :77-90)
            invertible = ("count", "sum", "avg", "count_distinct",
                          "approx_distinct", *VAR_KINDS_SQL, *REGR_KINDS_SQL)
            for s in specs:
                if s["kind"] not in invertible and not s["distinct"]:
                    s["replay"] = True
        out_fields = [
            pa.field(n, pre.schema.schema.field(i).type)
            for i, n in enumerate(key_names)
        ]
        for spec, call in zip(specs, agg_calls):
            out_fields.append(
                pa.field(spec["name"],
                         _agg_output_type(spec, call, pre.schema.schema))
            )
        out_fields.append(pa.field(UPDATING_META_FIELD, UPDATING_META_TYPE))
        agg_out_schema = StreamSchema(
            add_timestamp_field(pa.schema(out_fields))
        )
        agg_par = self.parallelism if key_names else 1
        agg_config = {
            "aggregates": specs,
            "key_cols": list(range(len(key_names))),
            "schema": agg_out_schema,
        }
        if upstream.updating:
            agg_config["retractable"] = True
            agg_config["meta_col"] = pre.schema.schema.names.index(
                UPDATING_META_FIELD
            )
        node = self.graph.add_node(
            LogicalNode.single(
                self._next_id(),
                OperatorName.UPDATING_AGGREGATE,
                agg_config,
                "updating_aggregate",
                parallelism=agg_par,
            )
        )
        self.graph.add_edge(
            pre.node_id, node.node_id, EdgeType.SHUFFLE,
            pre.schema.with_keys(key_names) if key_names else pre.schema,
        )
        agg_out = RelOutput(
            node.node_id,
            agg_out_schema,
            Scope.from_schema(agg_out_schema.schema),
            updating=True,
        )
        post_scope = _agg_post_scope(
            agg_out, key_names, group_exprs, agg_calls, agg_out_names
        )
        having = (
            bind(
                _rewrite_group_refs(
                    _rewrite_aggregates(sel.having, agg_calls, agg_out_names),
                    group_exprs, key_names,
                ),
                post_scope,
            )
            if sel.having is not None
            else None
        )
        post_exprs: List[BoundExpr] = []
        post_names: List[str] = []
        for it in items:
            rewritten = _rewrite_aggregates(it.expr, agg_calls, agg_out_names)
            rewritten = _rewrite_group_refs(rewritten, group_exprs, key_names)
            e = bind(rewritten, post_scope)
            post_exprs.append(e)
            post_names.append(it.alias or _default_name(it.expr, e))
        return self._add_value_node(
            agg_out, post_exprs, _dedup(post_names), having,
            _describe_items(post_names),
        )

    def _resolve_group_ref(self, g: Expr, items: List[SelectItem]) -> Expr:
        if isinstance(g, Literal) and isinstance(g.value, int):
            idx = g.value - 1
            if idx < 0 or idx >= len(items):
                raise SqlError(f"GROUP BY ordinal {g.value} out of range")
            return items[idx].expr
        if isinstance(g, Column) and g.table is None:
            hit = _find_item_by_alias(items, g.name)
            if hit is not None and not isinstance(hit.expr, Column):
                return hit.expr
        return g

    # -- joins --------------------------------------------------------------

    def plan_join(self, rel: Join) -> RelOutput:
        # FROM tbl CROSS JOIN UNNEST(expr) AS x — lateral explode
        # (reference: DataFusion's LogicalPlan::Unnest via UnnestRewriter)
        if isinstance(rel.right, Unnest):
            if rel.condition is not None:
                raise SqlError("UNNEST join takes no ON condition")
            return self._plan_lateral_unnest(
                self.plan_relation(rel.left), rel.right
            )
        if isinstance(rel.left, Unnest):
            raise SqlError("UNNEST must be the right side of a CROSS JOIN")
        # lookup tables join via the LookupConnector path (reference:
        # LookupExtension + lookup_join.rs)
        if isinstance(rel.right, TableRef):
            t = self.provider.get_table(rel.right.name)
            if t is not None and t.table_type == "lookup":
                return self._plan_lookup_join(rel, t)
        left = self.plan_relation(rel.left)
        right = self.plan_relation(rel.right)
        if rel.condition is None:
            raise SqlError("JOIN requires an ON condition")
        merged_scope = left.scope.merge(
            right.scope, len(left.schema.schema)
        )
        equi, residual = _split_join_condition(rel.condition)
        if not equi:
            raise SqlError("JOIN requires at least one equality condition")
        left_keys: List[BoundExpr] = []
        right_keys: List[BoundExpr] = []
        for a, b in equi:
            sides = _classify_sides(a, b, left.scope, right.scope)
            if sides is None:
                raise SqlError(
                    f"join condition {a} = {b} must compare the two inputs"
                )
            le, re_ = sides
            left_keys.append(bind(le, left.scope))
            right_keys.append(bind(re_, right.scope))

        both_windowed = (
            left.window is not None
            and right.window is not None
            and left.window == right.window
        )
        if both_windowed and (left.updating or right.updating):
            raise SqlError(
                "windowed joins over updating inputs are not yet supported"
            )
        windowed = both_windowed

        # project each side to key columns + payload
        lpre, nkeys = self._join_side_projection(left, left_keys, "jl")
        rpre, _ = self._join_side_projection(right, right_keys, "jr")

        out_fields, left_names, right_names = _join_output_fields(
            lpre, rpre, nkeys
        )
        out_schema = StreamSchema(add_timestamp_field(pa.schema(out_fields)))
        config = {
            "n_keys": nkeys,
            "join_type": rel.join_type,
            "schema": out_schema,
            "left_fields": left_names,
            "right_fields": right_names,
            "left_schema": lpre.schema,
            "right_schema": rpre.schema,
        }
        if residual:
            if not windowed and rel.join_type != "inner":
                raise SqlError(
                    "non-equality conditions on updating outer joins are "
                    "not yet supported (they change match semantics)"
                )
            # inner joins filter joined rows symmetrically (appends and the
            # retracts that cancel them see the same predicate)
            config["residual_py"] = self._bind_residual(
                residual, out_schema, left, right, lpre, rpre, nkeys
            )
        if windowed:
            op = OperatorName.INSTANT_JOIN
            config["window"] = dataclasses.asdict(left.window)
        else:
            # non-windowed joins materialize both sides and emit retraction
            # deltas (reference: updating joins); output is an updating
            # stream requiring a debezium-capable sink
            op = OperatorName.JOIN
            config["mode"] = "updating"
            from ..schema import UPDATING_META_FIELD, UPDATING_META_TYPE

            out_fields = out_fields + [
                pa.field(UPDATING_META_FIELD, UPDATING_META_TYPE)
            ]
            out_schema = StreamSchema(
                add_timestamp_field(pa.schema(out_fields))
            )
            config["schema"] = out_schema
        node = self.graph.add_node(
            LogicalNode.single(
                self._next_id(), op, config, f"{rel.join_type}_join",
                parallelism=self.parallelism,
            )
        )
        self.graph.add_edge(
            lpre.node_id, node.node_id, EdgeType.LEFT_JOIN,
            lpre.schema.with_keys(lpre.schema.names[:nkeys]),
        )
        self.graph.add_edge(
            rpre.node_id, node.node_id, EdgeType.RIGHT_JOIN,
            rpre.schema.with_keys(rpre.schema.names[:nkeys]),
        )
        scope = _join_output_scope(left, right, lpre, rpre, out_schema, nkeys)
        return RelOutput(
            node.node_id, out_schema, scope,
            window=left.window if windowed else None,
            window_field=None,
            updating=not windowed,
        )

    def _plan_lookup_join(self, rel: Join, t: TableDef) -> RelOutput:
        from ..connectors import get_connector

        left = self.plan_relation(rel.left)
        if rel.join_type not in ("inner", "left"):
            raise SqlError("lookup joins support INNER and LEFT JOIN")
        alias = rel.right.alias or rel.right.name
        right_fields = [f.name for f in t.fields]
        # condition must be stream_expr = lookup_key_column
        equi, residual = _split_join_condition(rel.condition)
        if len(equi) != 1 or residual:
            raise SqlError(
                "lookup joins require exactly one equality condition on the "
                "lookup table's key column"
            )
        a, b = equi[0]
        right_scope = Scope.from_schema(pa.schema(list(t.fields)), alias)
        sides = _classify_sides(a, b, left.scope, right_scope)
        if sides is None:
            raise SqlError("lookup join condition must compare the stream "
                           "with the lookup table")
        stream_expr, key_expr = sides
        lookup_key = t.options.get(
            "lookup_key", t.fields[0].name if t.fields else None
        )
        if not (
            isinstance(key_expr, Column) and key_expr.name == lookup_key
        ):
            raise SqlError(
                f"lookup joins must equate against {t.name}'s key column "
                f"{lookup_key!r} (got {key_expr})"
            )
        collisions = {f.name for f in t.fields} & {
            f.name for f in left.schema.schema if f.name != TIMESTAMP_FIELD
        }
        if collisions:
            raise SqlError(
                f"lookup table {t.name} fields collide with stream columns: "
                f"{sorted(collisions)} — alias or rename them"
            )
        conn = get_connector(t.connector)
        options = conn.validate_options(
            {k: v for k, v in t.options.items()
             if k not in ("connector", "type", "format")},
            None,
        )
        key_bound = bind(stream_expr, left.scope)
        exprs = [key_bound]
        names = ["__lookup_key"]
        for i, f in enumerate(left.schema.schema):
            if f.name == TIMESTAMP_FIELD:
                continue
            exprs.append(
                BoundExpr((lambda j: lambda bt: bt.column(j))(i), f.type,
                          f.name)
            )
            names.append(f.name)
        pre = self._add_value_node(left, exprs, _dedup(names), None, "lookup_in")
        out_fields = [
            f for f in pre.schema.schema
            if f.name not in (TIMESTAMP_FIELD, "__lookup_key")
        ] + [f for f in t.fields]
        out_schema = StreamSchema(add_timestamp_field(pa.schema(out_fields)))
        node = self.graph.add_node(
            LogicalNode.single(
                self._next_id(),
                OperatorName.LOOKUP_JOIN,
                {
                    "connector": t.connector,
                    "connector_config": options,
                    "key_col": 0,
                    "join_type": rel.join_type,
                    "right_fields": right_fields,
                    "schema": out_schema,
                },
                f"lookup_{t.name}",
                parallelism=self.parallelism,
            )
        )
        self.graph.add_edge(
            pre.node_id, node.node_id,
            self._edge(pre.node_id, self.parallelism), pre.schema,
        )
        scope = Scope.from_schema(out_schema.schema)
        for c in left.scope.cols:
            if c.qualifier and c.name in out_schema.names:
                scope.add(c.qualifier, c.name,
                          out_schema.names.index(c.name),
                          out_schema.schema.field(c.name).type)
        for f in t.fields:
            if f.name in out_schema.names:
                scope.add(alias, f.name, out_schema.names.index(f.name),
                          f.type)
        return RelOutput(node.node_id, out_schema, scope, window=left.window,
                         window_field=left.window_field)

    def _join_side_projection(
        self, side: RelOutput, keys: List[BoundExpr], tag: str
    ) -> Tuple[RelOutput, int]:
        """Key columns first, then all original columns. Struct keys (window
        structs) are exploded into child columns — Arrow's hash join does
        not take struct keys. Returns (projection, physical key count)."""
        import pyarrow.compute as pc

        exprs: List[BoundExpr] = []
        for k in keys:
            if pa.types.is_struct(k.dtype):
                for j in range(k.dtype.num_fields):
                    fname = k.dtype.field(j).name
                    exprs.append(
                        BoundExpr(
                            (lambda kk, fn: lambda b: pc.struct_field(
                                kk.eval(b), fn))(k, fname),
                            k.dtype.field(j).type,
                            fname,
                        )
                    )
            else:
                exprs.append(k)
        n_phys = len(exprs)
        names = [f"__key{i}" for i in range(n_phys)]
        for i, f in enumerate(side.schema.schema):
            if f.name == TIMESTAMP_FIELD:
                continue
            exprs.append(
                BoundExpr((lambda j: lambda b: b.column(j))(i), f.type, f.name)
            )
            names.append(f.name)
        return self._add_value_node(side, exprs, _dedup(names), None, tag), n_phys

    def _bind_residual(self, residual, out_schema, left, right, lpre, rpre,
                       nkeys):
        scope = _join_output_scope(left, right, lpre, rpre, out_schema, nkeys)
        from functools import reduce

        cond = reduce(lambda a, b: BinaryOp("AND", a, b), residual)
        bound = bind(cond, scope)

        def residual_fn(batch: pa.RecordBatch):
            return bound.eval(batch)

        return residual_fn

    # -- unions -------------------------------------------------------------

    def _plan_union(self, a: RelOutput, b: RelOutput) -> RelOutput:
        if len(a.schema.schema) != len(b.schema.schema):
            raise SqlError("UNION inputs must have the same number of columns")
        # align b's columns to a's schema (by position, cast types)
        exprs = []
        names = []
        for i, f in enumerate(a.schema.schema):
            if f.name == TIMESTAMP_FIELD:
                continue
            bf = b.schema.schema.field(i)
            be = BoundExpr(
                (lambda j: lambda bt: bt.column(j))(i), bf.type, f.name
            )
            if not bf.type.equals(f.type):
                from .expressions import _cast

                be = BoundExpr(
                    (lambda j, t: lambda bt: _cast(bt.column(j), t))(i, f.type),
                    f.type,
                    f.name,
                )
            exprs.append(be)
            names.append(f.name)
        b_aligned = self._add_value_node(b, exprs, names, None, "union_align")
        # merge node: identity op with two forward-ish edges (shuffle to
        # allow differing parallelism)
        node = self.graph.add_node(
            LogicalNode.single(
                self._next_id(),
                OperatorName.ARROW_VALUE,
                {"py_fn": lambda batch: batch, "schema": a.schema},
                "union",
                parallelism=self.parallelism,
            )
        )
        self.graph.add_edge(a.node_id, node.node_id, EdgeType.SHUFFLE, a.schema)
        self.graph.add_edge(
            b_aligned.node_id, node.node_id, EdgeType.SHUFFLE, b_aligned.schema
        )
        return RelOutput(
            node.node_id, a.schema, Scope.from_schema(a.schema.schema)
        )

    # -- sinks --------------------------------------------------------------

    def plan_insert(self, ins: Insert) -> Optional[int]:
        sink_table = self.provider.get_table(ins.table)
        if sink_table is None:
            raise SqlError(f"unknown sink table {ins.table}")
        out = self.plan_select(ins.query)
        if sink_table.is_memory:
            self._connect_memory(sink_table, out)
            return None
        return self._connect_sink(sink_table, out)

    def _connect_memory(self, t: TableDef, out: RelOutput):
        """INSERT INTO a memory (connector-less) table: positional-cast the
        select output to the declared columns and register the node as the
        table's readable stream."""
        if out.updating:
            raise SqlError(
                f"INSERT into memory table {t.name} from an updating "
                "(retracting) stream is not supported"
            )
        if t.name.lower() in self._memory_tables:
            raise SqlError(
                f"memory table {t.name} already has an INSERT; a single "
                "writer defines it"
            )
        declared = t.fields
        if not declared:
            raise SqlError(
                f"memory table {t.name} must declare its columns"
            )
        data_cols = [
            f for f in out.schema.schema if f.name != TIMESTAMP_FIELD
        ]
        if declared and len(declared) != len(data_cols):
            raise SqlError(
                f"memory table {t.name} declares {len(declared)} columns, "
                f"query produces {len(data_cols)}"
            )
        exprs, names = [], []
        for df, qf in zip(declared, data_cols):
            idx = out.schema.schema.names.index(qf.name)
            be = BoundExpr(
                (lambda j: lambda b: b.column(j))(idx), qf.type, df.name
            )
            if not qf.type.equals(df.type):
                from .expressions import _cast

                be = BoundExpr(
                    (lambda j, tt: lambda b: _cast(b.column(j), tt))(
                        idx, df.type
                    ),
                    df.type,
                    df.name,
                )
            exprs.append(be)
            names.append(df.name)
        rel = self._add_value_node(
            out, exprs, names, None, f"memory_{t.name}"
        )
        self._memory_tables[t.name.lower()] = rel

    def _connect_sink(self, t: TableDef, out: RelOutput) -> int:
        from ..connectors import get_connector

        conn = get_connector(t.connector)
        # cast/select columns to the declared sink schema by position
        from ..schema import UPDATING_META_FIELD

        if out.updating:
            # retract rows need an encoding; plain json/raw sinks would
            # silently serialize them as appends
            fmt = t.options.get("format")
            if fmt != "debezium_json" and t.connector not in (
                "vec", "preview", "blackhole"
            ):
                raise SqlError(
                    f"sink {t.name} receives an updating stream and must use "
                    "format = 'debezium_json' (or a debug sink)"
                )
        declared = t.fields
        data_cols = [
            f for f in out.schema.schema
            if f.name not in (TIMESTAMP_FIELD, UPDATING_META_FIELD)
        ]
        if declared and len(declared) != len(data_cols):
            raise SqlError(
                f"sink {t.name} expects {len(declared)} columns, query "
                f"produces {len(data_cols)}"
            )
        rel = out
        if declared:
            exprs = []
            names = []
            from .expressions import _jx_col

            for i, (df, qf) in enumerate(zip(declared, data_cols)):
                idx = out.schema.schema.names.index(qf.name)
                be = BoundExpr(
                    (lambda j: lambda b: b.column(j))(idx), qf.type, df.name,
                    # column passthroughs/casts must not block segment
                    # lowering (sink_cast is the tail of most chains)
                    jax=_jx_col(idx, qf.type),
                )
                if not qf.type.equals(df.type):
                    from .expressions import _cast, _jx_cast, jax_lowerable_type

                    jx = (
                        _jx_cast(be.jax, df.type)
                        if be.jax is not None
                        and jax_lowerable_type(df.type) else None
                    )
                    be = BoundExpr(
                        (lambda j, tt: lambda b: _cast(b.column(j), tt))(
                            idx, df.type
                        ),
                        df.type,
                        df.name,
                        jax=jx,
                    )
                exprs.append(be)
                names.append(df.name)
            rel = self._add_value_node(out, exprs, names, None, "sink_cast")
        # several INSERT INTO statements targeting one sink table merge
        # into a single sink node with one in-edge per statement (the
        # reference's test_merge_sink.sql shape; barrier alignment across
        # the edges is the runner's normal multi-input path)
        existing = self._sink_nodes.get(t.name)
        if existing is not None:
            prev_schema, sink_par = existing["schema"], existing["par"]
            if not prev_schema.schema.equals(rel.schema.schema):
                raise SqlError(
                    f"INSERT statements into sink {t.name} produce "
                    "different schemas (mixing updating and append streams "
                    "into one sink is not supported)"
                )
            self.graph.add_edge(
                rel.node_id, existing["node"],
                self._edge(rel.node_id, sink_par), rel.schema,
            )
            return existing["node"]
        options = conn.validate_options(
            {k: v for k, v in t.options.items()
             if k not in ("connector", "type", "format")},
            None,
        )
        config = {
            "connector": t.connector,
            "schema": rel.schema,
            "format": t.options.get("format"),
            "proto_descriptor": _proto_descriptor(t),
            **options,
        }
        # sinks default to parallelism 1 (single_file/stdout write one
        # stream; scalable sinks opt in via the sink_parallelism option)
        sink_par = int(t.options.get("sink_parallelism", 1))
        node = self.graph.add_node(
            LogicalNode.single(
                self._next_id(),
                OperatorName.CONNECTOR_SINK,
                config,
                t.name,
                parallelism=sink_par,
            )
        )
        self.graph.add_edge(
            rel.node_id, node.node_id,
            self._edge(rel.node_id, sink_par), rel.schema,
        )
        self._sink_nodes[t.name] = {
            "node": node.node_id, "schema": rel.schema, "par": sink_par,
        }
        return node.node_id


# ---------------------------------------------------------------------------
# Aggregate helpers
# ---------------------------------------------------------------------------


def _is_aggregate_name(name: str) -> bool:
    if name in AGG_FUNCS:
        return True
    from ..udf.registry import get_udaf

    return get_udaf(name) is not None


def _proto_descriptor(t) -> Optional[dict]:
    """Load {'descriptor_set', 'message_name'} from the table's
    proto.descriptor_file / proto.message options when format='protobuf'
    (reference proto/schema resolution, arroyo-formats/src/proto)."""
    if t.options.get("format") not in ("protobuf", "proto"):
        return None
    path = t.options.get("proto.descriptor_file")
    msg = t.options.get("proto.message")
    if not path or not msg:
        raise SqlError(
            "format = 'protobuf' requires the proto.descriptor_file "
            "(compiled FileDescriptorSet from `protoc "
            "--descriptor_set_out`) and proto.message options"
        )
    if t.connector in ("single_file", "filesystem"):
        raise SqlError(
            "protobuf is message-framed binary and cannot ride "
            "newline-framed file connectors; use a message-based "
            "connector (e.g. kafka)"
        )
    if t.connector not in ("kafka", "confluent"):
        raise SqlError(
            f"format = 'protobuf' is wired to the kafka/confluent "
            f"connectors; {t.connector} does not carry a descriptor yet"
        )
    try:
        with open(path, "rb") as f:
            return {"descriptor_set": f.read(), "message_name": msg}
    except OSError as e:
        raise SqlError(f"cannot read proto.descriptor_file {path!r}: {e}")


def _expr_children(e: Expr):
    """Immediate child expressions of an AST node, discovered generically
    through its dataclass fields (lists/tuples flattened) so walkers never
    miss a position — CASE branches, IN lists, BETWEEN bounds included."""

    def flatten(v):
        if isinstance(v, Expr):
            yield v
        elif isinstance(v, (list, tuple)):
            for item in v:
                yield from flatten(item)

    for f in dataclasses.fields(e):
        yield from flatten(getattr(e, f.name))


def _find_aggregates(e: Expr) -> List[FuncCall]:
    out: List[FuncCall] = []

    def walk(x):
        if (
            isinstance(x, FuncCall)
            and _is_aggregate_name(x.name)
            and x.over is None
        ):
            out.append(x)
            return  # don't descend into agg args
        for c in _expr_children(x):
            walk(c)

    walk(e)
    return out


def _agg_column_args(call: FuncCall) -> List[Expr]:
    """The column-input arguments of an aggregate call (trailing literal
    parameters like the percentile fraction excluded), arity-checked."""
    n_params = PARAM_AGGS.get(call.name, 0)
    col_args = call.args[: len(call.args) - n_params] if n_params else list(
        call.args
    )
    if call.name in TWO_ARG_AGGS:
        want = 2
    elif call.name not in AGG_FUNCS:
        from ..udf.registry import get_udaf

        u = get_udaf(call.name)
        want = min(len(u.arg_types), 2) if u is not None else 1
    else:
        want = 1
    if len(col_args) != want:
        raise SqlError(
            f"{call.name}() takes {want} column argument(s)"
            + (f" plus {n_params} literal parameter(s)" if n_params else "")
        )
    for p in call.args[len(col_args):]:
        if not isinstance(p, Literal):
            raise SqlError(
                f"{call.name}(): the trailing parameter must be a literal"
            )
    return col_args


def _collect_aggregates(items, scope):
    """Unique aggregate calls across select items + their bound column
    inputs (a list per call: [] for count(*), one entry for most, two for
    the regression family / weighted percentile)."""
    agg_calls: List[FuncCall] = []
    for it in items:
        for call in _find_aggregates(it.expr):
            if call not in agg_calls:
                agg_calls.append(call)
    agg_inputs: List[List[BoundExpr]] = []
    for call in agg_calls:
        if call.star or not call.args:
            if call.name != "count":
                raise SqlError(f"{call.name}() requires an argument")
            agg_inputs.append([])
            continue
        agg_inputs.append(
            [bind(a, scope) for a in _agg_column_args(call)]
        )
    return agg_calls, agg_inputs


def _rewrite_group_refs(
    e: Expr, group_exprs: List[Expr], key_names: List[str]
) -> Expr:
    """Replace subtrees structurally equal to a group-by expression with a
    reference to the aggregate's key output column."""
    if e is None:
        return None
    for g, name in zip(group_exprs, key_names):
        if e == g:
            return Column(name)
    if isinstance(e, BinaryOp):
        return BinaryOp(
            e.op,
            _rewrite_group_refs(e.left, group_exprs, key_names),
            _rewrite_group_refs(e.right, group_exprs, key_names),
        )
    if isinstance(e, FieldAccess):
        return FieldAccess(
            _rewrite_group_refs(e.base, group_exprs, key_names), e.field
        )
    if isinstance(e, FuncCall):
        return FuncCall(
            e.name,
            [_rewrite_group_refs(a, group_exprs, key_names) for a in e.args],
            e.distinct,
            e.star,
            e.over,
        )
    return e


def _rewrite_aggregates(
    e: Expr, calls: List[FuncCall], names: List[str]
) -> Expr:
    """Replace aggregate calls in an expression with references to the
    window operator's output columns."""
    if e is None:
        return None
    for call, name in zip(calls, names):
        if e == call:
            return Column(name)
    if isinstance(e, BinaryOp):
        return BinaryOp(
            e.op,
            _rewrite_aggregates(e.left, calls, names),
            _rewrite_aggregates(e.right, calls, names),
        )
    if isinstance(e, FieldAccess):
        return FieldAccess(_rewrite_aggregates(e.base, calls, names), e.field)
    if isinstance(e, FuncCall) and not (
        _is_aggregate_name(e.name) and e.over is None
    ):
        return FuncCall(
            e.name,
            [_rewrite_aggregates(a, calls, names) for a in e.args],
            e.distinct,
            e.star,
            e.over,
        )
    return e


def _make_spec(call: FuncCall, col_idx: list, pre_exprs, name: str) -> dict:
    from ..udf.registry import get_udaf

    kind = AGG_ALIASES.get(call.name, call.name)
    udaf = None
    if kind not in AGG_FUNCS and get_udaf(call.name) is not None:
        kind, udaf = "udaf", call.name
    distinct = False
    if call.distinct:
        if kind == "count":
            kind = "count_distinct"
        elif kind in ("sum", "avg", "min", "max") or kind == "udaf" or (
            kind in ("median", "approx_median", "array_agg")
        ):
            # dedupe through the value multiset, finalized per kind
            distinct = True
        else:
            raise SqlError(
                f"DISTINCT is not supported with {kind}()"
            )
    col = col_idx[0] if col_idx else None
    col2 = col_idx[1] if len(col_idx) > 1 else None
    param = None
    if call.name in PARAM_AGGS:
        lit = call.args[-1]
        param = float(lit.value)
        if not 0.0 <= param <= 1.0:
            raise SqlError(
                f"{call.name}(): percentile must be between 0 and 1"
            )
    is_float = (
        col is not None
        and pa.types.is_floating(pre_exprs[col].dtype)
    ) or kind == "avg"
    return {"kind": kind, "col": col, "name": name,
            "is_float": is_float, "udaf": udaf, "col2": col2,
            "param": param, "distinct": distinct}


def _agg_output_type(spec: dict, call: FuncCall, pre_schema: pa.Schema):
    from ..ops.aggregates import REGR_KINDS, VAR_KINDS

    kind = spec["kind"]
    if kind == "udaf":
        from ..udf.registry import get_udaf

        return get_udaf(spec["udaf"]).return_type
    if kind in ("count", "count_distinct", "approx_distinct",
                "bit_and", "bit_or", "bit_xor", "regr_count"):
        return pa.int64()
    if (
        kind == "avg"
        or kind in VAR_KINDS
        or kind in REGR_KINDS
        or kind in ("median", "approx_median", "approx_percentile_cont",
                    "approx_percentile_cont_with_weight")
    ):
        return pa.float64()
    if kind in ("bool_and", "bool_or"):
        return pa.bool_()
    col_t = pre_schema.field(spec["col"]).type
    if kind == "array_agg":
        return pa.list_(col_t)
    if kind == "sum":
        if pa.types.is_floating(col_t):
            return pa.float64()
        return pa.int64()
    return col_t  # min/max preserve type


def _agg_post_scope(agg_out, key_names, group_exprs, agg_calls, agg_names):
    """Scope over the window op output: group keys resolvable by their
    original names AND qualified forms."""
    scope = Scope.from_schema(agg_out.schema.schema)
    for i, g in enumerate(group_exprs):
        if isinstance(g, Column) and g.table is not None:
            scope.add(g.table, g.name, i, agg_out.schema.schema.field(i).type)
    return scope


# ---------------------------------------------------------------------------
# Join helpers
# ---------------------------------------------------------------------------


def _split_join_condition(cond: Expr):
    """AND-split into (equi pairs, residual exprs)."""
    conjuncts: List[Expr] = []

    def flat(e):
        if isinstance(e, BinaryOp) and e.op == "AND":
            flat(e.left)
            flat(e.right)
        else:
            conjuncts.append(e)

    flat(cond)
    equi, residual = [], []
    for c in conjuncts:
        if isinstance(c, BinaryOp) and c.op == "=":
            equi.append((c.left, c.right))
        else:
            residual.append(c)
    return equi, residual


def _side_of(e: Expr, scope: Scope) -> bool:
    """True if every column in e resolves in scope."""
    ok = True

    def walk(x):
        nonlocal ok
        if isinstance(x, Column):
            if scope.try_resolve(x.name, x.table) is None:
                ok = False
        elif isinstance(x, BinaryOp):
            walk(x.left)
            walk(x.right)
        elif isinstance(x, FieldAccess):
            walk(x.base)
        elif isinstance(x, FuncCall):
            for a in x.args:
                walk(a)
        elif hasattr(x, "operand"):
            walk(x.operand)

    walk(e)
    return ok


def _classify_sides(a: Expr, b: Expr, lscope: Scope, rscope: Scope):
    if _side_of(a, lscope) and _side_of(b, rscope):
        return a, b
    if _side_of(b, lscope) and _side_of(a, rscope):
        return b, a
    return None


def _join_output_fields(lpre: RelOutput, rpre: RelOutput, nkeys: int):
    """Left columns (keys + payload) then right payload; duplicate names get
    _right suffix. Input __updating_meta columns are consumed by the join
    itself (retraction routing), never forwarded. Returns
    (fields, left_names, right_names)."""
    from ..schema import UPDATING_META_FIELD

    fields: List[pa.Field] = []
    left_names: List[str] = []
    right_names: List[str] = []
    seen = set()
    for f in lpre.schema.schema:
        if f.name in (TIMESTAMP_FIELD, UPDATING_META_FIELD):
            continue
        fields.append(f)
        left_names.append(f.name)
        seen.add(f.name)
    for i, f in enumerate(rpre.schema.schema):
        if f.name in (TIMESTAMP_FIELD, UPDATING_META_FIELD) or i < nkeys:
            continue
        name = f.name
        while name in seen:
            name += "_right"
        seen.add(name)
        fields.append(pa.field(name, f.type))
        right_names.append(name)
    return fields, left_names, right_names


def _join_output_scope(left, right, lpre, rpre, out_schema, nkeys) -> Scope:
    scope = Scope.from_schema(out_schema.schema)
    # qualified access: left alias columns at their positions; right alias
    # payload after left block; right KEY columns resolve to the coalesced
    # left key positions
    from ..schema import UPDATING_META_FIELD

    left_quals = {c.qualifier for c in left.scope.cols if c.qualifier}
    right_quals = {c.qualifier for c in right.scope.cols if c.qualifier}
    n_left = len([
        f for f in lpre.schema.schema
        if f.name not in (TIMESTAMP_FIELD, UPDATING_META_FIELD)
    ])
    for q in left_quals:
        for c in left.scope.cols:
            if c.qualifier != q:
                continue
            hit = _find_field(out_schema, c.name)
            if hit is not None:
                scope.add(q, c.name, hit, out_schema.schema.field(hit).type)
    offset = n_left
    right_payload = [
        f for i, f in enumerate(rpre.schema.schema)
        if f.name not in (TIMESTAMP_FIELD, UPDATING_META_FIELD) and i >= nkeys
    ]
    for q in right_quals:
        for c in right.scope.cols:
            if c.qualifier != q:
                continue
            # payload position
            for j, f in enumerate(right_payload):
                if f.name == c.name or f.name == c.name + "_right":
                    idx = offset + j
                    scope.add(q, c.name, idx,
                              out_schema.schema.field(idx).type)
                    break
            else:
                # fall back to the coalesced left copy (join key)
                hit = _find_field(out_schema, c.name)
                if hit is not None:
                    scope.add(q, c.name, hit,
                              out_schema.schema.field(hit).type)
    return scope


def _find_field(schema: StreamSchema, name: str) -> Optional[int]:
    try:
        return schema.schema.names.index(name)
    except ValueError:
        return None


# ---------------------------------------------------------------------------
# misc helpers
# ---------------------------------------------------------------------------


def _contains_unnest(e: Expr) -> bool:
    if isinstance(e, FuncCall) and e.name == "unnest":
        return True
    return any(_contains_unnest(c) for c in _expr_children(e))


def _expr_references(e: Expr, col_name: str) -> bool:
    if isinstance(e, Column) and e.name.lower() == col_name.lower():
        return True
    return any(_expr_references(c, col_name) for c in _expr_children(e))


def _find_item_by_alias(items: List[SelectItem], name: str):
    for it in items:
        if it.alias == name:
            return it
    return None


def _default_name(e: Expr, bound: BoundExpr) -> str:
    if isinstance(e, Column):
        return e.name
    if isinstance(e, FieldAccess):
        return e.field
    if isinstance(e, FuncCall):
        return e.name
    return bound.name


def _dedup(names: List[str]) -> List[str]:
    seen: Dict[str, int] = {}
    out = []
    for n in names:
        if n in seen:
            seen[n] += 1
            out.append(f"{n}_{seen[n]}")
        else:
            seen[n] = 0
            out.append(n)
    return out


def _describe_items(names: List[str]) -> str:
    s = ", ".join(names[:4])
    return f"select({s}{'...' if len(names) > 4 else ''})"


def _passthrough_window_field(upstream: RelOutput, names: List[str]):
    if upstream.window_field and upstream.window_field in names:
        return upstream.window_field
    return None


def _requalify(out: RelOutput, alias: Optional[str]) -> RelOutput:
    scope = Scope.from_schema(out.schema.schema, alias)
    return RelOutput(
        out.node_id, out.schema, scope, out.window, out.window_field,
        out.updating,
    )


def _opt_float(v):
    return float(v) if v is not None else None


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class PlanResult:
    graph: LogicalGraph
    provider: SchemaProvider
    sink_nodes: List[int]


def plan_query(
    sql: str,
    provider: Optional[SchemaProvider] = None,
    parallelism: int = 1,
    preview_results: Optional[list] = None,
) -> PlanResult:
    """Compile a SQL script (CREATE TABLE/VIEW + INSERT/SELECT statements)
    into a LogicalGraph (reference: parse_and_get_arrow_program)."""
    provider = provider or SchemaProvider()
    statements = parse_statements(sql)
    planner = Planner(provider, parallelism)
    sinks: List[int] = []
    queries: List[Select] = []
    inserts: List[Insert] = []
    for st in statements:
        if isinstance(st, CreateTable):
            fields = [
                pa.field(c.name, sql_type_to_arrow(c.type_name), c.nullable)
                for c in st.columns
            ]
            provider.add_table(TableDef(
                st.name, fields, st.options,
                metadata_fields={
                    c.name: c.metadata_key for c in st.columns
                    if c.metadata_key
                },
                generated={
                    c.name: c.generated for c in st.columns
                    if c.generated is not None
                },
            ))
        elif isinstance(st, CreateView):
            provider.add_view(st.name, st.query)
        elif isinstance(st, Insert):
            inserts.append(st)
        elif isinstance(st, Select):
            queries.append(st)
    for ins in inserts:
        sink_id = planner.plan_insert(ins)
        if sink_id is not None:  # memory-table inserts have no sink node
            sinks.append(sink_id)
    for q in queries:
        out = planner.plan_select(q)
        # bare SELECT: attach a preview sink
        node = planner.graph.add_node(
            LogicalNode.single(
                planner._next_id(),
                OperatorName.CONNECTOR_SINK,
                {
                    "connector": "preview",
                    "results": preview_results if preview_results is not None
                    else [],
                    "schema": out.schema,
                },
                "preview",
            )
        )
        planner.graph.add_edge(
            out.node_id, node.node_id,
            planner._edge(out.node_id, 1), out.schema,
        )
        sinks.append(node.node_id)
    if not sinks:
        raise SqlError("query contains no INSERT or SELECT statement")
    # operator chaining at compile time, like the reference
    # (arroyo-planner/src/lib.rs:935-937 behind pipeline.chaining.enabled):
    # fused Forward chains execute in ONE subtask with direct calls, which
    # also guarantees they can never be scheduled onto different workers —
    # unchained, a forward edge crossing workers ships full pre-projection
    # rows (e.g. nexmark structs) over the TCP data plane
    from ..config import config as _config

    if _config().pipeline.chaining_enabled:
        from ..graph import ChainingOptimizer

        ChainingOptimizer().optimize(planner.graph)
    # segment fusion rides ON the chained nodes: maximal runs of
    # stateless value ops inside each chain become one FUSED_SEGMENT op
    # (one dispatch per batch); with engine.segment_fusion off the pass
    # instead annotates the members so the unfused A/B run counts its
    # per-operator dispatches into the same families
    from ..engine.segments import SegmentFusionPass

    SegmentFusionPass().optimize(planner.graph)
    return PlanResult(planner.graph, provider, sinks)
