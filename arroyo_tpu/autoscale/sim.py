"""Deterministic simulation harness: replay load traces through a policy.

Convergence properties ("reaches the right parallelism within N control
periods, then stops moving") are miserable to assert against wall-clock
cluster runs. This harness makes them unit-testable: a `SimJob` models
each operator as a fluid server with a known per-instance true rate, each
`step()` computes the steady-state signals for one control period from the
offered source rate (saturated operators throttle what flows downstream,
and their upstreams read as backpressured), and `run_scenario` drives the
REAL policy + actuation gate (policy.ActuationGate — the same cadence the
live manager runs) over a piecewise-constant load trace, applying each
rescale decision for the next period.

Everything is pure arithmetic: no clock, no randomness, no asyncio — the
same trace always produces the same decision log, which is what the
load-step acceptance test pins. `tools/autoscale_report.py` wraps this for
offline what-if runs against recorded rate traces.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from .policy import ActuationGate, Policy, PolicyDecision, Topology
from .signals import OperatorSignals


@dataclasses.dataclass
class SimOp:
    """One modeled operator. `rate_per_instance` is the true processing
    rate (rows per busy-second) of a single parallel instance; sources
    have no processing model — they emit the offered rate."""

    node_id: int
    rate_per_instance: float = 0.0
    parallelism: int = 1
    selectivity: float = 1.0
    source: bool = False
    sink: bool = False


class SimJob:
    """A DAG of SimOps. `edges` are (src, dst) node-id pairs."""

    def __init__(self, ops: Sequence[SimOp],
                 edges: Sequence[Tuple[int, int]]):
        self.ops = {op.node_id: op for op in ops}
        self.edges = list(edges)
        self._order = self._topo()

    def _topo(self) -> List[int]:
        indeg = {nid: 0 for nid in self.ops}
        for _s, d in self.edges:
            indeg[d] += 1
        order, ready = [], sorted(n for n, d in indeg.items() if d == 0)
        while ready:
            nid = ready.pop(0)
            order.append(nid)
            for s, d in self.edges:
                if s == nid:
                    indeg[d] -= 1
                    if indeg[d] == 0:
                        ready.append(d)
            ready.sort()
        assert len(order) == len(self.ops), "cycle in sim DAG"
        return order

    def topology(self) -> Topology:
        return Topology(
            order=list(self._order),
            upstream={
                nid: [s for s, d in self.edges if d == nid]
                for nid in self._order
            },
            current={nid: op.parallelism for nid, op in self.ops.items()},
            scalable={
                nid: not (op.source or op.sink)
                for nid, op in self.ops.items()
            },
        )

    def apply(self, targets: Dict[int, int]) -> None:
        for nid, p in targets.items():
            self.ops[nid].parallelism = max(1, p)

    def step(self, offered_rate: float) -> Dict[int, OperatorSignals]:
        """Steady-state signals for one control period at the given
        offered source rate. A saturated operator processes at capacity
        and throttles its downstream flow; its upstreams read full output
        queues (backpressure 1.0)."""
        flow: Dict[int, float] = {}       # actual emitted rate per op
        sigs: Dict[int, OperatorSignals] = {}
        saturated: set = set()
        for nid in self._order:
            op = self.ops[nid]
            ups = [s for s, d in self.edges if d == nid]
            if op.source or not ups:
                flow[nid] = offered_rate * op.selectivity
                sigs[nid] = OperatorSignals(
                    node_id=nid, parallelism=op.parallelism,
                    observed_rate=offered_rate,
                    output_rate=flow[nid],
                    selectivity=op.selectivity,
                )
                continue
            arriving = sum(flow[u] for u in ups)
            capacity = op.rate_per_instance * op.parallelism
            processed = min(arriving, capacity) if capacity > 0 else arriving
            busy = min(1.0, arriving / capacity) if capacity > 0 else 0.0
            if capacity > 0 and arriving > capacity:
                saturated.add(nid)
            flow[nid] = processed * op.selectivity
            sigs[nid] = OperatorSignals(
                node_id=nid, parallelism=op.parallelism,
                observed_rate=processed,
                output_rate=flow[nid],
                busy_ratio=busy,
                true_rate_per_instance=(
                    op.rate_per_instance if op.rate_per_instance > 0
                    else None
                ),
                selectivity=op.selectivity,
            )
        # an op whose downstream is saturated sees its output queue full
        for s, d in self.edges:
            if d in saturated:
                sigs[s].backpressure = 1.0
        return sigs


@dataclasses.dataclass
class SimRecord:
    period: int
    offered_rate: float
    action: str
    parallelism: Dict[int, int]          # AFTER this period's actuation
    targets: Dict[int, int]
    reasons: Dict[int, str]
    signals: Dict[int, dict]

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def run_scenario(job: SimJob, policy: Policy, cfg,
                 load_steps: Sequence[Tuple[int, float]],
                 gate: Optional[ActuationGate] = None) -> List[SimRecord]:
    """Drive `policy` over a piecewise-constant load trace:
    load_steps = [(n_periods, offered_rate), ...]. Each period: compute
    signals at the current parallelism, decide, gate, actuate. Returns the
    decision audit log (one record per control period)."""
    gate = gate or ActuationGate(cfg)
    log: List[SimRecord] = []
    period = 0
    for n_periods, rate in load_steps:
        for _ in range(n_periods):
            sigs = job.step(rate)
            decision: PolicyDecision = policy.decide(
                job.topology(), sigs, cfg
            )
            current = {nid: op.parallelism for nid, op in job.ops.items()}
            changed = decision.changed(current)
            action = gate.check(changed)
            if action == "rescale":
                job.apply(changed)
            log.append(SimRecord(
                period=period,
                offered_rate=rate,
                action=action,
                parallelism={
                    nid: op.parallelism for nid, op in job.ops.items()
                },
                targets=dict(decision.targets),
                reasons=dict(decision.reasons),
                signals={nid: s.summary() for nid, s in sigs.items()},
            ))
            period += 1
    return log


def converged_within(log: List[SimRecord], start: int,
                     periods: int) -> bool:
    """True when parallelism stops changing within `periods` periods of
    `start` and never moves again before the next load step (callers
    slice the log per step)."""
    window = log[start:start + periods]
    tail = log[start + periods:]
    if not window:
        return False
    settled = window[-1].parallelism
    return all(r.parallelism == settled for r in tail)
