"""MUST fire RACE003: `fired` is declared ``guarded_by("_lock")`` but is
mutated (append, clear) and read without the lock held."""
from arroyo_tpu.analysis.races import guarded_by


@guarded_by("_lock", "fired")
class Plan:
    def __init__(self):
        self.fired = []
        self._lock = None


class Driver:
    def touch(self, plan):
        plan.fired.append(1)

    def drain(self, plan):
        plan.fired.clear()

    def peek(self, plan):
        return len(plan.fired)
