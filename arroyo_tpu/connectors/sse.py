"""Placeholder: sse connector lands with the connector milestone."""
