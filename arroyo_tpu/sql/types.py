"""SQL <-> Arrow type mapping."""

from __future__ import annotations

import pyarrow as pa

from .lexer import SqlError

_TYPES = {
    "BOOLEAN": pa.bool_(),
    "BOOL": pa.bool_(),
    "TINYINT": pa.int8(),
    "SMALLINT": pa.int16(),
    "INT": pa.int32(),
    "INTEGER": pa.int32(),
    "BIGINT": pa.int64(),
    "INT UNSIGNED": pa.uint32(),
    "INTEGER UNSIGNED": pa.uint32(),
    "BIGINT UNSIGNED": pa.uint64(),
    "SMALLINT UNSIGNED": pa.uint16(),
    "TINYINT UNSIGNED": pa.uint8(),
    "FLOAT": pa.float32(),
    "REAL": pa.float32(),
    "DOUBLE": pa.float64(),
    "DOUBLE PRECISION": pa.float64(),
    "DECIMAL": pa.float64(),
    "NUMERIC": pa.float64(),
    "TEXT": pa.string(),
    "STRING": pa.string(),
    "VARCHAR": pa.string(),
    "CHAR": pa.string(),
    "CHARACTER VARYING": pa.string(),
    "BYTEA": pa.binary(),
    "BYTES": pa.binary(),
    "TIMESTAMP": pa.timestamp("ns"),
    "DATETIME": pa.timestamp("ns"),
    "DATE": pa.date32(),
    "TIME": pa.time64("ns"),
    "JSON": pa.string(),
}

WINDOW_TYPE = pa.struct(
    [
        pa.field("start", pa.timestamp("ns")),
        pa.field("end", pa.timestamp("ns")),
    ]
)


def sql_type_to_arrow(name: str) -> pa.DataType:
    up = name.upper().strip()
    if up.endswith(" ARRAY"):
        return pa.list_(sql_type_to_arrow(up[: -len(" ARRAY")]))
    if up in _TYPES:
        return _TYPES[up]
    raise SqlError(f"unsupported SQL type {name!r}")


def arrow_type_to_sql(t: pa.DataType) -> str:
    if pa.types.is_boolean(t):
        return "BOOLEAN"
    if pa.types.is_integer(t):
        if pa.types.is_unsigned_integer(t):
            return "BIGINT UNSIGNED"
        return "BIGINT" if t.bit_width == 64 else "INT"
    if pa.types.is_floating(t):
        return "DOUBLE" if t.bit_width == 64 else "FLOAT"
    if pa.types.is_timestamp(t):
        return "TIMESTAMP"
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return "TEXT"
    if pa.types.is_binary(t):
        return "BYTEA"
    if pa.types.is_struct(t):
        return "STRUCT"
    if pa.types.is_list(t):
        return f"{arrow_type_to_sql(t.value_type)} ARRAY"
    return str(t).upper()


def is_numeric(t: pa.DataType) -> bool:
    return pa.types.is_integer(t) or pa.types.is_floating(t)


def common_type(a: pa.DataType, b: pa.DataType) -> pa.DataType:
    """Binary-op result type promotion."""
    if a.equals(b):
        return a
    if pa.types.is_floating(a) or pa.types.is_floating(b):
        return pa.float64()
    if pa.types.is_integer(a) and pa.types.is_integer(b):
        if pa.types.is_unsigned_integer(a) != pa.types.is_unsigned_integer(b):
            return pa.int64()
        t = a if a.bit_width >= b.bit_width else b
        return t
    if pa.types.is_timestamp(a) and pa.types.is_integer(b):
        return a
    if pa.types.is_integer(a) and pa.types.is_timestamp(b):
        return b
    if (pa.types.is_string(a) and pa.types.is_string(b)):
        return pa.string()
    raise SqlError(f"incompatible types {a} and {b}")
