"""MUST fire CFG001: typo'd section, typo'd key, bad update() override,
bad env-var literal."""
from .config import config, update

ENV_OK = "ARROYO__PIPELINE__BATCH_SIZE"
ENV_BAD = "ARROYO__PIPELINE__BATCH_SZ"


def go():
    ok = config().pipeline.batch_size
    nested_ok = config().pipeline.checkpointing.interval
    typo_key = config().pipeline.batch_sz
    typo_section = config().pipelines.batch_size
    with update(pipeline={"batch_sz": 1}):
        pass
    return ok, nested_ok, typo_key, typo_section
