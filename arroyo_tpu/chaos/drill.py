"""Exactly-once verification drills: golden queries under fault plans.

A drill runs one committed golden query (tests/golden/queries/*.sql)
twice through the REAL embedded cluster — controller + N workers over the
gRPC control plane and TCP data plane:

  1. fault-free, to establish the reference output (also cross-checked
     against the committed golden file when one exists), then
  2. under an installed `FaultPlan` with a throttled source and a fast
     checkpoint cadence, so worker kills, data-plane drops, and storage
     faults land mid-stream and force recovery from durable checkpoints.

The drill passes iff the faulted run's canonicalized sink output is
identical to the fault-free run's AND every scheduled fault actually
fired (an unfired fault means the protocol wasn't exercised — that's a
coverage failure, not a pass). The fired-fault log's comparable view is
a pure function of the plan's seed, which is the reproducibility the
acceptance criteria pin.

Debezium outputs are compared by merged net state keyed by the query's
`--pk=` header — the retract/append interleaving is timing-dependent,
the net state is not (same canonicalization as tests/test_golden.py).
"""

from __future__ import annotations

import asyncio
import dataclasses
import glob
import json
import os
import random
from typing import Callable, Dict, List, Optional

from .. import chaos
from ..utils.logging import get_logger
from .plan import FaultPlan

logger = get_logger("chaos.drill")

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
DEFAULT_GOLDEN_DIR = os.path.join(REPO_ROOT, "tests", "golden")

# acceptance set: one windowed aggregate, one join, one updating query
DEFAULT_DRILL_QUERIES = (
    "hourly_by_event_type",   # tumbling windowed aggregate
    "offset_impulse_join",    # windowed join across two sources
    "updating_aggregate",     # updating aggregate with retractions
)


# -- golden-query plumbing (mirrors tests/test_golden.py) --------------------


def query_headers(path: str) -> Dict[str, str]:
    headers = {}
    for line in open(path):
        line = line.strip()
        if not line.startswith("--") or "=" not in line:
            break
        k, v = line[2:].split("=", 1)
        headers[k.strip()] = v.strip()
    return headers


def register_query_udfs(headers: Dict[str, str], golden_dir: str) -> None:
    if "udf" in headers:
        from ..udf import registry

        src = open(os.path.join(golden_dir, headers["udf"])).read()
        registry.register_from_source(src)


def load_query(path: str, output_path: str, golden_dir: str,
               throttle: Optional[float] = None) -> str:
    sql = open(path).read()
    sql = sql.replace("$input_dir", os.path.join(golden_dir, "inputs"))
    sql = sql.replace("$output_path", output_path)
    if throttle:
        sql = sql.replace(
            "type = 'source'",
            f"type = 'source',\n  throttle_per_sec = '{throttle}'",
        )
    return sql


def read_rows(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def canonical(rows: List[dict]) -> List[str]:
    return sorted(json.dumps(r, sort_keys=True, default=str) for r in rows)


def merge_debezium(rows: List[dict], pk: List[str]) -> List[dict]:
    state = {}
    for env in rows:
        if env["op"] == "d":
            key = tuple(env["before"][c] for c in pk)
            state.pop(key, None)
        else:
            row = env["after"]
            state[tuple(row[c] for c in pk)] = row
    return [state[k] for k in sorted(state)]


def canonicalize_output(path: str, sql: str,
                        headers: Dict[str, str]) -> List[str]:
    rows = read_rows(path)
    if "debezium_json" in sql:
        pk = headers.get("pk", "").split(",") if headers.get("pk") else None
        assert pk, "debezium drill queries need a --pk= header"
        return canonical(merge_debezium(rows, pk))
    return canonical(rows)


# -- fault plans -------------------------------------------------------------


def standard_plan(seed: int) -> FaultPlan:
    """The acceptance plan: SIGKILL a worker mid-window, drop a data-plane
    connection, and fail a manifest CAS write — each at a seed-chosen hit
    index. Hit windows are small enough that every fault is reachable in
    a throttled multi-second run, so the full schedule always fires and
    the comparable fired log equals `plan.expected_log()`."""
    rng = random.Random(int(seed))
    plan = FaultPlan(seed)
    # heartbeat ticks arrive every worker.heartbeat_interval across all
    # in-process workers (2 workers at 0.1s ≈ 20 hits/s): hits 8-16 land
    # the kill 0.4-0.8s in — after the job is Running, well before the
    # throttled source drains
    plan.add("worker.kill", at_hits=(rng.randint(8, 16),))
    plan.add("network.drop_connection", at_hits=(rng.randint(4, 16),))
    plan.add(
        "storage.cas_conflict",
        at_hits=(rng.randint(1, 2),),
        match={"key": "checkpoint-manifest"},
    )
    return plan


def fast_plan(seed: int) -> FaultPlan:
    """Smoke plan for the default (tier-1) suite: two quickly-detected
    faults, no heartbeat-timeout wait."""
    rng = random.Random(int(seed))
    plan = FaultPlan(seed)
    plan.add("network.drop_connection", at_hits=(rng.randint(3, 10),))
    plan.add(
        "storage.cas_conflict",
        at_hits=(1,),
        match={"key": "checkpoint-manifest"},
    )
    return plan


# -- drill execution ---------------------------------------------------------


@dataclasses.dataclass
class DrillResult:
    query: str
    seed: int
    passed: bool
    rows: int
    restarts: int
    fired: List[dict]          # full fired-fault log (wall-clock + ctx)
    comparable_log: List[dict]  # the reproducible view
    expected_log: List[dict]
    unfired: List[dict]
    error: Optional[str] = None
    # drill-specific measurements (e.g. the state-bloat flatness stats)
    extras: Optional[dict] = None
    # conservation-ledger breaches recorded DURING the drill (obs/audit.py):
    # auditing is on by default and every drill asserts audit SILENCE, so
    # this must be empty for passed=True
    audit_breaches: List[dict] = dataclasses.field(default_factory=list)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def _audit_mark() -> int:
    """Snapshot the conservation-ledger breach ring before a drill run.
    The ring survives job expunge precisely so this assertion works after
    the embedded controller tears the drill jobs down."""
    from ..obs import audit

    return audit.breach_mark()


def _audit_verdict(mark: int, passed: bool, error: Optional[str]):
    """Fold conservation breaches recorded since `mark` into the drill
    verdict: a single breach fails the drill even when the sink output
    is byte-identical — silent corruption is exactly what the ledger
    exists to catch."""
    from ..obs import audit

    breaches = audit.breaches_since(mark)
    if breaches and error is None:
        b = breaches[0]
        error = (
            f"{len(breaches)} conservation breach(es); first: "
            f"[{b['kind']}] edge={b['edge']} epoch={b['epoch']}: "
            f"{b['detail']}"
        )
    return passed and not breaches, error, breaches


def _run_embedded(sql: str, job_id: str, storage_url: Optional[str],
                  n_workers: int, parallelism: int, max_restarts: int,
                  heartbeat_interval: float, heartbeat_timeout: float,
                  checkpoint_interval: float, timeout: float) -> int:
    """One job through controller + embedded workers; returns restarts.
    Raises on FAILED."""
    from ..config import update
    from ..controller.controller import ControllerServer
    from ..controller.scheduler import EmbeddedScheduler
    from ..controller.state_machine import JobState

    async def go():
        with update(
            worker={"heartbeat_interval": heartbeat_interval},
            controller={"heartbeat_timeout": heartbeat_timeout},
            pipeline={"checkpointing": {"interval": checkpoint_interval}},
        ):
            c = await ControllerServer(
                EmbeddedScheduler(), max_restarts=max_restarts
            ).start()
            try:
                await c.submit_job(
                    job_id, sql=sql, storage_url=storage_url,
                    n_workers=n_workers, parallelism=parallelism,
                )
                state = await c.wait_for_state(
                    job_id, JobState.FINISHED, JobState.FAILED,
                    timeout=timeout,
                )
                job = c.jobs[job_id]
                if state != JobState.FINISHED:
                    raise RuntimeError(
                        f"drill job {job_id} failed: {job.failure}"
                    )
                return job.restarts
            finally:
                await c.stop()

    return asyncio.run(go())


def run_drill(
    query_name: str,
    seed: int,
    workdir: str,
    plan_factory: Callable[[int], FaultPlan] = standard_plan,
    golden_dir: str = DEFAULT_GOLDEN_DIR,
    n_workers: int = 2,
    parallelism: int = 2,
    throttle: float = 150.0,
    heartbeat_interval: float = 0.1,
    heartbeat_timeout: float = 1.5,
    checkpoint_interval: float = 0.15,
    timeout: float = 120.0,
) -> DrillResult:
    """Run one golden query fault-free, then under `plan_factory(seed)`,
    and verify byte-identical canonical sink output.

    The fault-free reference intentionally runs with SEGMENT FUSION OFF
    while the faulted run keeps the default (fusion + pipelining ON):
    every drill is therefore also a fused-vs-unfused A/B — the fused
    data plane must produce byte-identical output to the per-operator
    plan AND survive the fault schedule (ISSUE 14)."""
    from ..config import update

    query_path = os.path.join(golden_dir, "queries", f"{query_name}.sql")
    headers = query_headers(query_path)
    register_query_udfs(headers, golden_dir)
    os.makedirs(workdir, exist_ok=True)
    audit_mark = _audit_mark()

    # 1. fault-free reference through the same embedded cluster, on the
    # UNFUSED data plane (segment fusion off)
    clean_out = os.path.join(workdir, f"{query_name}-clean.json")
    clean_sql = load_query(query_path, clean_out, golden_dir)
    assert chaos.installed() is None, "a fault plan is already installed"
    with update(engine={"segment_fusion": False}):
        _run_embedded(
            clean_sql, f"drill-{query_name}-clean", None, n_workers,
            parallelism, max_restarts=0,
            heartbeat_interval=heartbeat_interval,
            heartbeat_timeout=30.0, checkpoint_interval=60.0,
            timeout=timeout,
        )
    want = canonicalize_output(clean_out, clean_sql, headers)
    if not want:
        raise RuntimeError(f"{query_name}: fault-free run produced no output")
    golden_file = os.path.join(golden_dir, "golden_outputs",
                               f"{query_name}.json")
    if os.path.exists(golden_file):
        committed = [line.strip() for line in open(golden_file)]
        if want != committed:
            raise RuntimeError(
                f"{query_name}: fault-free embedded-cluster output "
                "diverges from the committed golden — fix that before "
                "trusting any drill"
            )

    # 2. faulted run: throttled source + fast checkpoint cadence so the
    # scheduled faults land mid-stream
    fault_out = os.path.join(workdir, f"{query_name}-faulted.json")
    fault_sql = load_query(query_path, fault_out, golden_dir,
                           throttle=throttle)
    plan = chaos.install(plan_factory(seed))
    error = None
    restarts = 0
    try:
        restarts = _run_embedded(
            fault_sql, f"drill-{query_name}-faulted",
            os.path.join(workdir, f"{query_name}-ck"), n_workers,
            parallelism, max_restarts=8,
            heartbeat_interval=heartbeat_interval,
            heartbeat_timeout=heartbeat_timeout,
            checkpoint_interval=checkpoint_interval, timeout=timeout,
        )
    except Exception as e:  # noqa: BLE001 - recorded in the result
        error = repr(e)
    finally:
        chaos.clear()

    got = canonicalize_output(fault_out, fault_sql, headers)
    passed = error is None and got == want and not plan.unfired()
    if error is None and got != want:
        error = (
            f"output diverged: {len(got)} rows vs {len(want)} fault-free"
        )
    if error is None and plan.unfired():
        error = f"unfired faults: {[s.describe() for s in plan.unfired()]}"
    passed, error, audit_breaches = _audit_verdict(audit_mark, passed, error)
    return DrillResult(
        query=query_name,
        seed=seed,
        passed=passed,
        rows=len(got),
        restarts=restarts,
        fired=plan.fired_log(),
        comparable_log=plan.comparable_log(),
        expected_log=plan.expected_log(),
        unfired=[s.describe() for s in plan.unfired()],
        error=error,
        audit_breaches=audit_breaches,
    )


def run_drills(query_names, seed: int, workdir: str,
               plan_factory: Callable[[int], FaultPlan] = standard_plan,
               **kw) -> List[DrillResult]:
    out = []
    for i, name in enumerate(query_names):
        logger.info("drill %d/%d: %s (seed %s)", i + 1, len(query_names),
                    name, seed)
        out.append(run_drill(name, seed, os.path.join(workdir, name),
                             plan_factory=plan_factory, **kw))
    return out


# -- rescale drill (autoscaler-triggered, faulted mid-rescale) ---------------


def rescale_plan(seed: int) -> FaultPlan:
    """Faults aimed at the autoscaler's actuation path: stretch the
    decide->stop window, SIGKILL a worker inside it (the stop checkpoint
    fails, the job recovers, the autoscaler re-decides), then — on the
    rescale that survives to the generation-OVERLAP window (stop
    checkpoint durable, old generation draining, new incarnation staged
    and restoring) — SIGKILL a pool worker INSIDE that window and fail
    the promote (recovery must come back at the new parallelism). Every
    rescale.* fault implies a rescale actually triggered."""
    rng = random.Random(int(seed))
    plan = FaultPlan(seed)
    plan.add("rescale.stop_delay", at_hits=(1,),
             params={"delay": 0.8}, max_fires=1)
    # heartbeats tick every 0.1s across 2 workers (~20 hits/s): land the
    # kill around the first rescale decision (~0.9s in) so it interrupts
    # the decide/stop window the delay above holds open
    plan.add("worker.kill", at_hits=(rng.randint(16, 26),))
    # the first rescale to reach the overlap window (the staged new
    # incarnation is restoring, the old one draining): SIGKILL a pool
    # worker right there — byte-identical output is still required
    plan.add("rescale.overlap_kill", at_hits=(1,))
    # always the FIRST reschedule attempt: a rescale that survives the
    # kill may be the only one (min==max converges in a single step)
    plan.add("rescale.reschedule_fail", at_hits=(1,))
    return plan


def _measure_rescale_gap(mode: str, workdir: str,
                         timeout: float = 90.0) -> dict:
    """Output-gap probe (ISSUE 15): run a fault-free replay-impulse
    windowed pipeline, trigger ONE manual 1->2 rescale (source + window —
    the elastic-source path), and measure the RESCALING -> RUNNING wall
    time from the job's transition log plus the `rescale.overlap` span's
    own gap_ms. `mode` pins rescale.mode, so the same probe measures the
    generation-overlap path AND the stop-the-world baseline."""
    import asyncio as aio

    from .. import obs
    from ..config import update
    from ..controller.controller import ControllerServer
    from ..controller.scheduler import EmbeddedScheduler
    from ..controller.state_machine import JobState

    os.makedirs(workdir, exist_ok=True)
    out = os.path.join(workdir, f"gap-{mode}.json")
    n = 4000
    sql = f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '2000',
      message_count = '{n}', start_time = '0',
      realtime = 'true', replay = 'true'
    );
    CREATE TABLE out (k BIGINT UNSIGNED, start TIMESTAMP, cnt BIGINT) WITH (
      connector = 'single_file', path = '{out}',
      format = 'json', type = 'sink'
    );
    INSERT INTO out
    SELECT k, window.start as start, cnt FROM (
      SELECT counter % 4 as k, tumble(interval '500 millisecond') as window,
             count(*) as cnt
      FROM impulse GROUP BY 1, 2
    );
    """

    async def go():
        with update(pipeline={"checkpointing": {"interval": 0.25}},
                    rescale={"mode": mode}):
            obs.reset()
            c = await ControllerServer(EmbeddedScheduler()).start()
            try:
                await c.submit_job(
                    f"gap-{mode}", sql=sql,
                    storage_url=os.path.join(workdir, f"gap-{mode}-ck"),
                    n_workers=2, parallelism=1,
                )
                await c.wait_for_state(f"gap-{mode}", JobState.RUNNING,
                                       timeout=30)
                await aio.sleep(0.8)
                job = c.jobs[f"gap-{mode}"]
                targets = {
                    nid: 2 for nid, nd in job.graph.nodes.items()
                    if not nd.is_sink
                }
                await c.rescale_job(f"gap-{mode}", targets)
                state = await c.wait_for_state(
                    f"gap-{mode}", JobState.FINISHED, JobState.FAILED,
                    timeout=timeout,
                )
                events = list(job.events)
                spans = [
                    dict(s.get("attrs", {}))
                    for s in obs.recorder().snapshot()
                    if s.get("name") == "rescale.overlap"
                ]
                return state, job.failure, job.rescales, events, spans
            finally:
                await c.stop()

    state, failure, rescales, events, spans = asyncio.run(go())
    # RESCALING-entry -> back-to-RUNNING from the transition log: the
    # comparable gap measure across both modes (covers drain + stop
    # checkpoint + handoff; sources resume right after RUNNING)
    gaps = []
    t_resc = None
    for e in events:
        if e["to"] == "Rescaling":
            t_resc = e["time"]
        elif e["to"] == "Running" and t_resc is not None:
            gaps.append((e["time"] - t_resc) / 1e6)
            t_resc = None
    span_gaps = sorted(float(s["gap_ms"]) for s in spans if "gap_ms" in s)
    return {
        "mode": mode,
        "finished": str(state),
        "failure": failure,
        "rescales": rescales,
        "rescaling_to_running_ms": [round(g, 1) for g in sorted(gaps)],
        "overlap_gap_ms_p50": round(
            span_gaps[len(span_gaps) // 2], 1) if span_gaps else None,
        "overlap_gap_ms_max": round(span_gaps[-1], 1) if span_gaps else None,
    }


def run_rescale_drill(seed: int, workdir: str,
                      query_name: str = "hourly_by_event_type",
                      golden_dir: str = DEFAULT_GOLDEN_DIR,
                      throttle: float = 120.0,
                      timeout: float = 180.0) -> DrillResult:
    """Exactly-once through an AUTOSCALER-triggered rescale under faults.

    The reference run executes the golden fault-free. The drill run
    starts the same query at parallelism 1 with the autoscaler on and
    `autoscale.min_parallelism = 2`: the unconditional clamp makes the
    first post-warmup decision a deterministic scale-up, so a real
    automatic rescale happens mid-stream without depending on load
    timing. The fault plan kills a worker mid-rescale and fails a later
    rescale between its durable stop checkpoint and the reschedule; the
    canonical sink output must still be byte-identical to the fault-free
    run. The decision audit log is written to
    {workdir}/autoscale_decisions.json (CI uploads it on failure)."""
    from ..config import update
    from ..controller.controller import ControllerServer
    from ..controller.scheduler import EmbeddedScheduler
    from ..controller.state_machine import JobState

    query_path = os.path.join(golden_dir, "queries", f"{query_name}.sql")
    headers = query_headers(query_path)
    register_query_udfs(headers, golden_dir)
    os.makedirs(workdir, exist_ok=True)
    # explicit audit-silence assertion (ISSUE 19): the rescale drill's
    # generation-overlap window is exactly where rewind/zombie classes
    # would surface — a breach here fails the drill outright
    audit_mark = _audit_mark()

    clean_out = os.path.join(workdir, f"{query_name}-clean.json")
    clean_sql = load_query(query_path, clean_out, golden_dir)
    assert chaos.installed() is None, "a fault plan is already installed"
    _run_embedded(
        clean_sql, "drill-rescale-clean", None, 2, 1, max_restarts=0,
        heartbeat_interval=0.1, heartbeat_timeout=30.0,
        checkpoint_interval=60.0, timeout=timeout,
    )
    want = canonicalize_output(clean_out, clean_sql, headers)
    if not want:
        raise RuntimeError(f"{query_name}: fault-free run produced no output")

    fault_out = os.path.join(workdir, f"{query_name}-rescale.json")
    fault_sql = load_query(query_path, fault_out, golden_dir,
                           throttle=throttle)
    plan = chaos.install(rescale_plan(seed))
    from .. import obs

    # fresh span buffer: the drill reports barrier-drain time from the
    # faulted run's runner.pipeline_drain spans (ISSUE 14 — the
    # measurement ROADMAP item 4's generation-overlap rescale needs)
    obs.recorder().clear()
    error = None
    restarts = rescales = 0
    decisions: List[dict] = []

    async def go():
        nonlocal restarts, rescales
        with update(
            worker={"heartbeat_interval": 0.1},
            controller={"heartbeat_timeout": 1.5},
            pipeline={"checkpointing": {"interval": 0.15}},
            autoscale={
                "enabled": True, "period": 0.3, "warmup_periods": 1,
                "cooldown_periods": 2, "min_parallelism": 2,
                "max_parallelism": 2,
            },
        ):
            c = await ControllerServer(
                EmbeddedScheduler(), max_restarts=8
            ).start()
            try:
                await c.submit_job(
                    "drill-rescale-faulted", sql=fault_sql,
                    storage_url=os.path.join(workdir, "rescale-ck"),
                    n_workers=2, parallelism=1,
                )
                state = await c.wait_for_state(
                    "drill-rescale-faulted", JobState.FINISHED,
                    JobState.FAILED, timeout=timeout,
                )
                job = c.jobs["drill-rescale-faulted"]
                restarts, rescales = job.restarts, job.rescales
                decisions.extend(job.autoscale_decisions)
                if state != JobState.FINISHED:
                    raise RuntimeError(
                        f"rescale drill failed: {job.failure}"
                    )
            finally:
                await c.stop()

    try:
        asyncio.run(go())
    except Exception as e:  # noqa: BLE001 - recorded in the result
        error = repr(e)
    finally:
        chaos.clear()
    with open(os.path.join(workdir, "autoscale_decisions.json"), "w") as f:
        json.dump(decisions, f, indent=1, default=str)

    got = canonicalize_output(fault_out, fault_sql, headers)
    passed = (error is None and got == want and not plan.unfired()
              and rescales >= 1)
    if error is None and got != want:
        error = (
            f"output diverged: {len(got)} rows vs {len(want)} fault-free"
        )
    if error is None and plan.unfired():
        error = f"unfired faults: {[s.describe() for s in plan.unfired()]}"
    if error is None and rescales < 1:
        error = "the autoscaler never triggered a rescale"
    # barrier-drain measurement: per-barrier pipeline drain time from the
    # runner.pipeline_drain spans (the data the zero-downtime-rescale arc
    # needs: how long a barrier waits on in-flight staged batches)
    drains = [
        s for s in obs.recorder().snapshot()
        if s.get("name") == "runner.pipeline_drain"
    ]
    drain_ms = sorted(s["dur"] / 1000.0 for s in drains)
    # output-gap-per-rescale probes (ISSUE 15): a fault-free 1->2
    # source+window rescale per mode — the generation-overlap gap
    # (rescale.overlap span, checkpoint interval 0.25s) with the
    # stop-the-world teardown+reschedule baseline recorded alongside
    gap_overlap = gap_stw = None
    gap_error = None
    try:
        gap_overlap = _measure_rescale_gap(
            "overlap", os.path.join(workdir, "gap"))
        gap_stw = _measure_rescale_gap(
            "stop_the_world", os.path.join(workdir, "gap"))
        if "FINISHED" not in gap_overlap["finished"]:
            gap_error = f"overlap gap probe: {gap_overlap['failure']}"
        elif gap_overlap["rescales"] < 1:
            gap_error = "overlap gap probe: no rescale happened"
        elif gap_overlap["overlap_gap_ms_p50"] is None:
            gap_error = "overlap gap probe: no rescale.overlap span"
        elif "FINISHED" not in gap_stw["finished"]:
            gap_error = f"stop-the-world gap probe: {gap_stw['failure']}"
    except Exception as e:  # noqa: BLE001 - probe failure fails the drill
        gap_error = f"gap probe crashed: {e!r}"
    if error is None and gap_error is not None:
        error, passed = gap_error, False
    passed, error, audit_breaches = _audit_verdict(audit_mark, passed, error)
    return DrillResult(
        query=f"rescale_{query_name}",
        seed=seed,
        passed=passed,
        rows=len(got),
        restarts=restarts,
        fired=plan.fired_log(),
        comparable_log=plan.comparable_log(),
        expected_log=plan.expected_log(),
        unfired=[s.describe() for s in plan.unfired()],
        error=error,
        extras={
            "pipeline_drain_barriers": len(drains),
            "pipeline_drain_ms_p50": round(
                drain_ms[len(drain_ms) // 2], 3) if drain_ms else 0.0,
            "pipeline_drain_ms_max": round(drain_ms[-1], 3)
            if drain_ms else 0.0,
            "pipeline_drain_staged_max": max(
                (int(s.get("attrs", {}).get("staged", 0)) for s in drains),
                default=0,
            ),
            "rescale_gap_overlap": gap_overlap,
            "rescale_gap_stop_the_world": gap_stw,
        },
        audit_breaches=audit_breaches,
    )


# -- fused-pipeline drill (ISSUE 14 acceptance) ------------------------------


PIPELINE_DRILL_SQL = """
CREATE TABLE src (
  timestamp TIMESTAMP, k BIGINT NOT NULL, v BIGINT NOT NULL
) WITH (
  connector = 'single_file', path = '$src', format = 'json',
  type = 'source'{throttle}, event_time_field = 'timestamp'
);
CREATE TABLE out (
  k BIGINT NOT NULL, s BIGINT NOT NULL, c BIGINT NOT NULL
) WITH (
  connector = 'single_file', path = '$out', format = 'json', type = 'sink'
);
INSERT INTO out
SELECT k, sum(v_eur) AS s, count(*) AS c FROM (
  SELECT k, v_eur - v_eur % 10 AS v_eur FROM (
    SELECT k % 8 AS k, v * 100 / 121 AS v_eur FROM src WHERE v > 0
  )
)
GROUP BY k, tumble(interval '2 second');
"""


def pipeline_plan(seed: int) -> FaultPlan:
    """SIGKILL a worker while the fused segment's staging queue holds an
    in-flight batch (the throttled source + per-batch cadence keeps the
    two-deep pipeline primed), plus a data-plane drop for good measure —
    recovery must replay from the last durable epoch with no event lost
    or duplicated out of the staged (not yet emitted) batches."""
    rng = random.Random(int(seed))
    plan = FaultPlan(seed)
    plan.add("worker.kill", at_hits=(rng.randint(14, 26),))
    plan.add("network.drop_connection", at_hits=(rng.randint(4, 12),))
    return plan


def run_pipeline_drill(seed: int, workdir: str, n_rows: int = 6000,
                       timeout: float = 150.0) -> DrillResult:
    """ISSUE 14 acceptance: exactly-once through the fused segment
    runtime's double-buffered staging queue. A 3-op stateless chain
    (filter -> convert -> round) feeds a tumbling aggregate; the clean
    reference runs UNFUSED on the host kernels, the faulted run keeps
    fusion + two-deep pipelining ON with the segment's jitted device
    tier forced onto jax-CPU and small batches, so barriers routinely
    arrive while a dispatched batch is staged un-materialized, and a
    worker SIGKILL lands mid-stream. Passes iff
    (a) canonical output is byte-identical (no staged event lost or
    duplicated), (b) the kill forced a real recovery, and (c) the
    runner.pipeline_drain spans prove at least one barrier actually
    drained a staged batch (the scenario exercised what it claims)."""
    from .. import obs
    from ..config import update

    os.makedirs(workdir, exist_ok=True)
    audit_mark = _audit_mark()
    src = os.path.join(workdir, "pipe-in.json")
    with open(src, "w") as f:
        for i in range(n_rows):
            mins, secs = (i // 1200) % 60, (i // 20) % 60
            f.write(json.dumps({
                "k": i % 64,
                "v": (i * 37) % 1000 + 1,
                "timestamp": f"2023-03-01T00:{mins:02d}:{secs:02d}."
                             f"{(i % 20) * 50:03d}Z",
            }) + "\n")

    clean_out = os.path.join(workdir, "pipe-clean.json")
    clean_sql = PIPELINE_DRILL_SQL.replace("$src", src).replace(
        "$out", clean_out).format(throttle="")
    assert chaos.installed() is None, "a fault plan is already installed"
    with update(engine={"segment_fusion": False}):
        _run_embedded(
            clean_sql, "drill-pipe-clean", None, 2, 1, max_restarts=0,
            heartbeat_interval=0.1, heartbeat_timeout=30.0,
            checkpoint_interval=60.0, timeout=timeout,
        )
    want = canonicalize_output(clean_out, clean_sql, {})
    if not want:
        raise RuntimeError("pipeline drill: fault-free run had no output")

    fault_out = os.path.join(workdir, "pipe-faulted.json")
    fault_sql = PIPELINE_DRILL_SQL.replace("$src", src).replace(
        "$out", fault_out).format(
        throttle=",\n  throttle_per_sec = '1500'")
    plan = chaos.install(pipeline_plan(seed))
    obs.recorder().clear()
    error = None
    restarts = 0
    try:
        # small batches + two-deep staging, with the segment's JAX tier
        # forced (jax-CPU): dispatched-but-unmaterialized batches really
        # sit in the staging queue, so barriers land mid-pipeline —
        # host-tier results emit eagerly and would never stage
        with update(engine={"segment_fusion": True, "pipeline_depth": 2},
                    tpu={"enabled": True, "require_accelerator": False},
                    pipeline={"source_batch_size": 64}):
            restarts = _run_embedded(
                fault_sql, "drill-pipe-faulted",
                os.path.join(workdir, "pipe-ck"), 2, 1, max_restarts=8,
                heartbeat_interval=0.1, heartbeat_timeout=1.5,
                checkpoint_interval=0.15, timeout=timeout,
            )
    except Exception as e:  # noqa: BLE001 - recorded in the result
        error = repr(e)
    finally:
        chaos.clear()

    got = canonicalize_output(fault_out, fault_sql, {})
    drains = [
        s for s in obs.recorder().snapshot()
        if s.get("name") == "runner.pipeline_drain"
    ]
    staged_max = max(
        (int(s.get("attrs", {}).get("staged", 0)) for s in drains),
        default=0,
    )
    passed = (error is None and got == want and not plan.unfired()
              and restarts >= 1 and staged_max >= 1)
    if error is None and got != want:
        error = f"output diverged: {len(got)} rows vs {len(want)}"
    if error is None and plan.unfired():
        error = f"unfired faults: {[s.describe() for s in plan.unfired()]}"
    if error is None and restarts < 1:
        error = "the SIGKILL never forced a recovery"
    if error is None and staged_max < 1:
        error = ("no barrier ever drained a staged batch — the drill "
                 "did not exercise the mid-flight pipeline")
    passed, error, audit_breaches = _audit_verdict(audit_mark, passed, error)
    return DrillResult(
        query="fused_pipeline_kill",
        seed=seed,
        passed=passed,
        rows=len(got),
        restarts=restarts,
        fired=plan.fired_log(),
        comparable_log=plan.comparable_log(),
        expected_log=plan.expected_log(),
        unfired=[s.describe() for s in plan.unfired()],
        error=error,
        extras={
            "pipeline_drain_barriers": len(drains),
            "pipeline_drain_staged_max": staged_max,
            "barriers_with_staged": sum(
                1 for s in drains
                if int(s.get("attrs", {}).get("staged", 0)) >= 1
            ),
        },
        audit_breaches=audit_breaches,
    )


# -- state-bloat drill (ROADMAP item 4 acceptance) ---------------------------


STATE_BLOAT_SQL = """
CREATE TABLE src (
  timestamp TIMESTAMP, k BIGINT NOT NULL
) WITH (
  connector = 'single_file', path = '$src', format = 'json',
  type = 'source'{throttle}, event_time_field = 'timestamp'
);
CREATE TABLE out (
  k BIGINT NOT NULL, c BIGINT NOT NULL
) WITH (
  connector = 'single_file', path = '$out', format = 'json', type = 'sink'
);
INSERT INTO out
SELECT k, count(*) as c FROM src
GROUP BY k, session(interval '1 hour');
"""


def state_bloat_plan(seed: int) -> FaultPlan:
    """SIGKILL a worker mid-run with storage latency widening the upload
    windows, so the kill lands while checkpoint flushes are in flight —
    recovery must come back from the last *published* epoch with the
    blob chain intact."""
    rng = random.Random(int(seed))
    plan = FaultPlan(seed)
    plan.add("storage.latency", at_hits=tuple(range(2, 40, 3)),
             match={"key": "/data/"}, params={"delay": 0.08},
             max_fires=13)
    # heartbeats tick ~20/s across 2 workers: land the kill ~1.5-2.5s in,
    # after state has grown but with plenty of run left to re-grow it
    plan.add("worker.kill", at_hits=(rng.randint(30, 50),))
    return plan


def run_state_bloat_drill(seed: int, workdir: str, n_rows: int = 6000,
                          timeout: float = 180.0) -> DrillResult:
    """ROADMAP item 4 acceptance: session state grows ~10x during the
    run (every other row opens a NEW session key; the 1-hour gap keeps
    them all open until end-of-stream), a worker is SIGKILLed mid-upload,
    and the drill asserts (a) byte-identical exactly-once output, (b)
    checkpoint CAPTURE cost stays ~flat late-run vs early-run (median of
    per-epoch max checkpoint.capture span durations, <= 2x + a small
    absolute floor), and (c) the uploaded DELTA byte RATE for the
    session table stays ~flat (median late <= 2x median early, measured
    in bytes per second of epoch wall time so a slipping checkpoint
    cadence on a loaded host doesn't masquerade as state growth; base
    blobs are the amortized rebase cost and reported separately). A
    full-snapshot design shows ~10x growth on both."""
    import time as _time

    from .. import obs
    from ..config import update

    os.makedirs(workdir, exist_ok=True)
    audit_mark = _audit_mark()
    src = os.path.join(workdir, "bloat-in.json")
    with open(src, "w") as f:
        for i in range(n_rows):
            # monotonic event time, one NEW session key per two rows:
            # live state grows linearly all run (~10x early -> late)
            mins, secs = (i // 120) % 60, (i // 2) % 60
            f.write(json.dumps({
                "k": i // 2,
                "timestamp": f"2023-03-01T00:{mins:02d}:{secs:02d}.000Z",
            }) + "\n")

    clean_out = os.path.join(workdir, "bloat-clean.json")
    clean_sql = STATE_BLOAT_SQL.replace("$src", src).replace(
        "$out", clean_out).format(throttle="")
    assert chaos.installed() is None, "a fault plan is already installed"
    _run_embedded(
        clean_sql, "drill-bloat-clean", None, 2, 1, max_restarts=0,
        heartbeat_interval=0.1, heartbeat_timeout=30.0,
        checkpoint_interval=60.0, timeout=timeout,
    )
    want = canonicalize_output(clean_out, clean_sql, {})
    if not want:
        raise RuntimeError("state-bloat: fault-free run produced no output")

    fault_out = os.path.join(workdir, "bloat-faulted.json")
    fault_sql = STATE_BLOAT_SQL.replace("$src", src).replace(
        "$out", fault_out).format(
        throttle=",\n  throttle_per_sec = '1500'")
    plan = chaos.install(state_bloat_plan(seed))
    obs.recorder().clear()
    error = None
    restarts = 0
    storage = os.path.join(workdir, "bloat-ck")
    try:
        # rebase pushed out so the full delta chain survives GC — the
        # drill measures per-epoch delta flatness from the chain files
        with update(state={"rebase_epochs": 500,
                           "max_inflight_flushes": 2}):
            restarts = _run_embedded(
                fault_sql, "drill-bloat-faulted", storage, 2, 1,
                max_restarts=8, heartbeat_interval=0.1,
                heartbeat_timeout=1.5, checkpoint_interval=0.15,
                timeout=timeout,
            )
    except Exception as e:  # noqa: BLE001 - recorded in the result
        error = repr(e)
    finally:
        chaos.clear()

    got = canonicalize_output(fault_out, fault_sql, {})

    # (b) capture flatness from the flight recorder: per-epoch max of
    # checkpoint.capture span durations (ms), early vs late median
    spans = [
        s for s in obs.recorder().snapshot()
        if s.get("name") == "checkpoint.capture"
    ]
    by_epoch: Dict[tuple, float] = {}
    for s in spans:
        ep = s.get("attrs", {}).get("epoch")
        if ep is None:
            continue
        key = (ep, int(s["ts"] // 10_000_000))  # epoch reuse post-restore
        by_epoch[key] = max(by_epoch.get(key, 0.0), s["dur"] / 1000.0)
    ordered = [v for _k, v in sorted(by_epoch.items())]

    def _median(vals):
        vals = sorted(vals)
        return vals[len(vals) // 2] if vals else 0.0

    third = max(1, len(ordered) // 3)
    early_ms, late_ms = _median(ordered[:third]), _median(ordered[-third:])
    capture_flat = late_ms <= 2.0 * early_ms + 2.0

    # (c) delta-bytes flatness from storage.put spans in the flight
    # recording (disk listings lose epochs GC'd after the post-restore
    # rebase). Bases are exact to identify: a chain restarts per
    # generation (the -gNNNNN path component), so each generation's
    # lowest sess epoch is its base; everything else is a delta.
    per_epoch_bytes: Dict[tuple, int] = {}
    per_epoch_ts: Dict[tuple, float] = {}
    for s in obs.recorder().snapshot():
        if s.get("name") != "storage.put":
            continue
        key = s.get("attrs", {}).get("key", "")
        if "-sess-" not in key or not key.endswith(".bin"):
            continue
        try:
            epoch = int(key.split("checkpoint-")[1].split("/")[0])
            gen = key.rsplit("-g", 1)[1].split(".")[0]
        except (IndexError, ValueError):
            continue
        ek = (gen, epoch)
        per_epoch_bytes[ek] = (
            per_epoch_bytes.get(ek, 0) + int(s["attrs"].get("bytes", 0))
        )
        ts = float(s["ts"])
        per_epoch_ts[ek] = min(per_epoch_ts.get(ek, ts), ts)
    bases = {
        (g, min(e for g2, e in per_epoch_bytes if g2 == g))
        for g, _e in per_epoch_bytes
    }
    base_bytes = sum(
        v for k, v in per_epoch_bytes.items() if k in bases
    )
    # flatness is judged on the delta byte RATE (bytes per second of
    # epoch wall time), not bytes per epoch: on a loaded host the
    # checkpoint cadence slips, so a late epoch covers more wall time —
    # and therefore more throttle-paced input rows — than an early one.
    # Raw per-epoch bytes then grow with host slowness, not with state.
    # The throttled source feeds rows at a constant rate, so a
    # delta-encoded chain uploads a ~flat byte rate while a
    # full-snapshot design's rate still grows ~10x with live state.
    rate_series = []
    for g in {g for g, _e in per_epoch_bytes}:
        eps = sorted(e for g2, e in per_epoch_bytes if g2 == g)
        for a, b in zip(eps, eps[1:]):
            dur_s = (per_epoch_ts[(g, b)] - per_epoch_ts[(g, a)]) / 1e6
            if (g, a) in bases or dur_s <= 0.01:
                continue
            rate_series.append((g, a, per_epoch_bytes[(g, a)] / dur_s))
    byte_series = [r for _g, _e, r in sorted(rate_series)]
    bthird = max(1, len(byte_series) // 3)
    early_b = _median(byte_series[:bthird])
    late_b = _median(byte_series[-bthird:])
    bytes_flat = len(byte_series) >= 6 and late_b <= 2.0 * early_b + 4096

    passed = (error is None and got == want and not plan.unfired()
              and restarts >= 1 and capture_flat and bytes_flat)
    if error is None and got != want:
        error = f"output diverged: {len(got)} rows vs {len(want)}"
    if error is None and plan.unfired():
        error = f"unfired faults: {[s.describe() for s in plan.unfired()]}"
    if error is None and restarts < 1:
        error = "the SIGKILL never forced a recovery"
    if error is None and not capture_flat:
        error = (f"capture p99 grew with state: early {early_ms:.2f}ms "
                 f"-> late {late_ms:.2f}ms")
    if error is None and not bytes_flat:
        error = (f"delta byte rate grew with state: "
                 f"early {early_b:.0f} B/s -> late {late_b:.0f} B/s "
                 f"({len(byte_series)} epochs)")
    passed, error, audit_breaches = _audit_verdict(audit_mark, passed, error)
    return DrillResult(
        query="state_bloat_session",
        seed=seed,
        passed=passed,
        rows=len(got),
        restarts=restarts,
        fired=plan.fired_log(),
        comparable_log=plan.comparable_log(),
        expected_log=plan.expected_log(),
        unfired=[s.describe() for s in plan.unfired()],
        error=error,
        extras={
            "capture_ms_early_median": round(early_ms, 3),
            "capture_ms_late_median": round(late_ms, 3),
            "delta_bps_early_median": round(early_b, 1),
            "delta_bps_late_median": round(late_b, 1),
            "rebase_base_bytes": base_bytes,
            "epochs_measured": len(byte_series),
        },
        audit_breaches=audit_breaches,
    )


# -- kafka drill (in-memory fake broker, real connector operators) -----------


KAFKA_DRILL_SQL = """
CREATE TABLE src (
  n BIGINT
) WITH (
  connector = 'kafka', bootstrap_servers = 'fake:9092', topic = 'in',
  type = 'source', format = 'json', source.offset = 'earliest'
);
CREATE TABLE dst (
  n BIGINT
) WITH (
  connector = 'kafka', bootstrap_servers = 'fake:9092', topic = 'out',
  type = 'sink', format = 'json', sink.commit_mode = 'exactly_once'
);
INSERT INTO dst SELECT n * 10 as n FROM src;
"""


def kafka_plan(seed: int) -> FaultPlan:
    """Kill a worker mid-transaction and lose a manifest CAS: the fenced
    producer epochs + 2PC commit records must still deliver each row
    exactly once through the transactional sink."""
    rng = random.Random(int(seed))
    plan = FaultPlan(seed)
    plan.add("worker.kill", at_hits=(rng.randint(8, 14),))
    plan.add(
        "storage.cas_conflict",
        at_hits=(rng.randint(1, 2),),
        match={"key": "checkpoint-manifest"},
    )
    return plan


def run_kafka_drill(seed: int, workdir: str, n_rows: int = 120,
                    timeout: float = 90.0) -> DrillResult:
    """Drive the REAL kafka connector operators over the in-memory fake
    broker through the embedded cluster under a fault plan; assert the
    transactional sink's visible (read-committed) output is exactly-once."""
    import sys

    import arroyo_tpu.connectors.kafka as kmod

    sys.path.insert(0, os.path.join(REPO_ROOT, "tests"))
    try:
        from fake_clients import FakeKafkaBroker
    finally:
        sys.path.remove(os.path.join(REPO_ROOT, "tests"))

    from ..config import update
    from ..controller.controller import ControllerServer
    from ..controller.scheduler import EmbeddedScheduler
    from ..controller.state_machine import JobState

    audit_mark = _audit_mark()
    broker = FakeKafkaBroker(partitions_per_topic=2)
    for i in range(n_rows):
        broker.append("in", i % 2, None, json.dumps({"n": i}).encode(),
                      committed=True, tx_id=None)

    def visible():
        out = []
        for p in sorted(broker.topic("out")):
            for m in broker.visible("out", p):
                if m.committed:
                    out.append(json.loads(m.value())["n"])
        return sorted(out)

    plan = chaos.install(kafka_plan(seed))
    orig = kmod._load_client
    kmod._load_client = lambda: broker.make_module()
    error = None
    restarts = 0

    async def go():
        with update(
            worker={"heartbeat_interval": 0.1},
            # generous timeout: a loaded CI host must not misread an
            # event-loop stall as the injected kill
            controller={"heartbeat_timeout": 2.0},
            pipeline={"checkpointing": {"interval": 0.15}},
        ):
            c = await ControllerServer(
                EmbeddedScheduler(), max_restarts=8
            ).start()
            try:
                await c.submit_job(
                    "kafka-drill", sql=KAFKA_DRILL_SQL,
                    storage_url=os.path.join(workdir, "ck"), n_workers=2,
                    parallelism=1,
                )
                await c.wait_for_state("kafka-drill", JobState.RUNNING,
                                       timeout=30)
                # wait for the transactional sink to commit every row
                import time

                deadline = time.monotonic() + timeout
                while len(visible()) < n_rows:
                    if time.monotonic() > deadline:
                        break
                    if c.jobs["kafka-drill"].state == JobState.FAILED:
                        raise RuntimeError(
                            f"kafka drill failed: "
                            f"{c.jobs['kafka-drill'].failure}"
                        )
                    await asyncio.sleep(0.05)
                await c.stop_job("kafka-drill", "checkpoint")
                await c.wait_for_state(
                    "kafka-drill", JobState.STOPPED, JobState.FAILED,
                    timeout=60,
                )
                return c.jobs["kafka-drill"].restarts
            finally:
                await c.stop()

    try:
        os.makedirs(workdir, exist_ok=True)
        restarts = asyncio.run(go())
    except Exception as e:  # noqa: BLE001
        error = repr(e)
    finally:
        kmod._load_client = orig
        chaos.clear()

    got = visible()
    want = sorted(i * 10 for i in range(n_rows))
    passed = error is None and got == want and not plan.unfired()
    if error is None and got != want:
        dupes = len(got) - len(set(got))
        error = (
            f"kafka output not exactly-once: {len(got)} visible rows "
            f"({dupes} duplicates) vs {n_rows} produced"
        )
    if error is None and plan.unfired():
        error = f"unfired faults: {[s.describe() for s in plan.unfired()]}"
    passed, error, audit_breaches = _audit_verdict(audit_mark, passed, error)
    return DrillResult(
        query="kafka_exactly_once",
        seed=seed,
        passed=passed,
        rows=len(got),
        restarts=restarts,
        fired=plan.fired_log(),
        comparable_log=plan.comparable_log(),
        expected_log=plan.expected_log(),
        unfired=[s.describe() for s in plan.unfired()],
        error=error,
        audit_breaches=audit_breaches,
    )


# -- shared-plan drill (ISSUE 16: N tenants, one scan, one kill) -------------


SHARED_DRILL_SQL = """
CREATE TABLE impulse WITH (
  connector = 'impulse', event_rate = '$rate', message_count = '$n',
  start_time = '0', realtime = 'true', replay = 'true'
);
CREATE TABLE out (k BIGINT UNSIGNED, cnt BIGINT) WITH (
  connector = 'single_file', path = '$out', format = 'json', type = 'sink'
);
INSERT INTO out
SELECT k, cnt FROM (
  SELECT counter % $mod as k,
         tumble(interval '100 millisecond') as w, count(*) as cnt
  FROM impulse GROUP BY 1, 2
);
"""


def shared_plan(seed: int) -> FaultPlan:
    """One worker SIGKILL mid-checkpoint cadence. Same hit window as the
    sharedplan model's counterexample serialization
    (analysis/model/sharedplan.py sp_trace_to_fault_plan): heartbeat
    ticks arrive from THREE in-process workers here (host + 2 tenants at
    0.1s ≈ 30 hits/s), so hits 8-16 land ~0.3-0.6s in — both tenants
    mounted and checkpointing, the bounded scan still mid-stream. Which
    worker dies is seed-chosen; exactly-once per tenant must hold either
    way (tenant death = restore against the retained log; host death =
    durable host resume bounded by the publication gate)."""
    rng = random.Random(int(seed))
    plan = FaultPlan(seed)
    plan.add("worker.kill", at_hits=(rng.randint(8, 16),))
    return plan


def _shared_sql(out: str, mod: int, n: int, rate: int) -> str:
    return (SHARED_DRILL_SQL
            .replace("$out", out).replace("$mod", str(mod))
            .replace("$n", str(n)).replace("$rate", str(rate)))


def run_shared_drill(seed: int, workdir: str, n_rows: int = 4000,
                     rate: int = 2000, timeout: float = 120.0,
                     plan_factory: Callable[[int], FaultPlan] = shared_plan,
                     ) -> DrillResult:
    """ISSUE 16 acceptance: two tenants whose scans fingerprint
    identically mount ONE shared host scan (`__shared/<fp>`), a worker
    is SIGKILLed mid-checkpoint, and each tenant's canonicalized output
    must be byte-identical to its own SOLO unshared fault-free run. The
    drill also requires the mount to actually engage (one host, refcount
    2, observed live) and every scheduled fault to fire. Pass a
    model-checker counterexample plan via `plan_factory`
    (tools/chaos_drill.py --shared --plan FILE) to replay the
    `leaked_barrier_across_tenants` kill schedule end-to-end."""
    from ..config import update
    from ..controller.controller import ControllerServer
    from ..controller.scheduler import EmbeddedScheduler
    from ..controller.state_machine import JobState

    os.makedirs(workdir, exist_ok=True)
    audit_mark = _audit_mark()
    tenants = {"ta": 3, "tb": 5}

    # 1. fault-free SOLO references, sharing OFF: the A/B is
    # shared-vs-unshared, so the reference is each tenant owning its
    # whole data plane (replay-deterministic source => identical rows)
    want: Dict[str, List[str]] = {}
    assert chaos.installed() is None, "a fault plan is already installed"
    for tid, mod in tenants.items():
        solo_out = os.path.join(workdir, f"{tid}-solo.json")
        solo_sql = _shared_sql(solo_out, mod, n_rows, rate)
        with update(sharing={"enabled": False}):
            _run_embedded(
                solo_sql, f"shared-{tid}-solo", None, 1, 1, max_restarts=0,
                heartbeat_interval=0.1, heartbeat_timeout=30.0,
                checkpoint_interval=60.0, timeout=timeout,
            )
        want[tid] = canonicalize_output(solo_out, solo_sql, {})
        if not want[tid]:
            raise RuntimeError(
                f"shared drill: solo run for {tid} produced no output"
            )

    # 2. faulted SHARED run: both tenants on one controller, sharing ON,
    # durable host + durable tenants, kill mid-checkpoint
    fault_sqls = {
        tid: _shared_sql(os.path.join(workdir, f"{tid}-shared.json"),
                         mod, n_rows, rate)
        for tid, mod in tenants.items()
    }
    plan = chaos.install(plan_factory(seed))
    error = None
    restarts = 0
    refcount_peak = 0
    host_fp = None

    async def go():
        nonlocal refcount_peak, host_fp
        c = await ControllerServer(
            EmbeddedScheduler(), max_restarts=8
        ).start()
        try:
            for tid in tenants:
                await c.submit_job(
                    tid, sql=fault_sqls[tid],
                    storage_url=os.path.join(workdir, f"{tid}-ck"),
                    n_workers=1, parallelism=1,
                )
            # the mount must actually engage: one host, refcount 2
            import time as _time

            deadline = _time.monotonic() + 15.0
            while _time.monotonic() < deadline:
                st = c.sharing.status()
                if st:
                    host_fp = next(iter(st))
                    refcount_peak = max(refcount_peak,
                                        st[host_fp]["refcount"])
                if refcount_peak >= len(tenants):
                    break
                await asyncio.sleep(0.05)
            for tid in tenants:
                await c.wait_for_state(
                    tid, JobState.FINISHED, JobState.FAILED,
                    timeout=timeout,
                )
            total = 0
            for jid, job in c.jobs.items():
                if job.state != JobState.FINISHED and not \
                        jid.startswith("__shared/"):
                    raise RuntimeError(
                        f"shared drill job {jid} failed: {job.failure}"
                    )
                total += job.restarts
            return total
        finally:
            await c.stop()

    try:
        with update(
            sharing={"enabled": True,
                     "host_storage_url": os.path.join(workdir, "host-ck")},
            worker={"heartbeat_interval": 0.1},
            controller={"heartbeat_timeout": 1.5},
            pipeline={"checkpointing": {"interval": 0.15}},
        ):
            restarts = asyncio.run(go())
    except Exception as e:  # noqa: BLE001 - recorded in the result
        error = repr(e)
    finally:
        chaos.clear()

    got = {
        tid: canonicalize_output(
            os.path.join(workdir, f"{tid}-shared.json"),
            fault_sqls[tid], {},
        )
        for tid in tenants
    }
    diverged = [tid for tid in tenants if got[tid] != want[tid]]
    passed = (error is None and not diverged and not plan.unfired()
              and restarts >= 1 and refcount_peak >= len(tenants))
    if error is None and diverged:
        error = "per-tenant output diverged from solo runs: " + ", ".join(
            f"{tid} ({len(got[tid])} rows vs {len(want[tid])} solo)"
            for tid in diverged
        )
    if error is None and plan.unfired():
        error = f"unfired faults: {[s.describe() for s in plan.unfired()]}"
    if error is None and restarts < 1:
        error = "the SIGKILL never forced a recovery"
    if error is None and refcount_peak < len(tenants):
        error = (f"tenants never co-mounted: peak refcount "
                 f"{refcount_peak} < {len(tenants)}")
    passed, error, audit_breaches = _audit_verdict(audit_mark, passed, error)
    return DrillResult(
        query="shared_plan_fleet",
        seed=seed,
        passed=passed,
        rows=sum(len(v) for v in got.values()),
        restarts=restarts,
        fired=plan.fired_log(),
        comparable_log=plan.comparable_log(),
        expected_log=plan.expected_log(),
        unfired=[s.describe() for s in plan.unfired()],
        error=error,
        extras={
            "refcount_peak": refcount_peak,
            "shared_fingerprint": host_fp,
            "tenant_rows": {tid: len(v) for tid, v in got.items()},
        },
        audit_breaches=audit_breaches,
    )

# -- hot-standby failover drill (ISSUE 17 acceptance) ------------------------


FAILOVER_DRILL_SQL = """
CREATE TABLE impulse WITH (
  connector = 'impulse', event_rate = '$rate',
  message_count = '$n', start_time = '0',
  realtime = 'true', replay = 'true'
);
CREATE TABLE out (k BIGINT UNSIGNED, start TIMESTAMP, cnt BIGINT) WITH (
  connector = 'single_file', path = '$out',
  format = 'json', type = 'sink'
);
INSERT INTO out
SELECT k, window.start as start, cnt FROM (
  SELECT counter % 4 as k, tumble(interval '500 millisecond') as window,
         count(*) as cnt
  FROM impulse GROUP BY 1, 2
);
"""


def _failover_sql(out: str, n: int, rate: int) -> str:
    return (FAILOVER_DRILL_SQL
            .replace("$out", out).replace("$n", str(n))
            .replace("$rate", str(rate)))


def run_failover_drill(seed: int, workdir: str, n_rows: int = 4000,
                       rate: int = 1500, timeout: float = 120.0,
                       plan_factory: Optional[
                           Callable[[int], FaultPlan]] = None,
                       ) -> DrillResult:
    """ISSUE 17 acceptance: SIGKILL the primary under load with a hot
    standby armed.

    Three phases over the same replay-deterministic windowed pipeline:

      1. fault-free reference with failover OFF (the cold data plane).
      2. promotion: failover ON, wait for the standby to arm AND tail at
         least one published epoch, then SIGKILL-equivalent the worker
         hosting the primary. The job must finish with >= 1 promotion,
         ZERO cold restarts, no RECOVERING transition, byte-identical
         output — and the `failover.promote` span's gap_ms (detection ->
         processing released on the promoted generation) goes into the
         drill extras against the < 500 ms acceptance bar.
      3. standby-also-dies: kill the standby's worker AND the primary's.
         Promotion must be refused (stale standby) and the job must fall
         back to a cold restore — >= 1 restart, 0 promotions, still
         byte-identical. The RECOVERING -> RUNNING wall time is recorded
         as the multi-second cold baseline the gap_ms compares against.

    With `plan_factory` (tools/chaos_drill.py --failover --plan FILE, e.g.
    the serialized `promote_while_primary_alive` counterexample), phase 2
    runs under that plan INSTEAD of the targeted kill: a heartbeat
    blackout leaves the primary alive-but-silent, the standby promotes
    over it, and the fenced zombie must not double-emit — byte-identical
    output is still the bar. Phase 3 is skipped on the replay path."""
    from .. import obs
    from ..config import update
    from ..controller.controller import ControllerServer
    from ..controller.scheduler import EmbeddedScheduler
    from ..controller.state_machine import JobState
    from ..state.chain_cache import CACHE

    os.makedirs(workdir, exist_ok=True)
    audit_mark = _audit_mark()

    # 1. fault-free reference, failover off
    clean_out = os.path.join(workdir, "clean.json")
    clean_sql = _failover_sql(clean_out, n_rows, rate)
    assert chaos.installed() is None, "a fault plan is already installed"
    _run_embedded(
        clean_sql, "drill-failover-clean", None, 1, 1, max_restarts=0,
        heartbeat_interval=0.1, heartbeat_timeout=30.0,
        checkpoint_interval=60.0, timeout=timeout,
    )
    want = canonicalize_output(clean_out, clean_sql, {})
    if not want:
        raise RuntimeError("failover drill: fault-free run had no output")

    async def faulted(tag: str, kill: str, plan: Optional[FaultPlan]):
        """One faulted run. `kill` targets the dynamic SIGKILL at the
        'primary' worker, 'both' (standby first, then primary), or ''
        (the installed plan drives all faults). Returns (promotions,
        restarts, events, standby_epoch_at_kill)."""
        out = os.path.join(workdir, f"{tag}.json")
        fsql = _failover_sql(out, n_rows, rate)
        c = await ControllerServer(
            EmbeddedScheduler(), max_restarts=8
        ).start()
        sb_epoch = 0
        try:
            await c.submit_job(
                "drill-failover", sql=fsql,
                storage_url=os.path.join(workdir, f"{tag}-ck"),
                n_workers=1, parallelism=1,
            )
            await c.wait_for_state("drill-failover", JobState.RUNNING,
                                   timeout=30)
            job = c.jobs["drill-failover"]
            if plan is not None:
                # counterexample replay: the model's abstract worker
                # index names no real worker id — retarget every
                # worker-scoped fault at the job's PRIMARY worker (the
                # blackout must silence the primary, with the standby
                # armed, for the promotion-over-alive-primary scenario
                # to replay). Wait for the arm first: promotion needs a
                # standby to promote.
                deadline = asyncio.get_event_loop().time() + 20.0
                while asyncio.get_event_loop().time() < deadline:
                    if c.failover._standbys.get("drill-failover"):
                        break
                    await asyncio.sleep(0.05)
                if not c.failover._standbys.get("drill-failover"):
                    raise RuntimeError("standby never armed for replay")
                wid = str(job.workers[0].worker_id)
                for spec in plan.specs:
                    if (spec.point.startswith("worker.")
                            and "worker_id" not in spec.match):
                        spec.match["worker_id"] = wid
                chaos.install(plan)
            if kill:
                # the kill target is only known once the standby armed:
                # wait for the arm AND at least one tailed epoch, then
                # install the targeted worker.kill plan mid-run
                deadline = asyncio.get_event_loop().time() + 20.0
                while asyncio.get_event_loop().time() < deadline:
                    sb = c.failover._standbys.get("drill-failover")
                    if sb is not None and sb.epoch >= 1:
                        break
                    await asyncio.sleep(0.05)
                sb = c.failover._standbys.get("drill-failover")
                if sb is None or sb.epoch < 1:
                    raise RuntimeError(
                        "standby never armed/tailed before the kill window"
                    )
                sb_epoch = sb.epoch
                kp = FaultPlan(seed)
                if kill == "both":
                    for w in sb.workers:
                        kp.add("worker.kill", at_hits=(1,),
                               match={"worker_id": str(w.worker_id)})
                for w in job.workers:
                    kp.add("worker.kill", at_hits=(1,),
                           match={"worker_id": str(w.worker_id)})
                chaos.install(kp)
            state = await c.wait_for_state(
                "drill-failover", JobState.FINISHED, JobState.FAILED,
                timeout=timeout,
            )
            if state != JobState.FINISHED:
                raise RuntimeError(
                    f"failover drill ({tag}) failed: {job.failure}"
                )
            return (job.promotions, job.restarts, list(job.events),
                    sb_epoch, canonicalize_output(out, fsql, {}))
        finally:
            chaos.clear()
            await c.stop()

    def run_phase(tag, kill, plan):
        # replay cadence note: a successful checkpoint RPC refreshes the
        # controller's liveness view (_worker_call), so a heartbeat
        # blackout only trips detection when the fan-out period exceeds
        # the heartbeat timeout — the kill phases keep the fast cadence
        # (a dead worker refuses RPCs too)
        ckpt, hb_to = (1.0, 0.4) if plan is not None else (0.25, 0.5)
        with update(
            failover={"enabled": True},
            worker={"heartbeat_interval": 0.05},
            controller={"heartbeat_timeout": hb_to},
            pipeline={"checkpointing": {"interval": ckpt}},
        ):
            return asyncio.run(faulted(tag, kill, plan))

    error = None
    promotions = restarts = 0
    gap_ms: List[float] = []
    cold_ms: List[float] = []
    fb_restarts = fb_promotions = 0
    sb_epoch = 0
    replay_plan = plan_factory(seed) if plan_factory is not None else None

    # 2. promotion phase (targeted kill, or the replayed plan)
    obs.reset()
    try:
        promotions, restarts, events, sb_epoch, got = run_phase(
            "promote", "" if replay_plan is not None else "primary",
            replay_plan,
        )
        gap_ms = sorted(
            float(s["attrs"]["gap_ms"])
            for s in obs.recorder().snapshot()
            if s.get("name") == "failover.promote"
            and "gap_ms" in s.get("attrs", {})
        )
        if got != want:
            error = (f"promote phase diverged: {len(got)} rows vs "
                     f"{len(want)} fault-free")
        elif promotions < 1:
            error = "no promotion happened"
        elif replay_plan is None and restarts:
            error = f"promotion phase took {restarts} cold restarts"
        elif replay_plan is None and any(
                e["to"] == "Recovering" for e in events):
            error = "promotion phase passed through RECOVERING"
        elif not gap_ms:
            error = "no failover.promote span carried gap_ms"
    except Exception as e:  # noqa: BLE001 - recorded in the result
        error = repr(e)
    cache = dict(CACHE.stats())

    # 3. standby-also-dies phase: cold-restore fallback (skipped on the
    # counterexample replay path)
    if error is None and replay_plan is None:
        try:
            fb_promotions, fb_restarts, events, _sbe, got = run_phase(
                "fallback", "both", None,
            )
            t_rec = None
            for e in events:
                if e["to"] == "Recovering":
                    t_rec = e["time"]
                elif e["to"] == "Running" and t_rec is not None:
                    cold_ms.append((e["time"] - t_rec) / 1e6)
                    t_rec = None
            if got != want:
                error = (f"fallback phase diverged: {len(got)} rows vs "
                         f"{len(want)} fault-free")
            elif fb_restarts < 1:
                error = "standby-also-dies never forced a cold restore"
            elif fb_promotions:
                error = "a stale standby was promoted"
        except Exception as e:  # noqa: BLE001 - recorded in the result
            error = repr(e)

    passed = error is None
    passed, error, audit_breaches = _audit_verdict(audit_mark, passed, error)
    return DrillResult(
        query="failover_hot_standby",
        seed=seed,
        passed=passed,
        rows=len(want),
        restarts=restarts + fb_restarts,
        fired=[],
        comparable_log=[],
        expected_log=[],
        unfired=[],
        error=error,
        extras={
            "promotions": promotions,
            "standby_epoch_at_kill": sb_epoch,
            "promote_gap_ms_max": round(gap_ms[-1], 3) if gap_ms else None,
            "cold_recover_ms": [round(g, 1) for g in sorted(cold_ms)],
            "fallback_restarts": fb_restarts,
            "replayed_plan": replay_plan is not None,
            "chain_cache_hits": cache.get("hits"),
            "chain_cache_misses": cache.get("misses"),
        },
        audit_breaches=audit_breaches,
    )


# -- follower replica drill (ISSUE 20: serving-tier death mid-tail) ----------


def run_follower_drill(seed: int, workdir: str, n_rows: int = 12000,
                       rate: int = 1500, timeout: float = 120.0,
                       ) -> DrillResult:
    """ISSUE 20 acceptance: follower read-replica death mid-tail.

    One durable replay-deterministic windowed pipeline with a follower
    tailing its checkpoint stream, read continuously through the REAL
    serve gateway for the whole run:

      1. fault-free reference with the replica tier OFF — the data
         plane's byte-identical output baseline (followers are read-only
         consumers of published state, so the bar is that their
         existence, death, and reattach change NOTHING downstream).
      2. follower phase: replica ON, wait until gateway reads route
         follower-first, then fire the `replica.kill` chaos seam — the
         follower dies abruptly mid-tail (mounts dropped, no graceful
         detach). Reads must fail over worker-ward instantly (zero
         wrong values, zero non-retriable errors), the follower must
         reattach through the full _subscribe path — re-resolving
         latest.json, never an in-memory epoch (the
         follower_serves_unpublished_epoch mutant is the shortcut this
         forbids) — and reads must come back follower-sourced. Every
         read's staleness (published epoch minus served) stays <= 1
         checkpoint interval; the sink output stays byte-identical.

    The read log's source transitions (follower -> worker -> follower),
    the kill count, and the staleness ceiling land in the drill extras."""
    from ..config import update
    from ..controller.controller import ControllerServer
    from ..controller.scheduler import EmbeddedScheduler
    from ..controller.state_machine import JobState

    os.makedirs(workdir, exist_ok=True)
    audit_mark = _audit_mark()

    # 1. fault-free reference, replica off
    clean_out = os.path.join(workdir, "clean.json")
    clean_sql = _failover_sql(clean_out, n_rows, rate)
    assert chaos.installed() is None, "a fault plan is already installed"
    _run_embedded(
        clean_sql, "drill-follower-clean", None, 1, 1, max_restarts=0,
        heartbeat_interval=0.1, heartbeat_timeout=30.0,
        checkpoint_interval=60.0, timeout=timeout,
    )
    want = canonicalize_output(clean_out, clean_sql, {})
    if not want:
        raise RuntimeError("follower drill: fault-free run had no output")

    async def faulted():
        """Follower phase. Returns (stats, canonical output)."""
        out = os.path.join(workdir, "follower.json")
        fsql = _failover_sql(out, n_rows, rate)
        c = await ControllerServer(
            EmbeddedScheduler(), max_restarts=2
        ).start()
        stats = {"follower_reads": 0, "worker_reads": 0,
                 "staleness_max": 0, "wrong": 0, "fatal": 0,
                 "kills": 0, "reattached": False}
        try:
            await c.submit_job(
                "drill-follower", sql=fsql,
                storage_url=os.path.join(workdir, "follower-ck"),
                n_workers=1, parallelism=1,
            )
            await c.wait_for_state("drill-follower", JobState.RUNNING,
                                   timeout=30)

            async def read_table():
                tabs = await c.serve.tables("drill-follower")
                for name, info in tabs.items():
                    if info.get("kind") == "window":
                        return name
                return None

            loop = asyncio.get_event_loop()
            table = None
            deadline = loop.time() + 30.0
            while table is None and loop.time() < deadline:
                table = await read_table()
                if table is None:
                    await asyncio.sleep(0.1)
            if table is None:
                raise RuntimeError("no serve table ever listed")

            async def read_once():
                """One 4-key gateway read; folds into stats, returns
                the response's source ('' on a non-200 response)."""
                resp = await c.serve.read("drill-follower", table,
                                          [0, 1, 2, 3])
                if resp.get("status") != 200:
                    if not resp.get("retriable", True):
                        stats["fatal"] += 1
                    return ""
                src = resp.get("source", "")
                key = {"follower": "follower_reads",
                       "worker": "worker_reads"}.get(src)
                if key:
                    stats[key] += 1
                stats["staleness_max"] = max(
                    stats["staleness_max"], int(resp.get("staleness", 0)))
                for r in resp.get("results", []):
                    v = r.get("value") or {}
                    cnt = next((x for f, x in v.items()
                                if f.startswith("__agg_out")
                                or f == "cnt"), None)
                    if r.get("found") and cnt is not None and cnt > rate:
                        stats["wrong"] += 1  # > 1 s of events in 500 ms
                return src

            async def wait_source(srcname: str, secs: float) -> bool:
                end = loop.time() + secs
                while loop.time() < end:
                    if await read_once() == srcname:
                        return True
                    await asyncio.sleep(0.05)
                return False

            # (a) reads go follower-first once the mount catches up
            if not await wait_source("follower", 30.0):
                raise RuntimeError(
                    f"reads never follower-routed: {c.replicas.status()}")
            # (b) abrupt follower death mid-tail via the chaos seam
            kp = FaultPlan(seed)
            kp.add("replica.kill", at_hits=(1,))
            chaos.install(kp)
            deadline = loop.time() + 20.0
            while c.replicas.kills < 1 and loop.time() < deadline:
                await read_once()
                await asyncio.sleep(0.05)
            chaos.clear()
            if c.replicas.kills < 1:
                raise RuntimeError("replica.kill never fired")
            stats["kills"] = c.replicas.kills
            # (c) worker-ward fallback serves while the follower is down
            if not await wait_source("worker", 10.0):
                raise RuntimeError(
                    "no worker-ward fallback read after the kill")
            # (d) reattach: back through _subscribe off latest.json
            stats["reattached"] = await wait_source("follower", 30.0)
            if not stats["reattached"]:
                raise RuntimeError(
                    f"follower never reattached: {c.replicas.status()}")
            # keep reading to the finish line: staleness and value
            # checks must hold for the job's whole life
            while not c.jobs["drill-follower"].state.is_terminal():
                await read_once()
                await asyncio.sleep(0.1)
            state = c.jobs["drill-follower"].state
            if state != JobState.FINISHED:
                raise RuntimeError(
                    f"follower drill job failed: "
                    f"{c.jobs['drill-follower'].failure}")
            return stats, canonicalize_output(out, fsql, {})
        finally:
            chaos.clear()
            await c.stop()

    error = None
    stats: dict = {}
    got: list = []
    try:
        with update(
            replica={"followers": 1, "reattach_backoff": 0.5},
            worker={"heartbeat_interval": 0.05},
            controller={"heartbeat_timeout": 2.0},
            pipeline={"checkpointing": {"interval": 0.25}},
        ):
            stats, got = asyncio.run(faulted())
        if got != want:
            error = (f"follower phase diverged: {len(got)} rows vs "
                     f"{len(want)} fault-free")
        elif stats["wrong"]:
            error = f"{stats['wrong']} wrong values served"
        elif stats["fatal"]:
            error = f"{stats['fatal']} non-retriable read errors"
        elif stats["staleness_max"] > 1:
            error = (f"staleness {stats['staleness_max']} epochs exceeds "
                     "one checkpoint interval")
    except Exception as e:  # noqa: BLE001 - recorded in the result
        error = repr(e)

    passed = error is None
    passed, error, audit_breaches = _audit_verdict(audit_mark, passed, error)
    return DrillResult(
        query="follower_replica_kill",
        seed=seed,
        passed=passed,
        rows=len(want),
        restarts=0,
        fired=[],
        comparable_log=[],
        expected_log=[],
        unfired=[],
        error=error,
        extras=stats or None,
        audit_breaches=audit_breaches,
    )


# -- event-loop starvation drill (ISSUE 18: the double-emit watch item) ------


def starvation_plan(seed: int) -> FaultPlan:
    """Blocking `runner.stall` hits, tenant-scoped to the victim job: a
    CPU-bound UDF that never yields wedges the WHOLE shared event loop
    (params.block) on each of the victim's first 12 input items, while
    the squeezed heartbeat/checkpoint cadences keep ticking against it."""
    plan = FaultPlan(seed)
    plan.add("runner.stall", at_hits=tuple(range(1, 13)), max_fires=12,
             match={"job": "starve-victim"},
             params={"delay": 0.15, "block": True})
    return plan


def run_starvation_drill(seed: int, workdir: str, n_rows: int = 3000,
                         rate: int = 1500, timeout: float = 120.0,
                         plan_factory: Callable[[int], FaultPlan]
                         = starvation_plan) -> DrillResult:
    """ROADMAP watch item: can extreme event-loop lag double-emit a
    window without a restart? (Observed once when a rescale drill ran
    concurrently with a full-tree lint; never reproduced standalone.)

    Two tenants run the replay-deterministic 500 ms tumbling aggregate
    on one embedded cluster. The victim's input loop takes repeated
    BLOCKING stalls (`runner.stall` params.block — a UDF that never
    yields, starving heartbeat loops and the co-resident bystander),
    heartbeat and checkpoint cadences are squeezed tight around the
    stall width, and `max_restarts=0` so any heartbeat false-positive
    fails the run outright. The interleaving sanitizer
    (analysis/races/sanitizer.py) records every access to
    `@shared_state` fields live. The drill passes iff both tenants'
    outputs are byte-identical to their fault-free references, no
    (key, window) pair is emitted twice, restarts == 0, every scheduled
    stall fired, and the sanitizer saw zero conflicts. On failure the
    access log and a Perfetto trace land in the workdir (CI uploads
    them)."""
    from ..analysis.races import sanitizer
    from ..config import update
    from ..controller.controller import ControllerServer
    from ..controller.scheduler import EmbeddedScheduler
    from ..controller.state_machine import JobState

    os.makedirs(workdir, exist_ok=True)
    # explicit audit-silence assertion (ISSUE 19): this drill IS the
    # double-emit watch item's resurface detector — if extreme loop lag
    # ever re-emits a window, the conservation ledger flags the exact
    # (edge, epoch) even when the sink output happens to dedupe
    audit_mark = _audit_mark()
    tenants = ("starve-victim", "starve-bystander")

    def tenant_sql(tag: str, out: str) -> str:
        return (FAILOVER_DRILL_SQL
                .replace("$out", out)
                .replace("$n", str(n_rows))
                .replace("$rate", str(rate)))

    # 1. fault-free references (stall off, loose cadences)
    assert chaos.installed() is None, "a fault plan is already installed"
    want: Dict[str, List[str]] = {}
    for tid in tenants:
        ref_out = os.path.join(workdir, f"{tid}-ref.json")
        _run_embedded(
            tenant_sql(tid, ref_out), f"{tid}-ref", None, 1, 1,
            max_restarts=0, heartbeat_interval=0.1, heartbeat_timeout=30.0,
            checkpoint_interval=60.0, timeout=timeout,
        )
        want[tid] = canonicalize_output(ref_out, "", {})
        if not want[tid]:
            raise RuntimeError(
                f"starvation drill: reference for {tid} had no output"
            )

    # 2. faulted run: both tenants, blocking stalls on the victim,
    # heartbeat/checkpoint cadences squeezed around the stall width
    fault_outs = {tid: os.path.join(workdir, f"{tid}-stall.json")
                  for tid in tenants}
    plan = chaos.install(plan_factory(seed))
    sanitizer.reset()
    sanitizer.enable()
    error = None
    restarts = 0

    async def go():
        c = await ControllerServer(
            EmbeddedScheduler(), max_restarts=0
        ).start()
        try:
            for tid in tenants:
                await c.submit_job(
                    tid, sql=tenant_sql(tid, fault_outs[tid]),
                    storage_url=os.path.join(workdir, f"{tid}-ck"),
                    n_workers=1, parallelism=1,
                )
            total = 0
            for tid in tenants:
                state = await c.wait_for_state(
                    tid, JobState.FINISHED, JobState.FAILED, timeout=timeout,
                )
                job = c.jobs[tid]
                if state != JobState.FINISHED:
                    raise RuntimeError(
                        f"starvation drill job {tid} failed: {job.failure}"
                    )
                total += job.restarts
            return total
        finally:
            await c.stop()

    try:
        with update(
            worker={"heartbeat_interval": 0.05},
            controller={"heartbeat_timeout": 1.0},
            pipeline={"checkpointing": {"interval": 0.25},
                      "source_batch_size": 64},
        ):
            restarts = asyncio.run(go())
    except Exception as e:  # noqa: BLE001 - recorded in the result
        error = repr(e)
    finally:
        chaos.clear()
        sanitizer.disable()

    conflicts = sanitizer.conflicts()
    race_report = sanitizer.report()
    got = {tid: canonicalize_output(fault_outs[tid], "", {})
           for tid in tenants}
    dup: Dict[str, List] = {}
    for tid in tenants:
        rows = read_rows(fault_outs[tid])
        seen: Dict[tuple, int] = {}
        for r in rows:
            seen[(r.get("k"), r.get("start"))] = \
                seen.get((r.get("k"), r.get("start")), 0) + 1
        dup[tid] = sorted(k for k, n in seen.items() if n > 1)
    diverged = [tid for tid in tenants if got[tid] != want[tid]]

    if error is None and any(dup.values()):
        error = ("a window was emitted twice without a restart: " +
                 "; ".join(f"{tid}: {dup[tid]}" for tid in tenants
                           if dup[tid]))
    if error is None and diverged:
        error = "output diverged from fault-free references: " + ", ".join(
            f"{tid} ({len(got[tid])} rows vs {len(want[tid])})"
            for tid in diverged
        )
    if error is None and restarts:
        error = f"squeezed heartbeats tripped {restarts} restart(s)"
    if error is None and plan.unfired():
        error = f"unfired stalls: {[s.describe() for s in plan.unfired()]}"
    if error is None and conflicts:
        error = (f"sanitizer flagged {len(conflicts)} interleaving "
                 f"conflict(s): {conflicts[0]['detail']}")
    passed, error, audit_breaches = _audit_verdict(audit_mark,
                                                    error is None, error)
    if not passed:
        # CI failure artifacts: the full access log + a Perfetto trace
        sanitizer.dump(os.path.join(workdir, "race_access_log.json"))
        sanitizer.dump_trace(os.path.join(workdir, "race_trace.json"))
    return DrillResult(
        query="starvation_double_emit",
        seed=seed,
        passed=passed,
        rows=sum(len(v) for v in got.values()),
        restarts=restarts,
        fired=plan.fired_log(),
        comparable_log=plan.comparable_log(),
        expected_log=plan.expected_log(),
        unfired=[s.describe() for s in plan.unfired()],
        error=error,
        extras={
            "duplicate_windows": {tid: [list(k) for k in v]
                                  for tid, v in dup.items()},
            "tenant_rows": {tid: len(v) for tid, v in got.items()},
            "sanitizer": {
                "accesses": race_report["accesses"],
                "epochs": race_report["epochs"],
                "conflicts": conflicts,
            },
        },
        audit_breaches=audit_breaches,
    )
