"""REST API server (aiohttp).

Capability parity with the reference's API routes
(/root/reference/crates/arroyo-api/src/rest.rs:65-243): pipelines
CRUD/validate/preview/stop/restart, jobs, checkpoint listings, operator
metric groups, connectors metadata, connection profiles/tables (+test),
UDFs CRUD/validate, websocket tail of preview output. Served under
/api/v1; job output and state come straight from the in-process controller
(the reference couples these through Postgres + gRPC; this build embeds
the controller in the API process or is pointed at one).
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Optional

from aiohttp import web

from ..config import config
from ..controller.controller import ControllerServer
from ..controller.state_machine import JobState
from ..sql import plan_query
from ..sql.lexer import SqlError
from ..utils.logging import get_logger
from .db import ApiDb

logger = get_logger("api")


def json_response(data, status=200):
    return web.json_response(data, status=status, dumps=lambda d: json.dumps(
        d, default=str))


def error(status: int, message: str):
    return web.json_response({"error": message}, status=status)


class ApiServer:
    def __init__(self, controller: Optional[ControllerServer] = None,
                 db_path: Optional[str] = None):
        self.controller = controller
        self.db = ApiDb(
            db_path or config().database.path,
            remote_url=config().database.remote_url or None,
            backend=(
                "sqlite" if db_path else config().database.backend
            ),
            dsn=config().database.dsn,
        )
        self.previews: dict = {}  # pipeline id -> preview rows list
        # background tasks (job trackers, preview runs): the loop only
        # weak-refs tasks, so fire-and-forget work must be retained here
        # or it can be garbage-collected mid-flight
        self._bg_tasks: set = set()

    def _spawn(self, coro) -> asyncio.Task:
        task = asyncio.ensure_future(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)
        return task

    # -- pipelines ----------------------------------------------------------

    async def validate_query(self, request: web.Request):
        body = await request.json()
        try:
            plan = plan_query(body["query"],
                              parallelism=body.get("parallelism", 1))
        except SqlError as e:
            return json_response({"errors": [str(e)]}, status=400)
        g = plan.graph
        return json_response(
            {
                "graph": {
                    "nodes": [
                        {
                            "node_id": n.node_id,
                            "description": n.description,
                            "operator": " -> ".join(
                                op.operator.value for op in n.chain
                            ),
                            "parallelism": n.parallelism,
                        }
                        for n in g.nodes.values()
                    ],
                    "edges": [
                        {"src": e.src, "dst": e.dst,
                         "edge_type": e.edge_type.value}
                        for e in g.edges
                    ],
                },
                "errors": [],
            }
        )

    async def create_pipeline(self, request: web.Request):
        body = await request.json()
        name = body.get("name") or "pipeline"
        query = body.get("query")
        parallelism = int(body.get("parallelism", 1))
        if not query:
            return error(400, "query is required")
        try:
            plan = plan_query(query, parallelism=parallelism)
        except SqlError as e:
            return error(400, str(e))
        tenant = str(body.get("tenant") or "default")
        pipeline = self.db.create_pipeline(name, query, parallelism,
                                           tenant=tenant)
        if self.controller is not None:
            await self._submit_pipeline_job(
                pipeline["id"], query, parallelism, tenant=tenant
            )
        return json_response(pipeline)

    async def _submit_pipeline_job(self, pid: str, query: str,
                                   parallelism: int,
                                   tenant: str = "default") -> dict:
        """Create + submit + track one job of a pipeline. Checkpoint
        storage is keyed by PIPELINE id, so a restart or rescale restores
        the pipeline's latest durable checkpoint (state, source
        positions) instead of starting blank — the generation protocol
        fences any zombie writer from the previous job. The tenant rides
        into admission control (quota + fair share)."""
        job = self.db.create_job(pid)
        storage = config().pipeline.checkpointing.storage_url
        await self.controller.submit_job(
            job["id"], sql=query,
            storage_url=f"{storage}/{pid}" if storage else None,
            parallelism=parallelism,
            tenant=tenant,
        )
        self._spawn(self._track_job(pid, job["id"]))
        return job

    def _live_jobs(self, pid: str) -> list:
        if self.controller is None:
            return []
        return [
            j for j in self.db.jobs_for_pipeline(pid)
            if j["id"] in self.controller.jobs
            and not self.controller.jobs[j["id"]].state.is_terminal()
        ]

    async def _track_job(self, pid: str, jid: str):
        """Mirror a job's state into the DB. Event-driven: parked on the
        job's kick list (state transitions wake it) with a coarse
        fallback deadline, writing only on CHANGE — the old 0.2s poll
        loop burned 5 wakeups + 2 DB writes per second PER JOB even when
        nothing moved, which is O(jobs) idle cost a 100-job fleet
        notices."""
        job = self.controller.jobs.get(jid)
        last = None
        while job is not None and not job.state.is_terminal():
            if job.state.value != last:
                last = job.state.value
                self.db.update_job(jid, last, job.restarts)
                self.db.set_pipeline_state(pid, last)
            await job.wait_kick(self.controller.wheel, 30.0)
        if job is not None:
            self.db.update_job(jid, job.state.value, job.restarts)
            self.db.set_pipeline_state(pid, job.state.value)

    async def list_pipelines(self, request: web.Request):
        return json_response({"data": self.db.list_pipelines()})

    async def get_pipeline(self, request: web.Request):
        p = self.db.get_pipeline(request.match_info["id"])
        if p is None:
            return error(404, "pipeline not found")
        return json_response(p)

    async def delete_pipeline(self, request: web.Request):
        pid = request.match_info["id"]
        p = self.db.get_pipeline(pid)
        if p is None:
            return error(404, "pipeline not found")
        await self._stop_pipeline_jobs(pid, "immediate")
        self.db.delete_pipeline(pid)
        return json_response({"deleted": pid})

    async def patch_pipeline(self, request: web.Request):
        """stop modes and rescale (reference: PATCH /pipelines/{id} with
        stop / parallelism fields; parallelism change on a running
        pipeline stops with a checkpoint and resubmits at the new
        parallelism, like the reference's Rescaling transition)."""
        pid = request.match_info["id"]
        if self.db.get_pipeline(pid) is None:
            return error(404, "pipeline not found")
        body = await request.json()
        stop = body.get("stop")
        if stop not in (None, "none", "checkpoint", "graceful", "immediate"):
            return error(400, f"invalid stop mode {stop}")
        if stop and stop != "none":
            await self._stop_pipeline_jobs(pid, stop)
        if "parallelism" in body:
            try:
                par = int(body["parallelism"])
            except (TypeError, ValueError):
                return error(400, "parallelism must be an integer")
            if par < 1 or par > 128:
                return error(400, "parallelism must be in [1, 128]")
            p = self.db.get_pipeline(pid)
            if (stop in (None, "none") and self._live_jobs(pid)
                    and par != p["parallelism"]):
                # rescale: checkpoint-stop the running job, then resubmit
                # at the new parallelism (restores the pipeline's latest
                # checkpoint — key-range state sharding re-reads). The DB
                # records the new parallelism only AFTER the stop
                # succeeds: on the 409 path the job keeps running at the
                # old parallelism and the record must keep saying so
                # (ADVICE r4).
                await self._stop_pipeline_jobs(pid, "checkpoint")
                if self._live_jobs(pid):
                    # the stop timed out: running a second job against
                    # the same sources would double-process
                    return error(
                        409, "running job did not stop; rescale aborted"
                    )
                self.db.set_pipeline_parallelism(pid, par)
                await self._submit_pipeline_job(
                    pid, p["query"], par,
                    tenant=p.get("tenant", "default"),
                )
            else:
                self.db.set_pipeline_parallelism(pid, par)
        return json_response(self.db.get_pipeline(pid))

    async def restart_pipeline(self, request: web.Request):
        pid = request.match_info["id"]
        p = self.db.get_pipeline(pid)
        if p is None:
            return error(404, "pipeline not found")
        if self.controller is None:
            return error(400, "no controller attached")
        await self._stop_pipeline_jobs(pid, "checkpoint")
        if self._live_jobs(pid):
            return error(409, "running job did not stop; restart aborted")
        job = await self._submit_pipeline_job(
            pid, p["query"], p["parallelism"],
            tenant=p.get("tenant", "default"),
        )
        return json_response(job)

    async def _stop_pipeline_jobs(self, pid: str, mode: str):
        if self.controller is None:
            return
        for j in self.db.jobs_for_pipeline(pid):
            cjob = self.controller.jobs.get(j["id"])
            if cjob is not None and not cjob.state.is_terminal():
                await self.controller.stop_job(j["id"], mode)
                try:
                    await self.controller.wait_for_state(
                        j["id"], JobState.STOPPED, JobState.FAILED,
                        JobState.FINISHED, timeout=60,
                    )
                except TimeoutError:
                    pass
                cj = self.controller.jobs[j["id"]]
                self.db.update_job(j["id"], cj.state.value, cj.restarts)

    # -- jobs / checkpoints -------------------------------------------------

    async def pipeline_jobs(self, request: web.Request):
        return json_response(
            {"data": self.db.jobs_for_pipeline(request.match_info["id"])}
        )

    async def all_jobs(self, request: web.Request):
        return json_response({"data": self.db.all_jobs()})

    async def job_checkpoints(self, request: web.Request):
        jid = request.match_info["job_id"]
        if self.controller is None or jid not in self.controller.jobs:
            return json_response({"data": []})
        job = self.controller.jobs[jid]
        out = []
        if job.backend is not None:
            for epoch in sorted(job.checkpoints):
                out.append(
                    {
                        "epoch": epoch,
                        "tasks": len(job.checkpoints[epoch]),
                        "backend": job.backend.paths.checkpoint_dir(epoch),
                    }
                )
        return json_response({"data": out})

    async def operator_checkpoint_groups(self, request: web.Request):
        """Per-operator drill-down of one checkpoint (reference
        webui CheckpointDetails + api checkpoint details route): groups
        the tasks' completion reports by operator node with per-subtask
        state sizes, file/row counts and watermarks."""
        jid = request.match_info["job_id"]
        try:
            epoch = int(request.match_info["epoch"])
        except ValueError:
            return json_response({"data": []})
        job = self.controller.jobs.get(jid) if self.controller else None
        if job is None or epoch not in job.checkpoints:
            return json_response({"data": []})
        by_node: dict = {}
        for task_id, rep in sorted(job.checkpoints[epoch].items()):
            tables = []
            total_bytes = 0
            total_rows = 0
            # metadata nests per chained operator: {op{idx}: {table: meta}}
            for op_key, op_tables in (rep.get("metadata") or {}).items():
                for tname, meta in (op_tables or {}).items():
                    label = f"{op_key}/{tname}"
                    if meta.get("kind") == "global":
                        b = int(meta.get("bytes", 0))
                        tables.append({"table": label, "kind": "global",
                                       "bytes": b, "files": 1,
                                       "rows": None})
                        total_bytes += b
                    else:
                        files = meta.get("files") or []
                        b = sum(int(f.get("bytes", 0)) for f in files
                                if isinstance(f, dict))
                        r = sum(int(f.get("rows", 0)) for f in files
                                if isinstance(f, dict))
                        tables.append({"table": label, "kind": "time_key",
                                       "bytes": b, "files": len(files),
                                       "rows": r})
                        total_bytes += b
                        total_rows += r
            by_node.setdefault(rep.get("node_id"), []).append({
                "subtask": rep.get("subtask"),
                "task_id": task_id,
                "watermark": rep.get("watermark"),
                "bytes": total_bytes,
                "rows": total_rows,
                "tables": tables,
            })
        data = [
            {
                "node_id": nid,
                "bytes": sum(t["bytes"] for t in tasks),
                "tasks": sorted(tasks, key=lambda t: t["subtask"] or 0),
            }
            for nid, tasks in sorted(by_node.items(),
                                     key=lambda kv: kv[0] or 0)
        ]
        return json_response({"data": data, "epoch": epoch})

    async def job_traces(self, request: web.Request):
        """Flight-recorder export: this process's recorded spans for the
        job (trace ids are prefixed `{job_id}/`) as Chrome trace-event
        JSON — Perfetto-loadable directly, or merged across worker
        processes with tools/trace_report.py. `?trace=<id>` narrows to a
        single checkpoint epoch / lifecycle event."""
        from .. import obs

        jid = request.match_info["job_id"]
        spans = obs.recorder().snapshot(
            trace_prefix=f"{jid}/",
            trace_id=request.query.get("trace"),
        )
        if request.query.get("fmt") == "perfetto":
            # Perfetto export: spans plus the batch-phase timeline
            # ledger as named per-(job, phase) swimlanes
            body = obs.perfetto_trace(spans, job=jid)
        else:
            body = obs.chrome_trace(spans)
        body["spanCount"] = len(spans)
        return json_response(body)

    async def job_latency(self, request: web.Request):
        """Device-tier observatory surface: the job's latency-marker
        histograms (per-operator transit + end-to-end at the sinks, p50/
        p95/p99 in ms) and the XLA compile/dispatch telemetry summary
        (compiles, cache hit/miss, dispatch quantiles, padding waste,
        recompile-cause log). Reads this process's registry — merge
        worker dumps with tools/trace_report.py --latency for
        multi-process deployments."""
        from .. import obs

        return json_response(
            obs.latency_report(request.match_info["job_id"])
        )

    async def job_doctor(self, request: web.Request):
        """Bottleneck doctor (ISSUE 11): per-job busy ratio,
        backpressure, queue depth, watermark lag, dispatch floor,
        padding waste, loop lag and per-tenant attributed-cost shares
        combined into a ranked verdict naming the limiting operator and
        the suspected cause (host-bound / device-bound / exchange-bound
        / starved / noisy-neighbor — the latter names the co-resident
        tenant holding the shared worker). Reads this process's
        registry; for multi-process deployments run the doctor on each
        worker's admin server (/debug/doctor) or offline from a trace
        dump via tools/trace_report.py --doctor."""
        from ..obs import doctor

        jid = request.match_info["job_id"]
        if self.controller is not None and jid not in self.controller.jobs:
            return error(404, "job not found")
        rep = doctor.report(jid)
        if self.controller is not None:
            # StateServe wiring: a noisy-neighbor verdict squeezes the
            # suspect tenant's read quota at the serve gateway
            self.controller.serve.note_doctor_report(rep)
        return json_response(rep)

    # -- watchtower (ISSUE 13): alerts, metric history, bundles ------------

    def _watchtower(self):
        return getattr(self.controller, "watchtower", None)

    async def job_alerts(self, request: web.Request):
        """Watchtower SLO state for one job: per-rule alert states
        (ok/pending/firing/clearing — hysteresis per obs/watchtower.py)
        plus the job's slice of the firing/cleared ledger, each event
        carrying the cause series' recent history."""
        jid = request.match_info["job_id"]
        wt = self._watchtower()
        if wt is None:
            return json_response({"job": jid, "alerts": {},
                                  "firing": [], "ledger": []})
        return json_response(wt.alerts_for(jid))

    async def job_metrics_history(self, request: web.Request):
        """Retained metric history for one job: windowed samples plus
        derived rate/delta/quantiles per series (obs/history.py).
        `?series=<family>` narrows to one metric family, `?window=<s>`
        sets the lookback (default watch.window)."""
        from ..obs.history import HISTORY

        jid = request.match_info["job_id"]
        wt = self._watchtower()
        hist = wt.history if wt is not None else HISTORY
        try:
            window = float(request.query.get(
                "window", config().watch.window))
        except ValueError:
            return error(400, "bad window")
        series = request.query.get("series")
        return json_response({
            "job": jid,
            "window": window,
            "series": hist.export_job(jid, window=window, series=series),
        })

    async def job_audit(self, request: web.Request):
        """Conservation ledger for one job: per-edge epoch attestations
        (sender/receiver counts + digests), flow-check results and every
        recorded exactly-once breach (obs/audit.py)."""
        from ..obs import audit

        jid = request.match_info["job_id"]
        if (self.controller is not None and jid not in self.controller.jobs
                and audit.peek(jid) is None):
            return error(404, "job not found")
        return json_response(audit.status(jid))

    async def job_bundles(self, request: web.Request):
        """Diagnostic bundles captured for the job's SLO breaches:
        the bounded-spool index (download one via .../bundles/{n})."""
        jid = request.match_info["job_id"]
        wt = self._watchtower()
        metas = wt.bundles_for(jid) if wt is not None else []
        return json_response({"data": metas})

    async def job_bundle(self, request: web.Request):
        """Download one diagnostic bundle (doctor verdict + flight
        recording + Perfetto timeline + metric-history window around
        the breach) by sequence number."""
        jid = request.match_info["job_id"]
        wt = self._watchtower()
        try:
            n = int(request.match_info["n"])
        except ValueError:
            return error(400, "bad bundle number")
        bundle = wt.bundle(n) if wt is not None else None
        if bundle is None or bundle.get("job") not in (None, jid):
            return error(404, "no such bundle")
        return json_response(bundle)

    # -- queryable state (StateServe, ISSUE 12) ----------------------------

    async def job_state_tables(self, request: web.Request):
        """List the job's queryable tables: every keyed operator view
        (windowed aggregates, updating aggregates) with its key/value
        fields, parallelism and routability, plus the published epoch
        reads are currently served at."""
        jid = request.match_info["job_id"]
        if self.controller is None or jid not in self.controller.jobs:
            return error(404, "job not found")
        job = self.controller.jobs[jid]
        tables = await self.controller.serve.tables(jid)
        # follower replicas (ISSUE 20): surface whether reads route to
        # the follower tier and how far it trails publication
        replicas = getattr(self.controller, "replicas", None)
        lag = replicas.lag_epochs(job) if replicas is not None else None
        return json_response({
            "data": sorted(tables.values(), key=lambda d: d["table"]),
            "publishedEpoch": job.published_epoch,
            "replicaLagEpochs": lag,
            "state": job.state.value,
        })

    @staticmethod
    def _parse_state_key(raw: str):
        """`?key=` values parse as JSON where possible (numbers, quoted
        strings, composite `[a, b]` keys) and fall back to the raw
        string — `?key=42` is an int lookup, `?key=abc` a string one."""
        try:
            return json.loads(raw)
        except (json.JSONDecodeError, ValueError):
            return raw

    def _state_read_response(self, out: dict):
        status = out.pop("status", 200)
        out.pop("outcome", None)
        if "error" in out and "results" not in out:
            return json_response(
                {"error": out["error"],
                 "retriable": bool(out.get("retriable"))},
                status=status,
            )
        return json_response(out, status=status)

    async def job_state_get(self, request: web.Request):
        """Point lookup: GET .../state/{table}?key=K (epoch-consistent:
        the value is the key's aggregate at the last published
        checkpoint epoch; retriable errors mean back off and retry)."""
        if self.controller is None:
            return error(400, "no controller attached")
        raw = request.query.get("key")
        if raw is None:
            return error(400, "key query parameter is required")
        out = await self.controller.serve.read(
            request.match_info["job_id"], request.match_info["table"],
            [self._parse_state_key(raw)],
        )
        return self._state_read_response(out)

    async def job_state_bulk(self, request: web.Request):
        """Bulk multi-key lookup: POST {"keys": [k1, [k2a, k2b], ...]} —
        keys fan out to their owning workers concurrently and merge
        into one response (per-key found/value/error entries)."""
        if self.controller is None:
            return error(400, "no controller attached")
        body = await request.json()
        keys = body.get("keys")
        if not isinstance(keys, list) or not keys:
            return error(400, "body must carry a non-empty 'keys' list")
        out = await self.controller.serve.read(
            request.match_info["job_id"], request.match_info["table"],
            keys,
        )
        return self._state_read_response(out)

    def _autoscale_status(self, job) -> dict:
        return {
            "enabled": bool(config().autoscale.enabled),
            "policy": config().autoscale.policy,
            "pinned": job.autoscale_pinned,
            "rescales": job.rescales,
            "parallelism": {
                str(n.node_id): n.parallelism
                for n in job.graph.nodes.values()
            },
            "decisions": list(job.autoscale_decisions),
        }

    async def job_autoscale(self, request: web.Request):
        """Autoscaler surface: the job's decision audit log (one entry per
        control period: action, per-node targets, the signals they were
        decided from) plus pin state and current parallelism."""
        jid = request.match_info["job_id"]
        job = self.controller.jobs.get(jid) if self.controller else None
        if job is None:
            return error(404, "job not found")
        return json_response(self._autoscale_status(job))

    async def patch_job_autoscale(self, request: web.Request):
        """Pin (freeze automatic rescaling — decisions keep recording) or
        unpin a job: {"pinned": true|false}."""
        jid = request.match_info["job_id"]
        job = self.controller.jobs.get(jid) if self.controller else None
        if job is None:
            return error(404, "job not found")
        body = await request.json()
        if not isinstance(body.get("pinned"), bool):
            return error(400, "body must carry a boolean 'pinned'")
        job.autoscale_pinned = body["pinned"]
        return json_response(self._autoscale_status(job))

    async def job_errors(self, request: web.Request):
        jid = request.match_info["job_id"]
        job = self.controller.jobs.get(jid) if self.controller else None
        return json_response(
            {"data": [{"message": job.failure}] if job and job.failure else []}
        )

    async def operator_metric_groups(self, request: web.Request):
        """Per-operator metric groups (reference api/src/metrics.rs
        OperatorMetricGroup): task-labeled counters grouped by logical
        node, one single-point series per subtask (the UI polls and
        accumulates). The raw Prometheus text rides along for debugging."""
        import time as _time

        from ..metrics import REGISTRY, hist_quantiles

        now = int(_time.time() * 1000)
        job_id = request.match_info["job_id"]
        # operator id -> metric name -> subtask index -> value
        ops: dict = {}
        for name, entries in REGISTRY.snapshot().items():
            short = name.removeprefix("arroyo_worker_")
            for labels, value in entries:
                # split per-phase families (checkpoint_phase_seconds) into
                # one scalar series per phase; state families split per
                # table the same way (arroyo_state_bytes:sess, ...)
                metric = (f"{short}:{labels['phase']}"
                          if "phase" in labels else short)
                if "table" in labels:
                    metric = f"{metric}:{labels['table']}"
                task = labels.get("task")
                if task is None or "-" not in task:
                    continue
                if labels.get("job") != job_id:
                    continue  # counters from other jobs in this process
                node_id, _, sub = task.rpartition("-")
                try:
                    sub_i = int(sub)
                except ValueError:
                    continue
                if isinstance(value, dict):
                    # histogram snapshot ({sum, count, buckets}): one
                    # scalar series for the running mean plus tail
                    # quantiles estimated from the cumulative buckets —
                    # the autoscaler's audit log and the UI sparklines
                    # both need p95/p99, not just the mean
                    series = [(
                        metric,
                        value["sum"] / value["count"]
                        if value.get("count") else 0.0,
                    )]
                    series += [
                        (f"{metric}:{q}", v)
                        for q, v in sorted(hist_quantiles(value).items())
                    ]
                else:
                    series = [(metric, value)]
                for mname, v in series:
                    ops.setdefault(node_id, {}).setdefault(mname, {})[
                        sub_i
                    ] = v
        # device-tier families carry a `program` label instead of a task:
        # surface them under a synthetic "__device__" operator (one
        # series per program — the exchange/dispatch cost of the mesh
        # tier belongs beside the per-operator groups, not orphaned in
        # the raw prometheus text)
        for name, entries in REGISTRY.snapshot().items():
            if not (name.startswith("arroyo_device_")
                    or name.startswith("arroyo_xla_")):
                continue
            short = name.removeprefix("arroyo_")
            for labels, value in entries:
                program = labels.get("program")
                if program is None:
                    continue
                suffix = "".join(
                    f":{labels[k]}" for k in sorted(labels)
                    if k != "program"
                )
                metric = f"{short}:{program}{suffix}"
                if isinstance(value, dict):
                    series = [(
                        metric,
                        value["sum"] / value["count"]
                        if value.get("count") else 0.0,
                    )]
                    series += [
                        (f"{metric}:{q}", v)
                        for q, v in sorted(hist_quantiles(value).items())
                    ]
                else:
                    series = [(metric, value)]
                for mname, v in series:
                    ops.setdefault("__device__", {}).setdefault(
                        mname, {}
                    )[0] = v
        data = [
            {
                "operatorId": op,
                "metricGroups": [
                    {
                        "name": metric,
                        "subtasks": [
                            {"index": i,
                             "metrics": [{"time": now, "value": v}]}
                            for i, v in sorted(subs.items())
                        ],
                    }
                    for metric, subs in sorted(groups.items())
                ],
            }
            for op, groups in sorted(ops.items())
        ]
        return json_response(
            {"data": data, "prometheus": REGISTRY.expose()}
        )

    # -- preview ------------------------------------------------------------

    async def preview_pipeline(self, request: web.Request):
        """Bounded preview run executed in-process (reference: preview
        pipelines with the preview sink + websocket output tail)."""
        body = await request.json()
        query = body.get("query")
        if not query:
            return error(400, "query is required")
        results: list = []
        try:
            plan = plan_query(query, preview_results=results)
        except SqlError as e:
            return error(400, str(e))
        from ..engine import Engine

        pid = self.db.create_pipeline(body.get("name", "preview"), query, 1)
        # mark in the DB so the TTL sweep can find preview rows whose
        # registry entry is gone (cap eviction, process restart)
        self.db.set_pipeline_state(pid["id"], "Preview")
        self.previews[pid["id"]] = {"rows": results, "done": False,
                                    "created": time.time()}

        async def run():
            eng = None
            try:
                eng = Engine(plan.graph).start()
                await eng.join(body.get("timeout", 60))
            except Exception as e:  # noqa: BLE001
                self.previews[pid["id"]]["error"] = str(e)
                if eng is not None:
                    # a timed-out preview must not keep burning CPU
                    from ..types import StopMode

                    await eng.stop(StopMode.IMMEDIATE)
                    for t in eng.tasks:
                        t.cancel()
            finally:
                self.previews[pid["id"]]["done"] = True
                done_ids = [
                    k for k, v in self.previews.items()
                    if v.get("done") and k != pid["id"]
                ]
                while len(self.previews) > 20 and done_ids:
                    # evict finished previews only: a running preview's
                    # cleanup still needs its entry
                    self.previews.pop(done_ids.pop(0), None)

        self._spawn(run())
        return json_response(pid)

    def cleanup_previews(self, now: Optional[float] = None) -> int:
        """TTL sweep over stale previews (reference: the controller
        update loop cleans stale preview pipelines, arroyo-controller
        lib.rs:600-706). Two sources: FINISHED registry entries past the
        TTL, and DB rows in state 'Preview' past the TTL with no live
        registry entry — those cover cap-evicted previews and previews
        from a previous process (the registry is in-memory). Returns the
        number removed."""
        from ..config import config as config_fn

        ttl = float(config_fn().api.preview_ttl or 0)
        if ttl <= 0:
            return 0
        now = time.time() if now is None else now
        stale = [
            pid for pid, pv in self.previews.items()
            if pv.get("done") and now - pv.get("created", now) > ttl
        ]
        try:
            stale += [
                p["id"] for p in self.db.list_pipelines()
                if p.get("state") == "Preview"
                and now - p.get("created_at", now) > ttl
                # a LIVE registry entry means the preview may still be
                # running; only its own done+TTL path may remove it
                and p["id"] not in self.previews
            ]
        except Exception as e:  # noqa: BLE001 - sweep must not die
            logger.warning("preview ttl: db scan failed: %s", e)
        n = 0
        for pid in dict.fromkeys(stale):
            self.previews.pop(pid, None)
            try:
                self.db.delete_pipeline(pid)
                n += 1
            except Exception as e:  # noqa: BLE001
                logger.warning("preview ttl: delete %s failed: %s", pid, e)
        return n

    async def preview_ttl_loop(self):
        while True:
            await asyncio.sleep(30.0)
            try:
                n = self.cleanup_previews()
                if n:
                    logger.info("preview ttl: removed %d stale previews", n)
            except Exception as e:  # noqa: BLE001
                logger.warning("preview ttl sweep failed: %s", e)

    async def preview_output(self, request: web.Request):
        pv = self.previews.get(request.match_info["id"])
        if pv is None:
            return error(404, "no preview for pipeline")
        return json_response(
            {"rows": pv["rows"], "done": pv["done"],
             "error": pv.get("error")}
        )

    async def preview_output_ws(self, request: web.Request):
        """Websocket tail of preview rows (reference: job output ws)."""
        pv = self.previews.get(request.match_info["id"])
        if pv is None:
            return error(404, "no preview for pipeline")
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        sent = 0
        while not ws.closed:
            rows = pv["rows"]
            while sent < len(rows):
                await ws.send_json(rows[sent], dumps=lambda d: json.dumps(
                    d, default=str))
                sent += 1
            if pv["done"]:
                break
            await asyncio.sleep(0.1)
        await ws.close()
        return ws

    # -- connectors / connections ------------------------------------------

    async def list_connectors(self, request: web.Request):
        from ..connectors import connectors

        return json_response({"data": [c.metadata() for c in connectors()]})

    async def list_connection_profiles(self, request: web.Request):
        return json_response({"data": self.db.list_connection_profiles()})

    async def create_connection_profile(self, request: web.Request):
        body = await request.json()
        return json_response(
            self.db.create_connection_profile(
                body["name"], body["connector"], body.get("config", {})
            )
        )

    async def list_connection_tables(self, request: web.Request):
        return json_response({"data": self.db.list_connection_tables()})

    async def create_connection_table(self, request: web.Request):
        from ..connectors import get_connector

        body = await request.json()
        try:
            conn = get_connector(body["connector"])
            conn.validate_options(body.get("config", {}), None)
        except (ValueError, KeyError) as e:
            return error(400, str(e))
        return json_response(
            self.db.create_connection_table(
                body["name"], body["connector"], body.get("config", {}),
                body.get("schema"), body.get("table_type", "source"),
                body.get("profile_id"),
            )
        )

    async def delete_connection_table(self, request: web.Request):
        self.db.delete_connection_table(request.match_info["id"])
        return json_response({"deleted": request.match_info["id"]})

    async def test_connection_table(self, request: web.Request):
        from ..connectors import get_connector

        body = await request.json()
        try:
            conn = get_connector(body["connector"])
            cfg = conn.validate_options(body.get("config", {}), None)
            ok, message = conn.test(cfg)
        except (ValueError, KeyError) as e:
            ok, message = False, str(e)
        return json_response({"ok": ok, "message": message})

    # -- udfs ---------------------------------------------------------------

    async def validate_udf(self, request: web.Request):
        from ..udf import registry

        body = await request.json()
        snap = registry.snapshot()
        try:
            names = registry.register_from_source(body["definition"])
        except Exception as e:  # noqa: BLE001 - user code boundary
            return json_response({"errors": [str(e)]}, status=400)
        finally:
            registry.restore(snap)  # validation must not mutate the registry
        return json_response({"udfs": names, "errors": []})

    async def create_udf(self, request: web.Request):
        from ..udf import registry

        body = await request.json()
        try:
            names = registry.register_from_source(body["definition"])
        except Exception as e:  # noqa: BLE001
            return error(400, str(e))
        if not names:
            return error(400, "definition registers no UDFs")
        return json_response(
            self.db.create_udf(names[0], body["definition"])
        )

    async def list_udfs(self, request: web.Request):
        return json_response({"data": self.db.list_udfs()})

    async def delete_udf(self, request: web.Request):
        self.db.delete_udf(request.match_info["id"])
        return json_response({"deleted": request.match_info["id"]})

    async def ping(self, request: web.Request):
        return json_response({"pong": True})


def build_app(controller: Optional[ControllerServer] = None,
              db_path: Optional[str] = None) -> web.Application:
    api = ApiServer(controller, db_path)
    # re-register saved UDFs so pipelines can use them after restarts
    from ..udf import registry as udf_registry

    for u in api.db.list_udfs():
        try:
            udf_registry.register_from_source(u["definition"])
        except Exception:  # noqa: BLE001
            logger.warning("failed to re-register udf %s", u["name"])

    app = web.Application()
    r = app.router
    v1 = "/api/v1"
    # routes register from the same table that generates the OpenAPI spec
    # (openapi.py ROUTES), so /api/v1/openapi.json cannot drift
    from .openapi import ROUTES, build_spec

    for method, path, handler, *_ in ROUTES:
        if method == "get":  # add_get also registers HEAD
            r.add_get(v1 + path, getattr(api, handler))
        else:
            r.add_route(method.upper(), v1 + path, getattr(api, handler))

    spec = build_spec(v1)

    async def openapi_json(request: web.Request):
        return json_response(spec)

    r.add_get(f"{v1}/openapi.json", openapi_json)
    from .console import add_console_routes

    add_console_routes(app)
    app["api"] = api

    async def _preview_ttl_ctx(app_):
        task = asyncio.ensure_future(api.preview_ttl_loop())
        yield
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)

    app.cleanup_ctx.append(_preview_ttl_ctx)
    return app


async def serve_api(port: Optional[int] = None,
                    controller: Optional[ControllerServer] = None):
    cfg = config()
    app = build_app(controller)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(
        runner, cfg.api.bind_address, port or cfg.api.http_port
    )
    await site.start()
    logger.info("api listening on %s:%s", cfg.api.bind_address,
                port or cfg.api.http_port)
    await asyncio.Event().wait()
