"""Mesh-sharded accumulator on the virtual 8-device CPU mesh: all_to_all
routing + scatter-reduce must match the single-device result exactly."""

import numpy as np
import pandas as pd
import pytest

from arroyo_tpu.ops.aggregates import AggSpec
from arroyo_tpu.types import hash_column


@pytest.fixture(scope="module")
def mesh():
    import jax

    from arroyo_tpu.parallel import key_mesh

    devices = jax.devices()
    if len(devices) < 2:
        pytest.skip("needs multiple devices")
    return key_mesh(devices)


def test_sharded_accumulator_matches_pandas(mesh):
    from arroyo_tpu.parallel import ShardedAccumulator

    specs = [
        AggSpec("count", None, "cnt"),
        AggSpec("sum", 0, "total"),
        AggSpec("max", 1, "hi", is_float=True),
    ]
    acc = ShardedAccumulator(specs, mesh, capacity_per_shard=256,
                             rows_per_shard=512)
    rng = np.random.default_rng(3)
    n = 6000
    keys = rng.integers(0, 40, n)
    bins = rng.integers(0, 3, n)
    ints = rng.integers(-50, 50, n)
    floats = rng.random(n) * 10
    hashes = hash_column(keys)
    for lo in range(0, n, 1500):
        hi = min(lo + 1500, n)
        acc.update(
            hashes[lo:hi], bins[lo:hi], [keys[lo:hi]],
            {0: ints[lo:hi], 1: floats[lo:hi]},
        )
    df = pd.DataFrame({"b": bins, "k": keys, "i": ints, "f": floats})
    want = df.groupby(["b", "k"]).agg(
        cnt=("i", "size"), total=("i", "sum"), hi=("f", "max")
    )
    seen = 0
    for b in range(3):
        keys_out, gathered = acc.gather_bin(b)
        assert len(keys_out) == len(want.loc[b])
        for key, cnt, total, hi_ in zip(
            keys_out, gathered[0], gathered[1], gathered[2]
        ):
            row = want.loc[(b, key[0])]
            assert cnt == row["cnt"]
            assert total == row["total"]
            assert hi_ == pytest.approx(row["hi"])
            seen += 1
    assert seen == len(want)


def test_sharded_routing_respects_hash_ranges(mesh):
    """Rows must land on the shard that owns their hash range — the same
    mapping the host shuffle and state restore use."""
    from arroyo_tpu.parallel import ShardedAccumulator
    from arroyo_tpu.types import server_for_hash_array

    specs = [AggSpec("count", None, "cnt")]
    acc = ShardedAccumulator(specs, mesh, capacity_per_shard=64,
                             rows_per_shard=256)
    keys = np.arange(100, dtype=np.int64)
    hashes = hash_column(keys)
    owners = server_for_hash_array(hashes, acc.n_shards)
    acc.update(hashes, np.zeros(100, dtype=np.int64), [keys], {})
    for shard in range(acc.n_shards):
        expect = set(keys[owners == shard].tolist())
        got = {k[0] for _, k, _ in
               [(b, key, s) for b, key, s in acc.dirs[shard].items()]}
        assert got == expect
