"""ReplicaManager: mount, tail, and serve follower read replicas.

Lifecycle per durable job (all driven off the controller's event loop,
the StandbyManager pattern):

  mount  — on every _run pass (note_running), an eligible job with no
           mount gets assigned the least-loaded follower and a
           subscribe guard restores its serve tables from the latest
           PUBLISHED manifest (Follower._subscribe: read-only, no
           generation claim — a follower can never fence the primary).

  tail   — on each manifest publish (note_publish), a coalesced tail
           guard replays the delta-chain suffix onto the mount
           (Follower._tail), keeping follower lag at <= 1 checkpoint
           interval at delta cost. `replica.kill` is the chaos seam
           here: the drill detaches the follower abruptly mid-tail and
           asserts the gateway fails over worker-ward with zero wrong
           values; reattach goes back through _subscribe, re-resolving
           latest.json (the follower_serves_unpublished_epoch mutant
           is the reattach shortcut this forbids).

  serve  — the gateway calls route(job, table): the mounted view when
           follower lag <= replica.max_lag_epochs, else None
           (worker-ward fallback). tables_meta answers the gateway's
           table listing from the mirrored describe records, so durable
           jobs' serve traffic needs ZERO worker QueryState RPCs.

  detach — on job stop/expunge/terminal states: drop the mount and all
           pending work. Metrics are job-labeled; Registry.drop_job on
           the expunge path GCs the arroyo_replica_* series.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional

from .. import chaos
from ..analysis.model.effects import protocol_effect
from ..analysis.races.sanitizer import set_task_root
from ..config import config
from ..metrics import (
    REPLICA_LAG_EPOCHS,
    REPLICA_SERVED_EPOCH,
    REPLICA_SUBSCRIBES,
    REPLICA_TAILS,
)
from ..utils.logging import get_logger
from .follower import Follower

logger = get_logger("replica")


class ReplicaManager:
    def __init__(self, ctrl):
        self.ctrl = ctrl
        self.followers: List[Follower] = []
        self._assign: Dict[str, int] = {}     # job -> follower index
        self._sub_tasks: Dict[str, asyncio.Task] = {}
        self._tail_tasks: Dict[str, asyncio.Task] = {}
        self._tail_pending: Dict[str, int] = {}
        self._next_attach: Dict[str, float] = {}
        self.kills = 0

    # -- eligibility / mounting ----------------------------------------------

    def eligible(self, job) -> bool:
        cfg = config()
        return (
            cfg.replica.enabled
            and int(cfg.replica.followers) > 0
            and job.backend is not None   # durable jobs only
            and job.mount is None         # tenants ride their host's views
            and not job.stop_requested
        )

    def _ensure_followers(self) -> None:
        want = int(config().replica.followers)
        while len(self.followers) < want:
            self.followers.append(Follower(len(self.followers)))

    def _mount(self, jid: str):
        idx = self._assign.get(jid)
        if idx is None or idx >= len(self.followers):
            return None
        return self.followers[idx].mounts.get(jid)

    def note_running(self, job):
        """Called on every _run pass: keep each eligible job mounted on
        exactly one follower (or one subscribe attempt in flight). Cheap
        no-op guard on the non-replica path."""
        if not self.eligible(job):
            return
        self._ensure_followers()
        jid = job.job_id
        if jid in self._sub_tasks or self._mount(jid) is not None:
            return
        if time.monotonic() < self._next_attach.get(jid, 0.0):
            return
        idx = self._assign.get(jid)
        if idx is None or idx >= len(self.followers):
            idx = min(
                range(len(self.followers)),
                key=lambda i: (len(self.followers[i].mounts), i),
            )
            self._assign[jid] = idx
        self._sub_tasks[jid] = asyncio.ensure_future(
            self._subscribe_guard(job, idx)
        )

    async def _subscribe_guard(self, job, idx: int):
        jid = job.job_id
        set_task_root(f"replica-subscribe:{jid}")
        try:
            ok = await self.followers[idx]._subscribe(jid, job.storage_url)
            if not ok:
                # nothing published yet — back off and retry later
                self._next_attach[jid] = (
                    time.monotonic() + config().replica.reattach_backoff
                )
                return
            REPLICA_SUBSCRIBES.labels(job=jid).inc()
            self._gauges(job)
            # catch up anything published while the restore ran
            self.note_publish(job)
        except Exception as e:  # noqa: BLE001 - mounting is best-effort
            logger.warning("follower subscribe for %s failed: %r", jid, e)
            self._next_attach[jid] = (
                time.monotonic() + config().replica.reattach_backoff
            )
        finally:
            self._sub_tasks.pop(jid, None)
            job.kick()

    # -- tailing -------------------------------------------------------------

    def note_publish(self, job):
        """Called after each manifest publish: schedule a (coalesced)
        suffix tail of the new epoch onto the job's mount."""
        jid = job.job_id
        mount = self._mount(jid)
        if mount is None:
            return
        self._gauges(job)
        target = int(job.published_epoch or 0)
        if target <= mount.epoch:
            return
        self._tail_pending[jid] = max(self._tail_pending.get(jid, 0),
                                      target)
        if jid not in self._tail_tasks:
            self._tail_tasks[jid] = asyncio.ensure_future(
                self._tail_guard(job)
            )

    async def _tail_guard(self, job):
        jid = job.job_id
        set_task_root(f"replica-tail:{jid}")
        try:
            while True:
                mount = self._mount(jid)
                target = self._tail_pending.get(jid)
                if (mount is None or target is None
                        or target <= mount.epoch):
                    return
                await self._tail_one(job, target)
        except Exception as e:  # noqa: BLE001 - a broken mount reattaches
            logger.warning(
                "follower tail for %s failed: %r; detaching", jid, e
            )
            self.detach(jid)
            self._next_attach[jid] = (
                time.monotonic() + config().replica.reattach_backoff
            )
        finally:
            self._tail_tasks.pop(jid, None)
            job.kick()

    async def _tail_one(self, job, target: int):
        jid = job.job_id
        idx = self._assign.get(jid)
        if idx is None:
            return
        if chaos.fire("replica.kill", job_id=jid, follower=idx):
            # abrupt follower death mid-tail: every mount on this
            # follower drops without graceful detach. The gateway fails
            # over worker-ward instantly (route() finds no mount);
            # note_running reattaches via _subscribe, which re-resolves
            # latest.json — never the in-memory target epoch.
            self.kill(idx)
            raise RuntimeError(f"chaos: follower {idx} killed mid-tail")
        applied = await self.followers[idx]._tail(jid, target)
        mount = self._mount(jid)
        if mount is not None:
            REPLICA_TAILS.labels(job=jid).inc()
            self._gauges(job)
            logger.debug(
                "follower %d tailed %s to epoch %d (%d blobs)",
                idx, jid, mount.epoch, applied,
            )

    # -- serving (the gateway's entry points) --------------------------------

    def route(self, job, table: str):
        """The gateway's follower-first lookup: the mounted ServeView
        for (job, table) when the follower is within
        replica.max_lag_epochs of publication, else None — the caller
        falls back worker-ward (live jobs, unmounted tables, dead or
        lagging followers all land here, never on a wrong value)."""
        if not config().replica.enabled:
            return None
        jid = job.job_id
        mount = self._mount(jid)
        if mount is None:
            return None
        idx = self._assign[jid]
        view = self.followers[idx].view(jid, table)
        if view is None:
            return None
        lag = int(job.published_epoch or 0) - mount.epoch
        if lag > int(config().replica.max_lag_epochs):
            return None
        return view

    def read_one(self, job_id: str, table: str,
                 key_values) -> Optional[dict]:
        """One key lookup through the mounted follower's effect-
        annotated read path (replica.serve). None when the mount
        vanished since route() — the gateway degrades that key to a
        retriable error, never a wrong value."""
        idx = self._assign.get(job_id)
        if idx is None or idx >= len(self.followers):
            return None
        return self.followers[idx].read(job_id, table, key_values)

    def tables_meta(self, job_id: str) -> Optional[Dict[str, dict]]:
        """The job's table listing from mirrored describe records — the
        gateway's zero-RPC replacement for the per-worker `tables` fan
        when the job is mounted. None when unmounted (worker fallback)."""
        if not config().replica.enabled:
            return None
        mount = self._mount(job_id)
        if mount is None or not mount.meta:
            return None
        return dict(mount.meta)

    # -- lifecycle -----------------------------------------------------------

    def kill(self, idx: int):
        """Abrupt follower death (the chaos drill's seam, also exposed
        on /debug/replica-kill): drop every mount with no graceful
        detach. Jobs reattach through the full _subscribe path."""
        if idx >= len(self.followers):
            return
        f = self.followers[idx]
        dropped = sorted(f.mounts)
        f.mounts.clear()
        for jid in dropped:
            self._assign.pop(jid, None)
            self._tail_pending.pop(jid, None)
        self.kills += 1
        logger.warning(
            "follower %d killed (%d mounts dropped: %s)",
            idx, len(dropped), dropped,
        )

    @protocol_effect("replica.detach")
    def detach(self, job_id: str):
        """Graceful unmount on job stop/terminal/expunge: cancel pending
        work, drop the mount and assignment. Metric GC rides the expunge
        path's Registry.drop_job (all replica families are job-labeled)."""
        idx = self._assign.pop(job_id, None)
        for tasks in (self._sub_tasks, self._tail_tasks):
            t = tasks.pop(job_id, None)
            if t is not None:
                t.cancel()
        self._tail_pending.pop(job_id, None)
        if idx is not None and idx < len(self.followers):
            self.followers[idx].mounts.pop(job_id, None)

    def on_job_expunged(self, jid: str):
        self._next_attach.pop(jid, None)

    # -- observability -------------------------------------------------------

    def _gauges(self, job):
        mount = self._mount(job.job_id)
        if mount is None:
            return
        REPLICA_SERVED_EPOCH.labels(job=job.job_id).set(float(mount.epoch))
        REPLICA_LAG_EPOCHS.labels(job=job.job_id).set(
            float(max(0, int(job.published_epoch or 0) - mount.epoch))
        )

    def lag_epochs(self, job) -> Optional[int]:
        """published - served for a mounted job (the replica_staleness
        SLO input); None when unmounted."""
        mount = self._mount(job.job_id)
        if mount is None:
            return None
        return max(0, int(job.published_epoch or 0) - mount.epoch)

    def status(self) -> dict:
        return {
            "enabled": bool(config().replica.enabled),
            "followers": [f.stats() for f in self.followers],
            "assignments": dict(self._assign),
            "kills": self.kills,
            "subscribing": sorted(self._sub_tasks),
            "tail_pending": dict(self._tail_pending),
        }
