from . import registry  # noqa: F401
from .registry import udf, udaf, PythonUdf  # noqa: F401
