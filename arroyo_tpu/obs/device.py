"""Device-tier observatory: XLA compile & dispatch telemetry.

The JAX tier compiles one executable per (program, shape signature):
every new padding rung, accumulator capacity or dtype layout traces and
compiles a fresh program — ~ms on CPU-jax, 20-40s through the TPU relay
— and until now those cycles were invisible (ROADMAP item 1: the 8-way
mesh path loses to one process and nobody can say how much of the gap is
compile storms vs padding vs dispatch).

`InstrumentedJit` wraps a jitted callable and, per call, classifies it
as a compile (first time this process sees the call's shape signature)
or a steady-state dispatch:

* compiles feed `arroyo_xla_compiles_total`, the
  `arroyo_xla_compile_seconds` histogram, a compile-cache miss, a
  bounded recompile-cause log naming the program, the offending shape
  signature and the packing rung that produced it, and — when a trace
  context is ambient — a `jax.compile:<program>` span inside whatever
  batch/checkpoint trace triggered the compile;
* dispatches feed `arroyo_device_dispatch_seconds` and a cache hit.

`note_padding` records the per-(program, rung) padding-waste gauge from
the packing paths (aggregates + the mesh exchange in parallel/).

Everything is gated on `obs.device_telemetry`; when off, the wrapper
forwards straight to the jitted callable.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..metrics import (
    DEVICE_EXCHANGE_SECONDS,
    DEVICE_PADDING_WASTE,
    DEVICE_DISPATCH_SECONDS,
    SEGMENT_DISPATCH_SECONDS,
    XLA_COMPILE_CACHE,
    XLA_COMPILE_SECONDS,
    XLA_COMPILES,
)
from . import attribution, timeline, trace

logger = logging.getLogger("arroyo.obs.device")

_LOCK = threading.Lock()
_RECOMPILE_LOG: deque = deque(maxlen=256)
# bumped whenever a jax.compile span lands in the recorder: the runner's
# lazy batch anchors use it to decide whether to materialize themselves
_SPAN_EPOCH = 0
# per-(program, rung) cached gauge handles for the padding-waste path
_PAD_HANDLES: Dict[Tuple[str, str], Any] = {}


def enabled() -> bool:
    from ..config import config

    return bool(config().obs.device_telemetry)


def span_epoch() -> int:
    return _SPAN_EPOCH


def recompile_log() -> List[dict]:
    """The bounded recompile-cause log, oldest first. Each entry names
    the program, the full shape signature that forced the compile, the
    packing rung the call site padded to, and the call's wall time."""
    with _LOCK:
        return list(_RECOMPILE_LOG)


def reset() -> None:
    """Clear telemetry state (tests)."""
    global _SPAN_EPOCH
    with _LOCK:
        _RECOMPILE_LOG.clear()
        _PAD_HANDLES.clear()
        _SPAN_EPOCH = 0


def _resize_log() -> None:
    from ..config import config

    global _RECOMPILE_LOG
    cap = int(config().obs.recompile_log_entries)
    if cap > 0 and _RECOMPILE_LOG.maxlen != cap:
        _RECOMPILE_LOG = deque(_RECOMPILE_LOG, maxlen=cap)


def _sig_part(a: Any, parts: List[str]) -> None:
    if isinstance(a, (list, tuple)):
        for x in a:
            _sig_part(x, parts)
        return
    shape = getattr(a, "shape", None)
    dtype = getattr(a, "dtype", None)
    if shape is not None:
        parts.append(
            f"{dtype}[{'x'.join(str(d) for d in shape)}]"
        )
    else:
        parts.append(type(a).__name__)


def signature_of(args: tuple) -> str:
    """The call's shape signature — the key XLA specializes on: dtype and
    dimensions of every array argument, pytree-flattened in order."""
    parts: List[str] = []
    _sig_part(args, parts)
    return "(" + ", ".join(parts) + ")"


def _sig_key_part(a: Any, parts: List) -> None:
    if isinstance(a, (list, tuple)):
        for x in a:
            _sig_key_part(x, parts)
        return
    shape = getattr(a, "shape", None)
    if shape is not None:
        parts.append((getattr(a, "dtype", None), shape))
    else:
        parts.append(type(a))


def signature_key(args: tuple) -> tuple:
    """Hashable fast form of signature_of: (dtype, shape) tuples instead
    of built strings. The hot dispatch path classifies every call — at
    hundreds of dispatches per second the string rendering itself showed
    up in the mesh profile — so the string form is only materialized
    when a call is actually fresh (compiles are rare)."""
    parts: List = []
    _sig_key_part(args, parts)
    return tuple(parts)


def _record_compile(program: str, sig: str, rung: Optional[int],
                    nth: int, secs: float, start_us: float) -> None:
    global _SPAN_EPOCH
    cause = "first-compile" if nth == 1 else "shape-change"
    entry = {
        "ts": time.time(),
        "program": program,
        "signature": sig,
        "rung": rung,
        "nth_compile": nth,
        "compile_s": round(secs, 4),
        "cause": cause,
    }
    with _LOCK:
        _resize_log()
        _RECOMPILE_LOG.append(entry)
    logger.info(
        "xla compile #%d for %s (%s): signature=%s rung=%s %.3fs",
        nth, program, cause, sig, rung, secs,
    )
    ctx = trace.current()
    if ctx is None:
        return
    # retroactive span over the compiling call, parented into whatever
    # batch/checkpoint trace was ambient when the compile fired
    import os

    trace_id, parent_id = ctx
    from . import recorder

    recorder().record({
        "trace_id": trace_id,
        "span_id": trace.new_span_id(),
        "parent_id": parent_id,
        "name": f"jax.compile:{program}",
        "cat": "device",
        "ts": start_us,
        "dur": secs * 1e6,
        "attrs": {"signature": sig, "rung": rung, "nth_compile": nth,
                  "cause": cause},
        "events": [],
        "pid": os.getpid(),
        "tid": threading.get_ident(),
    })
    with _LOCK:
        _SPAN_EPOCH += 1


class InstrumentedJit:
    """Wrap one jitted program with compile/dispatch telemetry. The
    in-process signature set classifies each call: an unseen signature
    means jax traces + XLA compiles inside this call (cache miss), a seen
    one is a pure dispatch (cache hit). The persistent on-disk XLA cache
    (tpu.compilation_cache_dir) can make a "miss" cheap — the compile
    histogram will show it — but it still costs a python-side trace."""

    __slots__ = ("program", "fn", "seen", "_compiles", "_hit", "_miss",
                 "_compile_h", "_dispatch_h", "_exchange_h", "_segment_h")

    def __init__(self, program: str, fn, exchange: bool = False,
                 segment: bool = False):
        self.program = program
        self.fn = fn
        self.seen: set = set()
        self._compiles = XLA_COMPILES.labels(program=program)
        self._hit = XLA_COMPILE_CACHE.labels(program=program, result="hit")
        self._miss = XLA_COMPILE_CACHE.labels(program=program, result="miss")
        self._compile_h = XLA_COMPILE_SECONDS.labels(program=program)
        self._dispatch_h = DEVICE_DISPATCH_SECONDS.labels(program=program)
        # exchange programs (the mesh keyed shuffle: route/step kernels)
        # additionally feed arroyo_device_exchange_seconds so the
        # collective's per-flush cost is separable from emission reads
        self._exchange_h = (
            DEVICE_EXCHANGE_SECONDS.labels(program=program)
            if exchange else None
        )
        # fused-segment programs (engine/segments.py) additionally feed
        # arroyo_segment_dispatch_seconds{tier="jax"} so the per-segment
        # ledger separates whole-chain dispatches from other device work
        self._segment_h = (
            SEGMENT_DISPATCH_SECONDS.labels(program=program, tier="jax")
            if segment else None
        )

    def __call__(self, *args, rung: Optional[int] = None):
        if not enabled():
            return self.fn(*args)
        key = signature_key(args)
        fresh = key not in self.seen
        start_us = time.time() * 1e6
        t0 = time.perf_counter()
        out = self.fn(*args)
        dt = time.perf_counter() - t0
        # per-job device attribution (ISSUE 11): jitted programs are
        # cached process-wide ACROSS jobs, so the per-program families
        # cannot carry a job label — the ambient job context gives
        # dispatch/compile seconds their job dimension instead, and the
        # timeline ledger its device swimlane
        attribution.note(device=dt, dispatches=1)
        timeline.note("dispatch", dt)
        if fresh:
            self.seen.add(key)
            self._compiles.inc()
            self._miss.inc()
            self._compile_h.observe(dt)
            _record_compile(self.program, signature_of(args), rung,
                            len(self.seen), dt, start_us)
        else:
            self._hit.inc()
            self._dispatch_h.observe(dt)
            if self._exchange_h is not None:
                self._exchange_h.observe(dt)
            if self._segment_h is not None:
                self._segment_h.observe(dt)
        return out


def note_padding(program: str, rung: int, rows: int, shipped: int) -> None:
    """Record the padding waste of one packed dispatch: `rows` real rows
    shipped in a `shipped`-row buffer padded to `rung`. Gauge semantics
    (last dispatch wins) per (program, rung): the steady-state waste of
    each rung the pipeline actually hits, not a lifetime average — the
    lifetime totals stay in MESH_STATS / rows_padded."""
    if shipped <= 0 or not enabled():
        return
    key = (program, str(rung))
    h = _PAD_HANDLES.get(key)
    if h is None:
        with _LOCK:
            h = _PAD_HANDLES.setdefault(
                key,
                DEVICE_PADDING_WASTE.labels(program=program, rung=str(rung)),
            )
    h.set(round((shipped - rows) / shipped, 4))


# -- lazy trace anchors -------------------------------------------------------


class _NullAnchor:
    __slots__ = ()

    def close(self) -> None:
        pass


NULL_ANCHOR = _NullAnchor()


class _Anchor:
    """A deferred span: attaches a fresh trace context for the extent of
    one batch (or watermark advance), but only materializes the span in
    the recorder if a jax.compile span landed during the extent — so the
    hot loop pays a contextvar set/reset per batch, not a recorded span
    per batch (which would churn the ring buffer)."""

    __slots__ = ("trace_id", "span_id", "name", "attrs", "start_us",
                 "_tok", "_epoch0")

    def __init__(self, trace_id: str, name: str, attrs: dict):
        self.trace_id = trace_id
        self.span_id = trace.new_span_id()
        self.name = name
        self.attrs = attrs
        self.start_us = time.time() * 1e6
        self._epoch0 = _SPAN_EPOCH
        self._tok = trace.attach(trace_id, self.span_id)

    def close(self) -> None:
        trace.detach(self._tok)
        if _SPAN_EPOCH == self._epoch0:
            return
        import os

        from . import recorder

        recorder().record({
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": None,
            "name": self.name,
            "cat": "runner",
            "ts": self.start_us,
            "dur": time.time() * 1e6 - self.start_us,
            "attrs": dict(self.attrs),
            "events": [],
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        })


def anchor(trace_id: str, name: str, **attrs):
    """A lazy compile-trace anchor for the runner's batch/watermark hot
    paths. Inert when telemetry is off or a real trace context is already
    ambient (checkpoint captures: compiles parent there instead)."""
    from . import enabled as obs_enabled

    if not obs_enabled() or not enabled() or trace.current() is not None:
        return NULL_ANCHOR
    return _Anchor(trace_id, name, attrs)


# -- summary ------------------------------------------------------------------


def summary() -> dict:
    """Structured device-telemetry summary for /debug/latency and
    tools/trace_report.py: per-program compile/dispatch stats, padding
    gauges, and the recompile-cause log."""
    from ..metrics import REGISTRY, hist_quantiles

    snap = REGISTRY.snapshot()

    def by_program(name: str) -> Dict[str, Any]:
        return {
            labels.get("program", "?"): value
            for labels, value in snap.get(name, [])
        }

    programs: Dict[str, dict] = {}
    for prog, v in by_program("arroyo_xla_compiles_total").items():
        programs.setdefault(prog, {})["compiles"] = int(v)
    for prog, h in by_program("arroyo_xla_compile_seconds").items():
        programs.setdefault(prog, {})["compile_s_total"] = round(
            h.get("sum", 0.0), 4)
    for prog, h in by_program("arroyo_device_dispatch_seconds").items():
        p = programs.setdefault(prog, {})
        p["dispatches"] = int(h.get("count", 0))
        p["dispatch_quantiles"] = {
            q: round(v, 6) for q, v in hist_quantiles(h).items()
        }
    for prog, h in by_program("arroyo_device_exchange_seconds").items():
        p = programs.setdefault(prog, {})
        p["exchange_dispatches"] = int(h.get("count", 0))
        p["exchange_s_total"] = round(h.get("sum", 0.0), 4)
        p["exchange_quantiles"] = {
            q: round(v, 6) for q, v in hist_quantiles(h).items()
        }
    for labels, v in snap.get("arroyo_xla_compile_cache_total", []):
        p = programs.setdefault(labels.get("program", "?"), {})
        p[f"cache_{labels.get('result', '?')}"] = int(v)
    padding = [
        {"program": labels.get("program"), "rung": labels.get("rung"),
         "waste": v}
        for labels, v in snap.get("arroyo_device_padding_waste", [])
    ]
    padding.sort(key=lambda e: (e["program"], int(e["rung"] or 0)))
    # fused-segment ledger (engine/segments.py): per-segment dispatch
    # stats by tier plus the fused-op count — what the mesh_profile
    # BASELINE ledger renders as per-segment rows
    segments: Dict[str, dict] = {}
    for labels, h in snap.get("arroyo_segment_dispatch_seconds", []):
        s = segments.setdefault(labels.get("program", "?"), {})
        tier = labels.get("tier", "?")
        s[f"{tier}_dispatches"] = int(h.get("count", 0))
        s[f"{tier}_s_total"] = round(h.get("sum", 0.0), 4)
        s[f"{tier}_quantiles"] = {
            q: round(v, 6) for q, v in hist_quantiles(h).items()
        }
    for labels, v in snap.get("arroyo_segment_fused_ops", []):
        s = segments.setdefault(labels.get("program", "?"), {})
        s["fused_ops"] = int(v)
    return {
        "programs": programs,
        "padding_waste": padding,
        "segments": segments,
        "recompiles": recompile_log(),
    }
