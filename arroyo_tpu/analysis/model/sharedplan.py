"""Shared-plan operator lifecycle: one barrier, per-tenant epochs.

Shared-plan multi-tenancy (ISSUE 16) mounts N tenant jobs onto ONE
shared source/operator chain (a hidden host job). The host checkpoints
on its own cadence; each mounted tenant checkpoints its consumed bus
OFFSET in its own manifest chain. Exactly-once then hangs on a single
cross-job obligation the single-job model (spec.py) and the 2-job
shared-WORKER model (multitenant.py) cannot see:

  the host's durable restore offset must never pass a mounted tenant's
  durable position. After a crash the host replays from its last
  PUBLISHED epoch's offset; a tenant whose published position is behind
  that offset has a gap the host will never re-emit — silent per-tenant
  data loss.

The controller's publication gate discharges it: host epoch E publishes
only once every MOUNTED subscriber has published a tenant checkpoint at
position >= E's offset (shared fate on the barrier, per-tenant epochs
reconciled). Detach (refcounted, job-scoped) removes a tenant from the
gate set so one tenant's stop never stalls co-mounted tenants, and the
host is torn down only when the LAST tenant detaches.

Model shape: one host counter pair (captured epoch `h_cap`, published
epoch `h_pub`, at most one epoch in flight) over `tenants` subscriber
machines, each with a captured/published position pair (epoch-granular:
position k == the offset of host epoch k), a mounted flag, and a gate
membership flag. The one fault is the process kill: host restores to
`h_pub`, every tenant restores to its published position.

Mutants (each expected to be CAUGHT; the faithful model is clean):

  * `leaked_barrier_across_tenants` — the publication gate is skipped:
    the host publishes epoch E while a mounted tenant's durable
    position is still behind it. The kill then restores the host AHEAD
    of that tenant (V_LOSS): the barrier's shared fate leaked across
    tenant epoch chains. The counterexample's kill serializes to a
    seeded chaos FaultPlan replayable via tools/chaos_drill.py --plan.
  * `detach_leaves_gate` — detach clears the mount but NOT the gate
    membership: a stopped tenant keeps gating host publication forever
    and co-mounted tenants stall behind a barrier that can never clear
    (V_STALL).
  * `teardown_on_first_detach` — the refcount is ignored: the FIRST
    detach tears the shared host down under the remaining tenants
    (V_ORPHAN).

Explored exhaustively by `check_sharedplan`; wired into
tools/model_check.py (--shared, corpus) and tests/test_model_check.py.
"""

from __future__ import annotations

import hashlib
import json
from collections import deque
from typing import Dict, List, NamedTuple, Optional, Set, Tuple


class SPConfig(NamedTuple):
    tenants: int = 2          # jobs mounted on the shared chain
    epochs: int = 3           # host checkpoint epochs
    kills: int = 1            # process-kill fault budget
    mutant: str = ""          # "" | a SP_MUTANTS key


class SPSys(NamedTuple):
    h_cap: int = 0            # host epoch captured (offset frozen)
    h_pub: int = 0            # host epoch published (durable restore point)
    host_alive: bool = True   # shared chain still running
    mounted: Tuple[bool, ...] = ()
    gate: Tuple[bool, ...] = ()   # publication-gate membership
    cap: Tuple[int, ...] = ()     # tenant captured position (epochs)
    pub: Tuple[int, ...] = ()     # tenant published position (durable)
    kills: int = 0


class SPStep(NamedTuple):
    label: str
    arg: Tuple
    nxt: Optional[SPSys]
    violation: str = ""


class SPTrace(NamedTuple):
    violation: str
    events: List[Tuple[str, Tuple]]
    config: dict

    def to_json(self) -> dict:
        return {
            "violation": self.violation,
            "events": [[label, list(arg)] for label, arg in self.events],
            "config": dict(self.config),
            "model": "sharedplan",
        }

    def fault_events(self) -> List[Tuple[str, Tuple]]:
        return [(label, arg) for label, arg in self.events
                if label == "sp.kill"]


class SPResult(NamedTuple):
    states: int
    transitions: int
    violations: List[SPTrace]
    exhaustive: bool

    @property
    def clean(self) -> bool:
        return not self.violations


V_LOSS = "tenant-position-behind-host-restore"
V_STALL = "detached-tenant-gates-barrier"
V_ORPHAN = "host-torn-down-under-tenant"
V_DEADLOCK = "sharedplan-deadlock"


def _initial(cfg: SPConfig) -> SPSys:
    n = cfg.tenants
    return SPSys(
        mounted=tuple(True for _ in range(n)),
        gate=tuple(True for _ in range(n)),
        cap=tuple(0 for _ in range(n)),
        pub=tuple(0 for _ in range(n)),
    )


def _set(t: Tuple, i: int, v) -> Tuple:
    lst = list(t)
    lst[i] = v
    return tuple(lst)


class SPModel:
    """Enabled-transition enumerator over host x tenant positions."""

    def __init__(self, cfg: SPConfig):
        self.cfg = cfg

    def done(self, s: SPSys) -> bool:
        # the host is refcount-released when the last tenant detaches;
        # a run where every tenant detached is terminal
        return not any(s.mounted)

    # -- enumeration ---------------------------------------------------------

    def enabled(self, s: SPSys) -> List[SPStep]:
        cfg = self.cfg
        out: List[SPStep] = []
        if s.host_alive and any(s.mounted):
            # host capture: freeze the next epoch's offset (one barrier
            # for everyone; at most one epoch in flight)
            if s.h_cap < cfg.epochs and s.h_cap == s.h_pub:
                out.append(SPStep(
                    "sp.host_capture", (s.h_cap + 1,),
                    s._replace(h_cap=s.h_cap + 1),
                ))
            # host publish: the PUBLICATION GATE — epoch h_cap becomes
            # the durable restore point only once every gate member has
            # durably published a position that covers its offset. The
            # leaked-barrier mutant skips the gate entirely.
            if s.h_pub < s.h_cap:
                gated = (cfg.mutant != "leaked_barrier_across_tenants"
                         and any(s.gate[t] and s.pub[t] < s.h_cap
                                 for t in range(cfg.tenants)))
                if not gated:
                    out.append(SPStep(
                        "sp.host_publish", (s.h_cap,),
                        s._replace(h_pub=s.h_cap),
                    ))
        for t in range(cfg.tenants):
            if not s.mounted[t]:
                continue
            # tenant capture: the mounted source checkpoints its
            # consumed offset (it can always catch up to the host's
            # captured epoch — the offset total order makes any capture
            # alignment safe)
            if s.cap[t] < s.h_cap:
                out.append(SPStep(
                    "sp.tenant_capture", (t, s.cap[t] + 1),
                    s._replace(cap=_set(s.cap, t, s.cap[t] + 1)),
                ))
            # tenant publish: the tenant's own manifest chain commits
            if s.pub[t] < s.cap[t]:
                out.append(SPStep(
                    "sp.tenant_publish", (t, s.cap[t]),
                    s._replace(pub=_set(s.pub, t, s.cap[t])),
                ))
            out.append(self._detach(s, t))
        if s.kills < cfg.kills and s.host_alive and any(s.mounted):
            out.append(self._kill(s))
        return out

    def _detach(self, s: SPSys, t: int) -> SPStep:
        cfg = self.cfg
        mounted = _set(s.mounted, t, False)
        # job-scoped detach: leave the gate with the mount — the
        # detach_leaves_gate mutant forgets the gate half, so a stopped
        # tenant keeps stalling the co-mounted ones
        gate = (s.gate if cfg.mutant == "detach_leaves_gate"
                else _set(s.gate, t, False))
        host_alive = s.host_alive and any(mounted)
        if cfg.mutant == "teardown_on_first_detach":
            # refcount ignored: the first stop tears the host down
            host_alive = False
        return SPStep(
            "sp.tenant_detach", (t,),
            s._replace(mounted=mounted, gate=gate, host_alive=host_alive),
        )

    def _kill(self, s: SPSys) -> SPStep:
        cfg = self.cfg
        # process kill + recovery: the host restores from its last
        # PUBLISHED epoch's offset; every tenant restores from its own
        # published position. A mounted tenant behind the host's restore
        # point has a gap the replay will never cover — per-tenant data
        # loss, the exact state the publication gate makes unreachable.
        for t in range(cfg.tenants):
            if s.mounted[t] and s.pub[t] < s.h_pub:
                return SPStep(
                    "sp.kill", (), None,
                    f"{V_LOSS}: tenant {t} restored at position "
                    f"{s.pub[t]} but the host replays from published "
                    f"epoch {s.h_pub} — rows in between are lost for "
                    f"this tenant (publication gate leaked)",
                )
        return SPStep(
            "sp.kill", (),
            s._replace(
                h_cap=s.h_pub,
                cap=tuple(s.pub[t] if s.mounted[t] else s.cap[t]
                          for t in range(cfg.tenants)),
                kills=s.kills + 1,
            ),
        )

    # -- invariants ----------------------------------------------------------

    def check_state(self, s: SPSys,
                    enabled: List[SPStep]) -> Optional[str]:
        # refcount independence: a mounted tenant must always have a
        # live host under it
        for t in range(self.cfg.tenants):
            if s.mounted[t] and not s.host_alive:
                return (f"{V_ORPHAN}: tenant {t} is still mounted but "
                        f"the shared host was torn down (refcounted "
                        f"release broken)")
        # detach independence: if publication is blocked and every
        # MOUNTED tenant has already published past the barrier, the
        # only thing holding the gate is a tenant that already detached
        # — one tenant's stop is stalling its co-tenants forever
        if s.host_alive and s.h_pub < s.h_cap:
            mounted_ready = all(
                s.pub[t] >= s.h_cap
                for t in range(self.cfg.tenants) if s.mounted[t]
            )
            stale = [t for t in range(self.cfg.tenants)
                     if s.gate[t] and not s.mounted[t]
                     and s.pub[t] < s.h_cap]
            if mounted_ready and stale:
                return (f"{V_STALL}: host epoch {s.h_cap} cannot "
                        f"publish — detached tenant(s) {stale} still "
                        f"hold the publication gate while every mounted "
                        f"tenant has already reconciled")
        if not self.done(s) and not enabled:
            return (f"{V_DEADLOCK}: host {s.h_cap}/{s.h_pub}, "
                    f"tenants cap={s.cap} pub={s.pub}")
        return None


def check_sharedplan(cfg: SPConfig, budget: int = 500_000) -> SPResult:
    """BFS the host x tenants product; violations carry replayable
    event paths."""
    model = SPModel(cfg)
    init = _initial(cfg)
    parent: Dict[SPSys, Optional[Tuple[SPSys, Tuple[str, Tuple]]]] = {
        init: None
    }
    frontier = deque([init])
    violations: List[SPTrace] = []
    seen_kinds: Set[str] = set()
    n_trans = 0
    exhaustive = True

    def record(state: SPSys, ev, violation: str):
        kind = violation.split(":", 1)[0]
        if kind in seen_kinds:
            return
        seen_kinds.add(kind)
        events: List[Tuple[str, Tuple]] = [ev] if ev else []
        cur = state
        while parent[cur] is not None:
            prev, e = parent[cur]
            events.append(e)
            cur = prev
        events.reverse()
        violations.append(SPTrace(violation, events, cfg._asdict()))

    while frontier:
        if len(parent) > budget:
            exhaustive = False
            break
        state = frontier.popleft()
        steps = model.enabled(state)
        inv = model.check_state(state, steps)
        if inv is not None:
            record(state, None, inv)
            continue
        if model.done(state):
            continue
        for st in steps:
            n_trans += 1
            if st.violation:
                record(state, (st.label, st.arg), st.violation)
                continue
            if st.nxt is None or st.nxt in parent:
                continue
            parent[st.nxt] = (state, (st.label, st.arg))
            frontier.append(st.nxt)

    return SPResult(states=len(parent), transitions=n_trans,
                    violations=violations, exhaustive=exhaustive)


# -- replay: deterministic re-execution + seeded chaos plan ------------------


class SPReplayDivergence(Exception):
    """The trace names an event the model does not offer at that state."""


def replay_sharedplan(trace: SPTrace) -> str:
    """Re-execute an SPTrace event-for-event on a fresh model built from
    its recorded config; return the violation label reached."""
    cfg = SPConfig(**{k: v for k, v in dict(trace.config).items()
                      if k in SPConfig._fields})
    model = SPModel(cfg)
    state = _initial(cfg)
    for i, (label, arg) in enumerate(trace.events):
        steps = model.enabled(state)
        match = [st for st in steps
                 if st.label == label and tuple(st.arg) == tuple(arg)]
        if not match:
            offered = sorted({(st.label, tuple(st.arg)) for st in steps})
            raise SPReplayDivergence(
                f"event {i} {label}{tuple(arg)}: not enabled; "
                f"offered {offered}"
            )
        st = match[0]
        if st.violation:
            return st.violation
        if st.nxt is None:
            raise SPReplayDivergence(
                f"event {i} {label}{tuple(arg)}: dead step without "
                f"violation"
            )
        state = st.nxt
    inv = model.check_state(state, model.enabled(state))
    if inv is not None:
        return inv
    raise SPReplayDivergence("trace replayed to a state with no violation")


def sp_trace_seed(trace: SPTrace) -> int:
    """Deterministic seed from the trace content (not object identity)."""
    payload = json.dumps(trace.to_json(), sort_keys=True).encode()
    return int.from_bytes(hashlib.sha1(payload).digest()[:4], "big")


def sp_trace_to_fault_plan(trace: SPTrace):
    """Serialize the counterexample's kill schedule as a seeded chaos
    FaultPlan: the model's process kill maps onto the worker.kill seam
    mid-checkpoint, which is exactly the window where a leaked
    publication gate would lose a tenant's rows end-to-end."""
    import random

    from ... import chaos

    seed = sp_trace_seed(trace)
    rng = random.Random(seed)
    plan = chaos.FaultPlan(seed)
    for _label, _arg in trace.fault_events():
        plan.add("worker.kill", at_hits=(rng.randint(8, 16),))
    return plan


def sp_counterexample_payload(trace: SPTrace) -> dict:
    """The artifact written next to a violation: trace + replayable
    chaos plan + the drill command that runs it (the shared-fleet drill,
    so the kill lands on a worker hosting the shared chain)."""
    plan = sp_trace_to_fault_plan(trace)
    return {
        "trace": trace.to_json(),
        "fault_plan": json.loads(plan.to_json()),
        "replay_command": (
            "python tools/chaos_drill.py --shared --plan <this-file> "
            "# runs the serialized fault_plan against a shared-mount "
            "embedded fleet"
        ),
    }


class SPMutant(NamedTuple):
    name: str
    description: str
    expect_violation: str
    config: SPConfig


SP_MUTANTS: Dict[str, SPMutant] = {
    m.name: m
    for m in [
        SPMutant(
            name="leaked_barrier_across_tenants",
            description=(
                "the host publishes a checkpoint epoch without waiting "
                "for every mounted tenant's durable position to cover "
                "it (publication gate skipped): a kill then restores "
                "the shared chain AHEAD of a tenant's manifest chain "
                "and that tenant's gap rows are never replayed"
            ),
            expect_violation=V_LOSS,
            config=SPConfig(mutant="leaked_barrier_across_tenants"),
        ),
        SPMutant(
            name="detach_leaves_gate",
            description=(
                "a tenant's detach removes the mount but not its "
                "publication-gate membership: the stopped tenant gates "
                "every later host epoch and co-mounted tenants stall "
                "forever (job-scoped detach broken)"
            ),
            expect_violation=V_STALL,
            config=SPConfig(mutant="detach_leaves_gate"),
        ),
        SPMutant(
            name="teardown_on_first_detach",
            description=(
                "the mount refcount is ignored and the first tenant's "
                "stop tears down the shared host under the remaining "
                "mounted tenants"
            ),
            expect_violation=V_ORPHAN,
            config=SPConfig(mutant="teardown_on_first_detach"),
        ),
    ]
}
