"""Jitted merge-join probe: the device path for bin-local equi-joins.

TPU-native replacement for the reference's in-engine join probe
(/root/reference/crates/arroyo-worker/src/arrow/instant_join.rs:1-412,
join_with_expiration.rs:1-264): instead of a host hash join, the probe
runs as XLA programs — per-row key hashing (splitmix64 over the int64
key words), a device sort of the build side, a searchsorted range probe,
and vectorized pair expansion into a padded output bucket. Hash-equal
candidate pairs are verified against the full key words host-side, so
the join is exact even under 64-bit hash collisions (a collision only
costs spurious candidates, never wrong results).

Dynamic output size meets XLA's static-shape rule in two phases:
phase 1 computes per-probe-row match counts and their prefix sums on
device; only the scalar total crosses to host to pick a padded output
bucket; phase 2 expands the pair indices at that bucket size. All
arrays are padded to power-of-two buckets, so the compiled program
count stays O(log sizes) per key width.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ._jax import get_jax as _get_jax

_fns = None


def _build_fns():
    """Compile-cached device functions (jit caches per input shape)."""
    global _fns
    if _fns is not None:
        return _fns
    jax = _get_jax()
    jnp = jax.numpy

    U = jnp.uint64

    def mix(x):
        x = x + U(0x9E3779B97F4A7C15)
        x = (x ^ (x >> U(30))) * U(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> U(27))) * U(0x94D049BB133111EB)
        return x ^ (x >> U(31))

    def hash_rows(mat):
        h = jnp.zeros(mat.shape[0], dtype=jnp.uint64)
        for j in range(mat.shape[1]):
            h = mix(h ^ mat[:, j].astype(jnp.uint64))
        return h

    @jax.jit
    def phase1(l_mat, r_mat, n_l, n_r):
        """Sort the build side by hash, range-probe it with the probe
        side. Returns (order, lo, offs): build-side sort order, first
        candidate position per probe row, inclusive prefix sums of the
        candidate counts (offs[-1] = total candidate pairs)."""
        hl = hash_rows(l_mat)
        hr = hash_rows(r_mat)
        # padded build rows sort to the end under the max sentinel; a
        # real hash equal to the sentinel only adds candidates that the
        # host-side exact-key verification drops
        hr = jnp.where(
            jnp.arange(r_mat.shape[0]) < n_r, hr, U(0xFFFFFFFFFFFFFFFF)
        )
        order = jnp.argsort(hr)
        hrs = hr[order]
        lo = jnp.searchsorted(hrs, hl, side="left")
        hi = jnp.searchsorted(hrs, hl, side="right")
        counts = jnp.where(
            jnp.arange(l_mat.shape[0]) < n_l, hi - lo, 0
        )
        offs = jnp.cumsum(counts)
        return order, lo, offs

    # phase 2 expands candidate ranges into (probe_idx, build_idx) pairs
    # over a fixed-size output grid; slots past the total are invalid.
    # The output size is a shape, so it must be static: a size-keyed
    # cache of jitted closures instead of a traced argument
    phase2_cache = {}

    def phase2_at(size, order, lo, offs):
        fn = phase2_cache.get(size)
        if fn is None:
            def impl(order, lo, offs, _size=size):
                pos = jnp.arange(_size)
                li = jnp.searchsorted(offs, pos, side="right")
                li_c = jnp.clip(li, 0, offs.shape[0] - 1)
                start = jnp.where(li_c > 0, offs[li_c - 1], 0)
                rpos = lo[li_c] + (pos - start)
                ri = order[jnp.clip(rpos, 0, order.shape[0] - 1)]
                valid = pos < offs[-1]
                return li_c, ri, valid

            from ..obs import device as obs_device

            fn = obs_device.InstrumentedJit(
                "join.phase2", jax.jit(impl)
            )
            phase2_cache[size] = fn
        return fn(order, lo, offs, rung=size)

    from ..obs import device as obs_device

    _fns = (obs_device.InstrumentedJit("join.phase1", phase1), phase2_at)
    return _fns


def _bucket(n: int, lo: int = 1024) -> int:
    b = lo
    while b < n:
        b <<= 1
    return b


def _pad_matrix(cols: List[np.ndarray], bucket: int) -> np.ndarray:
    mat = np.zeros((bucket, len(cols)), dtype=np.int64)
    n = len(cols[0])
    for j, c in enumerate(cols):
        mat[:n, j] = c
    return mat


def probe(
    lcols: List[np.ndarray], rcols: List[np.ndarray]
) -> Tuple[np.ndarray, np.ndarray]:
    """Exact inner-join pair indices for int64 key columns.

    Returns (l_idx, r_idx): row indices into the probe/build sides such
    that the full key tuples are equal, in probe-side order."""
    n_l, n_r = len(lcols[0]), len(rcols[0])
    if n_l == 0 or n_r == 0:
        e = np.empty(0, dtype=np.int64)
        return e, e
    phase1, phase2_at = _build_fns()
    from ..obs import device as obs_device

    lb, rb = _bucket(n_l), _bucket(n_r)
    obs_device.note_padding("join.phase1", rb, n_l + n_r, lb + rb)
    l_mat = _pad_matrix(lcols, lb)
    r_mat = _pad_matrix(rcols, rb)
    order, lo, offs = phase1(
        l_mat, r_mat, np.int64(n_l), np.int64(n_r), rung=rb
    )
    total = int(offs[-1])
    if total == 0:
        e = np.empty(0, dtype=np.int64)
        return e, e
    li, ri, valid = phase2_at(_bucket(total), order, lo, offs)
    li = np.asarray(li)
    ri = np.asarray(ri)
    mask = np.asarray(valid) & (li < n_l) & (ri < n_r)
    li = li[mask]
    ri = ri[mask]
    # exact verification of hash-equal candidates on the real key words
    keep = np.ones(len(li), dtype=bool)
    for lc, rc in zip(lcols, rcols):
        keep &= lc[li] == rc[ri]
    return li[keep], ri[keep]


def available() -> bool:
    """Device probe usable in this process (jax importable)?"""
    try:
        _get_jax()
        return True
    except Exception:  # noqa: BLE001 - host-only deployment
        return False


def _codable(t) -> bool:
    import pyarrow as pa

    return (
        pa.types.is_integer(t)
        or pa.types.is_timestamp(t)
        or pa.types.is_boolean(t)
        or pa.types.is_string(t)
        or pa.types.is_large_string(t)
        or pa.types.is_binary(t)
    )


def prepare_join_keys(
    left, right, key_names: List[str]
) -> Optional[Tuple[List[np.ndarray], List[np.ndarray],
                    Optional[np.ndarray], Optional[np.ndarray]]]:
    """Two-sided key preparation for the device probe.

    Returns (lcols, rcols, lsel, rsel) — int64 key word columns per side
    plus the original-row indices they correspond to (None = identity),
    or None when some key type can't ride the probe.

    * String/binary keys are dictionary-encoded against a JOINT
      dictionary (both sides concatenated) so equal strings get equal
      int64 codes — the probe then stays exact, no hashing of values.
    * Nullable keys: SQL equi-joins never match on NULL, so rows with
      any null key word are pre-filtered and the selection mapping is
      returned for the caller to translate pair indices back.
    """
    import pyarrow as pa

    n_l, n_r = left.num_rows, right.num_rows
    lcols: List[np.ndarray] = []
    rcols: List[np.ndarray] = []
    l_valid = np.ones(n_l, dtype=bool)
    r_valid = np.ones(n_r, dtype=bool)
    any_null = False
    for name in key_names:
        lc = left.column(name).combine_chunks()
        rc = right.column(name).combine_chunks()
        if not (_codable(lc.type) and _codable(rc.type)):
            return None
        if lc.null_count or rc.null_count:
            any_null = True
            lm = np.asarray(lc.is_valid())
            rm = np.asarray(rc.is_valid())
            l_valid &= lm
            r_valid &= rm
        if pa.types.is_string(lc.type) or pa.types.is_large_string(
            lc.type
        ) or pa.types.is_binary(lc.type):
            # joint dictionary: codes are comparable across sides.
            # large_binary, not large_string: binary keys may hold
            # non-UTF8 bytes a string cast would reject
            both = pa.chunked_array([lc.cast(pa.large_binary()),
                                     rc.cast(pa.large_binary())])
            codes = both.combine_chunks().dictionary_encode().indices
            c = np.asarray(codes.fill_null(-1).cast(pa.int64()))
            lcols.append(c[:n_l])
            rcols.append(c[n_l:])
        else:
            lcols.append(
                np.asarray(lc.fill_null(0).cast(pa.int64(), safe=False))
            )
            rcols.append(
                np.asarray(rc.fill_null(0).cast(pa.int64(), safe=False))
            )
    if not any_null:
        return lcols, rcols, None, None
    lsel = np.nonzero(l_valid)[0]
    rsel = np.nonzero(r_valid)[0]
    return (
        [c[lsel] for c in lcols],
        [c[rsel] for c in rcols],
        lsel,
        rsel,
    )
