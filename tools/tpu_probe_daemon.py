#!/usr/bin/env python
"""TPU relay grant-capture daemon.

The axon relay that fronts the single real TPU chip is intermittently
wedged: most `jax.devices()` calls hang forever inside the PJRT claim
path, but occasionally a grant lands (round 2: exactly once, 13:49 UTC;
round 3: zero grants across ~11 probes). Round-2 evidence shows the
fatal pattern: the probe that captured the grant exited, and the *next*
process (the bench) wedged re-claiming.

Therefore this daemon's probe child converts a grant into benchmark
numbers AND device-backend golden verdicts IN-PROCESS, while it still
holds the claim:

  parent loop (this file, no jax import):
    spawn child --probe
      child: watchdog thread hard-exits (os._exit) if jax.devices()
             hasn't returned within PROBE_GRACE seconds
      child: on grant, prints GRANTED, runs the nexmark device benches
             (q5/q1/q7/q8) via bench.child(), then a device-backend
             golden subset (correctness evidence on the real chip).
    parent: 150 s deadline to see GRANTED, else kill -> log "wedged";
            after GRANTED, generous deadline for compiles through the
            relay (~20-40 s per XLA program).
    on success, fully automatic publication — no human involvement:
      1. TPU_GRANT.json (incl. git_commit of HEAD at capture so the
         round-end bench can refuse a stale substitution),
      2. a like-for-like CPU baseline re-measured at the grant's event
         count (subprocess pinned to JAX_PLATFORMS=cpu — never touches
         the relay),
      3. BENCH_r{N}.json with the real vs_baseline,
      4. a "TPU grant capture" section appended to BASELINE.md.
    sleep ~15 min (+/- jitter), repeat for the whole round; after a
    capture keep probing hourly and RE-capture (HEAD moves as the round
    progresses; a fresh capture re-binds the numbers to current code).

Run:  python tools/tpu_probe_daemon.py            # daemon
      python tools/tpu_probe_daemon.py --probe    # one probe child
      python tools/tpu_probe_daemon.py --once     # single parent cycle

Log:  tools/tpu_probe.log   (one line per probe: ts outcome detail)
Out:  TPU_GRANT.json + BENCH_r{N}.json + BASELINE.md appendix on first
      successful device bench.
"""

import json
import glob
import os
import random
import re
import signal
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "tools", "tpu_probe.log")
GRANT_JSON = os.path.join(REPO, "TPU_GRANT.json")
PROBE_GRACE = 100.0     # child self-kill if no grant within this
PARENT_PROBE_DEADLINE = 150.0   # parent kills child if no GRANTED line
BENCH_DEADLINE = 3600.0         # after GRANTED: compiles are slow
SLEEP_BASE = 900.0              # 15 min between probes while wedged
SLEEP_AFTER_GRANT = 3600.0      # once numbers exist, probe hourly
MAX_RUNTIME = 11.5 * 3600
CPU_BASELINE_TIMEOUT = 600.0

# (query, events) — q5 is the headline; sizes keep post-compile runtime
# in seconds while being large enough for a credible rate.
BENCH_PLAN = [("q5", 500_000), ("q1", 200_000), ("q7", 200_000),
              ("q8", 200_000), ("qu", 200_000)]

# Golden queries to re-verify on the device backend while holding the
# grant. Small on purpose: each distinct XLA program compiles through
# the relay at ~20-40 s. These four cover hop/sliding/tumbling windows,
# a windowed join (device probe forced on via device_join_min_rows=0),
# and retracting updating aggregates. session_window is deliberately
# absent: SessionWindowOperator forces the numpy backend on a single
# device, so its "device" verdict would attest the CPU path.
GOLDEN_PLAN = ["nexmark_q5", "sliding_window_end", "windowed_inner_join",
               "updating_aggregate"]


def log_line(msg: str) -> None:
    ts = time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime())
    line = f"{ts} {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def git_head() -> str:
    """HEAD sha, with a '-dirty' suffix when the working tree has
    uncommitted changes: a capture of never-committed code must not pass
    the round-end strict provenance gate (bench.py compares this value
    to a clean `git rev-parse HEAD`, so '-dirty' can never match —
    conservative and honest)."""
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=REPO, capture_output=True,
            text=True, timeout=10).stdout.strip()
        dirty = subprocess.run(
            ["git", "status", "--porcelain"], cwd=REPO,
            capture_output=True, text=True, timeout=10).stdout.strip()
        return f"{sha}-dirty" if dirty else sha
    except Exception:
        return "unknown"


def next_bench_round() -> int:
    """Round number to publish under. Normally max(existing)+1, but when
    the newest BENCH_r{N}.json is this daemon's OWN earlier capture
    (device_source marks it), reuse N — so a daemon restart mid-round
    keeps overwriting the same file instead of fabricating the next
    round's artifact."""
    rounds = {}
    for p in glob.glob(os.path.join(REPO, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        if m:
            rounds[int(m.group(1))] = p
    if not rounds:
        return 1
    mx = max(rounds)
    try:
        with open(rounds[mx]) as f:
            if "probe_daemon_capture" in json.load(f).get(
                    "device_source", ""):
                return mx
    except (OSError, json.JSONDecodeError):
        pass
    return mx + 1


# Bound once at daemon start so re-captures later in the round overwrite
# the SAME BENCH_r{N}.json instead of claiming the next round's name.
ROUND = next_bench_round()


def run_device_goldens() -> None:
    """Run GOLDEN_PLAN queries with the jax backend on the held device,
    comparing against the committed golden outputs. Prints one
    'GOLDEN <name> PASS|FAIL <detail>' line each. Runs inside the probe
    child (which already holds the claim)."""
    import asyncio
    import tempfile

    sys.path.insert(0, REPO)
    sys.path.insert(0, os.path.join(REPO, "tests"))
    from arroyo_tpu.config import config
    from arroyo_tpu.engine import Engine
    from arroyo_tpu.sql import plan_query
    import test_golden as tg

    import bench

    config().tpu.enabled = True
    config().tpu.shape_buckets = (8192, 65536)
    # golden fixtures are small (hundreds of rows): drop the row floor so
    # the windowed-join golden actually exercises the device join probe
    config().tpu.device_join_min_rows = 0
    def run_one(name: str, label: str):
        qpath = os.path.join(tg.GOLDEN, "queries", f"{name}.sql")
        gpath = os.path.join(tg.GOLDEN, "golden_outputs", f"{name}.json")
        try:
            with tempfile.TemporaryDirectory() as td:
                out = os.path.join(td, "out.json")
                sql = tg.load_query(qpath, out)
                plan = plan_query(sql, parallelism=2)
                bench.force_backend(plan, "jax")

                async def go():
                    eng = Engine(plan.graph).start()
                    await eng.join(300)

                asyncio.run(go())
                got = tg.canonicalize_output(out, sql)
                want = [ln.strip() for ln in open(gpath)]
                if got == want:
                    print(f"GOLDEN {label} PASS rows={len(got)}",
                          flush=True)
                else:
                    print(f"GOLDEN {label} FAIL got={len(got)} "
                          f"want={len(want)}", flush=True)
        except BaseException as e:
            print(f"GOLDEN {label} FAIL {type(e).__name__}: {e}",
                  flush=True)

    for name in GOLDEN_PLAN:
        run_one(name, name)
    # one more pass attesting the device-resident slot directory
    # (tpu.device_directory prototype) on the real chip. The verdict is
    # only meaningful if the directory actually engaged — the swap has
    # its own gates (_device_ok, accelerator, key widths), so count
    # instantiations and fail the attestation when none happened.
    import arroyo_tpu.ops.device_directory as dd

    engaged = {"n": 0}
    orig_init = dd.DeviceSlotDirectory.__init__

    def _spy(self, *a, **k):
        engaged["n"] += 1
        return orig_init(self, *a, **k)

    config().tpu.device_directory = True
    dd.DeviceSlotDirectory.__init__ = _spy
    try:
        run_one("nexmark_q5", "nexmark_q5_device_dir")
    finally:
        dd.DeviceSlotDirectory.__init__ = orig_init
        config().tpu.device_directory = False
    if engaged["n"] == 0:
        print("GOLDEN nexmark_q5_device_dir FAIL "
              "device directory never engaged", flush=True)


def probe_child() -> None:
    """Claim the device; on grant run benches + goldens while holding it."""
    granted = threading.Event()

    def watchdog():
        if not granted.wait(PROBE_GRACE):
            # jax.devices() is stuck in C inside the axon claim path —
            # no exception can unwind it; hard-exit so the parent sees a
            # clean death instead of a zombie holding half a claim.
            print("WEDGED probe watchdog fired", flush=True)
            os._exit(3)

    threading.Thread(target=watchdog, daemon=True).start()
    t0 = time.monotonic()
    import jax  # noqa: deferred heavy import
    devs = jax.devices()
    granted.set()
    kinds = ",".join(sorted({d.platform for d in devs}))
    if not any(d.platform == "tpu" for d in devs):
        print(f"NOTTPU {kinds}", flush=True)
        os._exit(4)
    print(f"GRANTED {kinds} in {time.monotonic() - t0:.1f}s", flush=True)

    sys.path.insert(0, REPO)
    import bench
    for query, events in BENCH_PLAN:
        print(f"BENCHQ {query} {events}", flush=True)
        try:
            bench.child(events, "jax", query)   # prints RESULT eps rows dt
        except BaseException as e:  # keep going; later queries may pass
            print(f"BENCHFAIL {query} {type(e).__name__}: {e}", flush=True)
    try:
        run_device_goldens()
    except BaseException as e:
        print(f"GOLDENSUITEFAIL {type(e).__name__}: {e}", flush=True)
    # per-batch slot-assignment cost on the real chip (python host dict
    # vs native C++ vs the device-resident sorted hash table); each tier
    # fails independently — the device number is the one this bench
    # exists to collect and a host-tier error must not skip it
    sys.path.insert(0, os.path.join(REPO, "tools"))
    for kind in ("python", "native", "device"):
        try:
            import assign_bench
            r = assign_bench.bench(kind, rows=8192, keys=20000, iters=40)
            if r is not None:
                print(f"ASSIGNBENCH {kind} {r[0]:.0f}us/batch "
                      f"{r[1] / 1e6:.2f}Mrows/s", flush=True)
        except BaseException as e:
            print(f"ASSIGNBENCHFAIL {kind} {type(e).__name__}: {e}",
                  flush=True)
    print("DONE", flush=True)
    os._exit(0)


def publish_capture(results: dict, goldens: dict, commit: str) -> None:
    """Fully automatic publication of a captured grant: TPU_GRANT.json,
    CPU baseline re-measure, BENCH_r{N}.json, BASELINE.md appendix."""
    payload = {
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "git_commit": commit,
        "source": "tools/tpu_probe_daemon.py in-process capture",
        "events": dict(BENCH_PLAN),
        **{f"{q}_eps": round(r["eps"], 1) for q, r in results.items()},
        "q5_rows": results["q5"]["rows"],
        "goldens": goldens,
    }
    tmp = GRANT_JSON + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1)
    os.replace(tmp, GRANT_JSON)  # atomic: bench.py may read anytime
    log_line(f"GRANT CAPTURED -> TPU_GRANT.json {payload}")

    # like-for-like CPU baseline at the grant's q5 event count; pinned
    # to the CPU platform so it can never touch (or wedge on) the relay
    cpu_env = dict(os.environ)
    cpu_env["JAX_PLATFORMS"] = "cpu"
    for var in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
                "AXON_POOL_SVC_OVERRIDE", "AXON_LOOPBACK_RELAY"):
        cpu_env.pop(var, None)
    g_events = dict(BENCH_PLAN)["q5"]
    sys.path.insert(0, REPO)
    import bench
    baseline = bench.run_child(g_events, "numpy", CPU_BASELINE_TIMEOUT,
                               env=cpu_env)
    if baseline is None:
        log_line("capture: CPU baseline re-measure failed; "
                 "BENCH json will carry vs_baseline=null")

    rnd = ROUND
    bench_json = {
        "metric": "nexmark_q5_events_per_sec",
        "value": payload["q5_eps"],
        "unit": "events/s",
        "vs_baseline": round(payload["q5_eps"] / baseline["eps"], 3)
        if baseline else None,
        "baseline_cpu_eps": round(baseline["eps"], 1) if baseline else None,
        "events": g_events,
        "result_rows": payload["q5_rows"],
        "side_backend": "jax",
        **{f"{q}_eps": payload[f"{q}_eps"]
           for q in ("q1", "q7", "q8", "qu") if f"{q}_eps" in payload},
        "device_source": f"probe_daemon_capture@{payload['captured_at']}",
        "git_commit": commit,
        "goldens": goldens,
    }
    bp = os.path.join(REPO, f"BENCH_r{rnd:02d}.json")
    with open(bp, "w") as f:
        json.dump(bench_json, f, indent=1)
    log_line(f"capture: wrote {os.path.basename(bp)} "
             f"vs_baseline={bench_json['vs_baseline']}")

    gsum = ", ".join(f"{k}={v}" for k, v in sorted(goldens.items())) or "none"
    lines = [
        "",
        f"## TPU grant capture ({payload['captured_at']}, "
        f"commit {commit[:12]})",
        "",
        "Captured automatically by `tools/tpu_probe_daemon.py` while the",
        "probe child held the device claim (relay grants do not survive",
        "process exit — see round-2 evidence).",
        "",
        f"| query | device ev/s | events |",
        f"|---|---|---|",
    ]
    ev = dict(BENCH_PLAN)
    for q in ("q5", "q1", "q7", "q8", "qu"):
        if f"{q}_eps" in payload:
            lines.append(f"| {q} | {payload[f'{q}_eps']:,} | {ev[q]:,} |")
    if baseline:
        lines += ["",
                  f"CPU baseline (same commit, {g_events:,} events): "
                  f"q5 {baseline['eps']:,.1f} ev/s → "
                  f"**vs_baseline {bench_json['vs_baseline']}**."]
    lines += ["", f"Device-backend goldens: {gsum}.", ""]
    with open(os.path.join(REPO, "BASELINE.md"), "a") as f:
        f.write("\n".join(lines))
    log_line("capture: appended section to BASELINE.md")


def run_one_probe() -> bool:
    """One parent cycle. Returns True if a grant produced numbers."""
    import queue

    cmd = [sys.executable, os.path.abspath(__file__), "--probe"]
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True,
                            stderr=subprocess.STDOUT, cwd=REPO)
    q: "queue.Queue" = queue.Queue()

    def reader():
        for ln in proc.stdout:
            q.put(ln)
        q.put(None)  # EOF

    threading.Thread(target=reader, daemon=True).start()
    deadline = time.monotonic() + PARENT_PROBE_DEADLINE
    granted = False
    results = {}
    goldens = {}
    cur_q = None
    lines = []
    commit = git_head()
    try:
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError
            try:
                line = q.get(timeout=min(remaining, 5.0))
            except queue.Empty:
                continue
            if line is None:
                # child exited; if it never printed a recognized marker
                # (e.g. import jax blew up), still leave a trail
                if not granted and not any(
                        ln.startswith(("WEDGED", "NOTTPU")) for ln in lines):
                    tail = "; ".join(lines[-3:]) or "<no output>"
                    log_line(f"probe exited rc={proc.poll()} "
                             f"without grant; tail=[{tail}]")
                break
            line = line.strip()
            if not line:
                continue
            lines.append(line)
            if line.startswith("GRANTED"):
                granted = True
                deadline = time.monotonic() + BENCH_DEADLINE
                log_line(f"probe GRANTED ({line})")
            elif line.startswith("BENCHQ"):
                cur_q = line.split()[1]
            elif line.startswith("RESULT") and cur_q:
                parts = line.split()
                results[cur_q] = {"eps": float(parts[1]),
                                  "rows": int(parts[2]),
                                  "secs": float(parts[3])}
            elif line.startswith("GOLDEN "):
                parts = line.split()
                goldens[parts[1]] = parts[2]
                log_line(f"probe: {line}")
            elif line.startswith("ASSIGNBENCH"):
                log_line(f"probe: {line}")
            elif line.startswith(("WEDGED", "NOTTPU", "BENCHFAIL",
                                  "GOLDENSUITEFAIL")):
                log_line(f"probe: {line}")
            elif line.startswith("DONE"):
                break
    except TimeoutError:
        _kill(proc)
        tail = "; ".join(lines[-3:])
        if granted:
            log_line(f"probe granted but bench DEADLINED; partial={list(results)} tail=[{tail}]")
        else:
            log_line("probe wedged (no grant within "
                     f"{PARENT_PROBE_DEADLINE:.0f}s)")
    finally:
        _kill(proc)

    if granted and "q5" in results:
        try:
            publish_capture(results, goldens, commit)
        except Exception as e:
            log_line(f"capture publication error {type(e).__name__}: {e}")
        return True
    if granted and results:
        log_line(f"grant produced partial results (no q5): {results}")
    return False


def _kill(proc):
    if proc.poll() is None:
        try:
            proc.send_signal(signal.SIGKILL)
            proc.wait(10)
        except Exception:
            pass


def main():
    if "--probe" in sys.argv:
        probe_child()
        return
    once = "--once" in sys.argv
    start = time.monotonic()
    log_line(f"daemon start pid={os.getpid()} commit={git_head()[:12]} "
             f"publishing BENCH_r{ROUND:02d}")
    have_grant = os.path.exists(GRANT_JSON)
    while True:
        try:
            got = run_one_probe()
            have_grant = have_grant or got
        except Exception as e:
            log_line(f"daemon cycle error {type(e).__name__}: {e}")
        if once:
            break
        if time.monotonic() - start > MAX_RUNTIME:
            log_line("daemon max runtime reached; exiting")
            break
        base = SLEEP_AFTER_GRANT if have_grant else SLEEP_BASE
        time.sleep(base + random.uniform(-60, 60))


if __name__ == "__main__":
    main()
