"""Segment-purity rule (fused segment runtime, engine/segments.py).

An operator class registered as fusable (`fusable = True`) may be fused
into a segment run that executes with ONE dispatch per batch and NO
per-operator checkpoint participation: the runner captures no state for
it and the segment drains, not snapshots, at barriers. A fusable
operator that quietly grows state (self._state...), reaches for the
state tables (ctx.table_manager / ctx.table(...)) or overrides the
checkpoint hooks would silently lose that state across recovery — its
writes would never ride a barrier. JAX004 makes that a lint failure
instead of a chaos-drill surprise.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from .core import FileContext, Finding, Rule, dotted_name, register

# hooks a stateless (fusable) operator must not implement: each one only
# exists to participate in checkpoint/2PC state capture
_FORBIDDEN_METHODS = {"handle_checkpoint", "handle_commit", "tables"}


def _is_fusable_class(cls: ast.ClassDef) -> bool:
    for stmt in cls.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = [t.id for t in stmt.targets if isinstance(t, ast.Name)]
        elif isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name):
            targets = [stmt.target.id]
        else:
            continue
        if "fusable" in targets and isinstance(stmt.value, ast.Constant) \
                and stmt.value.value is True:
            return True
    return False


@register
class SegmentPurityRule(Rule):
    id = "JAX004"
    name = "segment-purity"
    description = (
        "an operator class registered as fusable (`fusable = True`) must "
        "stay stateless: no self._state* attributes, no "
        "ctx.table_manager / ctx.table(...) access, and no "
        "handle_checkpoint/handle_commit/tables overrides — a fused "
        "segment executes as one dispatch and takes no per-operator "
        "state capture at barriers, so hidden state would silently skip "
        "every checkpoint"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            if not _is_fusable_class(node):
                continue
            self._check_class(ctx, node, out)
        return out

    def _check_class(self, ctx: FileContext, cls: ast.ClassDef,
                     out: List[Finding]) -> None:
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and stmt.name in _FORBIDDEN_METHODS:
                out.append(ctx.finding(
                    self, stmt,
                    f"fusable operator {cls.name} overrides {stmt.name}() — "
                    "checkpoint-hook state never survives inside a fused "
                    "segment",
                ))
        for node in ast.walk(cls):
            if isinstance(node, ast.Attribute):
                if node.attr.startswith("_state") or node.attr == "state":
                    if isinstance(node.value, ast.Name) \
                            and node.value.id == "self":
                        out.append(ctx.finding(
                            self, node,
                            f"fusable operator {cls.name} touches "
                            f"self.{node.attr} — hidden operator state "
                            "skips every barrier once fused",
                        ))
                elif node.attr == "table_manager":
                    out.append(ctx.finding(
                        self, node,
                        f"fusable operator {cls.name} reaches for "
                        ".table_manager — fused segments take no state "
                        "capture",
                    ))
            elif isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name in ("ctx.table", "context.table"):
                    out.append(ctx.finding(
                        self, node,
                        f"fusable operator {cls.name} opens a state table "
                        "via ctx.table() — fused segments take no state "
                        "capture",
                    ))
