"""Async UDF operator: out-of-band async user function execution.

Capability parity with the reference's async_udf.rs
(/root/reference/crates/arroyo-worker/src/arrow/async_udf.rs): rows fan out
to concurrent invocations of an async UDF with a bounded in-flight window
and a timeout; `ordered` mode re-emits rows in input order, `unordered`
emits as completions arrive. In-flight work drains at watermark/checkpoint
boundaries so exactly-once state stays simple (the reference persists
in-flight batches instead; drain-on-barrier trades a latency bubble for a
much smaller state surface — noted gap).
"""

from __future__ import annotations

import asyncio
from typing import List, Optional

import pyarrow as pa

from ..engine.construct import register_operator
from ..graph.logical import OperatorName
from ..schema import StreamSchema
from .base import Operator


class AsyncUdfOperator(Operator):
    def __init__(self, config: dict):
        super().__init__("async_udf")
        self.udf_name: str = config["udf"]
        self.arg_cols: List[int] = list(config["arg_cols"])
        self.out_field: str = config["out_field"]
        self.out_schema: StreamSchema = config["schema"]
        self.ordered: bool = config.get("ordered", True)
        self.max_concurrency: int = int(config.get("max_concurrency", 64))
        self.timeout: float = float(config.get("timeout", 10.0))
        self._sem: Optional[asyncio.Semaphore] = None
        self._fn = None

    async def on_start(self, ctx):
        from ..udf.registry import get

        udf = get(self.udf_name)
        if udf is None or not udf.is_async:
            raise ValueError(f"{self.udf_name} is not a registered async UDF")
        self._fn = udf.fn
        self._sem = asyncio.Semaphore(self.max_concurrency)

    async def _invoke(self, args):
        async with self._sem:
            return await asyncio.wait_for(self._fn(*args), self.timeout)

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        cols = [
            batch.column(i).to_pylist() for i in self.arg_cols
        ]
        if cols:
            arg_rows = zip(*cols)
        else:
            arg_rows = (() for _ in range(batch.num_rows))
        tasks = [
            asyncio.ensure_future(self._invoke(args)) for args in arg_rows
        ]
        try:
            if self.ordered:
                results = await asyncio.gather(*tasks)
                await self._emit(batch, list(range(batch.num_rows)), results,
                                 collector)
            else:
                # emit completion micro-batches as they arrive
                pending = {t: i for i, t in enumerate(tasks)}
                while pending:
                    done, _ = await asyncio.wait(
                        pending.keys(), return_when=asyncio.FIRST_COMPLETED
                    )
                    idxs = [pending.pop(t) for t in done]
                    await self._emit(
                        batch, idxs, [t.result() for t in done], collector
                    )
        except BaseException:
            # one failed/timed-out call fails the task; reap its siblings
            # so nothing runs detached past the operator
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            raise

    async def _emit(self, batch, row_idxs, results, collector):
        if not row_idxs:
            return
        sel = batch.take(pa.array(row_idxs))
        arrays = []
        for f in self.out_schema.schema:
            if f.name == self.out_field:
                arrays.append(pa.array(results, type=f.type))
            else:
                arrays.append(sel.column(sel.schema.names.index(f.name)))
        await collector.collect(
            pa.RecordBatch.from_arrays(arrays, schema=self.out_schema.schema)
        )


@register_operator(OperatorName.ASYNC_UDF)
def _make_async_udf(config: dict) -> Operator:
    return AsyncUdfOperator(config)
