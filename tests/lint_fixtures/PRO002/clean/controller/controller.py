"""Must NOT fire PRO002: only declared transitions, no direct sets."""
from .state_machine import JobState, TRANSITIONS  # noqa: F401


class Job:
    def __init__(self):
        self.state = JobState.CREATED

    def transition(self, nxt):
        self.state = nxt


def drive(job):
    job.transition(JobState.RUNNING)
    job.transition(JobState.STOPPED)
