"""Mutation harness: reintroduce three historical interleaving bugs and
prove the race tooling catches each one (ISSUE 18 acceptance).

1. PR 9's stop-without-durable-state hole — the controller's stop-path
   restore wrote a pre-await snapshot of `job.stop_requested` back after
   checkpoint awaits, destroying any stop mode requested meanwhile. The
   mutant reverts today's revalidating or-restore in a copy of the REAL
   controller.py; RACE002 must fire on the mutant and stay quiet on the
   unmutated file.

2. PR 10's pre-stampede heartbeat path — a heartbeat restore wrote a
   stale timestamp over fresher liveness evidence. Replayed as a live
   two-task scenario under the dynamic sanitizer: the stale restore must
   flag a lost-update, the monotonic max-merge (today's idiom at
   controller._heartbeat/_worker_call) must run clean.

3. An injected await-spanning read-modify-write in a copy of the REAL
   operators/runner.py (`hwm = self._flush_hwm; await ...;
   self._flush_hwm = hwm + 1`); RACE002 must fire on the mutant and stay
   quiet on the unmutated file.

Static mutants lint a single-file copy of the real source, so these
tests also pin that the production files are RACE002-clean standalone.
"""

import asyncio
from pathlib import Path

import pytest

from arroyo_tpu.analysis import get_rule, run_lint
from arroyo_tpu.analysis.races import sanitizer, shared_state

REPO = Path(__file__).resolve().parents[1]

STOP_RESTORE_FIXED = "job.stop_requested = job.stop_requested or mode"
STOP_RESTORE_BUGGY = "job.stop_requested = mode"

FLUSH_ANCHOR = 'set_task_root(f"flush:{self.task_info.task_id}")'
FLUSH_RMW = (
    FLUSH_ANCHOR
    + "\n        hwm = self._flush_hwm"
    + "\n        await asyncio.sleep(0)"
    + "\n        self._flush_hwm = hwm + 1"
)


def _race002(tmp_path: Path, source: str):
    (tmp_path / "mod.py").write_text(source)
    res = run_lint(tmp_path, rules=[get_rule("RACE002")], roots=(".",))
    assert not res.errors, res.errors
    return res.findings


# -- mutant 1: PR 9 stop-restore clobber (static catch) ----------------------


def test_stop_restore_revert_caught_by_race002(tmp_path):
    src = (REPO / "arroyo_tpu" / "controller" / "controller.py").read_text()
    assert src.count(STOP_RESTORE_FIXED) == 3, (
        "stop-restore or-idiom sites moved; update this mutant"
    )
    assert not _race002(tmp_path, src), (
        "real controller.py is not RACE002-clean standalone"
    )
    mutant = src.replace(STOP_RESTORE_FIXED, STOP_RESTORE_BUGGY)
    findings = _race002(tmp_path, mutant)
    assert len(findings) >= 3, findings
    assert all("stop_requested" in f.message for f in findings)


# -- mutant 2: PR 10 stale heartbeat restore (dynamic catch) -----------------


@shared_state("last_heartbeat", multi_writer=("last_heartbeat",))
class _Worker:
    def __init__(self):
        self.last_heartbeat = 0.0


def _heartbeat_scenario(restore):
    """Drive root snapshots the heartbeat, the RPC root refreshes it
    during the drive root's await, then `restore` writes it back."""

    async def go():
        w = _Worker()
        seen, done = asyncio.Event(), asyncio.Event()

        async def drive():
            sanitizer.set_task_root("drive")
            stale = w.last_heartbeat
            seen.set()
            await done.wait()
            restore(w, stale)

        async def rpc():
            sanitizer.set_task_root("main")
            await seen.wait()
            w.last_heartbeat = 100.0  # fresher evidence lands mid-await
            done.set()

        await asyncio.gather(asyncio.create_task(drive()),
                             asyncio.create_task(rpc()))
        return w

    sanitizer.enable()
    sanitizer.reset()
    try:
        w = asyncio.run(go())
        return w, sanitizer.conflicts()
    finally:
        sanitizer.disable()


def test_stale_heartbeat_restore_caught_by_sanitizer():
    def buggy(w, stale):
        w.last_heartbeat = stale  # PR 10's shape: destroys the refresh

    w, conflicts = _heartbeat_scenario(buggy)
    assert w.last_heartbeat == 0.0  # the refresh really was destroyed
    assert [c["kind"] for c in conflicts] == ["lost-update"], conflicts
    assert conflicts[0]["field"] == "last_heartbeat"


def test_monotonic_heartbeat_merge_is_clean():
    def fixed(w, stale):
        w.last_heartbeat = max(w.last_heartbeat, stale)

    w, conflicts = _heartbeat_scenario(fixed)
    assert w.last_heartbeat == 100.0  # newest evidence survives
    assert conflicts == [], conflicts


# -- mutant 3: injected await-spanning RMW in the runner (static catch) ------


def test_injected_runner_rmw_caught_by_race002(tmp_path):
    src = (REPO / "arroyo_tpu" / "operators" / "runner.py").read_text()
    assert FLUSH_ANCHOR in src, (
        "flush task-root anchor moved; update this mutant"
    )
    assert not _race002(tmp_path, src), (
        "real runner.py is not RACE002-clean standalone"
    )
    mutant = src.replace(FLUSH_ANCHOR, FLUSH_RMW, 1)
    findings = _race002(tmp_path, mutant)
    assert len(findings) == 1, findings
    assert "_flush_hwm" in findings[0].message


# -- the suppressions the mutants must not hide behind -----------------------


@pytest.mark.parametrize("path, expected", [
    ("arroyo_tpu/operators/runner.py", 1),
    ("arroyo_tpu/controller/controller.py", 0),
    ("arroyo_tpu/engine/worker.py", 0),
])
def test_inline_race_suppression_budget(path, expected):
    """Inline RACE suppressions are justified one-offs, not a release
    valve: new ones need the same scrutiny these tests encode."""
    text = (REPO / path).read_text()
    assert text.count("arroyolint: disable=RACE") == expected, path
