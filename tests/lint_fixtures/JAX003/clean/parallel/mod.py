"""Must NOT fire JAX003: syncs only in emission/capture functions, and
hot-path numpy calls only touch host buffers."""
import numpy as np


class Acc:
    def update(self, slots, vals):
        # host-side row buffers are fine: no device state involved
        slots = np.asarray(slots)
        self._pending.append((slots, np.asarray(vals)))

    def gather(self, slots):
        # emission read: materializing device state is the point
        return [np.asarray(s) for s in self.state]

    def snapshot(self, slots):
        for s in self.state:
            s.block_until_ready()
        return self.gather(slots)

    def _dispatch_rows(self, rows):
        n = int(rows.max()) + 1
        return np.zeros(n)
