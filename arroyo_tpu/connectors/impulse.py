"""Impulse connector — synthetic counter source for tests and benchmarks.

Capability parity with the reference's impulse connector
(/root/reference/crates/arroyo-connectors/src/impulse/mod.rs:182): emits
rows {counter, subtask_index} at `event_rate` events/sec/subtask, optionally
bounded by `message_count`; counter offset persists in state so restores
resume exactly. Deterministic event-time mode (`start_time` + i/rate) for
reproducible tests. `realtime` paces generation by wall clock and stamps
wall-clock event time; `replay = 'true'` (with `realtime`) keeps the wall
pacing but stamps the synthetic `start_time + i/rate` timestamps instead,
so a slow run's output is byte-identical to a fast one (the fleet harness
and multiplexed chaos smokes park/kill jobs mid-run and still demand
byte-identical output).
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

import pyarrow as pa

from ..operators.base import SourceFinishType, SourceOperator
from ..schema import StreamSchema
from ..types import now_nanos
from .base import ConnectionSchema, Connector, register_connector

IMPULSE_SCHEMA = StreamSchema.from_fields(
    [("counter", pa.uint64()), ("subtask_index", pa.uint64())]
)


class ImpulseSource(SourceOperator):
    def __init__(
        self,
        event_rate: float = 10_000.0,
        message_count: Optional[int] = None,
        start_time: Optional[int] = None,
        realtime: bool = False,
        replay: bool = False,
    ):
        super().__init__("impulse")
        self.event_rate = event_rate
        self.message_count = message_count
        self.start_time = start_time
        self.realtime = realtime
        self.replay = replay
        self.out_schema = IMPULSE_SCHEMA
        self.counter = 0

    def tables(self):
        from ..state.table_config import global_table

        return {"i": global_table("i")}

    async def on_start(self, ctx):
        if ctx.table_manager is not None:
            table = await ctx.table("i")
            stored = table.get(ctx.task_info.task_index)
            if stored is not None:
                self.counter = stored

    async def handle_checkpoint(self, barrier, ctx, collector):
        if ctx.table_manager is not None:
            table = await ctx.table("i")
            table.put(ctx.task_info.task_index, self.counter)

    async def run(self, ctx, collector) -> SourceFinishType:
        subtask = ctx.task_info.task_index
        start = self.start_time if self.start_time is not None else now_nanos()
        period = 1.0 / self.event_rate if self.event_rate > 0 else 0.0
        wall_start = time.monotonic()
        while self.message_count is None or self.counter < self.message_count:
            finish = await ctx.check_control(collector)
            if finish is not None:
                return finish
            if self.realtime:
                target = wall_start + self.counter * period
                delay = target - time.monotonic()
                while delay > 0:
                    # sleep in bounded slices: a low-rate source (parked
                    # fleet jobs pace one event per tens of seconds) must
                    # keep answering control — a stop or checkpoint
                    # barrier cannot wait out a full inter-event gap
                    await asyncio.sleep(min(delay, 0.5))
                    finish = await ctx.check_control(collector)
                    if finish is not None:
                        return finish
                    delay = target - time.monotonic()
                # replay mode: wall-paced arrival, synthetic event time
                # (byte-identical output whatever the wall clock did);
                # plain realtime keeps stamping wall-clock time
                if self.replay:
                    ts = start + int(
                        round(self.counter * (1e9 / self.event_rate))
                    )
                else:
                    ts = now_nanos()
            else:
                ts = start + int(round(self.counter * (1e9 / self.event_rate)))
            ctx.buffer_row(
                {"counter": self.counter, "subtask_index": subtask,
                 "_timestamp": ts}
            )
            self.counter += 1
            if ctx.should_flush():
                await self.flush_buffer(ctx, collector)
                # yield so queues/control stay live even in non-realtime mode
                await asyncio.sleep(0)
        await self.flush_buffer(ctx, collector)
        return SourceFinishType.FINAL


@register_connector
class ImpulseConnector(Connector):
    name = "impulse"
    description = "synthetic counter source at a fixed event rate"
    source = True
    config_schema = {
        "event_rate": {"type": "number", "required": True},
        "message_count": {"type": "integer"},
        "realtime": {"type": "boolean"},
        "replay": {"type": "boolean"},
    }

    def validate_options(self, options, schema):
        out = {
            "event_rate": float(options.get("event_rate", 10_000)),
            "realtime": str(options.get("realtime", "false")).lower() == "true",
            "replay": str(options.get("replay", "false")).lower() == "true",
        }
        if "message_count" in options:
            out["message_count"] = int(options["message_count"])
        if "start_time" in options:
            out["start_time"] = int(options["start_time"])
        return out

    def table_schema(self):
        return IMPULSE_SCHEMA

    def make_source(self, config, schema: ConnectionSchema) -> ImpulseSource:
        return ImpulseSource(
            event_rate=config.get("event_rate", 10_000.0),
            message_count=config.get("message_count"),
            start_time=config.get("start_time"),
            realtime=config.get("realtime", False),
            replay=config.get("replay", False),
        )
