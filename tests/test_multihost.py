"""Multi-host mesh: jax.distributed wiring (parallel/multihost.py).

A real TPU pod slice spans processes; the controller assigns
(coordinator, process count, rank) at scheduling time and each worker
joins the global mesh before any jax init. These tests validate the
scheduler-side assignment and run the 2-process x 2-device sharded step
across real process boundaries (gloo over localhost — the virtual-CPU
stand-in for per-host chip ownership).

Reference analog: the TCP shuffle's worker wiring
(crates/arroyo-worker/src/network_manager.rs:551-605), replaced here by
XLA collectives over the process-spanning mesh.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_scheduler_assigns_mesh_ranks():
    from arroyo_tpu.config import update
    from arroyo_tpu.controller.scheduler import (
        mesh_env_for_worker,
        pick_coordinator,
    )

    # single-host job: no assignment
    assert mesh_env_for_worker(0, 2, None) == {}

    with update(tpu={"mesh_processes": 2}):
        coord = pick_coordinator()
        assert ":" in coord
        e0 = mesh_env_for_worker(0, 2, coord)
        e1 = mesh_env_for_worker(1, 2, coord)
        assert e0["ARROYO__TPU__MESH_COORDINATOR"] == coord
        assert e0["ARROYO__TPU__MESH_PROCESS_ID"] == "0"
        assert e1["ARROYO__TPU__MESH_PROCESS_ID"] == "1"
        assert e0["ARROYO__TPU__MESH_PROCESSES"] == "2"
        # the mesh must span every worker of the job
        with pytest.raises(ValueError):
            mesh_env_for_worker(0, 3, coord)


def test_ensure_initialized_single_process_noop():
    from arroyo_tpu.parallel import multihost

    # default config: no multi-process mesh -> (1, 0) without touching
    # jax.distributed (which would need a coordinator)
    assert multihost.ensure_initialized() == (1, 0)
    assert multihost.process_info() == (1, 0)


def test_mesh_requires_assignment():
    from arroyo_tpu.config import update
    from arroyo_tpu.parallel import multihost

    # mesh_processes >= 2 without coordinator/rank must fail loudly,
    # not silently fall back to a single-process mesh
    multihost._initialized = None
    try:
        with update(tpu={"mesh_processes": 2}):
            with pytest.raises(ValueError):
                multihost.ensure_initialized()
    finally:
        multihost._initialized = None


def test_sharded_step_across_processes():
    """2 processes x 2 virtual CPU devices: the full ShardedAccumulator
    protocol (both exchange layouts, gather, reset, restore, salted
    fold) runs over a process-spanning mesh. Exercises the exact child
    the driver's dryrun uses."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    n_devices, n_proc = 4, 2
    procs = []
    for pid in range(n_proc):
        env = {
            k: v for k, v in os.environ.items()
            if k not in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
                         "AXON_POOL_SVC_OVERRIDE", "AXON_LOOPBACK_RELAY",
                         "PYTHONPATH", "XLA_FLAGS")
        }
        from arroyo_tpu.parallel.multihost import env_overrides

        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "PYTHONPATH": REPO,
            **env_overrides(coord, n_proc, pid),
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-c",
             "import __graft_entry__ as g; "
             f"g._dryrun_multiproc_child({n_devices})"],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        ))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    if any("Multiprocess computations aren't implemented" in out
           for out in outs):
        # jax 0.4.x CPU backend cannot run cross-process collectives at
        # all — the path needs either real devices or a newer jax; the
        # single-process mesh dryruns still cover the sharded step
        pytest.skip("CPU backend lacks multiprocess collectives")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {pid}:\n{out[-3000:]}"
        assert f"MULTIPROC pid={pid} ok" in out, out[-3000:]
