"""PRO004 clean fixture: every mutation reachable from an annotated
handler (directly, via a helper, or __init__ seeding)."""


def protocol_effect(name):
    def deco(fn):
        return fn
    return deco


class SubtaskRunner:
    def __init__(self):
        self._inflight_flushes = []
        self.pending_epochs = {}

    @protocol_effect("worker.capture")
    async def _checkpoint_chain(self, barrier):
        self._inflight_flushes.append(barrier)
        await self._reap_done()

    @protocol_effect("worker.drain_flushes")
    async def _await_pending_flush(self):
        flushes, self._inflight_flushes = self._inflight_flushes, []
        return flushes

    async def _reap_done(self):
        # helper called from an annotated handler: reachable, fine
        self._inflight_flushes = [
            t for t in self._inflight_flushes if not t.done()
        ]
        self.pending_epochs.pop(0, None)
