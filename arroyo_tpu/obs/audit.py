"""Conservation ledger (ISSUE 19): always-on exactly-once auditing.

Every data-plane edge accumulates an epoch-scoped ATTESTATION — a row
count plus an order-insensitive content digest — sealed at barrier
alignment on BOTH sides: the sender tap lives in the EdgeSender
(operators/collector.py, covering local queues AND the remote frame path,
since the remote sender pumps the very same tapped queue), the receiver
tap in the runner's input loop (operators/runner.py). The digest is a
commutative fold: each row's columns (struct children flattened in
order) combine linearly under per-column salts, one splitmix round mixes
the combined row, and the per-row hashes are summed mod 2^64 — invariant
to row order and batch slicing, so keyed shuffles and Arrow IPC
roundtrips do not perturb it, while any duplicated, lost, or torn frame
does.

Attestations ride the existing checkpoint reports
(CheckpointCompletedResp.audit) to a controller-resident Reconciler that
verifies, per epoch:

  (a) sender attestation == receiver attestation per edge at each
      manifest publish (catches dup/lost/torn delivery beyond TCP),
  (b) per-operator flow consistency — out-counts change only via the
      operator's declared selectivity class (Operator.flow_class),
      never silent duplication,
  (c) recovery conservation at report INTAKE: a re-emitted epoch at or
      behind the published epoch (rewind-behind-commit — the PR 15
      ``overlap_double_emission`` mutant class, live) and reports from a
      fenced data-plane generation (zombie append) are flagged with the
      exact (edge, epoch) culprit.

Breach records land in three places: the per-job reconciler (expunged
with the job, served by /debug/audit and GET /api/v1/jobs/{id}/audit),
the job-labeled arroyo_audit_* metric families (GC'd by
Registry.drop_job), and a small process-wide ring that deliberately
SURVIVES job expunge so chaos drills can assert audit silence after the
embedded controller tears the job down.

Rows emitted after the last sealed barrier (the trailing segment before
EndOfData) are unattested symmetrically on both sides — no attestation
is ever compared against a partial peer, so a clean run is audit-silent
by construction.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa

logger = logging.getLogger(__name__)

_MOD = 1 << 64
# digest contribution of a row in a zero-column batch (never happens in
# practice — every schema carries _timestamp — but keeps the fold total)
_EMPTY_ROW = 0x9E3779B97F4A7C15

# per-job breach list cap and process-wide ring cap: breaches are
# exceptional; a run that produces hundreds has already failed loudly
_JOB_BREACH_CAP = 256
_RING_CAP = 512


def enabled() -> bool:
    """Auditing is on by default (config().audit.enabled); the bench's
    overhead child turns it off with ARROYO__AUDIT__ENABLED=0."""
    from ..config import config

    return bool(config().audit.enabled)


# ---------------------------------------------------------------------------
# attestation accumulation (task side)


_SALTS = np.empty(0, dtype=np.uint64)


def _col_salts(n: int) -> np.ndarray:
    """Distinct odd multipliers per column position (splitmix of the
    index), so the linear combine keeps column pairing: swapping values
    between columns within a row changes the row hash."""
    global _SALTS
    if len(_SALTS) < n:
        from ..types import _splitmix64

        _SALTS = _splitmix64(
            np.arange(1, n + 1, dtype=np.uint64)
        ) | np.uint64(1)
    return _SALTS


def _col_u64(col: pa.Array) -> np.ndarray:
    """Raw uint64 view of one column (nulls -> type sentinel, -0.0
    normalized) WITHOUT per-column mixing — the audit fold mixes once
    per row after the linear combine, which is what keeps the always-on
    tap cheap enough for every data-plane edge."""
    from ..schema import _null_sentinel, _to_numpy

    if col.null_count:
        col = col.fill_null(_null_sentinel(col.type))
    arr = _to_numpy(col)
    kind = arr.dtype.kind
    if kind in ("i", "u", "b"):
        return arr.astype(np.uint64, copy=False)
    if kind == "f":
        arr = arr + 0.0  # normalize -0.0 == 0.0 before bit-viewing
        return (arr.view(np.uint64) if arr.dtype == np.float64
                else arr.astype(np.float64).view(np.uint64))
    if kind == "M":
        return arr.view("i8").astype(np.uint64)
    from ..types import hash_column  # strings/objects: pandas hash

    return hash_column(arr)


# extra odd salts for nested shapes: list length (so [a, b]+[] and
# [a]+[b] across adjacent rows differ) and the null-list sentinel (so a
# NULL list differs from an empty one)
_LIST_LEN_SALT = np.uint64(0xD6E8FEB86659FD93)
_NULL_LIST = np.uint64(0xA5A5A5A5A5A5A5A5)


def _row_u64(col: pa.Array) -> np.ndarray:
    """One uint64 per row for any column type, recursing into nested
    shapes: struct children combine linearly under the column salts
    (+ one mix), list elements get one mix each and sum within the row
    (order-insensitive, like the batch fold) with the length salted in.
    Flat columns stay on the raw-view fast path (`_col_u64`)."""
    t = col.type
    from ..types import _splitmix64

    if pa.types.is_struct(t):
        kids = [_row_u64(col.field(j)) for j in range(t.num_fields)]
        salts = _col_salts(len(kids))
        with np.errstate(over="ignore"):
            acc = kids[0] * salts[0]
            for i in range(1, len(kids)):
                acc = acc + kids[i] * salts[i]
        return _splitmix64(acc)
    if pa.types.is_fixed_size_list(t):
        col, t = col.cast(pa.list_(t.value_type)), None
    if t is None or pa.types.is_list(t) or pa.types.is_large_list(t):
        import pyarrow.compute as pc

        lens = np.asarray(
            pc.list_value_length(col).fill_null(0), dtype=np.int64)
        h = _splitmix64(_row_u64(col.flatten()))
        c = np.zeros(len(h) + 1, dtype=np.uint64)
        if len(h):
            np.cumsum(h, dtype=np.uint64, out=c[1:])  # wraps mod 2^64
        ends = np.cumsum(lens)
        with np.errstate(over="ignore"):
            rows = (c[ends] - c[ends - lens]
                    + _LIST_LEN_SALT * lens.astype(np.uint64))
        if col.null_count:
            rows = np.where(np.asarray(col.is_valid()), rows, _NULL_LIST)
        return rows
    return _col_u64(col)


def batch_fingerprint(batch: pa.RecordBatch) -> Tuple[int, int]:
    """(rows, digest) of one batch. Every column (struct children
    flattened in order) contributes its raw uint64 view to a per-row
    linear combine under distinct per-column odd salts; ONE splitmix
    round then mixes each combined row, and the rows are folded
    commutatively by summing mod 2^64 — the digest of a multiset of rows
    is independent of row order and of how the rows are sliced into
    batches, while a duplicated, lost, or torn row perturbs it. A single
    mixing pass (instead of two per column) is what holds the always-on
    overhead down; the linear pre-combine admits only contrived
    cancellations, far below the accidental-corruption signal this
    ledger exists to catch."""
    n = batch.num_rows
    if n == 0:
        return 0, 0
    cols: List[np.ndarray] = []
    for col in batch.columns:
        if pa.types.is_struct(col.type):
            for j in range(col.type.num_fields):
                cols.append(_row_u64(col.field(j)))
            continue
        cols.append(_row_u64(col))
    if not cols:
        return n, (n * _EMPTY_ROW) % _MOD
    from ..types import _splitmix64

    salts = _col_salts(len(cols))
    with np.errstate(over="ignore"):
        acc = cols[0] * salts[0]
        for i in range(1, len(cols)):
            acc = acc + cols[i] * salts[i]
        return n, int(_splitmix64(acc).sum(dtype=np.uint64))


class EdgeTap:
    """Running attestation for ONE direction of ONE edge, sealed per
    epoch when the barrier passes. The sender seals every output tap at
    barrier broadcast; the receiver seals input i's tap the moment input
    i delivers the barrier (aligned inputs deliver no further rows for
    that epoch), so both sides cut the stream at the same causal point."""

    __slots__ = ("edge", "rows", "digest", "sealed")

    def __init__(self, edge: str):
        self.edge = edge
        self.rows = 0
        self.digest = 0
        self.sealed: Dict[int, Tuple[int, int]] = {}

    def observe(self, batch: pa.RecordBatch) -> None:
        n, d = batch_fingerprint(batch)
        if n:
            self.rows += n
            self.digest = (self.digest + d) % _MOD

    def seal(self, epoch: int) -> None:
        self.sealed[epoch] = (self.rows, self.digest)
        self.rows = 0
        self.digest = 0

    def drain(self, epoch: int) -> Optional[Tuple[int, int]]:
        return self.sealed.pop(epoch, None)


def edge_key(src: str, src_subtask: int, dst: str, dst_subtask: int) -> str:
    """Canonical edge name: one attestation pair per (src subtask, dst
    subtask) channel — exactly the quad the data plane routes on."""
    return f"{src}:{src_subtask}->{dst}:{dst_subtask}"


# ---------------------------------------------------------------------------
# breach ring (process-wide, survives job expunge — drill assertions)

_RING_LOCK = threading.Lock()
_RING: deque = deque(maxlen=_RING_CAP)
_SEQ = 0


def _ring_push(rec: dict) -> None:
    global _SEQ
    with _RING_LOCK:
        _SEQ += 1
        _RING.append(dict(rec, seq=_SEQ))


def breach_mark() -> int:
    """Current breach sequence number: drills snapshot it before a run
    and assert breaches_since(mark) == [] after."""
    with _RING_LOCK:
        return _SEQ


def breaches_since(mark: int, job_id: Optional[str] = None) -> List[dict]:
    with _RING_LOCK:
        out = [dict(r) for r in _RING if r["seq"] > mark]
    if job_id is not None:
        out = [r for r in out if r["job"] == job_id]
    return out


# ---------------------------------------------------------------------------
# reconciler (controller side)


class Reconciler:
    """Controller-resident per-job conservation reconciler. intake() runs
    the recovery-conservation checks the moment a checkpoint report
    lands; reconcile() joins sealed attestations across the epoch's task
    reports when the manifest publishes."""

    def __init__(self, job_id: str):
        self.job_id = job_id
        self.lock = threading.Lock()
        # highest data-plane incarnation ("job@N" suffix) seen in any
        # report: once a newer generation reports, an older generation
        # appending NEW epochs is a zombie past its fencing
        self.max_incarnation: Optional[int] = None
        self.epochs_reconciled = 0
        self.edges_verified = 0
        self.rows_attested = 0
        self.last_epoch: Optional[int] = None
        self.breaches: List[dict] = []
        # last verified attestation per edge, for the report surfaces
        self.edges: Dict[str, dict] = {}

    # -- breach plumbing ----------------------------------------------------

    def _breach(self, kind: str, edge: str, epoch: int, detail: str) -> None:
        from ..metrics import AUDIT_BREACHES

        rec = {
            "job": self.job_id,
            "kind": kind,
            "edge": edge,
            "epoch": epoch,
            "detail": detail,
            "ts": time.time(),
        }
        with self.lock:
            self.breaches.append(rec)
            if len(self.breaches) > _JOB_BREACH_CAP:
                del self.breaches[0]
        _ring_push(rec)
        AUDIT_BREACHES.labels(job=self.job_id, kind=kind).inc()
        logger.warning(
            "conservation breach [%s] job=%s edge=%s epoch=%s: %s",
            kind, self.job_id, edge, epoch, detail,
        )

    @staticmethod
    def _first_edge(audit: Optional[dict]) -> Optional[str]:
        for side in ("tx", "rx"):
            d = (audit or {}).get(side) or {}
            for edge in d:
                return edge
        return None

    # -- checks -------------------------------------------------------------

    @staticmethod
    def _incarnation(gen: Optional[str]) -> Optional[int]:
        """Parse the schedule incarnation out of a data-plane namespace
        ("<job_id>@<incarnation>"); None when unstamped/unparseable."""
        if not gen or "@" not in gen:
            return None
        try:
            return int(gen.rsplit("@", 1)[1])
        except ValueError:
            return None

    def intake(self, task_id: str, epoch: int, audit: Optional[dict],
               published_epoch: Optional[int]) -> bool:
        """Recovery-conservation checks (c) at report intake time.
        Returns True when the report must be FENCED (not folded into the
        epoch bookkeeping): any epoch at/behind the published epoch, and
        any report from a generation older than one already seen. Only
        strictly-stale epochs are flagged as rewind breaches — an exact
        redelivery of the just-published epoch (an rpc retry racing the
        publish) is fenced silently, a REWIND re-emits history."""
        if not audit:
            return False
        edge = self._first_edge(audit) or f"task:{task_id}"
        if published_epoch is not None and epoch <= published_epoch:
            if epoch < published_epoch:
                self._breach(
                    "rewind_behind_commit", edge, epoch,
                    f"re-emitted epoch {epoch} behind published epoch "
                    f"{published_epoch} — source rewind behind committed "
                    f"output",
                )
            return True
        inc = self._incarnation(audit.get("gen"))
        if inc is not None:
            with self.lock:
                if self.max_incarnation is None or inc > self.max_incarnation:
                    self.max_incarnation = inc
                behind = inc < self.max_incarnation
            if behind:
                self._breach(
                    "zombie_generation", edge, epoch,
                    f"report from fenced generation "
                    f"{audit.get('gen')!r} (newest incarnation "
                    f"{self.max_incarnation}) — append past fencing",
                )
                return True
        return False

    def reconcile(self, epoch: int,
                  audits: Dict[str, Optional[dict]]) -> None:
        """Checks (a) + (b) at manifest publish: join the epoch's sealed
        sender/receiver attestations per edge and verify each operator's
        flow against its declared selectivity class. One-sided edges
        (peer finished before this barrier, or its report carried no
        attestation) are skipped, never flagged."""
        from ..metrics import AUDIT_EDGES_VERIFIED, AUDIT_EPOCHS

        tx: Dict[str, Tuple[int, int]] = {}
        rx: Dict[str, Tuple[int, int]] = {}
        # one epoch's barriers originate in exactly one generation, so an
        # epoch assembled from MIXED generations means an old incarnation
        # appended into a fenced epoch (zombie write that slipped intake)
        gens = {
            a.get("gen") for a in audits.values() if a and a.get("gen")
        }
        if len(gens) > 1:
            incs = {g: self._incarnation(g) for g in gens}
            if all(v is not None for v in incs.values()):
                live = max(gens, key=lambda g: incs[g])
                for task_id, a in audits.items():
                    if a and a.get("gen") not in (None, live):
                        self._breach(
                            "zombie_generation",
                            self._first_edge(a) or f"task:{task_id}", epoch,
                            f"epoch assembled from mixed generations: "
                            f"{a.get('gen')!r} behind live {live!r}",
                        )
        for task_id, audit in audits.items():
            if not audit:
                continue
            for edge, v in (audit.get("tx") or {}).items():
                tx[edge] = (int(v[0]), int(v[1]))
            for edge, v in (audit.get("rx") or {}).items():
                rx[edge] = (int(v[0]), int(v[1]))
            flow = audit.get("flow") or {}
            for op, v in (audit.get("ops") or {}).items():
                cls = flow.get(op, "any")
                rows_in, rows_out = int(v[0]), int(v[1])
                if cls == "exact" and rows_out != rows_in:
                    self._breach(
                        "flow_violation", f"op:{task_id}/{op}", epoch,
                        f"declared exact selectivity but {rows_in} in != "
                        f"{rows_out} out",
                    )
                elif cls == "contracting" and rows_out > rows_in:
                    self._breach(
                        "flow_violation", f"op:{task_id}/{op}", epoch,
                        f"declared contracting selectivity but amplified "
                        f"{rows_in} in -> {rows_out} out",
                    )
        verified = 0
        rows = 0
        for edge, (t_rows, t_dig) in tx.items():
            r = rx.get(edge)
            if r is None:
                continue
            r_rows, r_dig = r
            if t_rows != r_rows:
                self._breach(
                    "count_mismatch", edge, epoch,
                    f"sender attested {t_rows} rows, receiver {r_rows}",
                )
            elif t_dig != r_dig:
                self._breach(
                    "digest_mismatch", edge, epoch,
                    f"sender digest {t_dig:#018x} != receiver {r_dig:#018x} "
                    f"over {t_rows} rows",
                )
            else:
                verified += 1
                rows += t_rows
            with self.lock:
                self.edges[edge] = {
                    "epoch": epoch,
                    "tx": [t_rows, t_dig],
                    "rx": [r_rows, r_dig],
                    "ok": t_rows == r_rows and t_dig == r_dig,
                }
        with self.lock:
            self.epochs_reconciled += 1
            self.edges_verified += verified
            self.rows_attested += rows
            self.last_epoch = epoch
        AUDIT_EPOCHS.labels(job=self.job_id).inc()
        if verified:
            AUDIT_EDGES_VERIFIED.labels(job=self.job_id).inc(verified)

    # -- surfaces -----------------------------------------------------------

    def status(self) -> dict:
        with self.lock:
            return {
                "job": self.job_id,
                "incarnation": self.max_incarnation,
                "epochs_reconciled": self.epochs_reconciled,
                "edges_verified": self.edges_verified,
                "rows_attested": self.rows_attested,
                "last_epoch": self.last_epoch,
                "breach_count": len(self.breaches),
                "breaches": [dict(b) for b in self.breaches],
                "edges": {e: dict(v) for e, v in self.edges.items()},
            }


# ---------------------------------------------------------------------------
# per-job reconciler registry

_REG_LOCK = threading.Lock()
_RECONCILERS: Dict[str, Reconciler] = {}


def reconciler(job_id: str) -> Reconciler:
    with _REG_LOCK:
        r = _RECONCILERS.get(job_id)
        if r is None:
            r = _RECONCILERS[job_id] = Reconciler(job_id)
        return r


def peek(job_id: str) -> Optional[Reconciler]:
    with _REG_LOCK:
        return _RECONCILERS.get(job_id)


def breach_count(job_id: str) -> Optional[float]:
    """The watchtower conservation signal: breaches recorded for a live
    job, None (abstain) when no reconciler exists yet."""
    r = peek(job_id)
    if r is None:
        return None
    with r.lock:
        return float(len(r.breaches))


def status(job_id: Optional[str] = None) -> dict:
    """/debug/audit payload: every live reconciler (or one job's)."""
    with _REG_LOCK:
        recs = dict(_RECONCILERS)
    if job_id is not None:
        r = recs.get(job_id)
        return r.status() if r is not None else {"job": job_id}
    return {
        "enabled": enabled(),
        "jobs": {jid: r.status() for jid, r in recs.items()},
    }


def expunge_job(job_id: str) -> None:
    """Job-scoped GC, same path as Registry.drop_job / obs.expunge_job.
    The process-wide breach ring is deliberately NOT touched — drills
    assert over it after the job is torn down."""
    with _REG_LOCK:
        _RECONCILERS.pop(job_id, None)


def reset() -> None:
    """Test hygiene: drop all reconcilers AND the breach ring."""
    global _SEQ
    with _REG_LOCK:
        _RECONCILERS.clear()
    with _RING_LOCK:
        _RING.clear()
        _SEQ = 0
