"""State at scale (ISSUE 8): incremental global-table blob chains with
rebase + tombstones, multi-inflight off-barrier checkpoint flushes, and
the larger-than-RAM time-key spill tier.

Acceptance pins:
  * restore from a base+delta chain (tombstoned keys, post-rebase
    manifests, stale cross-subtask replicas) is byte-identical to a
    full-snapshot restore (property test);
  * multi-inflight flushes publish manifests strictly in epoch order and
    an in-flight flush failure routes TaskFailedResp with recovery from
    the last *published* epoch;
  * a session-window job round-trips byte-identically through the
    per-key incremental path, with delta bytes << full-snapshot bytes;
  * the spill tier bounds RAM at state.memory_budget_bytes while holding
    ~10x the budget, with identical drained output.
"""

import asyncio
import glob
import json
import os
import random

import numpy as np
import pyarrow as pa
import pytest

from arroyo_tpu import chaos
from arroyo_tpu.chaos.plan import FaultPlan
from arroyo_tpu.config import update
from arroyo_tpu.engine import Engine
from arroyo_tpu.sql import plan_query
from arroyo_tpu.state.backend import StateBackend
from arroyo_tpu.state.table_config import global_table, time_key_table
from arroyo_tpu.state.tables import GlobalTable, TimeKeyTable

MS = 1_000_000


# -- incremental global tables: chain == full snapshot (property) ------------


def _apply_ops(table: GlobalTable, ops):
    for op, k, v in ops:
        if op == "put":
            table.put(k, v)
        else:
            table.delete(k)


def test_global_chain_restore_equals_full_snapshot_property():
    """Random put/delete streams across epochs, chained with random
    rebase points: replaying the chain must reconstruct exactly the
    final map — including tombstoned keys and post-rebase manifests."""
    rng = random.Random(7)
    for trial in range(20):
        src = GlobalTable(global_table("g"))
        expect = {}
        chain = []
        for epoch in range(1, rng.randint(2, 9)):
            ops = []
            for _ in range(rng.randint(0, 12)):
                k = rng.randint(0, 15)
                if rng.random() < 0.25:
                    ops.append(("del", k, None))
                    expect.pop(k, None)
                else:
                    v = rng.randint(0, 999)
                    ops.append(("put", k, v))
                    expect[k] = v
            _apply_ops(src, ops)
            force = rng.random() < 0.3
            blob, is_base = src.serialize_delta(epoch, force_base=force)
            if blob is None:
                continue
            if is_base:
                chain = [blob]
            else:
                chain.append(blob)
        dst = GlobalTable(global_table("g"))
        dst.load_chain(chain)
        got = dict(dst.items())
        assert got == expect, f"trial {trial}: {got} != {expect}"


def test_global_chain_stale_replica_loses_by_stamp():
    """Replication re-persists every subtask's union view; the restore
    merge must prefer the owner's fresher entry over a peer's stale copy
    REGARDLESS of chain load order (pre-stamp code let dict order win)."""
    owner = GlobalTable(global_table("g"))
    owner.put("k", "old")
    b1, _ = owner.serialize_delta(1)
    # the peer restored the owner's epoch-1 state (stamp rides along)
    peer = GlobalTable(global_table("g"))
    peer.load_chain([b1])
    peer.put("mine", 1)
    peer_blob, _ = peer.serialize_delta(5)
    # the owner then advanced k
    owner.put("k", "new")
    b2, _ = owner.serialize_delta(3)
    for order in ([[b1, b2], [peer_blob]], [[peer_blob], [b1, b2]]):
        t = GlobalTable(global_table("g"))
        for sub_chain in order:
            t.load_chain(sub_chain)
        merged = dict(t.items())
        assert merged["k"] == "new", f"stale replica won under {order}"
        assert merged["mine"] == 1
    # tombstones beat stale entries the same way: owner deletes k at 6
    owner.delete("k")
    b3, _ = owner.serialize_delta(6)
    t = GlobalTable(global_table("g"))
    t.load_chain([peer_blob])       # stale k@1 replica
    t.load_chain([b1, b2, b3])      # owner chain ends in tombstone@6
    assert "k" not in dict(t.items())


def test_global_capture_is_o_dirty():
    """After the base, an epoch's blob carries only the dirty entries —
    bytes scale with the delta, not total state."""
    t = GlobalTable(global_table("g"))
    for i in range(2000):
        t.put(i, "x" * 20)
    base, is_base = t.serialize_delta(1)
    assert is_base and len(base) > 20_000
    t.put(1, "y")
    delta, is_base = t.serialize_delta(2)
    assert not is_base and len(delta) < 200, len(delta)
    # untouched epoch: no blob at all
    blob, _ = t.serialize_delta(3)
    assert blob is None


def test_rebase_policy_truncates_chain(tmp_storage):
    """TableManager rebases once the chain carries state.rebase_epochs
    deltas (or delta bytes exceed the factor), and the manifest's chain
    shrinks back to one base; restore replays correctly before and
    after the rebase boundary."""
    from arroyo_tpu.operators.control import CheckpointCompletedResp
    from arroyo_tpu.state.table_manager import TableManager
    from arroyo_tpu.types import TaskInfo

    url = f"{tmp_storage}/rb"

    async def run():
        b = StateBackend(url, "rb").initialize()
        tm = TableManager(b, TaskInfo("rb", 5, "op", 0, 1), 0)
        await tm.open({"g": global_table("g")})
        table = await tm.get_table("g")
        chain_lens = []
        for epoch in range(1, 10):
            table.put(f"k{epoch}", epoch)
            meta = await tm.checkpoint(epoch, None)
            chain_lens.append(len(meta["g"]["chain"]))
            resp = CheckpointCompletedResp(
                "5-0", 5, 0, epoch, subtask_metadata={"op0": meta},
                watermark=None,
            )
            b.publish_checkpoint(epoch, {"5-0": resp})
            b.retire_unreferenced()
        return chain_lens

    with update(state={"rebase_epochs": 3, "rebase_bytes_factor": 100.0}):
        chain_lens = asyncio.run(run())
    # base, +1, +2, +3 deltas -> rebase to 1, ...
    assert chain_lens[0] == 1
    assert max(chain_lens) == 4 and chain_lens.count(1) >= 2, chain_lens

    async def restore():
        b2 = StateBackend(url, "rb").initialize()
        tm2 = TableManager(b2, TaskInfo("rb", 5, "op", 0, 1), 0)
        await tm2.open({"g": global_table("g")})
        t2 = await tm2.get_table("g")
        return dict(t2.items())

    got = asyncio.run(restore())
    assert got == {f"k{e}": e for e in range(1, 10)}


# -- spill tier ---------------------------------------------------------------


def _ts_batch(n, ts_base, key_base=0):
    return pa.RecordBatch.from_arrays(
        [pa.array(np.arange(n) + key_base),
         pa.array(np.full(n, ts_base, dtype=np.int64))],
        names=["v", "_timestamp"],
    )


def test_timekey_spill_bounds_memory_and_drains_identically():
    """Hold ~10x the budget: in-memory bytes stay <= budget, spilled rows
    come back byte-identical when the watermark drains them."""
    budget = 60_000
    with update(state={"memory_budget_bytes": budget}):
        spilling = TimeKeyTable(time_key_table("x"))
        plain = TimeKeyTable(time_key_table("x"))
    for i in range(60):
        spilling.insert(_ts_batch(1000, i * 10, i * 1000))
        plain.insert(_ts_batch(1000, i * 10, i * 1000))
    mem, spilled, rows, batches = spilling.entry_stats()
    assert rows == 60_000 and batches == 60
    assert mem <= budget, f"budget exceeded: {mem}"
    assert spilled > budget * 5, "held ~10x the budget without spilling"

    def drain(t):
        return [
            (ts, b.column(0).to_pylist())
            for ts, b in t.take_bins_upto(10**9)
        ]

    assert drain(spilling) == drain(plain)
    assert spilling.entry_stats()[:3] == (0, 0, 0)


def test_timekey_spill_restore_roundtrip():
    """load_batches beyond the budget spills like live inserts; the
    restored view is identical."""
    src = [_ts_batch(500, i * 7) for i in range(40)]
    with update(state={"memory_budget_bytes": 20_000}):
        t = TimeKeyTable(time_key_table("x"))
    t.load_batches(src)
    assert t.entry_stats()[0] <= 20_000
    got = [b.column(1).to_pylist() for b in t.all_batches()]
    want = [b.column(1).to_pylist() for b in src]
    assert got == want
    t.clear_batches()  # releases scratch files


def test_expire_row_level_compaction():
    """A batch pinned by one live row no longer keeps its dead rows in
    RAM: expire() compacts row-level past the configured fraction."""
    with update(state={"expire_compact_fraction": 0.5}):
        t = TimeKeyTable(time_key_table("y", retention_nanos=100))
        mixed = pa.RecordBatch.from_arrays(
            [pa.array(np.arange(100)),
             pa.array(np.r_[np.full(90, 0), np.full(10, 1000)])],
            names=["v", "_timestamp"],
        )
        t.insert(mixed)
        before = t.entry_stats()[0]
        t.expire(600)  # cutoff 500: 90% dead, max_ts live
        assert sum(b.num_rows for b in t.all_batches()) == 10
        assert t.entry_stats()[0] < before
        # below the fraction the batch survives whole (no copy churn)
        t2 = TimeKeyTable(time_key_table("y", retention_nanos=100))
        t2.insert(mixed)
        t2.expire(100)  # cutoff 0: nothing dead
        assert sum(b.num_rows for b in t2.all_batches()) == 100


# -- multi-inflight flushes ---------------------------------------------------


def _agg_sql(src, sink, throttle=None):
    th = f"throttle_per_sec = '{throttle}'," if throttle else ""
    return f"""
    CREATE TABLE src (timestamp TIMESTAMP, k BIGINT NOT NULL)
    WITH (connector = 'single_file', path = '{src}', format = 'json',
          type = 'source', {th} event_time_field = 'timestamp');
    CREATE TABLE out (k BIGINT NOT NULL, c BIGINT NOT NULL)
    WITH (connector = 'single_file', path = '{sink}', format = 'json',
          type = 'sink');
    INSERT INTO out SELECT k, count(*) as c FROM src
    GROUP BY 1, tumble(interval '1 hour');
    """


def _write_rows(path, n=3000, keys=64):
    with open(path, "w") as f:
        for i in range(n):
            mins, secs = (i // 60) % 60, i % 60
            f.write(json.dumps({
                "k": i % keys,
                "timestamp": f"2023-03-01T00:{mins:02d}:{secs:02d}.000Z",
            }) + "\n")


def test_multi_inflight_flushes_publish_in_epoch_order(tmp_path):
    """Three barriers injected back-to-back under slow storage: flushes
    overlap (high-water mark > 1), completion reports stay epoch-ordered
    per subtask, and the manifests publish 1, 2, 3."""
    src = str(tmp_path / "in.json")
    _write_rows(src)
    sink = str(tmp_path / "out.json")
    storage = str(tmp_path / "ck")
    published = []

    plan = FaultPlan(seed=1)
    plan.add("storage.latency", at_hits=tuple(range(1, 200)),
             match={"key": "/data/"}, params={"delay": 0.05},
             max_fires=200)

    async def run():
        plan_q = plan_query(_agg_sql(src, sink, throttle=6000),
                            parallelism=1)
        eng = Engine(plan_q.graph, job_id="mi", storage_url=storage).start()
        await asyncio.sleep(0.15)
        epochs = [await eng.checkpoint() for _ in range(3)]
        for e in epochs:
            await eng.wait_checkpoint(e)
            published.append(
                eng.backend.latest_manifest()["epoch"]
            )
        hwm = max(
            s.runner._flush_hwm for s in eng.program.subtasks
        )
        await eng.checkpoint_and_wait(then_stop=True)
        await eng.join(60)
        return hwm

    chaos.install(plan)
    try:
        with update(state={"max_inflight_flushes": 3}):
            hwm = asyncio.run(run())
    finally:
        chaos.clear()
    assert published == [1, 2, 3], published
    assert hwm >= 2, f"flushes never overlapped (hwm={hwm})"
    # per-subtask reports arrived in epoch order -> every manifest's
    # chain references exist
    b = StateBackend(storage, "mi").initialize()
    manifest = b.latest_manifest()
    for task in manifest["tasks"].values():
        for tables in task["op_tables"].values():
            for meta in tables.values():
                for f in meta.get("chain", []):
                    assert b.read_blob(f["path"]) is not None, f["path"]


def test_inflight_flush_failure_recovers_from_published_epoch(tmp_path):
    """An injected storage failure inside a checkpoint flush routes
    TaskFailedResp (not a silent hang); the embedded cluster recovers
    from the last *published* epoch and the final output is identical
    to a fault-free run — exactly-once across a flush-path fault."""
    from arroyo_tpu.chaos.drill import _run_embedded

    src = str(tmp_path / "in.json")
    _write_rows(src, n=2500)
    clean, faulted = str(tmp_path / "clean.json"), str(tmp_path / "f.json")

    _run_embedded(
        _agg_sql(src, clean), "fl-clean", None, 2, 1, max_restarts=0,
        heartbeat_interval=0.1, heartbeat_timeout=30.0,
        checkpoint_interval=60.0, timeout=90.0,
    )
    want = sorted(line.strip() for line in open(clean) if line.strip())
    assert want

    plan = FaultPlan(seed=3)
    # fail a checkpoint DATA file write (the async flush path), twice
    plan.add("storage.write_fail", at_hits=(2, 3), match={"key": "/data/"})
    chaos.install(plan)
    try:
        with update(state={"max_inflight_flushes": 2}):
            restarts = _run_embedded(
                _agg_sql(src, faulted, throttle=2500), "fl-faulted",
                str(tmp_path / "ck"), 2, 1, max_restarts=8,
                heartbeat_interval=0.1, heartbeat_timeout=2.0,
                checkpoint_interval=0.15, timeout=120.0,
            )
    finally:
        chaos.clear()
    assert not plan.unfired(), [s.describe() for s in plan.unfired()]
    assert restarts >= 1, "flush failure never surfaced"
    got = sorted(line.strip() for line in open(faulted) if line.strip())
    assert got == want


def test_capture_flush_overlap_exactly_once_under_storage_chaos(tmp_path):
    """The tier-1 storage faults (lost CAS race + injected latency) with
    multi-inflight flushes enabled: capture->flush overlap preserves
    byte-identical exactly-once output."""
    from arroyo_tpu.chaos.drill import _run_embedded

    src = str(tmp_path / "in.json")
    _write_rows(src, n=2500)
    clean, faulted = str(tmp_path / "clean.json"), str(tmp_path / "f.json")
    _run_embedded(
        _agg_sql(src, clean), "ov-clean", None, 2, 1, max_restarts=0,
        heartbeat_interval=0.1, heartbeat_timeout=30.0,
        checkpoint_interval=60.0, timeout=90.0,
    )
    want = sorted(line.strip() for line in open(clean) if line.strip())

    plan = FaultPlan(seed=11)
    plan.add("storage.cas_conflict", at_hits=(1,),
             match={"key": "checkpoint-manifest"})
    plan.add("storage.latency", at_hits=(2, 5, 9),
             match={"key": "/data/"}, params={"delay": 0.2})
    chaos.install(plan)
    try:
        with update(state={"max_inflight_flushes": 3}):
            _run_embedded(
                _agg_sql(src, faulted, throttle=2500), "ov-faulted",
                str(tmp_path / "ck"), 2, 1, max_restarts=8,
                heartbeat_interval=0.1, heartbeat_timeout=2.0,
                checkpoint_interval=0.15, timeout=120.0,
            )
    finally:
        chaos.clear()
    assert not plan.unfired(), [s.describe() for s in plan.unfired()]
    got = sorted(line.strip() for line in open(faulted) if line.strip())
    assert got == want


# -- session windows: per-key incremental global state ------------------------


def _session_sql(src, sink, throttled):
    th = "throttle_per_sec = '8000'," if throttled else ""
    return f"""
    CREATE TABLE src (timestamp TIMESTAMP, k BIGINT NOT NULL)
    WITH (connector='single_file', path='{src}', format='json',
          type='source', {th} event_time_field='timestamp');
    CREATE TABLE out (k BIGINT NOT NULL, c BIGINT NOT NULL)
    WITH (connector='single_file', path='{sink}', format='json',
          type='sink');
    INSERT INTO out SELECT k, count(*) as c FROM src
    GROUP BY k, session(interval '30 second');
    """


def test_session_incremental_restore_identical(tmp_path):
    """Session state checkpoints per dirty key (base + deltas +
    tombstones for closed sessions); checkpoint -> stop -> restore ->
    finish equals an uninterrupted run, and no epoch after the base
    rewrites the whole session map."""
    src = str(tmp_path / "in.json")
    with open(src, "w") as f:
        for i in range(3600):
            mins, secs = (i // 60) % 60, i % 60
            f.write(json.dumps({
                "k": i % 200,
                "timestamp": f"2023-03-01T00:{mins:02d}:{secs:02d}.000Z",
            }) + "\n")

    full = str(tmp_path / "full.json")

    async def run_full():
        eng = Engine(plan_query(_session_sql(src, full, False),
                                parallelism=1).graph).start()
        await eng.join(120)

    asyncio.run(run_full())

    rest = str(tmp_path / "rest.json")
    storage = str(tmp_path / "ck")

    async def p1():
        eng = Engine(plan_query(_session_sql(src, rest, True),
                                parallelism=1).graph,
                     job_id="s", storage_url=storage).start()
        for _ in range(3):
            await asyncio.sleep(0.1)
            await eng.checkpoint_and_wait()
        await eng.checkpoint_and_wait(then_stop=True)
        await eng.join(120)

    asyncio.run(p1())

    async def p2():
        eng = Engine(plan_query(_session_sql(src, rest, False),
                                parallelism=1).graph,
                     job_id="s", storage_url=storage).start()
        # state-size observability: the sess table's scrape-time gauges
        # are live while the job runs
        await asyncio.sleep(0.1)
        from arroyo_tpu.metrics import REGISTRY

        snap = REGISTRY.snapshot()
        sess_rows = [
            v for labels, v in snap.get("arroyo_state_rows", [])
            if labels.get("table") == "sess" and labels.get("job") == "s"
        ]
        assert sess_rows, "arroyo_state_rows gauge missing for sess"
        assert any(
            labels.get("table") == "sess"
            for labels, _v in snap.get("arroyo_state_delta_chain_len", [])
        ), "delta-chain gauge missing"
        await eng.join(120)

    asyncio.run(p2())

    read = lambda p: sorted(  # noqa: E731
        json.dumps(json.loads(x), sort_keys=True)
        for x in open(p) if x.strip()
    )
    assert read(rest) == read(full)
    # incremental evidence: several sess blobs exist and no post-base
    # blob rewrites the whole map
    blobs = sorted(glob.glob(
        os.path.join(storage, "**", "*-sess-*.bin"), recursive=True
    ))
    assert len(blobs) >= 2, blobs
    sizes = [os.path.getsize(b) for b in blobs]
    assert min(sizes) < max(sizes), sizes


def test_session_restore_at_higher_parallelism(tmp_path):
    """Per-key session entries re-partition on rescale: each new subtask
    keeps only its key range (retain prunes the rest) and the union of
    the final outputs is exactly-once."""
    src = str(tmp_path / "in.json")
    with open(src, "w") as f:
        for i in range(2400):
            mins, secs = (i // 60) % 60, i % 60
            f.write(json.dumps({
                "k": i % 100,
                "timestamp": f"2023-03-01T00:{mins:02d}:{secs:02d}.000Z",
            }) + "\n")

    full = str(tmp_path / "full.json")

    async def run_full():
        eng = Engine(plan_query(_session_sql(src, full, False),
                                parallelism=1).graph).start()
        await eng.join(120)

    asyncio.run(run_full())

    rest = str(tmp_path / "rest.json")
    storage = str(tmp_path / "ck")

    async def p1():
        eng = Engine(plan_query(_session_sql(src, rest, True),
                                parallelism=1).graph,
                     job_id="sp", storage_url=storage).start()
        await asyncio.sleep(0.15)
        await eng.checkpoint_and_wait()
        await eng.checkpoint_and_wait(then_stop=True)
        await eng.join(120)

    asyncio.run(p1())

    async def p2():
        eng = Engine(plan_query(_session_sql(src, rest, False),
                                parallelism=2).graph,
                     job_id="sp", storage_url=storage).start()
        await eng.join(120)

    asyncio.run(p2())

    read = lambda p: sorted(  # noqa: E731
        json.dumps(json.loads(x), sort_keys=True)
        for x in open(p) if x.strip()
    )
    assert read(rest) == read(full)
