"""Protobuf decoding via dynamic messages.

Capability parity with the reference's prost-reflect path
(/root/reference/crates/arroyo-formats/src/proto/*): a compiled
FileDescriptorSet (bytes of `protoc --descriptor_set_out`) + message name
produce a dynamic decoder; fields map to columns by name.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class ProtoDecoder:
    def __init__(self, descriptor: Optional[dict]):
        if not descriptor or "descriptor_set" not in descriptor:
            raise ValueError(
                "protobuf format requires protobuf.descriptor_set (bytes of a "
                "compiled FileDescriptorSet) and protobuf.message_name"
            )
        from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

        fds = descriptor_pb2.FileDescriptorSet()
        fds.ParseFromString(descriptor["descriptor_set"])
        pool = descriptor_pool.DescriptorPool()
        for f in fds.file:
            pool.Add(f)
        desc = pool.FindMessageTypeByName(descriptor["message_name"])
        self.cls = message_factory.GetMessageClass(desc)

    def decode(self, record: bytes) -> Dict[str, Any]:
        msg = self.cls()
        msg.ParseFromString(record)
        out = {}
        for field in msg.DESCRIPTOR.fields:
            v = getattr(msg, field.name)
            if field.type == field.TYPE_MESSAGE:
                v = str(v)
            out[field.name] = v
        return out
