"""SQL window functions over event-time windows.

Capability parity with the reference's window_fn.rs
(/root/reference/crates/arroyo-worker/src/arrow/window_fn.rs): rows of a
windowed stream buffer per bin (all rows of one emitted window share a
_timestamp); when the watermark passes a bin, the window functions
(ROW_NUMBER / RANK / DENSE_RANK ... OVER (PARTITION BY ... ORDER BY ...))
evaluate over the bin's rows and the augmented rows emit. The reference
runs a DataFusion BoundedWindowAggExec per bin; here the ranking kernels
are numpy lexsort-based.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import pyarrow as pa

from ..engine.construct import register_operator
from ..graph.logical import OperatorName
from ..schema import StreamSchema, TIMESTAMP_FIELD
from ..types import WatermarkKind
from .base import Operator

SUPPORTED = ("row_number", "rank", "dense_rank", "count")


class WindowFunctionOperator(Operator):
    flow_class = "buffering"  # buffers partitions until the watermark closes them

    def __init__(self, config: dict):
        super().__init__("window_fn")
        self.fn: str = config["fn"]  # row_number | rank | dense_rank
        if self.fn not in SUPPORTED:
            raise ValueError(f"unsupported window function {self.fn}")
        self.partition_cols: List[int] = list(config.get("partition_cols", []))
        # [(col_idx, descending)]
        self.order_by: List[tuple] = [tuple(o) for o in config.get("order_by", [])]
        self.out_schema: StreamSchema = config["schema"]
        self.out_field: str = config["out_field"]
        self.bins: Dict[int, List[pa.RecordBatch]] = {}
        self.emitted_up_to: Optional[int] = None

    def tables(self):
        from ..state.table_config import global_table

        return {"wf": global_table("wf")}

    async def on_start(self, ctx):
        if ctx.table_manager is not None:
            from .joins import _ipc_read

            table = await ctx.table("wf")
            for snap in table.all_values():
                if snap.get("emitted_up_to") is not None:
                    self.emitted_up_to = max(
                        self.emitted_up_to or 0, snap["emitted_up_to"]
                    )
                for ts_s, blobs in snap.get("bins", {}).items():
                    self.bins.setdefault(int(ts_s), []).extend(
                        _ipc_read(b) for b in blobs
                    )

    async def handle_checkpoint(self, barrier, ctx, collector):
        if ctx.table_manager is not None:
            from .joins import _ipc_write

            table = await ctx.table("wf")
            table.put(
                ctx.task_info.task_index,
                {
                    "emitted_up_to": self.emitted_up_to,
                    "subtask": ctx.task_info.task_index,
                    "bins": {
                        str(ts): [_ipc_write(b) for b in batches]
                        for ts, batches in self.bins.items()
                    },
                },
            )

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        ts = np.asarray(
            batch.column(batch.schema.names.index(TIMESTAMP_FIELD)).cast(
                pa.int64()
            )
        )
        if self.emitted_up_to is not None:
            live = ts > self.emitted_up_to
            if not live.all():
                if not live.any():
                    return
                batch = batch.filter(pa.array(live))
                ts = ts[live]
        for t in np.unique(ts):
            mask = ts == t
            self.bins.setdefault(int(t), []).append(
                batch.filter(pa.array(mask)) if not mask.all() else batch
            )

    async def handle_watermark(self, watermark, ctx, collector):
        if watermark.kind != WatermarkKind.EVENT_TIME:
            return watermark
        t = watermark.timestamp
        for ts in sorted(b for b in self.bins if b <= t):
            batches = self.bins.pop(ts)
            table = pa.Table.from_batches(batches).combine_chunks()
            out = self._evaluate(table)
            if out is not None and out.num_rows:
                await collector.collect(out)
            self.emitted_up_to = max(self.emitted_up_to or 0, ts)
        return watermark

    def _evaluate(self, table: pa.Table) -> Optional[pa.RecordBatch]:
        n = table.num_rows
        if n == 0:
            return None
        # partition ids
        if self.partition_cols:
            import pandas.util

            parts = None
            for c in self.partition_cols:
                col = np.asarray(
                    table.column(c).to_numpy(zero_copy_only=False)
                )
                h = pandas.util.hash_array(
                    col.astype(object), categorize=False
                )
                parts = h if parts is None else parts * np.uint64(31) + h
            _, part_ids = np.unique(parts, return_inverse=True)
        else:
            part_ids = np.zeros(n, dtype=np.int64)
        # order keys (last key = primary in lexsort)
        sort_keys = []
        for col_idx, desc in reversed(self.order_by):
            col = np.asarray(
                table.column(col_idx).to_numpy(zero_copy_only=False)
            )
            if col.dtype == object:
                _, col = np.unique(col, return_inverse=True)
            sort_keys.append(-col if desc else col)
        sort_keys.append(part_ids)
        order = np.lexsort(sort_keys)
        ranks = self._rank(part_ids[order], sort_keys, order)
        values = np.empty(n, dtype=np.int64)
        values[order] = ranks
        arrays = [table.column(f.name).combine_chunks()
                  if f.name != self.out_field else pa.array(values, type=f.type)
                  for f in self.out_schema.schema]
        return pa.RecordBatch.from_arrays(arrays, schema=self.out_schema.schema)

    def _rank(self, sorted_parts: np.ndarray, sort_keys, order) -> np.ndarray:
        """Vectorized ranking over partition-sorted rows: positions come
        from a cumulative count reset at partition starts; rank/dense_rank
        additionally detect ties on the order keys."""
        n = len(sorted_parts)
        idx = np.arange(n, dtype=np.int64)
        new_part = np.empty(n, dtype=bool)
        new_part[0] = True
        np.not_equal(sorted_parts[1:], sorted_parts[:-1], out=new_part[1:])
        # index of each row's partition start
        part_start = np.maximum.accumulate(np.where(new_part, idx, 0))
        pos = idx - part_start + 1  # 1-based position within partition
        if self.fn in ("row_number", "count"):
            return pos
        keys_sorted = [np.asarray(k)[order] for k in sort_keys[:-1]]
        new_group = new_part.copy()
        for k in keys_sorted:
            new_group[1:] |= k[1:] != k[:-1]
        if self.fn == "dense_rank":
            # count of group starts within the partition
            group_num = np.cumsum(new_group)
            return group_num - group_num[part_start] + 1
        # rank: position of the first row of each tie group
        group_start = np.maximum.accumulate(np.where(new_group, idx, 0))
        return group_start - part_start + 1


@register_operator(OperatorName.WINDOW_FUNCTION)
def _make_window_fn(config: dict) -> Operator:
    return WindowFunctionOperator(config)
