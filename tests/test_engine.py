"""End-to-end engine tests: pipelines built by hand as logical graphs
(mirrors the reference smoke-test style but without SQL)."""

import asyncio

import pyarrow as pa
import pytest

from arroyo_tpu.config import update
from arroyo_tpu.engine import Engine
from arroyo_tpu.graph import ChainingOptimizer, EdgeType, LogicalGraph, OperatorName
from arroyo_tpu.graph.logical import ChainedOp, LogicalNode
from arroyo_tpu.connectors.impulse import IMPULSE_SCHEMA
from arroyo_tpu.types import StopMode


def impulse_pipeline(
    n_events=100, sink_results=None, mid_parallelism=1, keyed=False, chain_wm=True,
    py_fn=None,
):
    """impulse -> [watermark] -> map -> vec sink."""
    g = LogicalGraph()
    source_chain = [
        ChainedOp(
            OperatorName.CONNECTOR_SOURCE,
            {
                "connector": "impulse",
                "event_rate": 1e9,
                "message_count": n_events,
                "start_time": 0,
                "schema": IMPULSE_SCHEMA,
            },
        )
    ]
    if chain_wm:
        source_chain.append(
            ChainedOp(OperatorName.EXPRESSION_WATERMARK, {"interval_nanos": 0})
        )
    g.add_node(LogicalNode(1, "impulse", source_chain, 1))
    g.add_node(
        LogicalNode.single(
            2,
            OperatorName.ARROW_VALUE,
            {"py_fn": py_fn or (lambda b: b)},
            parallelism=mid_parallelism,
        )
    )
    g.add_node(
        LogicalNode.single(
            3,
            OperatorName.CONNECTOR_SINK,
            {"connector": "vec", "results": sink_results},
            parallelism=mid_parallelism,
        )
    )
    schema = IMPULSE_SCHEMA.with_keys(["counter"]) if keyed else IMPULSE_SCHEMA
    g.add_edge(1, 2, EdgeType.SHUFFLE, schema)
    g.add_edge(2, 3, EdgeType.FORWARD, IMPULSE_SCHEMA)
    return g


def run_graph(g, timeout=30.0):
    async def run():
        eng = Engine(g).start()
        await eng.join(timeout)
        return eng

    return asyncio.run(run())


def test_end_to_end_impulse_to_vec():
    results = []
    g = impulse_pipeline(100, results)
    run_graph(g)
    assert len(results) == 100
    assert sorted(r["counter"] for r in results) == list(range(100))


def test_shuffle_parallelism_2_completeness():
    results = []
    with update(pipeline={"source_batch_size": 16}):
        g = impulse_pipeline(200, results, mid_parallelism=2, keyed=True)
        run_graph(g)
    assert sorted(r["counter"] for r in results) == list(range(200))


def test_map_transform_applied():
    results = []

    def double(batch: pa.RecordBatch) -> pa.RecordBatch:
        counter = pa.compute.multiply(batch.column(0), 2)
        return pa.RecordBatch.from_arrays(
            [counter, batch.column(1), batch.column(2)], schema=batch.schema
        )

    g = impulse_pipeline(50, results, py_fn=double)
    run_graph(g)
    assert sorted(r["counter"] for r in results) == [2 * i for i in range(50)]


def test_chaining_optimizer_fuses_forward_edges():
    g = impulse_pipeline(10, [])
    # make all edges forward + same parallelism so the non-sink prefix
    # fuses; the sink keeps its own node (checkpoint/commit control
    # targets sink tasks, so the optimizer never folds sinks in)
    for e in g.edges:
        e.edge_type = EdgeType.FORWARD
    ChainingOptimizer().optimize(g)
    assert len(g.nodes) == 2
    chains = sorted(len(n.chain) for n in g.nodes.values())
    assert chains == [1, 3]  # [sink], [source, wm, map]
    results = []
    sink = next(n for n in g.nodes.values() if len(n.chain) == 1)
    sink.chain[-1].config["results"] = results
    run_graph(g)
    assert sorted(r["counter"] for r in results) == list(range(10))


def test_checkpoint_barrier_alignment_p2():
    """Checkpoint completes across a parallelism-2 shuffle (alignment)."""
    results = []

    async def run():
        with update(pipeline={"source_batch_size": 8}):
            g = impulse_pipeline(
                500, results, mid_parallelism=2, keyed=True
            )
            eng = Engine(g).start()
            cps = await eng.checkpoint_and_wait()
            # all 5 subtasks (1 src + 2 map + 2 sink) completed the epoch
            assert len(cps) == 5
            await eng.join()

    asyncio.run(run())
    assert sorted(r["counter"] for r in results) == list(range(500))


def test_graceful_stop_mid_stream():
    results = []

    async def run():
        g = impulse_pipeline(None, results)  # unbounded
        g.nodes[1].chain[0].config["message_count"] = None
        g.nodes[1].chain[0].config["event_rate"] = 1e5
        g.nodes[1].chain[0].config["realtime"] = True
        eng = Engine(g).start()
        await asyncio.sleep(0.3)
        await eng.stop(StopMode.GRACEFUL)
        await eng.join()

    asyncio.run(run())
    assert len(results) > 0
    # no gaps: graceful stop drains in-flight data
    assert sorted(r["counter"] for r in results) == list(range(len(results)))


def test_task_failure_propagates():
    def boom(batch):
        raise RuntimeError("kaboom")

    results = []
    g = impulse_pipeline(10, results, py_fn=boom)
    from arroyo_tpu.engine.engine import JobFailed

    with pytest.raises(JobFailed, match="kaboom"):
        run_graph(g)


def test_checkpoint_continues_after_finite_source_finishes(tmp_path):
    """Mixed finite/infinite job: once the finite source finishes,
    checkpoints must keep publishing (finished tasks recorded in the
    manifest as a consistent cut), and a restore must not re-run the
    finished source (engine.py wait_checkpoint / run_prefinished)."""
    import json

    from arroyo_tpu.sql import plan_query

    out = str(tmp_path / "out.json")
    sql = f"""
    CREATE TABLE fast WITH (connector = 'impulse', event_rate = '100000',
      message_count = '20', start_time = '0');
    CREATE TABLE slow WITH (connector = 'impulse', event_rate = '400',
      message_count = '120', start_time = '0');
    CREATE TABLE out (c BIGINT, src TEXT) WITH (
      connector = 'single_file', path = '{out}', format = 'json',
      type = 'sink');
    INSERT INTO out SELECT counter, 'fast' as src FROM fast;
    INSERT INTO out SELECT counter, 'slow' as src FROM slow;
    """
    storage = str(tmp_path / "ckpt")

    async def phase1():
        plan = plan_query(sql, parallelism=1)
        eng = Engine(plan.graph, job_id="fin", storage_url=storage).start()
        # wait for the fast source to finish (slow one keeps running)
        while not eng.finished:
            eng.drain_responses()
            await asyncio.sleep(0.01)
        await eng.checkpoint_and_wait()
        manifest = eng.backend.latest_manifest()
        assert manifest["finished_tasks"], (
            "checkpoint after a source finished must record it as finished"
        )
        await eng.checkpoint_and_wait(then_stop=True)
        await eng.join(60)

    asyncio.run(phase1())

    async def phase2():
        plan = plan_query(sql, parallelism=1)
        eng = Engine(plan.graph, job_id="fin", storage_url=storage).start()
        assert eng.prefinished, "restore must mark finished tasks"
        await eng.join(60)

    asyncio.run(phase2())

    rows = [json.loads(l) for l in open(out) if l.strip()]
    fast = sorted(r["c"] for r in rows if r["src"] == "fast")
    slow = sorted(r["c"] for r in rows if r["src"] == "slow")
    assert fast == list(range(20)), "finished source re-ran or lost rows"
    assert slow == list(range(120))
