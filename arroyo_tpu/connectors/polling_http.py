"""Placeholder: polling_http connector lands with the connector milestone."""
