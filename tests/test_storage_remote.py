"""Remote (S3) storage path driven against an in-process fake S3 server.

Covers what the reference exercises with object_store's localstack tests:
the StorageProvider's get/put/list/delete through a real S3 client stack
(pyarrow's AWS C++ SDK with endpoint_override) plus the atomic CAS
(`put_if_not_exists` via SigV4-signed conditional PUT, If-None-Match: *)
that the checkpoint fencing protocol depends on.
"""

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse

import pytest

from arroyo_tpu.state.storage import CasConflict, StorageProvider


class _FakeS3Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):  # quiet
        pass

    # -- helpers ------------------------------------------------------------

    def _key(self):
        return unquote(urlparse(self.path).path).lstrip("/")

    def _query(self):
        return parse_qs(urlparse(self.path).query, keep_blank_values=True)

    def _body(self):
        if (self.headers.get("Transfer-Encoding") or "").lower() == "chunked":
            data = b""
            while True:
                line = self.rfile.readline()
                size = int(line.split(b";")[0].strip() or b"0", 16)
                if size == 0:
                    self.rfile.readline()
                    break
                data += self.rfile.read(size)
                self.rfile.readline()
        else:
            n = int(self.headers.get("Content-Length") or 0)
            data = self.rfile.read(n) if n else b""
        sha = self.headers.get("x-amz-content-sha256", "")
        if sha.startswith("STREAMING"):
            # aws-chunked framing: <hex-size>;chunk-signature=...\r\n<data>\r\n
            out = b""
            rest = data
            while rest:
                head, _, rest = rest.partition(b"\r\n")
                size = int(head.split(b";")[0], 16)
                if size == 0:
                    break
                out += rest[:size]
                rest = rest[size + 2 :]
            return out
        return data

    def _respond(self, code, body=b"", headers=(), content_length=None):
        self.send_response(code)
        for k, v in headers:
            self.send_header(k, v)
        self.send_header(
            "Content-Length",
            str(len(body) if content_length is None else content_length),
        )
        self.end_headers()
        if body:
            self.wfile.write(body)

    # -- verbs --------------------------------------------------------------

    def do_PUT(self):
        key = self._key()
        srv = self.server
        q = self._query()
        if "partNumber" in q:
            body = self._body()
            uid = q["uploadId"][0]
            with srv.lock:
                srv.uploads.setdefault(uid, {})[int(q["partNumber"][0])] = body
            self._respond(200, headers=[("ETag", '"part"')])
            return
        srv.events.append(
            (
                "PUT",
                key,
                self.headers.get("If-None-Match"),
                self.headers.get("Authorization", ""),
            )
        )
        body = self._body()
        with srv.lock:
            if self.headers.get("If-None-Match") == "*" and key in srv.objects:
                self._respond(412, b"<Error><Code>PreconditionFailed</Code></Error>")
                return
            srv.objects[key] = body
        self._respond(200, headers=[("ETag", '"fake"')])

    def do_GET(self):
        key = self._key()
        q = self._query()
        srv = self.server
        if "/" not in key or "list-type" in q or "prefix" in q:
            # ListObjectsV2 on the bucket
            bucket = key.split("/")[0]
            prefix = (q.get("prefix") or [""])[0]
            full_prefix = f"{bucket}/{prefix}"
            with srv.lock:
                keys = sorted(
                    k for k in srv.objects if k.startswith(full_prefix)
                )
            contents = "".join(
                f"<Contents><Key>{k[len(bucket) + 1:]}</Key>"
                f"<LastModified>2026-01-01T00:00:00.000Z</LastModified>"
                f'<ETag>"fake"</ETag>'
                f"<Size>{len(srv.objects[k])}</Size>"
                f"<StorageClass>STANDARD</StorageClass></Contents>"
                for k in keys
            )
            xml = (
                '<?xml version="1.0" encoding="UTF-8"?>'
                f"<ListBucketResult><Name>{bucket}</Name>"
                f"<Prefix>{prefix}</Prefix><KeyCount>{len(keys)}</KeyCount>"
                f"<MaxKeys>1000</MaxKeys><IsTruncated>false</IsTruncated>"
                f"{contents}</ListBucketResult>"
            )
            self._respond(200, xml.encode())
            return
        with srv.lock:
            data = srv.objects.get(key)
        if data is None:
            self._respond(404, b"<Error><Code>NoSuchKey</Code></Error>")
            return
        rng = self.headers.get("Range")
        if rng and rng.startswith("bytes="):
            lo_s, _, hi_s = rng[6:].partition("-")
            lo = int(lo_s or 0)
            hi = min(int(hi_s) if hi_s else len(data) - 1, len(data) - 1)
            part = data[lo : hi + 1]
            self._respond(
                206,
                part,
                headers=[
                    ("Content-Range", f"bytes {lo}-{hi}/{len(data)}")
                ],
            )
        else:
            self._respond(200, data)

    def do_HEAD(self):
        key = self._key()
        srv = self.server
        with srv.lock:
            data = srv.objects.get(key)
        if "/" not in key:  # HeadBucket
            self._respond(200, headers=[("x-amz-bucket-region", "us-east-1")])
        elif data is None:
            self._respond(404)
        else:
            self._respond(200, content_length=len(data))

    def do_DELETE(self):
        key = self._key()
        srv = self.server
        with srv.lock:
            srv.objects.pop(key, None)
        self._respond(204)

    def do_POST(self):
        key = self._key()
        q = self._query()
        srv = self.server
        body = self._body()
        if "delete" in q:  # bulk delete
            import re

            deleted = re.findall(r"<Key>([^<]+)</Key>", body.decode())
            bucket = key.split("/")[0]
            with srv.lock:
                for k in deleted:
                    srv.objects.pop(f"{bucket}/{k}", None)
            xml = (
                '<?xml version="1.0" encoding="UTF-8"?><DeleteResult>'
                + "".join(f"<Deleted><Key>{k}</Key></Deleted>" for k in deleted)
                + "</DeleteResult>"
            )
            self._respond(200, xml.encode())
            return
        if "uploads" in q:  # initiate multipart
            with srv.lock:
                uid = f"up{len(srv.uploads)}"
                srv.uploads[uid] = {}
            bucket, _, rest = key.partition("/")
            xml = (
                '<?xml version="1.0" encoding="UTF-8"?>'
                f"<InitiateMultipartUploadResult><Bucket>{bucket}</Bucket>"
                f"<Key>{rest}</Key><UploadId>{uid}</UploadId>"
                "</InitiateMultipartUploadResult>"
            )
            self._respond(200, xml.encode())
            return
        if "uploadId" in q:  # complete multipart
            uid = q["uploadId"][0]
            with srv.lock:
                parts = srv.uploads.pop(uid, {})
                srv.objects[key] = b"".join(
                    parts[i] for i in sorted(parts)
                )
            bucket, _, rest = key.partition("/")
            xml = (
                '<?xml version="1.0" encoding="UTF-8"?>'
                "<CompleteMultipartUploadResult>"
                f"<Key>{rest}</Key><ETag>\"fake\"</ETag>"
                "</CompleteMultipartUploadResult>"
            )
            self._respond(200, xml.encode())
            return
        self._respond(400)


class _FakeS3Server(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self):
        super().__init__(("127.0.0.1", 0), _FakeS3Handler)
        self.objects = {}
        self.uploads = {}
        self.events = []
        self.lock = threading.Lock()


@pytest.fixture()
def fake_s3(monkeypatch):
    srv = _FakeS3Server()
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    monkeypatch.setenv(
        "AWS_ENDPOINT_URL", f"http://127.0.0.1:{srv.server_address[1]}"
    )
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", "testing")
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "testing")
    monkeypatch.setenv("AWS_DEFAULT_REGION", "us-east-1")
    monkeypatch.setenv("AWS_EC2_METADATA_DISABLED", "true")
    monkeypatch.delenv("AWS_SESSION_TOKEN", raising=False)
    yield srv
    srv.shutdown()
    srv.server_close()


def test_fake_s3_roundtrip(fake_s3):
    sp = StorageProvider("s3://ckpts/pipeline-1")
    sp.put("epoch-1/manifest.json", b'{"epoch": 1}')
    assert sp.get("epoch-1/manifest.json") == b'{"epoch": 1}'
    assert sp.exists("epoch-1/manifest.json")
    assert not sp.exists("epoch-2/manifest.json")
    sp.put("epoch-1/data-0.bin", b"\x00" * 128)
    keys = sp.list("epoch-1")
    assert keys == ["epoch-1/data-0.bin", "epoch-1/manifest.json"]
    sp.delete("epoch-1/data-0.bin")
    assert sp.list("epoch-1") == ["epoch-1/manifest.json"]


def test_fake_s3_conditional_put_is_atomic(fake_s3):
    sp = StorageProvider("s3://ckpts/job")
    sp.put_if_not_exists("gen/claim-3", b"owner-a")
    with pytest.raises(CasConflict):
        sp.put_if_not_exists("gen/claim-3", b"owner-b")
    assert sp.get("gen/claim-3") == b"owner-a"
    # both PUTs carried the conditional header + a SigV4 signature: the
    # CAS rides the server's atomicity, not a check-then-create race
    cas_puts = [e for e in fake_s3.events if e[0] == "PUT" and "claim-3" in e[1]]
    assert len(cas_puts) == 2
    assert all(e[2] == "*" for e in cas_puts)
    assert all(e[3].startswith("AWS4-HMAC-SHA256") for e in cas_puts)


def test_fencing_protocol_over_fake_s3(fake_s3):
    """Generation fencing + exactly-once commit authorization on object
    storage — the failover race the conditional put exists to close."""
    from arroyo_tpu.state.protocol import (
        ProtocolPaths,
        claim_commit,
        initialize_generation,
    )

    sp = StorageProvider("s3://ckpts/cluster")
    paths = ProtocolPaths("job-9")
    g1 = initialize_generation(sp, paths)
    g2 = initialize_generation(sp, paths)  # second controller takes over
    assert g2 == g1 + 1
    # exactly one of two racing controllers wins the epoch commit
    wins = [claim_commit(sp, paths, g, 5) for g in (g1, g2)]
    assert wins == [True, False]


def test_fake_s3_conditional_put_write_visible(fake_s3):
    sp = StorageProvider("s3://ckpts/job2")
    sp.put_if_not_exists("commits/epoch-7", b"commit-record")
    assert fake_s3.objects["ckpts/job2/commits/epoch-7"] == b"commit-record"
