"""One follower: read-only serve-state mounts tailing published chains.

A follower owns, per mounted job, a generation-less StateBackend and
one TableManager per (node, op) whose manifest publishes a `__serve__`
table. Restore and tail both run the PR 17 machinery verbatim —
`TableManager.open` (with `restore_manifest` pointed at a published
manifest) unions ALL subtasks' chains because the follower's TaskInfo
claims parallelism 1, and `tail_chains` replays only the delta-chain
suffix per publish, at delta cost through the shared chain cache.

Views are rebuilt from the mirrored rows after every restore/tail and
stamped with the manifest epoch they reflect; `read` serves from them
without touching the compiled program, the workers, or the job's
generation. The `__serve_meta__` record carries the WORKER-side
describe() (true parallelism included), so the gateway can keep using
it for worker-ward fallback routing unchanged.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from ..analysis.model.effects import protocol_effect
from ..serve.store import META_KEY, SERVE_TABLE, ServeView
from ..state import protocol
from ..state.backend import StateBackend
from ..state.table_config import global_table
from ..state.table_manager import TableManager
from ..types import TaskInfo
from ..utils.logging import get_logger

logger = get_logger("replica")


class _Mount:
    """One job's serve state mounted on this follower."""

    def __init__(self, backend: StateBackend):
        self.backend = backend
        # (node_id, op_idx) -> TableManager over that op's __serve__ chain
        self.tms: Dict[Tuple[int, int], TableManager] = {}
        self.views: Dict[str, ServeView] = {}
        self.meta: Dict[str, dict] = {}  # bare table -> worker describe()
        self.epoch = 0                   # manifest epoch currently served


class Follower:
    def __init__(self, index: int):
        self.index = index
        self.mounts: Dict[str, _Mount] = {}

    @protocol_effect("replica.subscribe")
    async def _subscribe(self, job_id: str, storage_url: str) -> bool:
        """Mount a job: full restore from the latest PUBLISHED manifest.
        Always re-resolves latest.json from storage — a reattach after
        death must never trust a controller-side epoch counter, which
        runs ahead of publication while a checkpoint is in flight (the
        follower_serves_unpublished_epoch mutant). Read-only by
        construction: the backend never claims a generation, so a
        follower can never fence the primary. False = nothing published
        yet (the manager backs off and retries)."""
        backend = StateBackend(storage_url, job_id)
        manifest = protocol.resolve_latest(backend.storage, backend.paths)
        if manifest is None:
            return False
        backend.restore_manifest = manifest
        mount = _Mount(backend)
        for node_id, op_idx in self._serve_ops(manifest):
            ti = TaskInfo(
                job_id=job_id, node_id=node_id, operator_name="replica",
                task_index=0, parallelism=1,
            )
            tm = TableManager(backend, ti, op_idx)
            await tm.open({SERVE_TABLE: global_table(SERVE_TABLE)})
            mount.tms[(node_id, op_idx)] = tm
        mount.epoch = int(manifest["epoch"])
        self._refresh_views(job_id, mount)
        self.mounts[job_id] = mount
        logger.info(
            "follower %d mounted %s at epoch %d (%d serve ops, %d views)",
            self.index, job_id, mount.epoch, len(mount.tms),
            len(mount.meta),
        )
        return True

    @protocol_effect("replica.tail")
    async def _tail(self, job_id: str, target: int) -> int:
        """Advance a mount by replaying the delta-chain SUFFIX of a
        newer published manifest (TableManager.tail_chains). The target
        manifest is read back from storage — a missing manifest file
        (retention raced the notification) degrades to re-resolving
        latest, never to trusting the in-memory target. Returns blobs
        applied (0 = already caught up)."""
        mount = self.mounts[job_id]
        backend = mount.backend
        manifest = protocol.load_manifest(backend.storage, backend.paths,
                                          target)
        if manifest is None:
            manifest = protocol.resolve_latest(backend.storage,
                                               backend.paths)
        if manifest is None or int(manifest["epoch"]) <= mount.epoch:
            return 0
        backend.restore_manifest = manifest
        applied = 0
        for tm in mount.tms.values():
            applied += tm.tail_chains()
        mount.epoch = int(manifest["epoch"])
        self._refresh_views(job_id, mount)
        return applied

    @protocol_effect("replica.serve")
    def read(self, job_id: str, table: str,
             key_values) -> Optional[dict]:
        """One key lookup from this follower's materialized view. None
        when the job/table is not mounted here (the gateway falls back
        worker-ward); otherwise {found, value, epoch} with epoch = the
        published manifest epoch the whole view reflects."""
        mount = self.mounts.get(job_id)
        if mount is None:
            return None
        view = self.view(job_id, table)
        if view is None:
            return None
        key = view.canon_key(tuple(key_values))
        found, value = view.read(key, mount.epoch)
        return {"found": found, "value": value, "epoch": mount.epoch}

    def view(self, job_id: str, table: str) -> Optional[ServeView]:
        mount = self.mounts.get(job_id)
        if mount is None:
            return None
        return (mount.views.get(table)
                or mount.views.get(str(table).split("@")[0]))

    # -- internals -----------------------------------------------------------

    @staticmethod
    def _serve_ops(manifest: dict):
        """Sorted (node_id, op_idx) pairs whose manifest entry carries a
        `__serve__` table."""
        pairs = set()
        for task in manifest.get("tasks", {}).values():
            for op_key, tables in (task.get("op_tables") or {}).items():
                if SERVE_TABLE in tables:
                    pairs.add((int(task["node_id"]), int(op_key[2:])))
        return sorted(pairs)

    def _refresh_views(self, job_id: str, mount: _Mount) -> None:
        """Rebuild the mount's ServeViews from the mirrored rows. The
        follower holds every subtask's rows in one table (parallelism-1
        restore unions the chains, the global merge resolving replicated
        copies by entry stamp), so the local view claims parallelism 1 —
        every key is owned — while `meta` keeps the worker describe()
        verbatim for the gateway's fallback routing."""
        views: Dict[str, ServeView] = {}
        meta: Dict[str, dict] = {}
        for (node_id, _op_idx), tm in mount.tms.items():
            table = tm.tables.get(SERVE_TABLE)
            if table is None:
                continue
            desc = table.get(META_KEY)
            if not isinstance(desc, dict):
                continue  # mirror chain predates its first seal
            name = desc["table"]
            view = ServeView(
                job_id=job_id, table=name, node_id=int(desc["node_id"]),
                task_index=0, parallelism=1,
                key_names=list(desc["key_fields"]),
                key_kinds=tuple(desc["key_kinds"]),
                value_names=list(desc["value_fields"]),
                kind=desc["kind"], live_mode=False,
            )
            served: Dict[Tuple, Any] = {}
            for k, v in table.items():
                if k == META_KEY or not isinstance(k, tuple):
                    continue
                served[k] = v
            view.served = served
            view.served_epoch = mount.epoch
            views[f"{name}@{node_id}"] = view
            if name in views:
                # bare-name collision across nodes: qualified names only
                views.pop(name, None)
            else:
                views[name] = view
            meta[name] = desc
        mount.views = views
        mount.meta = meta

    def stats(self) -> dict:
        return {
            "index": self.index,
            "mounts": {
                jid: {
                    "epoch": m.epoch,
                    "tables": {
                        name: len(v.served)
                        for name, v in m.views.items() if "@" not in name
                    },
                }
                for jid, m in self.mounts.items()
            },
        }
