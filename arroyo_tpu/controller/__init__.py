from .controller import ControllerServer  # noqa: F401
from .state_machine import JobState  # noqa: F401
