"""Protocol model checker tests (ISSUE 9).

Tier-1 coverage: extraction fidelity (the model runs on the SAME
TRANSITIONS table the controller does), the model<->code bijection
(strict-clean on the real tree, and drift actually detected), the
JobState graph properties (every state reachable, every non-terminal
state reaches a terminal — the dead/orphan-state detector), a
small-budget exhaustive exploration with zero violations, the mutant
regression corpus (every reintroduced bug — including the three
historical PR 2 protocol bugs — yields a counterexample that replays
deterministically and serializes to a valid seeded chaos plan), and the
SARIF emission both reporters share.

The full acceptance-scale exploration (2 workers x 3 epochs x 2
in-flight x all fault kinds x rescale) runs in the nightly model-check
CI lane; a slow-tier test pins it here too.
"""

import json
import subprocess
import sys
from collections import deque
from pathlib import Path

import pytest

from arroyo_tpu.analysis.model import explore as explore_mod
from arroyo_tpu.analysis.model import mutants as mutants_mod
from arroyo_tpu.analysis.model import replay as replay_mod
from arroyo_tpu.analysis.model.extract import (
    annotated_handlers,
    check_bijection,
    job_state_machine,
    load_project,
)
from arroyo_tpu.analysis.model.spec import (
    HANDLER_BINDINGS,
    Model,
    ModelConfig,
    USED_EFFECTS,
    VIOLATIONS,
)
from arroyo_tpu.controller import state_machine as sm

REPO = Path(__file__).resolve().parents[1]

_project = None


def project():
    global _project
    if _project is None:
        _project = load_project(REPO)
    return _project


def machine():
    return job_state_machine(project())


# -- extraction fidelity -----------------------------------------------------


def test_extraction_matches_runtime_table():
    members, terminals, table = machine()
    assert members == {s.name for s in sm.JobState}
    assert terminals == {
        s.name for s in sm.JobState if s.is_terminal()
    }
    runtime = {
        k.name: {v.name for v in vs} for k, vs in sm.TRANSITIONS.items()
    }
    assert table == runtime


def test_extraction_refuses_empty_anchor(tmp_path):
    from arroyo_tpu.analysis.model.extract import ExtractionError
    from arroyo_tpu.analysis.engine import parse_project

    (tmp_path / "controller").mkdir()
    (tmp_path / "controller" / "state_machine.py").write_text("x = 1\n")
    proj = parse_project(
        tmp_path, [tmp_path / "controller" / "state_machine.py"]
    )
    with pytest.raises(ExtractionError):
        job_state_machine(proj)


# -- model <-> code bijection ------------------------------------------------


def test_bijection_clean_on_real_tree():
    problems = check_bijection(project(), HANDLER_BINDINGS, USED_EFFECTS)
    assert not problems, "\n".join(problems)


def test_bijection_catches_missing_annotation(tmp_path):
    from arroyo_tpu.analysis.engine import parse_project

    # a mini-tree whose controller lacks the annotation the model binds
    (tmp_path / "controller").mkdir()
    (tmp_path / "controller" / "controller.py").write_text(
        "async def _checkpoint_start(job):\n    pass\n"
    )
    proj = parse_project(
        tmp_path, [tmp_path / "controller" / "controller.py"]
    )
    problems = check_bijection(
        proj, {"ctrl.checkpoint_start":
               ("controller/controller.py", "_checkpoint_start")},
        {"ctrl.checkpoint_start"},
    )
    assert any("not annotated" in p for p in problems)


def test_bijection_catches_unknown_annotation(tmp_path):
    from arroyo_tpu.analysis.engine import parse_project

    (tmp_path / "controller").mkdir()
    (tmp_path / "controller" / "controller.py").write_text(
        "def protocol_effect(n):\n"
        "    def deco(fn):\n        return fn\n    return deco\n\n"
        "@protocol_effect('ctrl.not_a_real_effect')\n"
        "async def _mystery(job):\n    pass\n"
    )
    proj = parse_project(
        tmp_path, [tmp_path / "controller" / "controller.py"]
    )
    problems = check_bijection(proj, {}, set())
    assert any("no such binding" in p for p in problems)


def test_every_binding_annotated_exactly_once():
    found = annotated_handlers(project())
    for effect, (suffix, fn) in HANDLER_BINDINGS.items():
        sites = {(p, f) for (p, f, _ln) in found.get(effect, ())}
        assert len(sites) == 1, (effect, sites)


# -- JobState graph properties (satellite: dead/orphan-state detector) -------


def test_every_jobstate_reachable_from_initial():
    members, _terminals, table = machine()
    seen = {"CREATED"}
    work = deque(seen)
    while work:
        cur = work.popleft()
        for nxt in table.get(cur, ()):
            if nxt not in seen:
                seen.add(nxt)
                work.append(nxt)
    assert seen == members, f"orphan states: {sorted(members - seen)}"


def test_every_nonterminal_reaches_a_terminal():
    members, terminals, table = machine()
    # backward reachability from terminals over the declared edges
    rev = {}
    for src, dsts in table.items():
        for d in dsts:
            rev.setdefault(d, set()).add(src)
    ok = set(terminals)
    work = deque(ok)
    while work:
        cur = work.popleft()
        for p in rev.get(cur, ()):
            if p not in ok:
                ok.add(p)
                work.append(p)
    stuck = members - ok
    assert not stuck, f"states that cannot terminate: {sorted(stuck)}"


def test_terminal_states_have_no_outgoing_edges():
    _members, terminals, table = machine()
    for t in terminals:
        assert t not in table or not table[t], (
            f"terminal state {t} has outgoing transitions"
        )


# -- exhaustive exploration (tier-1 smoke; full config runs nightly) ---------

SMOKE = ModelConfig(workers=2, epochs=2, inflight=2, faults=1, restarts=1,
                    rescales=0,
                    fault_kinds=("fault.kill", "fault.cas_race"))


def test_smoke_exploration_clean_and_exhaustive():
    _m, terminals, table = machine()
    res = explore_mod.explore(
        Model(SMOKE, table, terminals), budget=200_000
    )
    assert res.exhaustive, "smoke config must fit the budget"
    assert not res.violations, [t.violation for t in res.violations]
    # sanity: the space is non-trivial and runs actually terminate
    assert res.states > 1_000
    assert res.terminal_states > 0


def test_exploration_reports_truncation():
    _m, terminals, table = machine()
    res = explore_mod.explore(Model(SMOKE, table, terminals), budget=50)
    assert not res.exhaustive


@pytest.mark.slow
def test_full_acceptance_config_exhaustive_clean():
    """ISSUE 9 acceptance: >=2 workers, >=3 epochs, >=2 inflight, ALL
    fault event types enabled, a rescale — zero violations, exhaustive."""
    _m, terminals, table = machine()
    cfg = ModelConfig(workers=2, epochs=3, inflight=2, faults=1,
                      restarts=2, rescales=1)
    res = explore_mod.explore(
        Model(cfg, table, terminals), budget=2_000_000
    )
    assert res.exhaustive
    assert not res.violations, [t.violation for t in res.violations]
    assert res.states > 100_000


def test_overlap_smoke_exploration_clean_and_exhaustive():
    """Generation-overlap rescale (ISSUE 15), tier-1 smoke: the overlap
    window (prepare while the old generation drains, activate at the
    durable rescale checkpoint, RESCALING -> RUNNING) is exhaustive-clean
    with a kill/reschedule-fail fault budget."""
    _m, terminals, table = machine()
    cfg = ModelConfig(
        workers=2, epochs=2, inflight=2, faults=1, restarts=1,
        rescales=1, overlap=1,
        fault_kinds=("fault.kill", "fault.reschedule_fail"),
    )
    res = explore_mod.explore(Model(cfg, table, terminals), budget=500_000)
    assert res.exhaustive
    assert not res.violations, [t.violation for t in res.violations]
    # the overlap path is actually taken: activation events exist on the
    # explored graph — pin it by finding a trace-free exhaustive run with
    # a non-trivial space (prepare/activate multiply the rescale states)
    assert res.states > 10_000


@pytest.mark.slow
def test_full_acceptance_overlap_exhaustive_clean():
    """ISSUE 15 acceptance: the overlap protocol is exhaustive-clean at
    the acceptance config — 2 workers x 3 epochs x 2 inflight x the full
    fault-kind set x a rescale THROUGH the overlap window."""
    _m, terminals, table = machine()
    cfg = ModelConfig(workers=2, epochs=3, inflight=2, faults=1,
                      restarts=2, rescales=1, overlap=1)
    res = explore_mod.explore(
        Model(cfg, table, terminals), budget=2_000_000
    )
    assert res.exhaustive
    assert not res.violations, [t.violation for t in res.violations]
    assert res.states > 200_000


# -- mutant regression corpus ------------------------------------------------


@pytest.mark.parametrize("por", [True, False], ids=["por", "no-por"])
@pytest.mark.parametrize("name", sorted(mutants_mod.MUTANTS))
def test_mutant_yields_counterexample(name, por):
    _m, terminals, table = machine()
    m = mutants_mod.get_mutant(name)
    res = explore_mod.explore(
        Model(m.config, table, terminals), budget=300_000, por=por,
        first_violation=True,
    )
    kinds = [t.violation.split(":", 1)[0] for t in res.violations]
    assert m.expect_violation in kinds, (
        f"{name}: expected {m.expect_violation}, got {kinds}"
    )


def test_corpus_includes_the_three_historical_bugs():
    hist = {m.name for m in mutants_mod.historical_mutants()}
    assert hist == {
        "stop_strands_commit",
        "commit_fanout_all_workers",
        "no_liveness_in_stop_wait",
    }


def test_overlap_mutant_counterexample_crosses_the_overlap_window():
    """The overlap_double_emission counterexample is a real overlap run:
    it prepares BEFORE the stop epoch publishes, activates, and the new
    generation re-seals an epoch the old generation committed."""
    trace, _table, _terminals = _first_counterexample(
        "overlap_double_emission"
    )
    labels = [lb for lb, _arg in trace.events]
    assert "overlap.prepare" in labels
    assert "overlap.activate" in labels
    assert labels.index("overlap.prepare") < labels.index("stop.publish")
    assert trace.violation.startswith(VIOLATIONS.OVERLAP_EMIT)


def _first_counterexample(name):
    _m, terminals, table = machine()
    m = mutants_mod.get_mutant(name)
    res = explore_mod.explore(
        Model(m.config, table, terminals), budget=300_000,
        first_violation=True,
    )
    hit = [t for t in res.violations
           if t.violation.split(":", 1)[0] == m.expect_violation]
    assert hit, f"{name} produced no counterexample"
    return hit[0], table, terminals


@pytest.mark.parametrize("name", sorted(mutants_mod.MUTANTS))
def test_counterexample_replays_deterministically(name):
    trace, table, terminals = _first_counterexample(name)
    m = mutants_mod.get_mutant(name)
    # replay the exact event list: same violation kind, twice
    for _ in range(2):
        got = replay_mod.replay_trace(trace, table, terminals)
        assert got.split(":", 1)[0] == m.expect_violation
    # a JSON round-trip must not change the replay
    back = explore_mod.Trace.from_json(trace.to_json())
    got = replay_mod.replay_trace(back, table, terminals)
    assert got.split(":", 1)[0] == m.expect_violation


def test_replay_divergence_detected():
    trace, table, terminals = _first_counterexample("stop_strands_commit")
    bogus = explore_mod.Trace(
        violation=trace.violation,
        events=[("w.flush", (0, 99))] + trace.events,
        config=trace.config, mutant=trace.mutant,
    )
    with pytest.raises(replay_mod.ReplayDivergence):
        replay_mod.replay_trace(bogus, table, terminals)


# -- counterexample -> chaos plan (the replay pipeline) ----------------------


def test_trace_serializes_to_valid_seeded_fault_plan():
    from arroyo_tpu.chaos import FaultPlan

    trace, _table, _terminals = _first_counterexample(
        "no_liveness_in_stop_wait"
    )
    plan = replay_mod.trace_to_fault_plan(trace)
    # the model's kill fault maps to the registered worker.kill seam
    points = [s.point for s in plan.specs]
    assert "worker.kill" in points
    # every point passed FaultPlan's registry validation on construction;
    # a JSON round trip preserves the schedule exactly
    again = FaultPlan.from_json(plan.to_json())
    assert again.to_json() == plan.to_json()
    # determinism: same trace content -> same seed -> same plan
    plan2 = replay_mod.trace_to_fault_plan(trace)
    assert plan2.seed == plan.seed
    assert plan2.to_json() == plan.to_json()


def test_counterexample_payload_is_drill_consumable(tmp_path):
    from arroyo_tpu.chaos import FaultPlan

    trace, _table, _terminals = _first_counterexample(
        "unstamped_data_paths"
    )
    payload = replay_mod.counterexample_payload(trace)
    # what tools/chaos_drill.py --plan loads: payload["fault_plan"]
    plan = FaultPlan.from_json(json.dumps(payload["fault_plan"]))
    assert plan.specs, "counterexample with faults must carry a schedule"
    assert payload["trace"]["violation"].startswith(
        VIOLATIONS.OVERWRITE
    )
    # round-trips through disk (the --trace-dir artifact)
    p = tmp_path / "ce.json"
    p.write_text(json.dumps(payload))
    reloaded = json.loads(p.read_text())
    back = explore_mod.Trace.from_json(reloaded["trace"])
    assert back.events == trace.events


def test_every_model_fault_maps_to_registered_point():
    from arroyo_tpu.chaos import FAULT_POINTS

    for label, (point, _m, _p, _w) in replay_mod.FAULT_MAP.items():
        assert point in FAULT_POINTS, (label, point)


# -- SARIF -------------------------------------------------------------------


def test_sarif_document_from_lint_findings():
    from arroyo_tpu.analysis.core import Finding
    from arroyo_tpu.analysis.reporters import sarif_document

    doc = sarif_document([
        Finding(rule="PRO004", path="a/b.py", line=3, col=1, message="m"),
    ])
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "arroyolint"
    assert [r["id"] for r in run["tool"]["driver"]["rules"]] == ["PRO004"]
    res = run["results"][0]
    assert res["ruleId"] == "PRO004"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "a/b.py"
    assert loc["region"]["startLine"] == 3
    assert res["partialFingerprints"]["arroyolint/v1"]


def test_lint_cli_sarif(tmp_path):
    out = tmp_path / "lint.sarif"
    r = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint.py"),
         "--sarif", str(out)],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(out.read_text())
    assert doc["version"] == "2.1.0"
    assert doc["runs"][0]["results"] == []  # tree is clean


# -- CLI ---------------------------------------------------------------------


def test_model_check_cli_smoke(tmp_path):
    out = tmp_path / "summary.json"
    r = subprocess.run(
        [sys.executable, str(REPO / "tools" / "model_check.py"),
         "--smoke", "--out", str(out)],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "bijection: clean" in r.stdout
    doc = json.loads(out.read_text())
    assert doc["bijection"] == "clean"
    run = doc["runs"][0]
    assert run["exhaustive"] and not run["violations"]


def test_model_check_cli_single_mutant(tmp_path):
    r = subprocess.run(
        [sys.executable, str(REPO / "tools" / "model_check.py"),
         "--mutant", "publish_without_reports",
         "--trace-dir", str(tmp_path)],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    payload = json.loads(
        (tmp_path / "publish_without_reports.json").read_text()
    )
    assert payload["trace"]["violation"].startswith(VIOLATIONS.ATOMIC)


# -- multitenant: 2 jobs x shared multiplexed workers (ISSUE 10) -------------


def test_multitenant_faithful_clean_and_exhaustive():
    """The 2-job shared-worker configuration: a shared worker kill fails
    both jobs at once (shared fate), each recovers independently, and
    every JobState move of either job goes through the extracted table.
    The faithful model must explore exhaustively with zero violations."""
    from arroyo_tpu.analysis.model import multitenant as mt

    _members, terminals, table = machine()
    res = mt.check_multitenant(
        mt.MTConfig(), transitions=table, terminals=terminals
    )
    assert res.exhaustive, f"budget truncated at {res.states} states"
    assert res.clean, [t.violation for t in res.violations]
    assert res.states > 10_000  # the product space is genuinely explored


@pytest.mark.parametrize(
    "name", sorted(__import__(
        "arroyo_tpu.analysis.model.multitenant",
        fromlist=["MT_MUTANTS"],
    ).MT_MUTANTS),
)
def test_multitenant_mutant_yields_counterexample(name):
    """Each cross-job mutant (a barrier leaking across job namespaces on
    the shared worker; a teardown wiping the co-tenant's namespace) must
    produce a counterexample of its declared violation kind."""
    from arroyo_tpu.analysis.model import multitenant as mt

    _members, terminals, table = machine()
    m = mt.MT_MUTANTS[name]
    res = mt.check_multitenant(
        m.config, transitions=table, terminals=terminals
    )
    kinds = {t.violation.split(":", 1)[0] for t in res.violations}
    assert m.expect_violation in kinds, (name, kinds)
    # the counterexample carries a replayable event path from the
    # initial state
    trace = next(t for t in res.violations
                 if t.violation.startswith(m.expect_violation))
    assert trace.events and trace.events[0][0] in (
        "mt.schedule_init", "mt.kill_worker"
    )


# -- shared-plan: N tenants mounted on one operator chain (ISSUE 16) ---------


def test_sharedplan_faithful_clean_and_exhaustive():
    """The shared-plan lifecycle: one host barrier, per-tenant epoch
    chains reconciled by the publication gate, refcounted detach, a kill
    budget. The faithful model must explore exhaustively with zero
    violations at the acceptance configuration."""
    from arroyo_tpu.analysis.model import sharedplan as sp

    res = sp.check_sharedplan(sp.SPConfig())
    assert res.exhaustive, f"budget truncated at {res.states} states"
    assert res.clean, [t.violation for t in res.violations]
    assert res.states > 100  # host x tenant positions genuinely explored


@pytest.mark.parametrize(
    "name", sorted(__import__(
        "arroyo_tpu.analysis.model.sharedplan",
        fromlist=["SP_MUTANTS"],
    ).SP_MUTANTS),
)
def test_sharedplan_mutant_yields_counterexample(name):
    """Each shared-lifecycle mutant (publication gate leaked across
    tenants; detach leaving its gate membership; refcount-ignoring
    teardown) must produce a counterexample of its declared violation
    kind, and the counterexample must REPLAY deterministically to the
    same violation."""
    from arroyo_tpu.analysis.model import sharedplan as sp

    m = sp.SP_MUTANTS[name]
    res = sp.check_sharedplan(m.config)
    kinds = {t.violation.split(":", 1)[0] for t in res.violations}
    assert m.expect_violation in kinds, (name, kinds)
    trace = next(t for t in res.violations
                 if t.violation.startswith(m.expect_violation))
    got = sp.replay_sharedplan(trace)
    assert got.split(":", 1)[0] == m.expect_violation


def test_sharedplan_leaked_barrier_plan_is_seeded_kill():
    """The leaked_barrier_across_tenants counterexample must serialize
    to a seeded chaos FaultPlan containing the worker kill that
    demonstrates the modeled loss end-to-end (the drill CI replays)."""
    from arroyo_tpu.analysis.model import sharedplan as sp

    m = sp.SP_MUTANTS["leaked_barrier_across_tenants"]
    res = sp.check_sharedplan(m.config)
    trace = next(t for t in res.violations
                 if t.violation.startswith(m.expect_violation))
    payload = sp.sp_counterexample_payload(trace)
    assert payload["fault_plan"]["faults"], payload
    assert payload["fault_plan"]["faults"][0]["point"] == "worker.kill"
    # deterministic: same trace -> same seed -> same plan
    assert (sp.sp_trace_to_fault_plan(trace).seed
            == sp.sp_trace_to_fault_plan(trace).seed)


def test_model_check_cli_shared_lane(tmp_path):
    r = subprocess.run(
        [sys.executable, str(REPO / "tools" / "model_check.py"),
         "--shared", "--trace-dir", str(tmp_path)],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    payload = json.loads(
        (tmp_path / "leaked_barrier_across_tenants.json").read_text()
    )
    assert payload["trace"]["violation"].startswith(
        "tenant-position-behind-host-restore"
    )
    assert payload["fault_plan"]["faults"]
