"""_LazyFilteredBatch coverage (ADVICE round 5): every expression family
must evaluate correctly through a PARTIALLY-selective predicate — the
only path that builds the lazy filtered view (zero-pass and all-pass
predicates bypass it) — and an expression reaching for an unsupported
RecordBatch attribute must fail with a descriptive AttributeError naming
the view, not an anonymous duck-typing error."""

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pytest

from arroyo_tpu.sql.expressions import (
    CompiledProjection,
    Scope,
    _LazyFilteredBatch,
    bind,
)
from arroyo_tpu.sql.parser import parse_expr_text


def _batch(n=10):
    return pa.RecordBatch.from_arrays(
        [
            pa.array(np.arange(n, dtype=np.int64)),
            pa.array(np.arange(n, dtype=np.float64) * 1.5),
            pa.array([f"s{i}" for i in range(n)]),
            pa.array(
                np.arange(n, dtype=np.int64) * 1_000_000_000
            ).cast(pa.timestamp("ns")),
            pa.array([[i, i + 1] for i in range(n)],
                     type=pa.list_(pa.int64())),
        ],
        names=["a", "f", "s", "t", "l"],
    )


PREDICATE = "a % 2 = 0"  # partially selective: keeps half the rows

# one representative expression per family (arithmetic, comparison,
# boolean logic, CASE, CAST, null handling, math fn, string fns, LIKE,
# temporal extract/trunc, list ops)
FAMILY_EXPRS = [
    "a * 3 + 1",
    "f / 2.0 - a",
    "a >= 4",
    "a > 1 AND NOT (a = 6)",
    "CASE WHEN a < 4 THEN a ELSE -a END",
    "CAST(a AS DOUBLE) + 0.5",
    "coalesce(nullif(a, 2), -1)",
    "abs(a - 5)",
    "concat(s, '_x')",
    "upper(s)",
    "substr(s, 1, 1)",
    "s LIKE 's%'",
    "extract(second FROM t)",
    "date_trunc('minute', t)",
    "array_element(l, 1)",
    "cardinality(l)",
]


@pytest.mark.parametrize("expr_text", FAMILY_EXPRS)
def test_expression_families_through_partial_predicate(expr_text):
    batch = _batch()
    scope = Scope.from_schema(batch.schema)
    pred = bind(parse_expr_text(PREDICATE), scope)
    expr = bind(parse_expr_text(expr_text), scope)
    proj = CompiledProjection(
        [expr], pa.schema([pa.field("x", expr.dtype)]), predicate=pred
    )
    got = proj(batch)
    assert got is not None
    # reference: eager filter first, then evaluate (no lazy view)
    mask = pc.fill_null(pred.eval(batch), False)
    eager = batch.filter(mask)
    assert 0 < eager.num_rows < batch.num_rows, "predicate must be partial"
    want = expr.eval(eager)
    if not want.type.equals(got.column(0).type):
        want = want.cast(got.column(0).type)
    assert got.column(0).to_pylist() == want.to_pylist()
    assert got.num_rows == eager.num_rows


def test_lazy_view_names_itself_on_unsupported_attribute():
    batch = _batch()
    mask = pa.array(np.arange(batch.num_rows) % 2 == 0)
    view = _LazyFilteredBatch(batch, mask, 5)
    assert view.num_rows == 5
    assert view.column(0).to_pylist() == [0, 2, 4, 6, 8]
    with pytest.raises(AttributeError, match="_LazyFilteredBatch"):
        view.columns  # noqa: B018 - attribute probe is the assertion
    with pytest.raises(AttributeError, match="select"):
        view.select([0])
