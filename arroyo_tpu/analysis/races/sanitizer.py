"""Dynamic interleaving sanitizer: the runtime half of arroyoracer.

The static rules prove what *can* interleave; this module observes what
*does*. Opt-in (``ARROYO_RACE_SANITIZER=1`` or :func:`enable`): every
class decorated with ``@shared_state``/``@guarded_by`` gets class-level
``__getattribute__``/``__setattr__`` instrumentation that records each
access to a *declared* field as ``(task root, yield epoch, kind, site)``
and checks two conflict shapes as they happen:

lost-update (``read-await-write``)
    root A reads a field, root B writes it, then A writes it back
    without re-reading — A's write is computed from a stale value and
    B's update is silently destroyed. This is PR 9's stop-path bug and
    PR 10's heartbeat-restore bug, observed live instead of post-hoc.
    ``multi_writer`` does NOT waive it: last-writer-wins is a defensible
    policy, resurrecting overwritten state is not.

write/write
    two different task roots write a field not declared
    ``multi_writer`` — the dynamic mirror of RACE001.

Design notes, in decreasing order of subtlety:

* In single-threaded asyncio, *any* interleaved access by another root
  between A's read and A's write proves a yield happened in between —
  so lost-update detection needs only access ordering, not precise
  yield-epoch bookkeeping. Epochs (a global counter bumped whenever the
  recording (thread, task) changes) are still recorded: they key the
  access log and the Perfetto dump, where "which scheduling burst did
  this land in" is what a human reads.
* Instrumentation is per-class, not per-object (no proxies): wrapping
  instances would break ``isinstance`` and identity checks throughout
  the engine. :func:`disable` restores the original class attributes.
* The first write to a not-yet-existing attribute is initialization
  (the constructor publishing the field) and seeds no conflict state —
  otherwise every field would count its creator as a concurrent writer.
* Accesses can arrive from storage/executor threads (FaultPlan's seams
  fire under them), so recording takes a ``threading.Lock`` and the
  task root falls back from the ContextVar to "main".

Zero overhead when disabled beyond an ``is_enabled()`` check at class
decoration time; nothing is imported into hot paths.
"""

from __future__ import annotations

import contextvars
import json
import os
import sys
import threading
from typing import Any, Dict, List, Optional, Tuple

ENV_FLAG = "ARROYO_RACE_SANITIZER"

_MAX_RECORDS = 200_000  # ring-buffer cap on the access log

_enabled = False
_lock = threading.Lock()

_task_root: "contextvars.ContextVar[str]" = contextvars.ContextVar(
    "arroyo_race_task_root", default="main"
)

# class -> {"fields": {...}, "multi_writer": {...},
#           "saved": {attr: original-or-None}}
_instrumented: Dict[type, dict] = {}

_records: List[dict] = []
_dropped = 0
_conflicts: List[dict] = []
_seq = 0
_epoch = 0
_last_actor: Optional[Tuple[int, int]] = None  # (thread ident, task id)

# (obj id, field) -> {"readers": {root: seq}, "last_write": (root, seq, site)}
_state: Dict[Tuple[int, str], dict] = {}


def is_enabled() -> bool:
    return _enabled


def enabled_by_env() -> bool:
    return os.environ.get(ENV_FLAG, "") == "1"


def enable() -> None:
    """Switch the sanitizer on and instrument every decorated class."""
    global _enabled
    if _enabled:
        return
    _enabled = True
    from .annotations import decorated_classes

    for cls in decorated_classes():
        instrument_class(cls)


def disable() -> None:
    """Switch off and restore original class attributes; keeps findings."""
    global _enabled
    _enabled = False
    for cls, info in list(_instrumented.items()):
        for attr, orig in info["saved"].items():
            if orig is None:
                try:
                    delattr(cls, attr)
                except AttributeError:
                    pass
            else:
                setattr(cls, attr, orig)
    _instrumented.clear()


def maybe_enable_from_env() -> bool:
    if enabled_by_env():
        enable()
        return True
    return False


def reset() -> None:
    """Drop the access log, conflicts, and per-object state (keeps on)."""
    global _seq, _epoch, _last_actor, _dropped
    with _lock:
        _records.clear()
        _conflicts.clear()
        _state.clear()
        _seq = 0
        _epoch = 0
        _dropped = 0
        _last_actor = None


class task_root:
    """Name the current task's spawn root for sanitizer reports.

    Context manager placed at task-root entry points (the runner loop,
    heartbeat loop, pump loops, drive task...). Setting a ContextVar in
    the task's own context scopes the name to that task and everything
    it awaits — exactly the static analysis' root-propagation rule.
    """

    __slots__ = ("name", "_token")

    def __init__(self, name: str):
        self.name = name
        self._token = None

    def __enter__(self) -> "task_root":
        self._token = _task_root.set(self.name)
        return self

    def __exit__(self, *exc) -> None:
        if self._token is not None:
            _task_root.reset(self._token)
            self._token = None


def set_task_root(name: str) -> None:
    """Set-and-forget variant for the first line of a root coroutine:
    the ContextVar lives in the task's own context, so it dies with the
    task — no reset needed, no indentation tax on instrumented loops."""
    _task_root.set(name)


def current_root() -> str:
    return _task_root.get()


def instrument_class(cls: type) -> None:
    """Install access recording for `cls`'s declared fields."""
    from .annotations import SHARED_STATE_ATTR

    if cls in _instrumented:
        return
    decl = getattr(cls, SHARED_STATE_ATTR, None)
    if not decl:
        return
    fields = frozenset(decl)
    multi = frozenset(f for f, meta in decl.items() if meta["multi_writer"])
    saved = {
        "__setattr__": cls.__dict__.get("__setattr__"),
        "__getattribute__": cls.__dict__.get("__getattribute__"),
    }
    orig_set = cls.__setattr__
    orig_get = cls.__getattribute__
    cls_name = cls.__name__

    def __setattr__(self, name, value):
        if _enabled and name in fields:
            init = name not in getattr(self, "__dict__", {})
            _record(self, cls_name, name, "init" if init else "write",
                    name in multi)
        orig_set(self, name, value)

    def __getattribute__(self, name):
        if _enabled and name in fields:
            _record(self, cls_name, name, "read", name in multi)
        return orig_get(self, name)

    cls.__setattr__ = __setattr__
    cls.__getattribute__ = __getattribute__
    _instrumented[cls] = {"fields": fields, "multi": multi, "saved": saved}


def _caller_site() -> str:
    f = sys._getframe(2)
    here = __file__
    while f is not None and f.f_code.co_filename == here:
        f = f.f_back
    if f is None:
        return "?"
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


def _record(obj: Any, cls_name: str, field: str, kind: str,
            multi_writer: bool) -> None:
    global _seq, _epoch, _last_actor, _dropped
    try:
        import asyncio

        task = asyncio.current_task()
    except RuntimeError:
        task = None
    root = _task_root.get()
    site = _caller_site()
    actor = (threading.get_ident(), id(task) if task else 0)
    with _lock:
        _seq += 1
        if actor != _last_actor:
            _epoch += 1
            _last_actor = actor
        rec = {
            "seq": _seq, "epoch": _epoch, "root": root, "class": cls_name,
            "field": field, "kind": kind, "site": site,
        }
        if len(_records) >= _MAX_RECORDS:
            _records.pop(0)
            _dropped += 1
        _records.append(rec)
        key = (id(obj), field)
        st = _state.setdefault(key, {"readers": {}, "last_write": None})
        if kind == "read":
            st["readers"][root] = (_seq, site)
        elif kind == "init":
            # constructor publishing the field: reset conflict state
            st["readers"] = {root: (_seq, site)}
            st["last_write"] = None
        else:  # write
            lw = st["last_write"]
            my_read = st["readers"].get(root)
            if lw is not None and lw[0] != root:
                if my_read is not None and my_read[0] < lw[1]:
                    _conflicts.append({
                        "kind": "lost-update",
                        "class": cls_name, "field": field,
                        "root": root, "other_root": lw[0],
                        "read_site": my_read[1],
                        "intervening_write_site": lw[2],
                        "write_site": site,
                        "detail": (
                            f"{root} read {cls_name}.{field} at "
                            f"{my_read[1]}, {lw[0]} wrote it at {lw[2]}, "
                            f"then {root} wrote it back at {site} without "
                            f"re-reading — {lw[0]}'s update is destroyed"
                        ),
                    })
                elif not multi_writer:
                    _conflicts.append({
                        "kind": "write/write",
                        "class": cls_name, "field": field,
                        "root": root, "other_root": lw[0],
                        "other_site": lw[2], "write_site": site,
                        "detail": (
                            f"{cls_name}.{field} written by roots "
                            f"{lw[0]} ({lw[2]}) and {root} ({site}) but "
                            f"not declared multi_writer"
                        ),
                    })
            st["last_write"] = (root, _seq, site)
            st["readers"][root] = (_seq, site)


def conflicts() -> List[dict]:
    with _lock:
        return list(_conflicts)


def access_log() -> List[dict]:
    with _lock:
        return list(_records)


def report() -> dict:
    with _lock:
        return {
            "enabled": _enabled,
            "accesses": len(_records) + _dropped,
            "dropped": _dropped,
            "epochs": _epoch,
            "conflicts": list(_conflicts),
        }


def dump(path: str) -> None:
    """Write the access log + conflicts as JSON (CI failure artifact)."""
    doc = report()
    doc["log"] = access_log()
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)


def dump_trace(path: str) -> None:
    """Write the access log as a Perfetto-loadable Chrome trace: one
    instant event per access, one track per task root, conflicts on
    their own track — scrubbing the interleaving beats reading seqs."""
    roots = sorted({r["root"] for r in access_log()}) or ["main"]
    tid_of = {root: i + 1 for i, root in enumerate(roots)}
    events: List[dict] = [{
        "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
        "args": {"name": f"root:{root}"},
    } for root, tid in tid_of.items()]
    events.append({
        "name": "thread_name", "ph": "M", "pid": 1, "tid": 0,
        "args": {"name": "conflicts"},
    })
    for rec in access_log():
        events.append({
            "name": f"{rec['kind']} {rec['class']}.{rec['field']}",
            "ph": "i", "s": "t", "pid": 1,
            "tid": tid_of.get(rec["root"], 0),
            "ts": rec["seq"] * 10,  # synthetic time: order is the data
            "args": {"site": rec["site"], "epoch": rec["epoch"]},
        })
    for i, c in enumerate(conflicts()):
        events.append({
            "name": f"{c['kind']} {c['class']}.{c['field']}",
            "ph": "i", "s": "g", "pid": 1, "tid": 0, "ts": i * 10,
            "args": {k: v for k, v in c.items() if isinstance(v, str)},
        })
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, fh)
