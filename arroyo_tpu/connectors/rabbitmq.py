"""RabbitMQ connector (reference: crates/arroyo-connectors/src/rabbitmq/,
467 LoC): durable queues with consumer prefetch, at-least-once delivery
(messages are acked at the CHECKPOINT barrier, after their rows are
flushed downstream and covered by the epoch — a crash before the ack
redelivers, never loses), persistent delivery on the sink, and optional
exchange/routing-key addressing. Client gated on aio-pika/pika."""

from __future__ import annotations

import asyncio
from typing import Optional

from ..operators.base import Operator, SourceFinishType, SourceOperator
from ..formats.de import Deserializer
from ..formats.ser import Serializer
from ._gated import require_client
from .base import ConnectionSchema, Connector, register_connector


class RabbitmqSource(SourceOperator):
    def __init__(self, url: str, queue: str, schema, format, bad_data,
                 prefetch: int = 100):
        super().__init__("rabbitmq_source")
        self.url = url
        self.queue = queue
        self.out_schema = schema
        self.format = format
        self.bad_data = bad_data
        self.prefetch = prefetch
        self._unacked: list = []

    async def handle_checkpoint(self, barrier, ctx, collector):
        # rows from these messages were flushed before the barrier, so
        # the epoch covers them — safe to ack (at-least-once: a crash
        # before this point redelivers)
        unacked, self._unacked = self._unacked, []
        for m in unacked:
            await m.ack()

    async def run(self, ctx, collector) -> SourceFinishType:
        aio_pika = require_client("aio_pika")
        deser = Deserializer(self.out_schema, format=self.format or "json",
                             bad_data=self.bad_data)
        conn = await aio_pika.connect_robust(self.url)
        async with conn:
            channel = await conn.channel()
            await channel.set_qos(prefetch_count=self.prefetch)
            queue = await channel.declare_queue(self.queue, durable=True)
            async with queue.iterator() as it:
                # persistent in-flight __anext__: an idle queue must not
                # starve control handling, and cancelling __anext__ (as
                # wait_for would) can orphan the client's internal getter
                ait = it.__aiter__()
                pending = None
                while True:
                    finish = await ctx.check_control(collector)
                    if finish is not None:
                        if pending is not None:
                            pending.cancel()
                        return finish
                    if pending is None:
                        pending = asyncio.ensure_future(ait.__anext__())
                    done, _ = await asyncio.wait({pending}, timeout=0.05)
                    if not done:
                        await self.flush_buffer(ctx, collector)
                        continue
                    task, pending = pending, None
                    try:
                        message = task.result()
                    except StopAsyncIteration:
                        break
                    for row in deser.deserialize_slice(
                        message.body, error_reporter=ctx.error_reporter
                    ):
                        ctx.buffer_row(row)
                    self._unacked.append(message)
                    if ctx.should_flush():
                        await self.flush_buffer(ctx, collector)
                # stream ended: the tail is flushed at source close and
                # the pipeline drains, so ack the remainder
                await self.flush_buffer(ctx, collector)
                for m in self._unacked:
                    await m.ack()
                self._unacked = []
        return SourceFinishType.FINAL


class RabbitmqSink(Operator):
    def __init__(self, url: str, queue: str, format,
                 exchange: Optional[str] = None,
                 routing_key: Optional[str] = None):
        super().__init__("rabbitmq_sink")
        self.url = url
        self.queue = queue
        self.exchange_name = exchange
        self.routing_key = routing_key or queue
        self.serializer = Serializer(format=format or "json")
        self.conn = None
        self.channel = None
        self.exchange = None

    async def on_start(self, ctx):
        aio_pika = require_client("aio_pika")
        self.conn = await aio_pika.connect_robust(self.url)
        self.channel = await self.conn.channel()
        if self.exchange_name:
            self.exchange = await self.channel.get_exchange(
                self.exchange_name
            )
        else:
            self.exchange = self.channel.default_exchange
        self._aio_pika = aio_pika

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        persistent = getattr(
            self._aio_pika, "DeliveryMode", None
        )
        for rec in self.serializer.serialize(batch):
            msg = self._aio_pika.Message(
                body=rec,
                **(
                    {"delivery_mode": persistent.PERSISTENT}
                    if persistent is not None else {}
                ),
            )
            await self.exchange.publish(msg, routing_key=self.routing_key)

    async def on_close(self, ctx, collector, is_eod: bool):
        if self.conn is not None:
            await self.conn.close()
        return None


@register_connector
class RabbitmqConnector(Connector):
    name = "rabbitmq"
    description = "RabbitMQ source and sink"
    source = True
    sink = True
    config_schema = {
        "url": {"type": "string", "required": True},
        "queue": {"type": "string", "required": True},
        "prefetch": {"type": "integer"},
        "exchange": {"type": "string"},
        "routing_key": {"type": "string"},
    }

    def validate_options(self, options, schema):
        for k in ("url", "queue"):
            if k not in options:
                raise ValueError(f"rabbitmq requires a {k} option")
        return {
            "url": options["url"],
            "queue": options["queue"],
            "prefetch": int(options.get("prefetch", 100)),
            "exchange": options.get("exchange"),
            "routing_key": options.get("routing_key"),
        }

    def make_source(self, config, schema: ConnectionSchema):
        return RabbitmqSource(config["url"], config["queue"],
                              config.get("schema"), config.get("format"),
                              config.get("bad_data", "fail"),
                              prefetch=config.get("prefetch", 100))

    def make_sink(self, config, schema: ConnectionSchema):
        return RabbitmqSink(config["url"], config["queue"],
                            config.get("format"),
                            exchange=config.get("exchange"),
                            routing_key=config.get("routing_key"))
