CREATE TABLE cars (
  timestamp TIMESTAMP,
  driver_id BIGINT,
  event_type TEXT,
  location TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/cars.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE memory (
  event_type TEXT,
  location TEXT,
  driver_id BIGINT
);
CREATE TABLE cars_output (
  driver_id BIGINT,
  event_type TEXT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO memory SELECT event_type, location, driver_id FROM cars;
INSERT INTO cars_output SELECT driver_id, event_type FROM memory;
