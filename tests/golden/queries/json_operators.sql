CREATE TABLE cars (
  value JSON
) WITH (
  connector = 'single_file',
  path = '$input_dir/cars.json',
  format = 'json',
  type = 'source',
  unstructured = 'true'
);
CREATE TABLE sink WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO sink
SELECT 'test' as a, value->'driver_id' as b, value->'event_type' as c,
       value->'not_a_field' as d
FROM cars;
