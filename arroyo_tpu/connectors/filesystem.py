"""Filesystem connector: file source + rolling Parquet/JSON sink.

Capability parity with the reference's filesystem connector
(/root/reference/crates/arroyo-connectors/src/filesystem/, 12,086 LoC incl.
Delta/Iceberg): this round implements the core — a source that reads
json/parquet files under a path (positions checkpointed), and a sink that
writes rolling files (rotated on row-count/byte-size/age policies) through
the two-phase pattern: data lands in `.tmp` files, files are finalized
(renamed visible) on `handle_commit` after the checkpoint that contains
them is durable. JSON files stream across epochs with checkpointed byte
offsets (restores resume mid-file), and output can be partitioned by
field values and/or an event-time strftime pattern. Delta Lake and
Iceberg table formats build on this sink (delta.py, iceberg.py).
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import List, Optional

import pyarrow as pa
import pyarrow.parquet as pq

from ..operators.base import Operator, SourceFinishType, SourceOperator
from ..formats.de import Deserializer
from ..formats.ser import Serializer
from .base import ConnectionSchema, Connector, register_connector


class FileSystemSource(SourceOperator):
    def __init__(self, path: str, schema, format: str, bad_data: str):
        super().__init__("filesystem_source")
        self.path = path
        self.out_schema = schema
        self.format = format or "json"
        self.deserializer = (
            Deserializer(schema, format=self.format, bad_data=bad_data)
            if self.format not in ("parquet",)
            else None
        )
        self.position = [0, 0]  # file index, row index

    def tables(self):
        from ..state.table_config import global_table

        return {"fs": global_table("fs")}

    async def on_start(self, ctx):
        if ctx.table_manager is not None:
            table = await ctx.table("fs")
            stored = table.get(ctx.task_info.task_index)
            if stored is not None:
                self.position = list(stored)

    async def handle_checkpoint(self, barrier, ctx, collector):
        if ctx.table_manager is not None:
            table = await ctx.table("fs")
            table.put(ctx.task_info.task_index, list(self.position))

    def _files(self) -> List[str]:
        if os.path.isfile(self.path):
            return [self.path]
        out = []
        for root, _, names in os.walk(self.path):
            for n in sorted(names):
                if not n.startswith(".") and not n.endswith(".tmp"):
                    out.append(os.path.join(root, n))
        return sorted(out)

    async def run(self, ctx, collector) -> SourceFinishType:
        files = self._files()
        p = ctx.task_info.parallelism
        me = ctx.task_info.task_index
        for fi, fpath in enumerate(files):
            if fi % p != me or fi < self.position[0]:
                continue
            start_row = self.position[1] if fi == self.position[0] else 0
            row_idx = 0
            if fpath.endswith(".parquet") or self.format == "parquet":
                from ..schema import TIMESTAMP_FIELD
                from ..types import now_nanos

                table = pq.read_table(fpath)
                for batch in table.to_batches():
                    for row in batch.to_pylist():
                        if row_idx >= start_row:
                            finish = await ctx.check_control(collector)
                            if finish is not None:
                                return finish
                            if row.get(TIMESTAMP_FIELD) is None:
                                row[TIMESTAMP_FIELD] = now_nanos()
                            ctx.buffer_row(row)
                            self.position = [fi, row_idx + 1]
                            if ctx.should_flush():
                                await self.flush_buffer(ctx, collector)
                        row_idx += 1
            else:
                with _open_decompressed(fpath) as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            row_idx += 1
                            continue
                        if row_idx >= start_row:
                            finish = await ctx.check_control(collector)
                            if finish is not None:
                                return finish
                            for row in self.deserializer.deserialize_slice(
                                line, error_reporter=ctx.error_reporter
                            ):
                                ctx.buffer_row(row)
                            self.position = [fi, row_idx + 1]
                            if ctx.should_flush():
                                await self.flush_buffer(ctx, collector)
                        row_idx += 1
            self.position = [fi + 1, 0]
        await self.flush_buffer(ctx, collector)
        return SourceFinishType.FINAL


def _open_decompressed(fpath: str):
    """Open a line-format source file, transparently decompressing by
    extension — the reference's source reads gzip/zstd the same way
    (/root/reference/crates/arroyo-connectors/src/filesystem/source.rs,
    CompressionFormat none|gzip|zstd)."""
    if fpath.endswith(".gz"):
        import gzip

        return gzip.open(fpath, "rb")
    if fpath.endswith((".zst", ".zstd")):
        import io

        import zstandard

        # the raw ZstdDecompressionReader has no line iteration
        return io.BufferedReader(zstandard.open(fpath, "rb"))
    return open(fpath, "rb")


class _PartWriter:
    """One in-progress output file for one partition. JSON files stream
    row-by-row (byte offset checkpointed, so a restore truncates to the
    offset and resumes mid-file — the reference v2 sink's checkpointed
    multipart-upload state re-expressed for appendable media); parquet
    buffers batches and serializes whole files."""

    def __init__(self, tmp: str, fmt: str, resume_offset: int = 0):
        self.tmp = tmp
        self.fmt = fmt
        self.rows: List[pa.RecordBatch] = []  # parquet buffering
        self.n_rows = 0
        self.n_bytes = 0
        self.opened_at = time.monotonic()
        self.f = None
        if fmt != "parquet":
            os.makedirs(os.path.dirname(tmp), exist_ok=True)
            if resume_offset and os.path.exists(tmp):
                with open(tmp, "r+b") as trunc:
                    trunc.truncate(resume_offset)
                self.f = open(tmp, "ab")
            else:
                self.f = open(tmp, "wb")
            self.n_bytes = resume_offset

    def write_json(self, records):
        for rec in records:
            self.f.write(rec + b"\n")
            self.n_bytes += len(rec) + 1
            self.n_rows += 1

    def buffer(self, batch: pa.RecordBatch):
        self.rows.append(batch)
        self.n_rows += batch.num_rows
        self.n_bytes += batch.nbytes

    def flush(self):
        if self.f is not None:
            self.f.flush()
            os.fsync(self.f.fileno())

    def close(self, prepare_table):
        if self.f is not None:
            self.f.close()
            self.f = None
        elif self.rows:
            os.makedirs(os.path.dirname(self.tmp), exist_ok=True)
            pq.write_table(
                prepare_table(pa.Table.from_batches(self.rows)), self.tmp
            )
            self.rows = []


class FileSystemSink(Operator):
    """Rolling file sink with two-phase commit: rows stream into open
    `.tmp` files (one per active partition); files roll on row-count,
    byte-size, or age policies; rolled files seal at the next barrier and
    are renamed visible on `handle_commit` once that checkpoint is durable
    (reference: filesystem/sink v2 mod.rs two-phase flow + rolling
    policies). JSON files may span epochs — their byte offsets checkpoint
    and restores resume mid-file; parquet rolls at every barrier so each
    file serializes once."""

    def __init__(self, path: str, format: str,
                 rollover_rows: Optional[int] = None,
                 rollover_bytes: int = 0, rollover_seconds: float = 0,
                 partition_fields: Optional[List[str]] = None,
                 time_partition_pattern: Optional[str] = None):
        super().__init__("filesystem_sink")
        self.path = path
        self.format = format or "json"
        # json files span epochs (offset-checkpointed), so when NO policy
        # is configured at all a default 30s age roll bounds how long
        # output stays invisible (reference v2 rollover_seconds default);
        # an explicitly configured policy is never overridden
        if (
            self.format != "parquet" and rollover_rows is None
            and not rollover_bytes and not rollover_seconds
        ):
            rollover_seconds = 30.0
        self.rollover_rows = (
            rollover_rows if rollover_rows is not None else 100_000
        )
        self.rollover_bytes = rollover_bytes
        self.rollover_seconds = rollover_seconds
        self.partition_fields = partition_fields or []
        self.time_partition_pattern = time_partition_pattern
        self.serializer = (
            Serializer(format="json") if self.format == "json" else None
        )
        self._open: dict = {}  # partition -> _PartWriter
        self._pending_tmp: List[str] = []  # rolled since the last barrier
        self._committing: dict = {}  # epoch -> files sealed at that barrier
        self._file_seq = 0

    def tables(self):
        from ..state.table_config import global_table

        return {"fsk": global_table("fsk")}

    def tick_interval(self):
        return min(self.rollover_seconds, 1.0) if self.rollover_seconds \
            else None

    async def on_start(self, ctx):
        os.makedirs(self.path, exist_ok=True)
        if ctx.table_manager is not None:
            table = await ctx.table("fsk")
            stored = table.get(ctx.task_info.task_index)
            if stored is not None:
                self._file_seq = stored.get("file_seq", 0)
                # finalize files whose checkpoint committed but rename was
                # lost in the crash
                for tmp in stored.get("pending", []):
                    if os.path.exists(tmp):
                        os.replace(tmp, tmp[: -len(".tmp")])
                # resume in-progress json files at their checkpointed
                # offsets (uncheckpointed tail bytes are truncated away)
                for of in stored.get("open_files", []):
                    if os.path.exists(of["tmp"]):
                        w = _PartWriter(
                            of["tmp"], self.format,
                            resume_offset=of["offset"],
                        )
                        w.n_rows = of.get("rows", 0)
                        self._open[of["partition"]] = w

    # -- partitioning -----------------------------------------------------

    def _partitions(self, batch: pa.RecordBatch) -> List[tuple]:
        """[(partition string, row mask)] for one batch; [('', None)] when
        unpartitioned (reference v2 partitioning.rs: field values +
        strftime of the event time compose the directory)."""
        if not self.partition_fields and not self.time_partition_pattern:
            return [("", None)]
        import numpy as np

        n = batch.num_rows
        parts = [[] for _ in range(n)]
        if self.time_partition_pattern:
            from datetime import datetime, timezone

            from ..schema import TIMESTAMP_FIELD

            ts = batch.column(
                batch.schema.names.index(TIMESTAMP_FIELD)
            ).cast(pa.int64()).to_pylist()
            for i, t in enumerate(ts):
                parts[i].append(datetime.fromtimestamp(
                    (t or 0) / 1e9, tz=timezone.utc
                ).strftime(self.time_partition_pattern))
        for fname in self.partition_fields:
            col = batch.column(batch.schema.names.index(fname)).to_pylist()
            for i, v in enumerate(col):
                parts[i].append(f"{fname}={v}")
        keys = np.asarray(["/".join(p) for p in parts], dtype=object)
        out = []
        for k in sorted(set(keys.tolist())):
            out.append((k, keys == k))
        return out

    def _writer(self, partition: str, ctx) -> _PartWriter:
        w = self._open.get(partition)
        if w is None:
            ext = "parquet" if self.format == "parquet" else "json"
            name = (
                f"{ctx.task_info.task_index:03d}-{self._file_seq:05d}-"
                f"{uuid.uuid4().hex[:8]}.{ext}"
            )
            self._file_seq += 1
            d = os.path.join(self.path, partition) if partition else self.path
            w = _PartWriter(os.path.join(d, name + ".tmp"), self.format)
            self._open[partition] = w
        return w

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        for partition, mask in self._partitions(batch):
            b = batch if mask is None else batch.filter(pa.array(mask))
            if not b.num_rows:
                continue
            if self.format == "parquet":
                w = self._writer(partition, ctx)
                w.buffer(b)
                if self._should_roll(w):
                    self._roll_one(partition)
            else:
                # roll mid-batch so byte/row policies hold even when one
                # arriving batch exceeds the target file size
                for rec in self.serializer.serialize(b):
                    w = self._writer(partition, ctx)
                    w.write_json((rec,))
                    if self._should_roll(w):
                        self._roll_one(partition)

    def _should_roll(self, w: _PartWriter) -> bool:
        return (
            w.n_rows >= self.rollover_rows
            or (self.rollover_bytes and w.n_bytes >= self.rollover_bytes)
            or (self.rollover_seconds
                and time.monotonic() - w.opened_at >= self.rollover_seconds)
        )

    def _roll_one(self, partition: str):
        w = self._open.pop(partition, None)
        if w is None or (w.n_rows == 0 and not w.rows):
            if w is not None:
                w.close(self._prepare_table)
                if os.path.exists(w.tmp):
                    os.remove(w.tmp)
            return
        w.close(self._prepare_table)
        self._pending_tmp.append(w.tmp)

    def _roll(self, ctx, json_too: bool = True):
        for partition in list(self._open):
            w = self._open[partition]
            if w.fmt == "parquet" or json_too:
                self._roll_one(partition)

    async def handle_tick(self, tick, ctx, collector):
        for partition, w in list(self._open.items()):
            if self.rollover_seconds and (
                time.monotonic() - w.opened_at >= self.rollover_seconds
            ):
                self._roll_one(partition)

    def _prepare_table(self, table: pa.Table) -> pa.Table:
        """Hook: adjust the table before writing a file (IcebergSink drops
        internal columns and stamps parquet field ids)."""
        return table

    async def handle_checkpoint(self, barrier, ctx, collector):
        # parquet files must serialize whole: roll them at the barrier.
        # json writers survive the barrier — flush and checkpoint offsets
        self._roll(ctx, json_too=False)
        for w in self._open.values():
            w.flush()
        # seal exactly the files rolled before this barrier; later rolls
        # belong to the next epoch and must not become visible on commit
        sealed, self._pending_tmp = self._pending_tmp, []
        self._committing[barrier.epoch] = sealed
        ctx.commit_data = json.dumps(sealed).encode()
        if ctx.table_manager is not None:
            table = await ctx.table("fsk")
            table.put(
                ctx.task_info.task_index,
                {
                    "file_seq": self._file_seq,
                    "pending": [
                        f for files in self._committing.values() for f in files
                    ],
                    "open_files": [
                        {"tmp": w.tmp, "offset": w.n_bytes,
                         "rows": w.n_rows, "partition": p}
                        for p, w in self._open.items()
                        if w.fmt != "parquet"
                    ],
                },
            )

    async def handle_commit(self, epoch, commit_data, ctx):
        sealed = self._committing.pop(epoch, None)
        if sealed is None:
            # recovery path: the manifest's commit payload names the files
            payload = (commit_data or {}).get("data", {}).get(
                ctx.task_info.task_index
            )
            if isinstance(payload, dict) and "__hex__" in payload:
                sealed = json.loads(bytes.fromhex(payload["__hex__"]))
            else:
                sealed = []
        finalized = self._finalize(sealed)
        await self._committed(finalized, ctx, epoch=epoch)
        return finalized

    @staticmethod
    def _finalize(tmps: List[str]) -> List[str]:
        """Rename committed .tmp files visible; returns the final paths."""
        out = []
        for tmp in tmps:
            if os.path.exists(tmp):
                os.replace(tmp, tmp[: -len(".tmp")])
                out.append(tmp[: -len(".tmp")])
        return out

    async def _committed(self, files: List[str], ctx, epoch=None):
        """Hook: files became visible under a durable commit (DeltaSink
        appends them to the transaction log; IcebergSink commits a
        snapshot). `epoch` is None on the EOD/recovery paths."""

    async def on_close(self, ctx, collector, is_eod: bool):
        # EOD without a final checkpoint: finalize remaining data directly
        if is_eod:
            self._roll(ctx, json_too=True)
            finalized = self._finalize(self._pending_tmp)
            self._pending_tmp = []
            await self._committed(finalized, ctx)
            for epoch in list(self._committing):
                await self.handle_commit(epoch, {}, ctx)
        else:
            for w in self._open.values():
                w.flush()
        return None


@register_connector
class FileSystemConnector(Connector):
    name = "filesystem"
    description = "reads/writes files (json, parquet) under a directory"
    source = True
    sink = True
    config_schema = {
        "path": {"type": "string", "required": True},
        "rollover_rows": {"type": "integer"},
        "rollover_bytes": {"type": "integer"},
        "rollover_seconds": {"type": "number"},
        "partition_fields": {"type": "string"},  # comma-separated
        "time_partition_pattern": {"type": "string"},  # strftime
    }

    def validate_options(self, options, schema):
        if "path" not in options:
            raise ValueError("filesystem requires a path option")
        out = {"path": options["path"]}
        if "rollover_rows" in options:
            out["rollover_rows"] = int(options["rollover_rows"])
        if "rollover_bytes" in options:
            out["rollover_bytes"] = int(options["rollover_bytes"])
        if "rollover_seconds" in options:
            out["rollover_seconds"] = float(options["rollover_seconds"])
        if "partition_fields" in options:
            out["partition_fields"] = [
                f.strip() for f in options["partition_fields"].split(",")
                if f.strip()
            ]
        if "time_partition_pattern" in options:
            out["time_partition_pattern"] = options["time_partition_pattern"]
        return out

    def make_source(self, config, schema: ConnectionSchema):
        return FileSystemSource(
            config["path"], config.get("schema"), config.get("format"),
            config.get("bad_data", "fail"),
        )

    def make_sink(self, config, schema: ConnectionSchema):
        return FileSystemSink(
            config["path"], config.get("format"),
            config.get("rollover_rows"),
            config.get("rollover_bytes", 0),
            config.get("rollover_seconds", 0),
            config.get("partition_fields"),
            config.get("time_partition_pattern"),
        )
