"""Mini job state machine for the transition-conformance fixtures."""
import enum


class JobState(enum.Enum):
    CREATED = "Created"
    RUNNING = "Running"
    STALLED = "Stalled"  # non-terminal, deliberately missing from TRANSITIONS
    STOPPED = "Stopped"
    FAILED = "Failed"

    def is_terminal(self):
        return self in (JobState.STOPPED, JobState.FAILED)


TRANSITIONS = {
    JobState.CREATED: {JobState.RUNNING, JobState.FAILED},
    JobState.RUNNING: {JobState.STOPPED, JobState.FAILED},
}
