"""Staged TPU grant-capture machinery (tools/tpu_probe_daemon.py).

The relay has been wedged for rounds 3-4 (zero grants), so the staged
capture path would otherwise first execute on the next real grant. The
daemon's --selftest runs one full parent cycle on the CPU backend with
a simulated short grant window (child killed right after the q5small
tier) and asserts the partial artifacts carry real numbers — this test
wires that demonstration into the suite.

Reference analog: arroyo ships its benches as CI-run harnesses; here
the capture harness itself is under test because the hardware window is
the scarce resource.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DAEMON = os.path.join(REPO, "tools", "tpu_probe_daemon.py")


def test_staged_capture_selftest():
    """The daemon's --selftest simulates a short grant window (child
    killed right after the q5small tier) on the CPU backend and asserts
    the partial artifacts carry real numbers. Delegate to it — ONE
    check suite, no drift between the test and the demo."""
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    for var in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
                "AXON_POOL_SVC_OVERRIDE", "AXON_LOOPBACK_RELAY",
                "TPU_PROBE_OUT_DIR", "TPU_PROBE_KILL_AFTER_TIER"):
        env.pop(var, None)
    out = subprocess.run(
        [sys.executable, DAEMON, "--selftest"], env=env,
        capture_output=True, text=True, timeout=900)
    assert out.returncode == 0, out.stdout[-3000:] + out.stderr[-1000:]
    assert "SELFTEST OK" in out.stdout


def test_grant_substitution_accepts_partial():
    """bench.py must recognize a staged partial grant that only carries
    the q5small tier, and prefer the full q5 when both exist."""
    sys.path.insert(0, REPO)
    import bench

    assert bench.grant_q5_key({"q5small_eps": 1.0}) == "q5small"
    assert bench.grant_q5_key({"q5_eps": 2.0, "q5small_eps": 1.0}) == "q5"
    assert bench.grant_q5_key({"kernels": {"matmul": {}}}) is None
