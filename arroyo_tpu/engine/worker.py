"""Worker server: runs a partition of a job's subtasks.

Capability parity with the reference's WorkerServer
(/root/reference/crates/arroyo-worker/src/lib.rs:666-1197): registers with
the controller (RegisterWorkerReq), serves WorkerGrpc (StartExecution,
Checkpoint, Commit, StopExecution), heartbeats, streams task events
(checkpoint progress, finish/failure) back to the controller, and hosts the
TCP data plane endpoint for cross-worker edges.
"""

from __future__ import annotations

import asyncio
import os
from typing import Dict, Optional

from ..config import config
from ..graph.logical import LogicalGraph
from ..operators.control import (
    CheckpointCompletedResp,
    CheckpointEventResp,
    CheckpointMsg,
    CommitMsg,
    StopMsg,
    TaskFailedResp,
    TaskFinishedResp,
)
from ..types import CheckpointBarrier, StopMode, now_nanos
from ..utils.logging import get_logger
from .network import DataPlaneServer
from .program import Program
from .rpc import RpcClient, RpcServer

logger = get_logger("worker")


class WorkerServer:
    def __init__(self, controller_addr: str, worker_id: Optional[int] = None,
                 bind: str = "127.0.0.1"):
        self.controller_addr = controller_addr
        if worker_id is None:
            worker_id = int(os.environ.get("ARROYO_WORKER_ID", os.getpid()))
        self.worker_id = worker_id
        self.bind = bind
        self.rpc = RpcServer(bind)
        self.data = DataPlaneServer(bind)
        self.controller: Optional[RpcClient] = None
        self.program: Optional[Program] = None
        self.tasks = []
        self._running = asyncio.Event()
        self._finished = asyncio.Event()
        self._n_running = 0

    # -- lifecycle ----------------------------------------------------------

    async def start(self):
        self.rpc.add_service(
            "WorkerGrpc",
            {
                "StartExecution": self.start_execution,
                "StartProcessing": self.start_processing,
                "Checkpoint": self.checkpoint,
                "Commit": self.commit,
                "LoadCompacted": self.load_compacted,
                "StopExecution": self.stop_execution,
                "GetMetrics": self.get_metrics,
            },
        )
        rpc_port = await self.rpc.start()
        data_port = await self.data.start()
        self.rpc_addr = f"{self.bind}:{rpc_port}"
        self.data_addr = f"{self.bind}:{data_port}"
        self.controller = RpcClient(self.controller_addr)
        await self.controller.call(
            "ControllerGrpc",
            "RegisterWorker",
            {
                "worker_id": self.worker_id,
                "rpc_addr": self.rpc_addr,
                "data_addr": self.data_addr,
                "slots": config().worker.task_slots,
            },
        )
        self._hb = asyncio.ensure_future(self._heartbeat())
        logger.info(
            "worker %s up (rpc %s, data %s)", self.worker_id, self.rpc_addr,
            self.data_addr,
        )
        return self

    async def _heartbeat(self):
        while not self._finished.is_set():
            try:
                await self.controller.call(
                    "ControllerGrpc", "Heartbeat",
                    {"worker_id": self.worker_id, "time": now_nanos()},
                )
            except Exception as e:  # noqa: BLE001
                logger.warning("heartbeat failed: %s", e)
            await asyncio.sleep(2.0)

    # -- WorkerGrpc ---------------------------------------------------------

    async def start_execution(self, req: dict) -> dict:
        if req.get("sql"):
            from ..sql import plan_query

            graph = plan_query(
                req["sql"], parallelism=req.get("parallelism", 1)
            ).graph
        else:
            graph = LogicalGraph.from_json(req["graph"])
        assignments = {
            (a["node_id"], a["subtask"]): a["worker_id"]
            for a in req["assignments"]
        }
        worker_addrs = {
            int(w): addr for w, addr in req["worker_data_addrs"].items()
        }
        self.job_id = req["job_id"]
        program = Program(graph, self.job_id)
        if req.get("storage_url"):
            from ..state.backend import StateBackend

            backend = StateBackend(req["storage_url"], self.job_id)
            backend.generation = req.get("generation")
            if req.get("restore_epoch") is not None:
                from ..state import protocol

                backend.restore_manifest = protocol.load_manifest(
                    backend.storage, backend.paths, req["restore_epoch"]
                )
            program.with_state(backend)
        program.build(
            assignments=assignments,
            my_worker=self.worker_id,
            worker_addrs=worker_addrs,
            data_server=self.data,
        )
        self.program = program

        def pump_failed(quad, exc):
            program.control_resp.put_nowait(
                TaskFailedResp(
                    f"net-{quad[0]}-{quad[1]}", quad[0], quad[1],
                    f"data plane edge {quad} failed: {exc!r}",
                )
            )

        for rs in program.remote_senders:
            rs.on_error = pump_failed
            await rs.start()
        return {"subtasks": len(program.subtasks)}

    async def start_processing(self, req: dict) -> dict:
        """Phase 2 of the barrier-synchronized start (reference
        Engine::start, engine.rs:525): runners only spawn once every worker
        has built its partition and registered its data-plane routes, so a
        fast source can't race peers' route registration."""
        program = self.program
        for sub in program.subtasks:
            self.tasks.append(asyncio.ensure_future(sub.runner.run()))
        self._n_running = len(program.subtasks)
        self._pump_task = asyncio.ensure_future(self._pump_responses())
        self._running.set()
        return {}

    async def checkpoint(self, req: dict) -> dict:
        barrier = CheckpointBarrier(
            epoch=req["epoch"], min_epoch=req.get("min_epoch", 0),
            timestamp=now_nanos(), then_stop=req.get("then_stop", False),
        )
        for sub in self.program.source_subtasks():
            sub.control_rx.put_nowait(CheckpointMsg(barrier))
        return {}

    async def commit(self, req: dict) -> dict:
        data: Dict[int, dict] = {}
        for node_id, subs in (req.get("committing") or {}).items():
            data[int(node_id)] = {"data": {int(s): v for s, v in subs.items()}}
        for sub in self.program.subtasks:
            sub.control_rx.put_nowait(CommitMsg(req["epoch"], data))
        return {}

    async def load_compacted(self, req: dict) -> dict:
        """Swap an operator table's file references for a compacted file
        (controller-driven compaction; reference LoadCompacted control)."""
        if self.program is not None:
            self.program.send_load_compacted(req)
        return {}

    async def stop_execution(self, req: dict) -> dict:
        mode = StopMode(req.get("mode", "graceful"))
        targets = (
            self.program.source_subtasks()
            if mode == StopMode.GRACEFUL
            else self.program.subtasks
        )
        for sub in targets:
            sub.control_rx.put_nowait(StopMsg(mode))
        return {}

    async def get_metrics(self, req: dict) -> dict:
        from ..metrics import REGISTRY

        return {"prometheus": REGISTRY.expose()}

    # -- task event forwarding ---------------------------------------------

    async def _pump_responses(self):
        q = self.program.control_resp
        while self._n_running > 0:
            resp = await q.get()
            try:
                await self._forward(resp)
            except Exception as e:  # noqa: BLE001
                logger.warning("event forward failed: %s", e)
        self._finished.set()
        await self.controller.call(
            "ControllerGrpc", "WorkerFinished", {"worker_id": self.worker_id}
        )

    async def _forward(self, resp):
        c = self.controller
        wid = self.worker_id
        if isinstance(resp, CheckpointCompletedResp):
            await c.call(
                "ControllerGrpc", "TaskCheckpointCompleted",
                {
                    "worker_id": wid,
                    "task_id": resp.task_id,
                    "node_id": resp.node_id,
                    "subtask": resp.subtask_index,
                    "epoch": resp.epoch,
                    "metadata": resp.subtask_metadata,
                    "watermark": resp.watermark,
                    "commit_data": resp.commit_data,
                },
            )
        elif isinstance(resp, CheckpointEventResp):
            await c.call(
                "ControllerGrpc", "TaskCheckpointEvent",
                {
                    "worker_id": wid, "task_id": resp.task_id,
                    "epoch": resp.epoch, "event": resp.event,
                },
            )
        elif isinstance(resp, TaskFinishedResp):
            self._n_running -= 1
            await c.call(
                "ControllerGrpc", "TaskFinished",
                {"worker_id": wid, "task_id": resp.task_id},
            )
        elif isinstance(resp, TaskFailedResp):
            self._n_running -= 1
            await c.call(
                "ControllerGrpc", "TaskFailed",
                {"worker_id": wid, "task_id": resp.task_id,
                 "error": resp.error},
            )

    async def shutdown(self):
        """Force teardown: cancel every task and close servers/clients so a
        force-stopped embedded worker leaves no heartbeats or runners
        behind."""
        self._finished.set()
        for t in self.tasks:
            t.cancel()
        for attr in ("_hb", "_pump_task"):
            t = getattr(self, attr, None)
            if t is not None:
                t.cancel()
        await asyncio.gather(*self.tasks, return_exceptions=True)
        if self.controller is not None:
            await self.controller.close()
        await self.rpc.stop(grace=0.1)
        await self.data.stop()

    async def run_until_finished(self):
        await self._finished.wait()
        await asyncio.gather(*self.tasks, return_exceptions=True)
        self._hb.cancel()
        await asyncio.gather(self._hb, return_exceptions=True)
        await self.controller.close()
        await self.rpc.stop()
        await self.data.stop()


async def worker_main(controller_addr: str):
    w = WorkerServer(controller_addr)
    await w.start()
    await w.run_until_finished()
