"""Minimal Avro binary codec (record of primitives + nullable unions).

Capability parity target: the reference decodes Avro with apache-avro and
resolves writer schemas from a Confluent schema registry
(/root/reference/crates/arroyo-formats/src/avro/*). This is a dependency-
free subset: record schemas of null/boolean/int/long/float/double/string/
bytes and 2-branch nullable unions, plus the Confluent wire framing
(magic 0 + 4-byte schema id) which is skipped when present.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, List, Optional

import pyarrow as pa


def _zigzag_encode(n: int) -> bytes:
    n = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def long(self) -> int:
        shift = 0
        acc = 0
        while True:
            b = self.data[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)

    def bytes_(self) -> bytes:
        n = self.long()
        out = self.data[self.pos: self.pos + n]
        self.pos += n
        return out

    def float_(self) -> float:
        (v,) = struct.unpack_from("<f", self.data, self.pos)
        self.pos += 4
        return v

    def double(self) -> float:
        (v,) = struct.unpack_from("<d", self.data, self.pos)
        self.pos += 8
        return v

    def boolean(self) -> bool:
        v = self.data[self.pos] == 1
        self.pos += 1
        return v


class AvroDecoder:
    def __init__(self, schema_json: Optional[str]):
        if not schema_json:
            raise ValueError("avro format requires avro.schema option")
        self.schema = json.loads(schema_json)
        assert self.schema["type"] == "record"
        self.fields: List[Dict] = self.schema["fields"]

    def decode(self, record: bytes) -> Dict[str, Any]:
        if len(record) > 5 and record[0] == 0:
            # Confluent wire format: magic 0 + schema id
            record = record[5:]
        r = _Reader(record)
        return {f["name"]: self._read(r, f["type"]) for f in self.fields}

    def _read(self, r: _Reader, t) -> Any:
        if isinstance(t, list):  # union
            idx = r.long()
            return self._read(r, t[idx])
        if isinstance(t, dict):
            t = t.get("logicalType") and t["type"] or t["type"]
        if t == "null":
            return None
        if t == "boolean":
            return r.boolean()
        if t in ("int", "long"):
            return r.long()
        if t == "float":
            return r.float_()
        if t == "double":
            return r.double()
        if t == "string":
            return r.bytes_().decode()
        if t == "bytes":
            return r.bytes_()
        raise ValueError(f"unsupported avro type {t!r}")


class AvroEncoder:
    def __init__(self, schema_json: Optional[str], arrow_schema: pa.Schema):
        if schema_json:
            self.schema = json.loads(schema_json)
        else:
            self.schema = schema_from_arrow(arrow_schema)
        self.fields = self.schema["fields"]

    def encode(self, row: Dict[str, Any]) -> bytes:
        out = bytearray()
        for f in self.fields:
            self._write(out, f["type"], row.get(f["name"]))
        return bytes(out)

    def _write(self, out: bytearray, t, v):
        if isinstance(t, list):
            if v is None:
                out += _zigzag_encode(t.index("null"))
                return
            branch = next(i for i, b in enumerate(t) if b != "null")
            out += _zigzag_encode(branch)
            self._write(out, t[branch], v)
            return
        if t == "boolean":
            out.append(1 if v else 0)
        elif t in ("int", "long"):
            out += _zigzag_encode(int(v))
        elif t == "float":
            out += struct.pack("<f", float(v))
        elif t == "double":
            out += struct.pack("<d", float(v))
        elif t == "string":
            b = str(v).encode()
            out += _zigzag_encode(len(b)) + b
        elif t == "bytes":
            out += _zigzag_encode(len(v)) + v
        else:
            raise ValueError(f"unsupported avro type {t!r}")


def schema_from_arrow(schema: pa.Schema, name: str = "Record") -> dict:
    """Arrow schema -> Avro record schema (sink schema generator,
    reference ser.rs:89-101)."""
    fields = []
    for f in schema:
        if f.name.startswith("_"):
            continue
        if pa.types.is_boolean(f.type):
            t = "boolean"
        elif pa.types.is_integer(f.type):
            t = "long"
        elif pa.types.is_float32(f.type):
            t = "float"
        elif pa.types.is_floating(f.type):
            t = "double"
        elif pa.types.is_binary(f.type):
            t = "bytes"
        elif pa.types.is_timestamp(f.type):
            t = {"type": "long", "logicalType": "timestamp-micros"}
        else:
            t = "string"
        fields.append(
            {"name": f.name, "type": ["null", t] if f.nullable else t}
        )
    return {"type": "record", "name": name, "fields": fields}
