import asyncio

import numpy as np
import pyarrow as pa

from arroyo_tpu.operators.queues import BatchQueue, QueueClosed
from arroyo_tpu.types import SignalMessage


def make_batch(n=10):
    return pa.RecordBatch.from_arrays([pa.array(np.arange(n))], names=["x"])


def test_queue_backpressure_on_count():
    async def run():
        q = BatchQueue(max_batches=2, max_bytes=1 << 30)
        await q.send(make_batch())
        await q.send(make_batch())
        send3 = asyncio.ensure_future(q.send(make_batch()))
        await asyncio.sleep(0.01)
        assert not send3.done()  # blocked at capacity
        await q.recv()
        await asyncio.sleep(0.01)
        assert send3.done()

    asyncio.run(run())


def test_queue_backpressure_on_bytes():
    async def run():
        q = BatchQueue(max_batches=100, max_bytes=100)
        big = make_batch(1000)  # 8KB > 100 bytes
        await q.send(big)  # first send always admitted
        send2 = asyncio.ensure_future(q.send(make_batch(1)))
        await asyncio.sleep(0.01)
        assert not send2.done()
        await q.recv()
        await asyncio.sleep(0.01)
        assert send2.done()

    asyncio.run(run())


def test_signals_bypass_bounds():
    async def run():
        q = BatchQueue(max_batches=1, max_bytes=1)
        await q.send(make_batch())
        # queue is full but a signal must never block
        await asyncio.wait_for(q.send(SignalMessage.stop()), timeout=1.0)
        assert q.qsize() == 2

    asyncio.run(run())


def test_fifo_order_preserved():
    async def run():
        q = BatchQueue(8, 1 << 30)
        for i in range(5):
            await q.send(make_batch(i + 1))
        sizes = [(await q.recv()).num_rows for _ in range(5)]
        assert sizes == [1, 2, 3, 4, 5]

    asyncio.run(run())


def test_closed_queue_raises():
    async def run():
        q = BatchQueue(8, 1 << 30)
        q.close()
        try:
            await q.recv()
            assert False
        except QueueClosed:
            pass

    asyncio.run(run())
