CREATE TABLE cars (
  timestamp TIMESTAMP,
  driver_id BIGINT,
  event_type TEXT,
  location TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/cars.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE most_active_driver (
  start TIMESTAMP,
  end TIMESTAMP,
  driver_id BIGINT,
  count BIGINT,
  row_number BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO most_active_driver
SELECT window.start, window.end, driver_id, count, row_number FROM (
  SELECT *, ROW_NUMBER() OVER (
    PARTITION BY window
    ORDER BY count DESC, driver_id DESC) as row_number
  FROM (
    SELECT driver_id,
           hop(interval '1 minute', interval '2 minute') as window,
           count(*) as count
    FROM cars
    GROUP BY 1, 2
  )
) WHERE row_number = 1;
