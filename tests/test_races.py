"""arroyoracer units: the async call graph (roots, locksets, caching),
the RACE rule family's engine integration, the dynamic interleaving
sanitizer, and the FaultPlan locked-reader API the sanitizer work
hardened.

The per-rule fire/clean behavior itself is pinned by the fixture pairs
under tests/lint_fixtures/RACE00x/ (tests/test_lint.py parametrizes
over every registered rule); these tests cover the machinery those
fixtures can't see."""

import asyncio
import json
import subprocess
import sys
from pathlib import Path

from arroyo_tpu.analysis import get_rule, run_lint
from arroyo_tpu.analysis.engine import collect_files, parse_project
from arroyo_tpu.analysis.races import callgraph, sanitizer, shared_state
from arroyo_tpu.chaos.plan import FaultPlan

REPO = Path(__file__).resolve().parents[1]


# -- call graph --------------------------------------------------------------


GRAPH_SRC = '''
import asyncio

from arroyo_tpu.analysis.races import shared_state


@shared_state("counter")
class Job:
    def __init__(self):
        self.counter = 0
        self._lock = None


class Engine:
    async def drive(self, job):
        await self.helper(job)

    async def helper(self, job):
        job.counter = 1

    async def pump(self, job):
        with job._lock:
            self.locked_touch(job)

    def locked_touch(self, job):
        job.counter = 2

    def start(self, job):
        asyncio.ensure_future(self.drive(job))
        asyncio.ensure_future(self.pump(job))
'''


def _graph(tmp_path):
    (tmp_path / "mod.py").write_text(GRAPH_SRC)
    project = parse_project(tmp_path, collect_files(tmp_path, (".",)))
    return callgraph.build(project), project


def test_spawn_sites_become_roots(tmp_path):
    graph, _ = _graph(tmp_path)
    root_names = {r.split("::")[-1] for r in graph.roots_of}
    assert "Engine.drive" in root_names
    assert "Engine.pump" in root_names


def test_roots_propagate_through_calls_not_spawns(tmp_path):
    graph, _ = _graph(tmp_path)
    helper = next(q for q in graph.funcs if q.endswith("Engine.helper"))
    drive = next(q for q in graph.funcs if q.endswith("Engine.drive"))
    start = next(q for q in graph.funcs if q.endswith("Engine.start"))
    # helper is only called from drive: it inherits drive's root
    assert graph.roots(helper) == graph.roots(drive)
    # the spawnER does not adopt the spawned task's root — `start` runs
    # under whoever calls it (main), not under drive/pump
    assert graph.roots(start) == {callgraph.MAIN_ROOT}


def test_entry_lockset_intersection(tmp_path):
    graph, _ = _graph(tmp_path)
    touch = next(q for q in graph.funcs
                 if q.endswith("Engine.locked_touch"))
    # every call site of locked_touch holds _lock
    assert "_lock" in graph.entry_lockset(touch)
    pump = next(q for q in graph.funcs if q.endswith("Engine.pump"))
    assert graph.entry_lockset(pump) == frozenset()


def test_field_writes_exclude_constructors(tmp_path):
    graph, _ = _graph(tmp_path)
    writes = graph.field_writes("counter")
    assert writes, "counter writes not found"
    assert all("__init__" not in fi.qualname for fi, _ in writes)


def test_build_is_cached_per_project(tmp_path):
    graph, project = _graph(tmp_path)
    # all four RACE rules share one graph build per Project — the lever
    # that keeps full-tree --strict within the 1.5x wall-time budget
    assert callgraph.build(project) is graph


def test_debug_json_shape(tmp_path):
    graph, _ = _graph(tmp_path)
    doc = graph.to_debug_json()
    assert set(doc) == {"declared_fields", "n_functions", "roots"}
    root = next(k for k in doc["roots"] if k.endswith("Engine.drive"))
    info = doc["roots"][root]
    assert info["spawned_at"]
    assert any(a["field"] == "counter" for a in info["shared_accesses"])


def test_call_graph_cli(tmp_path):
    (tmp_path / "mod.py").write_text(GRAPH_SRC)
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint.py"),
         "--root", str(tmp_path), "--call-graph", "."],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    assert doc["n_functions"] >= 6
    assert any(k.endswith("Engine.pump") for k in doc["roots"])


# -- sanitizer ---------------------------------------------------------------


@shared_state("value")
class _Single:
    def __init__(self):
        self.value = 0


@shared_state("value", multi_writer=("value",))
class _Multi:
    def __init__(self):
        self.value = 0


def _with_sanitizer(coro):
    sanitizer.enable()
    sanitizer.reset()
    try:
        asyncio.run(coro)
        return sanitizer.conflicts()
    finally:
        sanitizer.disable()


async def _two_roots(obj, first, second):
    """Deterministic interleave: `first` runs to its await, `second`
    runs fully, `first` finishes."""
    gate1, gate2 = asyncio.Event(), asyncio.Event()

    async def a():
        sanitizer.set_task_root("root-a")
        await first(obj, gate1, gate2)

    async def b():
        sanitizer.set_task_root("root-b")
        await gate1.wait()
        second(obj)
        gate2.set()

    await asyncio.gather(asyncio.create_task(a()), asyncio.create_task(b()))


def test_write_write_conflict_on_single_writer():
    async def go():
        async def first(obj, g1, g2):
            obj.value = 1
            g1.set()
            await g2.wait()

        await _two_roots(_Single(), first, lambda o: setattr(o, "value", 2))

    conflicts = _with_sanitizer(go())
    assert any(c["kind"] == "write/write" for c in conflicts), conflicts


def test_multi_writer_waives_write_write_but_not_lost_update():
    async def ww():
        async def first(obj, g1, g2):
            obj.value = 1
            g1.set()
            await g2.wait()

        await _two_roots(_Multi(), first, lambda o: setattr(o, "value", 2))

    assert _with_sanitizer(ww()) == []

    async def lost():
        async def first(obj, g1, g2):
            stale = obj.value
            g1.set()
            await g2.wait()
            obj.value = stale + 1  # computed from the pre-await snapshot

        await _two_roots(_Multi(), first, lambda o: setattr(o, "value", 7))

    conflicts = _with_sanitizer(lost())
    assert [c["kind"] for c in conflicts] == ["lost-update"], conflicts


def test_reread_before_write_is_clean():
    async def go():
        async def first(obj, g1, g2):
            stale = obj.value
            g1.set()
            await g2.wait()
            obj.value = obj.value or stale  # revalidates: fresh read wins

        await _two_roots(_Multi(), first, lambda o: setattr(o, "value", 7))

    assert _with_sanitizer(go()) == []


def test_constructor_init_is_exempt():
    async def go():
        sanitizer.set_task_root("creator")
        obj = _Single()  # init write must not count as a conflicting write
        sanitizer.set_task_root("user")
        obj.value = 1

    # different "roots" in sequence, but the first write was the init
    conflicts = _with_sanitizer(go())
    assert conflicts == [], conflicts


def test_disable_restores_class_attrs():
    had_setattr = "__setattr__" in _Single.__dict__
    sanitizer.enable()
    assert "__setattr__" in _Single.__dict__
    sanitizer.disable()
    assert ("__setattr__" in _Single.__dict__) == had_setattr
    assert not sanitizer.is_enabled()


def test_task_root_context_manager():
    with sanitizer.task_root("scoped"):
        assert sanitizer.current_root() == "scoped"
    assert sanitizer.current_root() == "main"


def test_env_flag_name_single_underscore():
    # ARROYO_RACE_SANITIZER is a process flag, not a config override:
    # the double-underscore ARROYO__ namespace is reserved for CFG002
    assert sanitizer.ENV_FLAG == "ARROYO_RACE_SANITIZER"
    assert "__" not in sanitizer.ENV_FLAG


def test_dump_and_trace(tmp_path):
    async def go():
        sanitizer.set_task_root("writer")
        obj = _Single()
        obj.value = 3

    _with_sanitizer(go())
    log = tmp_path / "log.json"
    trace = tmp_path / "trace.json"
    sanitizer.dump(str(log))
    sanitizer.dump_trace(str(trace))
    doc = json.loads(log.read_text())
    assert doc["accesses"] >= 2 and "log" in doc
    tdoc = json.loads(trace.read_text())
    names = {e["name"] for e in tdoc["traceEvents"]}
    assert any("write _Single.value" in n for n in names)


# -- FaultPlan locked readers ------------------------------------------------


def test_fired_log_returns_snapshot_copies():
    plan = FaultPlan(1)
    plan.add("runner.stall", at_hits=(1,), params={"delay": 0.0})
    assert plan.fire("runner.stall", job="j") is not None
    log = plan.fired_log()
    assert len(log) == 1
    log[0]["point"] = "tampered"
    log.append({"fake": True})
    # the plan's own log is untouched: fired_log hands out copies so
    # drill readers never alias state mutated under plan._lock
    assert plan.fired_log()[0]["point"] == "runner.stall"
    assert len(plan.fired_log()) == 1
    assert plan.comparable_log() == [
        {"point": "runner.stall", "hit": 1, "match": {},
         "params": {"delay": 0.0}}
    ]
    assert plan.unfired() == []


# -- the annotated real tree -------------------------------------------------


def test_real_tree_race_rules_clean():
    """The tier-1 bar for ISSUE 18: every RACE00x finding in the real
    tree was fixed or carries an inline justified suppression — nothing
    is baselined."""
    rules = [get_rule(r) for r in
             ("RACE001", "RACE002", "RACE003", "RACE004")]
    res = run_lint(REPO, rules=rules)
    assert not res.findings, "\n".join(
        f"{f.path}:{f.line} [{f.rule}] {f.message}" for f in res.findings
    )


def test_real_tree_declares_shared_state():
    """The annotation DSL is actually deployed on the hot classes."""
    project = parse_project(REPO, collect_files(REPO))
    decls = callgraph.extract_decls(project)
    owners = {d.cls for d in decls.values()}
    for cls in ("JobHandle", "WorkerHandle", "_JobRuntime",
                "SubtaskRunner", "FaultPlan"):
        assert cls in owners, f"{cls} lost its shared-state declaration"
