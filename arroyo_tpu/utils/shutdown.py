"""Cooperative shutdown token tree.

Capability parity with the reference's Shutdown/ShutdownGuard
(/root/reference/crates/arroyo-server-common/src/shutdown.rs:17-133):
a root token with child guards; cancelling the root signals every guard,
then waits (with a deadline) for all guards to drop before returning.
asyncio-native: guards wrap tasks.
"""

from __future__ import annotations

import asyncio
import signal
from typing import Optional


class ShutdownGuard:
    def __init__(self, shutdown: "Shutdown", name: str):
        self._shutdown = shutdown
        self.name = name
        self._done = asyncio.Event()

    def child(self, name: str) -> "ShutdownGuard":
        return self._shutdown.guard(name)

    @property
    def cancelled(self) -> asyncio.Event:
        return self._shutdown._cancelled

    def is_cancelled(self) -> bool:
        return self._shutdown._cancelled.is_set()

    async def wait_cancelled(self):
        await self._shutdown._cancelled.wait()

    def done(self):
        if not self._done.is_set():
            self._done.set()
            self._shutdown._guards.discard(self)

    def spawn(self, coro) -> asyncio.Task:
        """Run a coroutine; the guard completes when it returns."""

        async def runner():
            try:
                await coro
            finally:
                self.done()

        task = asyncio.ensure_future(runner())
        self._shutdown._tasks.append(task)
        return task


class Shutdown:
    def __init__(self, name: str = "cluster"):
        self.name = name
        self._cancelled = asyncio.Event()
        self._guards: set[ShutdownGuard] = set()
        self._tasks: list[asyncio.Task] = []

    def guard(self, name: str) -> ShutdownGuard:
        g = ShutdownGuard(self, name)
        self._guards.add(g)
        return g

    def cancel(self):
        self._cancelled.set()

    def is_cancelled(self) -> bool:
        return self._cancelled.is_set()

    def handle_signals(self, loop: Optional[asyncio.AbstractEventLoop] = None):
        loop = loop or asyncio.get_event_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, self.cancel)
            except (NotImplementedError, RuntimeError):
                pass

    async def wait(self, deadline: float = 30.0) -> bool:
        """Wait for cancellation, then drain guards. Returns True on clean
        drain, False if the deadline expired (guards abandoned)."""
        await self._cancelled.wait()
        try:
            await asyncio.wait_for(self._drain(), timeout=deadline)
            return True
        except asyncio.TimeoutError:
            for t in self._tasks:
                t.cancel()
            await asyncio.gather(*self._tasks, return_exceptions=True)
            return False

    async def _drain(self):
        while self._guards:
            guard = next(iter(self._guards))
            await guard._done.wait()
        for t in self._tasks:
            if not t.done():
                try:
                    await t
                except asyncio.CancelledError:
                    # the CHILD task being cancelled is normal teardown;
                    # _drain itself being cancelled must propagate or the
                    # drain becomes uncancellable
                    if not t.cancelled():
                        raise
                except Exception:
                    pass
