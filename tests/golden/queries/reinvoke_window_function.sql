CREATE TABLE cars (
  timestamp TIMESTAMP,
  driver_id BIGINT,
  event_type TEXT,
  location TEXT
) WITH (
  connector = 'single_file',
  path = '$input_dir/cars.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE output (
  start TIMESTAMP,
  end TIMESTAMP,
  drivers BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO output
SELECT window.start as start, window.end as end, drivers
FROM (
  SELECT tumble(interval '1 minute') as window,
         count(DISTINCT driver_id) as drivers
  FROM (
    SELECT driver_id, tumble(interval '1 minute') as w,
           count(*) as pickups
    FROM cars WHERE event_type = 'pickup'
    GROUP BY 1, 2
  ) WHERE pickups > 2
  GROUP BY 1
);
