"""Object storage provider.

Capability parity with the reference's StorageProvider
(/root/reference/crates/arroyo-storage/src/lib.rs:56): URL-scheme-dispatched
backends (local FS, S3/GCS/Azure via pyarrow.fs), get/put/list/delete,
`put_if_not_exists` (the CAS primitive the checkpoint protocol fences with),
and recursive directory delete. Local CAS uses O_EXCL; remote filesystems
fall back to check-then-create (documented weaker guarantee — single-writer
controllers make this safe in practice; S3 conditional puts can harden it
later).
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import List, Optional, Tuple
from urllib.parse import urlparse


class CasConflict(Exception):
    """put_if_not_exists target already exists."""


class StorageProvider:
    def __init__(self, url: str):
        self.url = url
        scheme, path = _parse(url)
        self.scheme = scheme
        if scheme == "file":
            self.root = Path(path)
            self.fs = None
        else:
            import pyarrow.fs as pafs

            if scheme == "s3":
                self.fs = pafs.S3FileSystem()
            elif scheme in ("gs", "gcs"):
                self.fs = pafs.GcsFileSystem()
            else:
                raise ValueError(f"unsupported storage scheme {scheme!r}")
            self.root = Path(path)

    # -- core ---------------------------------------------------------------

    def _full(self, key: str) -> str:
        return str(self.root / key)

    def put(self, key: str, data: bytes):
        if self.fs is None:
            p = Path(self._full(key))
            p.parent.mkdir(parents=True, exist_ok=True)
            tmp = p.with_suffix(p.suffix + f".tmp{os.getpid()}")
            tmp.write_bytes(data)
            os.replace(tmp, p)
        else:
            with self.fs.open_output_stream(self._full(key)) as f:
                f.write(data)

    def put_if_not_exists(self, key: str, data: bytes):
        """CAS create: raises CasConflict if the key exists."""
        if self.fs is None:
            p = Path(self._full(key))
            p.parent.mkdir(parents=True, exist_ok=True)
            try:
                fd = os.open(p, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                raise CasConflict(key)
            with os.fdopen(fd, "wb") as f:
                f.write(data)
        else:
            if self.exists(key):
                raise CasConflict(key)
            self.put(key, data)

    def get(self, key: str) -> Optional[bytes]:
        if self.fs is None:
            p = Path(self._full(key))
            if not p.exists():
                return None
            return p.read_bytes()
        import pyarrow.fs as pafs

        try:
            with self.fs.open_input_stream(self._full(key)) as f:
                return f.read()
        except (FileNotFoundError, OSError):
            return None

    def exists(self, key: str) -> bool:
        if self.fs is None:
            return Path(self._full(key)).exists()
        import pyarrow.fs as pafs

        info = self.fs.get_file_info(self._full(key))
        return info.type != pafs.FileType.NotFound

    def delete(self, key: str):
        if self.fs is None:
            Path(self._full(key)).unlink(missing_ok=True)
        else:
            try:
                self.fs.delete_file(self._full(key))
            except (FileNotFoundError, OSError):
                pass

    def delete_directory(self, key: str):
        if self.fs is None:
            import shutil

            shutil.rmtree(self._full(key), ignore_errors=True)
        else:
            try:
                self.fs.delete_dir(self._full(key))
            except (FileNotFoundError, OSError):
                pass

    def list(self, prefix: str) -> List[str]:
        """Keys under prefix (relative to root)."""
        if self.fs is None:
            base = Path(self._full(prefix))
            if not base.exists():
                return []
            out = []
            for p in base.rglob("*"):
                if p.is_file():
                    out.append(str(p.relative_to(self.root)))
            return sorted(out)
        import pyarrow.fs as pafs

        sel = pafs.FileSelector(self._full(prefix), recursive=True,
                                allow_not_found=True)
        return sorted(
            str(Path(fi.path).relative_to(self.root))
            for fi in self.fs.get_file_info(sel)
            if fi.type == pafs.FileType.File
        )

    # -- arrow IO helpers ----------------------------------------------------

    def write_parquet(self, key: str, table) -> int:
        import io

        import pyarrow.parquet as pq

        buf = io.BytesIO()
        pq.write_table(table, buf)
        data = buf.getvalue()
        self.put(key, data)
        return len(data)

    def read_parquet(self, key: str):
        import io

        import pyarrow.parquet as pq

        data = self.get(key)
        if data is None:
            return None
        return pq.read_table(io.BytesIO(data))


def _parse(url: str) -> Tuple[str, str]:
    if "://" not in url:
        return "file", str(Path(url).absolute())
    u = urlparse(url)
    if u.scheme == "file":
        return "file", u.path
    return u.scheme, (u.netloc + u.path)
