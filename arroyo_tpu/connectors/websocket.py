"""Placeholder: websocket connector lands with the connector milestone."""
