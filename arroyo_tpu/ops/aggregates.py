"""Keyed aggregate accumulators: the device-resident window state.

This is the TPU-native replacement for the reference's per-bin DataFusion
partial-aggregation streams (/root/reference/crates/arroyo-worker/src/arrow/
tumbling_aggregating_window.rs:66-110): instead of running a CPU physical
plan per bin, ALL (bin, key) groups share flat device arrays of accumulator
slots, updated with one jitted scatter-reduce per batch and drained with one
gather per watermark. Slot assignment (the "hash table") stays host-side in
round 1 — a python dict over unique (bin, key) pairs, O(unique) per batch —
while the O(rows) arithmetic runs on device.

Shape discipline: `slots`/value arrays are padded to bucket sizes
(config.tpu.shape_buckets) so XLA compiles O(buckets × capacities) programs,
not one per batch size. Padded rows scatter neutral elements into a
reserved scratch slot.

Supported aggregate kinds: count, sum, min, max, avg (each decomposes into
"physical" accumulators: add/min/max over a column or the constant 1).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config import config
from ..obs import device as obs_device

# jax import deferred so host-only deployments can import the module tree
from ._jax import get_jax as _get_jax
from ._jax import safe_donate


INT_MIN = np.iinfo(np.int64).min
INT_MAX = np.iinfo(np.int64).max


# one-argument variance family: decomposes to (Σx, Σx², n) — pure
# add-reductions, so updates stay on-device AND invert under retraction
VAR_KINDS = ("var", "var_samp", "var_pop", "stddev", "stddev_samp",
             "stddev_pop")
# two-argument regression family over (y, x): (Σy, Σx, Σxy, Σy², Σx², n)
REGR_KINDS = ("covar_pop", "covar_samp", "corr", "regr_slope",
              "regr_intercept", "regr_r2", "regr_avgx", "regr_avgy",
              "regr_count", "regr_sxx", "regr_syy", "regr_sxy")
# host-buffered builtins (raw values kept per slot; finalized at emission)
BUFFER_KINDS = ("median", "approx_median", "approx_percentile_cont",
                "approx_percentile_cont_with_weight", "bit_and", "bit_or",
                "bit_xor", "array_agg")


@dataclasses.dataclass(frozen=True)
class AggSpec:
    kind: str  # count | sum | min | max | avg | count_distinct | udaf | ...
    col: Optional[int]  # input column index (None for count(*))
    name: str  # output field name
    is_float: bool = False  # input/output numeric class
    udaf: Optional[str] = None  # registered UDAF name when kind == "udaf"
    col2: Optional[int] = None  # second argument (regr family, weights)
    param: Optional[float] = None  # percentile fraction etc.
    # DISTINCT modifier on a non-count aggregate (sum/avg/min/max DISTINCT):
    # values dedupe through the multiset, finalized per kind
    distinct: bool = False
    # retraction replay (reference incremental_aggregator.rs raw-value
    # replay, :77-90): a non-invertible aggregate consuming an updating
    # input keeps value -> signed count and re-aggregates at emission, so
    # retractions erase their contribution exactly
    replay: bool = False

    def host_state(self) -> Optional[str]:
        """Host-resident per-slot state flavor, or None when the aggregate
        decomposes fully onto device phys arrays. 'buffer' = raw value
        chunks (UDAFs, median/percentile/bit/array_agg; append-only).
        'multiset' = value -> signed count (count_distinct/approx_distinct,
        DISTINCT modifiers, and retraction replay; retractable,
        mergeable)."""
        if self.kind in ("count_distinct", "approx_distinct"):
            return "multiset"
        if self.distinct or self.replay:
            return "multiset"
        if self.kind == "udaf" or self.kind in BUFFER_KINDS:
            return "buffer"
        return None

    def phys(self) -> List[Tuple[str, str, str]]:
        """[(op, dtype, source)]: op in add|min|max, dtype i8|f8, source
        col|col2|one|sq (col²)|sq2 (col2²)|prod (col·col2)."""
        if self.host_state() is not None:
            # host-state aggregates keep raw values host-side (the
            # reference hands all values to its UDAFs too, udafs.rs;
            # count_distinct is a DataFusion grouped-distinct there)
            return []
        if self.kind == "count":
            return [("add", "i8", "one")]
        if self.kind in VAR_KINDS:
            return [("add", "f8", "col"), ("add", "f8", "sq"),
                    ("add", "i8", "one")]
        if self.kind in REGR_KINDS:
            return [("add", "f8", "col"), ("add", "f8", "col2"),
                    ("add", "f8", "prod"), ("add", "f8", "sq"),
                    ("add", "f8", "sq2"), ("add", "i8", "one")]
        if self.kind == "bool_and":
            return [("min", "i8", "col")]
        if self.kind == "bool_or":
            return [("max", "i8", "col")]
        d = "f8" if self.is_float else "i8"
        if self.kind == "sum":
            return [("add", d, "col")]
        if self.kind == "min":
            return [("min", d, "col")]
        if self.kind == "max":
            return [("max", d, "col")]
        if self.kind == "avg":
            return [("add", "f8", "col"), ("add", "i8", "one")]
        raise ValueError(f"unknown aggregate {self.kind}")


def _buffer_reducer(spec: "AggSpec"):
    """Grouped-values reducer for one buffered aggregate: the registered
    user function for UDAFs, a builtin for median/percentile/bit/array."""
    kind = spec.kind
    if kind == "udaf":
        from ..udf.registry import get_udaf

        u = get_udaf(spec.udaf)
        if u is None:
            raise ValueError(f"unknown UDAF {spec.udaf!r}")
        if spec.col2 is not None:
            return lambda g: u.fn(g[:, 0], g[:, 1])
        return u.fn
    if kind in ("median", "approx_median"):
        def median_fn(g):
            v = _not_null(g)
            return float(np.median(v)) if len(v) else np.nan

        return median_fn
    if kind == "approx_percentile_cont":
        p = float(spec.param) * 100.0

        def pct_fn(g):
            v = _not_null(g)
            return float(np.percentile(v, p)) if len(v) else np.nan

        return pct_fn
    if kind == "approx_percentile_cont_with_weight":
        p = float(spec.param)

        def weighted(g):
            if not len(g):
                return np.nan
            vals = g[:, 0].astype(np.float64)
            w = g[:, 1].astype(np.float64)
            order = np.argsort(vals, kind="stable")
            vals, w = vals[order], w[order]
            cum = np.cumsum(w)
            total = cum[-1]
            if total <= 0:
                return np.nan
            return float(vals[np.searchsorted(cum, p * total, "left")])

        return weighted
    if kind in ("bit_and", "bit_or", "bit_xor"):
        op = {"bit_and": np.bitwise_and, "bit_or": np.bitwise_or,
              "bit_xor": np.bitwise_xor}[kind]

        def bit_fn(g):
            v = _not_null(g)
            return int(op.reduce(v.astype(np.int64))) if len(v) else 0

        return bit_fn
    if kind == "array_agg":
        return lambda g: list(g)
    raise ValueError(f"unknown buffered aggregate {kind}")


def _reduce_multiset(spec: "AggSpec", d: dict):
    """Finalize one slot's value->count multiset. DISTINCT modifiers
    ignore the counts (each live value contributes once); retraction
    replay (spec.replay) expands values by their signed live counts and
    re-aggregates, so a fully-retracted value contributes nothing."""
    kind = spec.kind
    if not d:
        if kind == "count":
            return 0
        return [] if kind == "array_agg" else None
    keys = list(d.keys())
    if kind == "min":
        return min(keys)
    if kind == "max":
        return max(keys)
    if kind == "bool_and":
        return all(bool(k) for k in keys)
    if kind == "bool_or":
        return any(bool(k) for k in keys)
    counts = (
        np.ones(len(d), dtype=np.int64)
        if spec.distinct
        else np.fromiter(d.values(), dtype=np.int64, count=len(d))
    )
    if kind == "count":
        return int(counts.sum())
    if kind == "sum":
        vals = np.asarray(keys)
        return (vals * counts).sum()
    if kind == "avg":
        vals = np.asarray(keys, dtype=np.float64)
        return float((vals * counts).sum() / counts.sum())
    # buffered builtins / UDAFs: expand to the raw value group and reduce
    karr = np.empty(len(keys), dtype=object)
    karr[:] = keys
    expanded = np.repeat(karr, counts)
    if spec.col2 is not None:
        rows = [list(t) for t in expanded]
        try:
            g = np.asarray(rows, dtype=np.float64)
        except (ValueError, TypeError):
            # non-numeric 2-arg groups (e.g. string UDAF args) keep
            # object dtype, matching the buffer path's column_stack
            g = np.empty((len(rows), 2), dtype=object)
            for i, r in enumerate(rows):
                g[i] = r
    else:
        g = np.asarray(expanded.tolist())
    return _buffer_reducer(spec)(g)


def _not_null(g: np.ndarray) -> np.ndarray:
    return g[_not_null_mask(g)]


def _finalize_variance(kind: str, vals: List[np.ndarray]) -> np.ndarray:
    """(Σx, Σx², n) -> variance/stddev. Sample variants return NaN below
    two rows (SQL NULL); population variants need one."""
    s, ss, n = (v.astype(np.float64) for v in vals)
    pop = kind.endswith("_pop")
    denom = n if pop else n - 1
    var = (ss - s * s / np.maximum(n, 1)) / denom
    var = np.where(denom > 0, np.maximum(var, 0.0), np.nan)
    if kind.startswith("stddev"):
        return np.sqrt(var)
    return var


def _finalize_regression(kind: str, vals: List[np.ndarray]) -> np.ndarray:
    """(Σy, Σx, Σxy, Σy², Σx², n) -> the SQL regression family over
    (y, x) argument order (regr_slope(y, x) regresses y on x)."""
    sy, sx, sxy, syy, sxx, n = (v.astype(np.float64) for v in vals)
    nz = np.maximum(n, 1)
    cxy = sxy - sx * sy / nz  # n·cov
    cxx = sxx - sx * sx / nz
    cyy = syy - sy * sy / nz
    if kind == "covar_pop":
        return np.where(n > 0, cxy / nz, np.nan)
    if kind == "covar_samp":
        return np.where(n > 1, cxy / (n - 1), np.nan)
    if kind == "corr":
        return np.where(
            (n > 0) & (cxx > 0) & (cyy > 0),
            cxy / np.sqrt(cxx * cyy), np.nan,
        )
    if kind == "regr_slope":
        return np.where((n > 0) & (cxx != 0), cxy / cxx, np.nan)
    if kind == "regr_intercept":
        slope = np.where((n > 0) & (cxx != 0), cxy / cxx, np.nan)
        return sy / nz - slope * sx / nz
    if kind == "regr_r2":
        r = np.where(
            (n > 0) & (cxx > 0) & (cyy > 0),
            cxy / np.sqrt(cxx * cyy), np.nan,
        )
        return r * r
    if kind == "regr_avgx":
        return np.where(n > 0, sx / nz, np.nan)
    if kind == "regr_avgy":
        return np.where(n > 0, sy / nz, np.nan)
    if kind == "regr_count":
        return n.astype(np.int64)
    if kind == "regr_sxx":
        return np.where(n > 0, cxx, np.nan)
    if kind == "regr_syy":
        return np.where(n > 0, cyy, np.nan)
    if kind == "regr_sxy":
        return np.where(n > 0, cxy, np.nan)
    raise ValueError(f"unknown regression kind {kind}")


def _src_values(spec: "AggSpec", src: str, cols: Dict) -> np.ndarray:
    """Row values for one physical accumulator source. Derived sources
    (sq/prod) compute in float64 so Σx² and Σxy never overflow int64."""
    if src == "col":
        return cols[spec.col]
    if src == "col2":
        return cols[spec.col2]
    if src == "sq":
        x = cols[spec.col].astype(np.float64, copy=False)
        return x * x
    if src == "sq2":
        x = cols[spec.col2].astype(np.float64, copy=False)
        return x * x
    if src == "prod":
        return (
            cols[spec.col].astype(np.float64, copy=False)
            * cols[spec.col2].astype(np.float64, copy=False)
        )
    raise ValueError(f"unknown phys source {src}")


def _not_null_mask(vals: np.ndarray) -> np.ndarray:
    """True per row where the value is non-null (None or NaN)."""
    if vals.dtype == object:
        return np.fromiter(
            (v is not None and v == v for v in vals),
            dtype=bool, count=len(vals),
        )
    if vals.dtype.kind == "f":
        return ~np.isnan(vals)
    if vals.dtype.kind == "M":
        return ~np.isnat(vals)
    return np.ones(len(vals), dtype=bool)


def _neutral(op: str, dtype: str, use32: bool = False):
    if op == "add":
        return 0
    if dtype == "f8":
        return np.inf if op == "min" else -np.inf
    if use32:
        info = np.iinfo(np.int32)
        return info.max if op == "min" else info.min
    return INT_MAX if op == "min" else INT_MIN


def _np_dtype(d: str, use32: bool = False):
    if use32:
        return np.float32 if d == "f8" else np.int32
    return np.float64 if d == "f8" else np.int64


def _bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return int(2 ** np.ceil(np.log2(max(n, 1))))


class Accumulator:
    """Flat slot-indexed accumulator state shared by all (bin, key) groups of
    one window-operator subtask. Backend 'jax' (device) or 'numpy' (host)."""

    def __init__(self, specs: List[AggSpec], capacity: int = 4096,
                 backend: str = "jax"):
        self.specs = specs
        self.backend = backend
        # TPU v5e has no native int64/float64 (emulated, slow); the
        # opt-in 32-bit mode keeps device accumulators in int32/float32.
        # Counts/mins/maxes of bounded values are exact; large sums can
        # overflow — hence opt-in (config tpu.use_32bit_accumulators)
        self.use32 = bool(
            backend == "jax"
            and getattr(config().tpu, "use_32bit_accumulators", False)
        )
        self.capacity = capacity  # last slot is scratch for padded rows
        self.phys: List[Tuple[str, str, str, int]] = []  # op,dtype,src,spec_idx
        for si, spec in enumerate(specs):
            for op, dtype, src in spec.phys():
                self.phys.append((op, dtype, src, si))
        self._buckets = tuple(config().tpu.shape_buckets)
        # host-side per-slot state: spec idx -> slot -> chunks ('buffer',
        # UDAFs) or value->count dict ('multiset', count_distinct)
        self.host_kinds: Dict[int, str] = {
            i: s.host_state() for i, s in enumerate(specs)
            if s.host_state() is not None
        }
        self.udaf_idx = [
            i for i, k in self.host_kinds.items() if k == "buffer"
        ]
        self.multiset_idx = [
            i for i, k in self.host_kinds.items() if k == "multiset"
        ]
        self.udaf_store: Dict[int, Dict[int, list]] = {
            i: {} for i in self.udaf_idx
        }
        self.multiset_store: Dict[int, Dict[int, dict]] = {
            i: {} for i in self.multiset_idx
        }
        self._gather_slots: Optional[np.ndarray] = None
        self._segment_udaf: Optional[Dict[int, list]] = None
        self._segment_multiset: Optional[Dict[int, list]] = None
        if backend == "jax":
            jnp = _get_jax().numpy
            self.state = [
                jnp.full(capacity, self._neutral(op, dt), dtype=self._dt(dt))
                for op, dt, _, _ in self.phys
            ]
            self._update_fn = self._make_update_fn()
            self._gather_fn = self._make_gather_fn()
        else:
            self.state = [
                np.full(capacity, self._neutral(op, dt), dtype=self._dt(dt))
                for op, dt, _, _ in self.phys
            ]

    def _dt(self, d: str):
        return _np_dtype(d, self.use32)

    def _neutral(self, op: str, dt: str):
        return _neutral(op, dt, self.use32)

    # -- capacity -----------------------------------------------------------

    def grow(self, min_capacity: int):
        # 4x steps (not 2x): every growth re-specializes the jitted
        # update/gather/reset programs for the new state shape, so fewer,
        # larger jumps bound recompilation churn at high cardinality
        new_cap = self.capacity
        while new_cap < min_capacity:
            new_cap *= 4
        if new_cap == self.capacity:
            return
        # the old scratch slot (capacity-1) absorbed padded-row scatters;
        # it becomes an allocatable slot after growth and must restart
        # from neutral
        if self.backend == "jax":
            jnp = _get_jax().numpy
            self.state = [
                jnp.concatenate(
                    [s, jnp.full(new_cap - self.capacity,
                                 self._neutral(op, dt),
                                 dtype=self._dt(dt))]
                ).at[self.capacity - 1].set(self._neutral(op, dt))
                for s, (op, dt, _, _) in zip(self.state, self.phys)
            ]
        else:
            self.state = [
                np.concatenate(
                    [s, np.full(new_cap - self.capacity,
                                self._neutral(op, dt),
                                dtype=self._dt(dt))]
                )
                for s, (op, dt, _, _) in zip(self.state, self.phys)
            ]
            for (op, dt, _, _), s in zip(self.phys, self.state):
                s[self.capacity - 1] = self._neutral(op, dt)
        self.capacity = new_cap

    # -- update (hot path) --------------------------------------------------

    def update(self, slots: np.ndarray, cols: Dict[int, np.ndarray],
               signs: Optional[np.ndarray] = None):
        """Scatter-reduce a batch. slots[i] = accumulator slot of row i
        (must be < capacity-1; capacity-1 is scratch). cols maps input column
        index -> numpy array of row values. `signs` (+1 append / -1 retract
        per row) makes the update invertible for retraction-consuming
        aggregates: add-reductions (count/sum/avg/variance/regression)
        apply the sign arithmetically, multisets (count_distinct, DISTINCT
        modifiers, replay specs) track signed value counts. Non-add device
        reductions (min/max phys) cannot invert — the planner must mark
        those specs `replay` first."""
        n = len(slots)
        if n == 0:
            return
        self._check_signed(signs)
        self._update_host(slots, cols, signs)
        if not self.phys:
            return
        if self.backend == "numpy":
            self._np_update(slots, cols, signs)
            return
        jnp = _get_jax().numpy
        padded = _bucket(n, self._buckets)
        slots_p = np.full(padded, self.capacity - 1, dtype=np.int64)
        slots_p[:n] = slots
        valid = np.zeros(padded, dtype=np.int64)
        valid[:n] = 1 if signs is None else signs
        inputs = []
        for op, dt, src, si in self.phys:
            spec = self.specs[si]
            if src == "one":
                vals = valid
            else:
                vals = np.zeros(padded, dtype=self._dt(dt))
                base = _src_values(spec, src, cols)
                vals[:n] = base if signs is None else base * signs
                if op != "add":
                    vals[n:] = self._neutral(op, dt)
            inputs.append(jnp.asarray(vals))
        obs_device.note_padding("agg.update", padded, n, padded)
        self.state = self._update_fn(
            self.state, jnp.asarray(slots_p), *inputs, rung=padded
        )

    def _check_signed(self, signs: Optional[np.ndarray]):
        if signs is not None and (
            self.udaf_idx or any(op != "add" for op, _, _, _ in self.phys)
        ):
            raise ValueError(
                "signed (retractable) update reached a non-invertible "
                "accumulator (min/max phys or append-only buffer); the "
                "planner should have marked these specs replay=True"
            )

    def _update_host(self, slots: np.ndarray, cols: Dict[int, np.ndarray],
                     signs: Optional[np.ndarray] = None):
        """Fold a batch into the host-side per-slot states: value chunks
        for 'buffer' specs, signed value counts for 'multiset' specs."""
        if not self.host_kinds:
            return
        n = len(slots)
        order = np.argsort(slots, kind="stable")
        s_sorted = slots[order]
        bounds = np.nonzero(np.diff(s_sorted))[0] + 1
        starts = np.concatenate([[0], bounds])
        ends = np.concatenate([bounds, [n]])
        sg_sorted = signs[order] if signs is not None else None
        for si in self.udaf_idx:
            vals = self._host_vals(si, cols)[order]
            spec = self.specs[si]
            if spec.col2 is not None:
                # two-argument buffers (weighted percentile, 2-arg UDAFs)
                # stack to one (rows, 2) chunk so chunks concatenate
                second = cols[("raw", spec.col2)] if (
                    "raw", spec.col2) in cols else cols[spec.col2]
                vals = np.column_stack([vals, second[order]])
            store = self.udaf_store[si]
            for lo, hi in zip(starts, ends):
                store.setdefault(int(s_sorted[lo]), []).append(vals[lo:hi])
        for si in self.multiset_idx:
            # SQL aggregates exclude NULLs; raw columns carry them as None
            # (object dtype) or NaN (float)
            vals = self._host_vals(si, cols)[order]
            valid = _not_null_mask(vals)
            spec = self.specs[si]
            if spec.col2 is not None:
                # two-argument multisets (weighted percentile / 2-arg UDAF
                # replay): the multiset key is the (v1, v2) pair. col2
                # nulls/NaNs must be masked too — None breaks np.unique's
                # sort and a NaN-bearing pair key never equals itself, so
                # a retraction could never cancel its insert
                second = cols[("raw", spec.col2)] if (
                    "raw", spec.col2) in cols else cols[spec.col2]
                second = second[order]
                valid = valid & _not_null_mask(second)
                pairs = np.empty(len(vals), dtype=object)
                pairs[:] = list(zip(vals.tolist(), second.tolist()))
                vals = pairs
            store = self.multiset_store[si]
            for lo, hi in zip(starts, ends):
                d = store.setdefault(int(s_sorted[lo]), {})
                gv = valid[lo:hi]
                group = vals[lo:hi][gv]
                if sg_sorted is None:
                    uniq, counts = np.unique(group, return_counts=True)
                    for v, c in zip(uniq.tolist(), counts.tolist()):
                        d[v] = d.get(v, 0) + c
                else:
                    for v, sg in zip(group.tolist(),
                                     sg_sorted[lo:hi][gv].tolist()):
                        nc = d.get(v, 0) + int(sg)
                        if nc <= 0:
                            d.pop(v, None)
                        else:
                            d[v] = nc

    def _host_vals(self, si: int, cols: Dict) -> np.ndarray:
        """Host-state specs read the raw (uncast) representation when the
        operator provided one under ('raw', col) — a column shared with a
        float-cast numeric spec would otherwise lose integer precision
        above 2^53 in the multiset keys."""
        c = self.specs[si].col
        return cols[("raw", c)] if ("raw", c) in cols else cols[c]

    def _make_update_fn(self):
        jax = _get_jax()
        phys = list(self.phys)

        @partial(jax.jit, donate_argnums=safe_donate(0))
        def update(state, slots, *vals):
            out = []
            for (op, dt, src, si), s, v in zip(phys, state, vals):
                if op == "add":
                    out.append(s.at[slots].add(v))
                elif op == "min":
                    out.append(s.at[slots].min(v))
                else:
                    out.append(s.at[slots].max(v))
            return out

        return obs_device.InstrumentedJit("agg.update", update)

    def _np_update(self, slots, cols, signs=None):
        for (op, dt, src, si), s in zip(self.phys, self.state):
            spec = self.specs[si]
            if src == "one":
                vals = (
                    np.ones(len(slots), dtype=np.int64)
                    if signs is None else signs.astype(np.int64)
                )
            else:
                vals = _src_values(spec, src, cols).astype(
                    self._dt(dt), copy=False
                )
                if signs is not None:
                    vals = vals * signs
            if op == "add":
                np.add.at(s, slots, vals)
            elif op == "min":
                np.minimum.at(s, slots, vals)
            else:
                np.maximum.at(s, slots, vals)

    # -- drain --------------------------------------------------------------

    def gather(self, slots: np.ndarray,
               materialize: bool = True) -> List[np.ndarray]:
        """Read accumulator values for `slots` (emission); returns one numpy
        array per physical accumulator. The slots are remembered so
        finalize() can resolve UDAF value buffers for the same emission.
        With materialize=False the jax device->host copy is only
        *dispatched*: the returned arrays are device arrays whose
        np.asarray completes later (async snapshot overlap)."""
        self._gather_slots = np.asarray(slots)
        self._segment_udaf = None
        self._segment_multiset = None
        if len(slots) == 0:
            return [np.empty(0, dtype=s.dtype) for s in
                    (self.state if self.backend == "numpy" else self.state)]
        if self.backend == "numpy":
            return [s[slots] for s in self.state]
        jnp = _get_jax().numpy
        padded = _bucket(len(slots), self._buckets)
        slots_p = np.full(padded, self.capacity - 1, dtype=np.int64)
        slots_p[: len(slots)] = slots
        obs_device.note_padding("agg.gather", padded, len(slots), padded)
        outs = self._gather_fn(
            self.state, jnp.asarray(slots_p), rung=padded
        )
        if not materialize:
            return [o[: len(slots)] for o in outs]
        return [np.asarray(o)[: len(slots)] for o in outs]

    def _make_gather_fn(self):
        jax = _get_jax()

        @jax.jit
        def gather(state, slots):
            return [s[slots] for s in state]

        return obs_device.InstrumentedJit("agg.gather", gather)

    def drop_host_state(self, slots: np.ndarray):
        """Forget host-side per-slot state (UDAF buffers / multisets) for
        freed slots — the host half of reset_slots, for callers that
        fused the device half into the gather (gather_and_reset)."""
        self._drop_udaf_slots(slots)

    def _drop_udaf_slots(self, slots: np.ndarray):
        for si in self.udaf_idx:
            store = self.udaf_store[si]
            for s in slots:
                store.pop(int(s), None)
        for si in self.multiset_idx:
            store = self.multiset_store[si]
            for s in slots:
                store.pop(int(s), None)

    def reset_slots(self, slots: np.ndarray):
        """Return emitted slots to neutral so they can be reused."""
        self._drop_udaf_slots(slots)
        if len(slots) == 0 or not self.phys:
            return
        if self.backend == "numpy":
            for (op, dt, _, _), s in zip(self.phys, self.state):
                s[slots] = self._neutral(op, dt)
            return
        jnp = _get_jax().numpy
        padded = _bucket(len(slots), self._buckets)
        slots_p = np.full(padded, self.capacity - 1, dtype=np.int64)
        slots_p[: len(slots)] = slots
        if not hasattr(self, "_reset_fn"):
            jax = _get_jax()
            neutrals = [
                self._neutral(op, dt) for op, dt, _, _ in self.phys
            ]

            @partial(jax.jit, donate_argnums=safe_donate(0))
            def reset(state, s_idx):
                return [
                    s.at[s_idx].set(nv) for s, nv in zip(state, neutrals)
                ]

            self._reset_fn = obs_device.InstrumentedJit("agg.reset", reset)
        self.state = self._reset_fn(
            self.state, jnp.asarray(slots_p), rung=padded
        )

    # -- finalize -----------------------------------------------------------

    def finalize(self, gathered: List[np.ndarray]) -> List[np.ndarray]:
        """Physical accumulator values -> one output column per spec.
        Host-state specs resolve from the per-slot stores of the slots from
        the preceding gather()/combine_for_segments()."""
        out = []
        pi = 0
        for si, spec in enumerate(self.specs):
            hs = spec.host_state()
            if hs == "buffer":
                out.append(self._finalize_udaf(si))
                continue
            if hs == "multiset":
                out.append(self._finalize_multiset(si))
                continue
            n_phys = len(spec.phys())
            vals = gathered[pi: pi + n_phys]
            pi += n_phys
            with np.errstate(invalid="ignore", divide="ignore"):
                if spec.kind == "avg":
                    out.append(vals[0] / np.maximum(vals[1], 1))
                elif spec.kind in VAR_KINDS:
                    out.append(_finalize_variance(spec.kind, vals))
                elif spec.kind in REGR_KINDS:
                    out.append(_finalize_regression(spec.kind, vals))
                elif spec.kind in ("bool_and", "bool_or"):
                    out.append(vals[0] != 0)
                else:
                    out.append(vals[0])
        return out

    def _finalize_multiset(self, si: int) -> np.ndarray:
        spec = self.specs[si]
        if self._segment_multiset is not None:
            dicts = self._segment_multiset.get(si, [])
        else:
            store = self.multiset_store[si]
            dicts = [store.get(int(s), {}) for s in self._gather_slots]
        if spec.kind in ("count_distinct", "approx_distinct"):
            return np.asarray([len(d) for d in dicts], dtype=np.int64)
        out = [_reduce_multiset(spec, d) for d in dicts]
        if spec.kind == "array_agg":
            arr = np.empty(len(out), dtype=object)
            arr[:] = out
            return arr
        return np.asarray(out)

    def _finalize_udaf(self, si: int) -> np.ndarray:
        """Evaluate a buffered aggregate (registered UDAF or builtin
        median/percentile/bit/array_agg reducer) per emitted slot."""
        spec = self.specs[si]
        if self._segment_udaf is not None:
            groups = self._segment_udaf.get(si, [])
        else:
            store = self.udaf_store[si]
            empty = (
                np.empty((0, 2)) if spec.col2 is not None else np.empty(0)
            )
            groups = [
                np.concatenate(store.get(int(s), [empty]))
                for s in self._gather_slots
            ]
        fn = _buffer_reducer(spec)
        out = [fn(g) for g in groups]
        if spec.kind == "array_agg":
            arr = np.empty(len(out), dtype=object)
            arr[:] = out
            return arr
        return np.asarray(out)

    def combine_for_segments(
        self, slots: np.ndarray, seg_ids: np.ndarray, n_segments: int
    ) -> List[np.ndarray]:
        """Merge per-slot accumulators into per-segment values (sliding
        window emission): device phys arrays segment-reduce on host; UDAF
        buffers concatenate per segment for the subsequent finalize()."""
        return self._combine_gathered(
            self.gather(slots), slots, seg_ids, n_segments
        )

    def combine_for_segments_and_free(
        self, slots: np.ndarray, seg_ids: np.ndarray, n_segments: int,
        free_n: int = 0,
    ) -> List[np.ndarray]:
        """combine_for_segments, additionally freeing the device state of
        the FIRST free_n slots — the sliding merge frees the bin exiting
        the window in the same wave it last reads it, so the union is
        ordered freed-bin-first. The mesh accumulator overrides this with
        ONE fused gather+reset dispatch; here the reset is a second pass."""
        combined = self.combine_for_segments(slots, seg_ids, n_segments)
        if free_n:
            self.reset_slots(np.asarray(slots)[:free_n])
        return combined

    def _combine_gathered(
        self, gathered: List[np.ndarray], slots: np.ndarray,
        seg_ids: np.ndarray, n_segments: int,
    ) -> List[np.ndarray]:
        combined = []
        for (op, dt, _, _), vals in zip(self.phys, gathered):
            outv = np.full(n_segments, self._neutral(op, dt), dtype=self._dt(dt))
            if op == "add":
                np.add.at(outv, seg_ids, vals)
            elif op == "min":
                np.minimum.at(outv, seg_ids, vals)
            else:
                np.maximum.at(outv, seg_ids, vals)
            combined.append(outv)
        if self.udaf_idx:
            seg_map: Dict[int, list] = {}
            for si in self.udaf_idx:
                store = self.udaf_store[si]
                empty = (
                    np.empty((0, 2))
                    if self.specs[si].col2 is not None else np.empty(0)
                )
                groups = [[] for _ in range(n_segments)]
                for s, seg in zip(slots, seg_ids):
                    groups[int(seg)].extend(store.get(int(s), []))
                seg_map[si] = [
                    np.concatenate(g) if g else empty for g in groups
                ]
            self._segment_udaf = seg_map
        if self.multiset_idx:
            mseg: Dict[int, list] = {}
            for si in self.multiset_idx:
                store = self.multiset_store[si]
                dicts: List[dict] = [{} for _ in range(n_segments)]
                for s, seg in zip(slots, seg_ids):
                    d = dicts[int(seg)]
                    for v, c in store.get(int(s), {}).items():
                        d[v] = d.get(v, 0) + c
                mseg[si] = dicts
            self._segment_multiset = mseg
        return combined

    def merge_slot_into(self, dst: int, src: int):
        """Fold slot src into dst (session merges): device phys via
        gather/restore is handled by the caller; host state moves here."""
        for si in self.udaf_idx:
            store = self.udaf_store[si]
            if src in store:
                store.setdefault(dst, []).extend(store.pop(src))
        for si in self.multiset_idx:
            store = self.multiset_store[si]
            if src in store:
                d = store.setdefault(dst, {})
                for v, c in store.pop(src).items():
                    d[v] = d.get(v, 0) + c

    # -- checkpoint ---------------------------------------------------------

    def snapshot(self, slots: np.ndarray,
                 materialize: bool = True) -> List[np.ndarray]:
        """Device->host copy of live slots for checkpointing; host state
        rides along as one list-valued column per host-state spec (value
        chunks for buffers, [value, count] pairs for multisets), ordered
        buffers-then-multisets by spec index."""
        out = self.gather(slots, materialize=materialize)
        for si in self.udaf_idx:
            store = self.udaf_store[si]
            out.append(np.asarray(
                [np.concatenate(store.get(int(s), [np.empty(0)])).tolist()
                 for s in slots],
                dtype=object,
            ))
        for si in self.multiset_idx:
            store = self.multiset_store[si]
            out.append(np.asarray(
                [[[v, c] for v, c in store.get(int(s), {}).items()]
                 for s in slots],
                dtype=object,
            ))
        return out

    def _restore_udaf_cols(
        self, slots: np.ndarray, values: List[np.ndarray]
    ) -> List[np.ndarray]:
        """Consume trailing host-state columns; returns the physical
        accumulator columns."""
        if not self.host_kinds:
            return values
        n_phys = len(self.phys)
        host_cols = values[n_phys:]
        values = values[:n_phys]
        n_buf = len(self.udaf_idx)
        for si, col in zip(self.udaf_idx, host_cols[:n_buf]):
            store = self.udaf_store[si]
            for s, vals in zip(slots, col):
                arr = np.asarray(list(vals))
                if len(arr):
                    store.setdefault(int(s), []).append(arr)
        for si, col in zip(self.multiset_idx, host_cols[n_buf:]):
            store = self.multiset_store[si]
            for s, pairs in zip(slots, col):
                if len(pairs):
                    d = store.setdefault(int(s), {})
                    for v, c in pairs:
                        # msgpack round-trips tuple keys (two-argument
                        # multisets) as lists; re-hash as tuples
                        k = tuple(v) if isinstance(v, list) else v
                        d[k] = d.get(k, 0) + int(c)
        return values

    def restore(self, slots: np.ndarray, values: List[np.ndarray]):
        """Write physical accumulator values back into `slots` (the tail
        columns are host-state buffers when such specs exist)."""
        values = self._restore_udaf_cols(slots, values)
        if len(slots) == 0 or not self.phys:
            return
        if self.backend == "numpy":
            for s, v in zip(self.state, values):
                s[slots] = v
            return
        jnp = _get_jax().numpy
        self.state = [
            s.at[jnp.asarray(slots)].set(jnp.asarray(v))
            for s, v in zip(self.state, values)
        ]

    def block_until_ready(self):
        if self.backend != "numpy":
            for s in self.state:
                s.block_until_ready()


def make_accumulator(specs: List[AggSpec], capacity: Optional[int] = None,
                     backend: Optional[str] = None) -> Accumulator:
    if backend is None:
        from ._jax import device_tier_active

        backend = "jax" if device_tier_active() else "numpy"
    if capacity is None:
        capacity = int(config().tpu.initial_capacity)
    return Accumulator(specs, capacity, backend)
