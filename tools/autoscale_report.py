#!/usr/bin/env python
"""Replay load traces through the autoscale policy, offline.

    python tools/autoscale_report.py
        Built-in load-step scenario (1x -> 4x -> 1x offered rate through
        a source -> operator -> sink chain): prints one line per control
        period — offered rate, action, per-node parallelism — plus a
        convergence summary. This is the acceptance scenario the tier-1
        test pins (tests/test_autoscale.py).

    python tools/autoscale_report.py --trace trace.json
        Replay a recorded trace. The file is
        {"ops": [{"node_id", "rate_per_instance", "parallelism",
                  "selectivity"?, "source"?, "sink"?}],
         "edges": [[src, dst]],
         "steps": [[n_periods, offered_rate], ...]} — the shape
        `SimJob`/`run_scenario` consume; record one from a live run's
        /api/v1/jobs/{id}/autoscale decision log.

    python tools/autoscale_report.py --json out.json
        Also write the full decision log as JSON.

Policy knobs come from the normal config tree (ARROYO__AUTOSCALE__* env
vars work), so "what would the controller have done with hysteresis 0.3"
is a one-env-var experiment, no cluster needed.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def default_scenario():
    from arroyo_tpu.autoscale import SimJob, SimOp

    job = SimJob(
        [
            SimOp(1, source=True),
            SimOp(2, rate_per_instance=1000.0, parallelism=1),
            SimOp(3, sink=True, rate_per_instance=1e9),
        ],
        [(1, 2), (2, 3)],
    )
    steps = [(8, 700.0), (8, 2800.0), (8, 700.0)]
    return job, steps


def load_trace(path):
    from arroyo_tpu.autoscale import SimJob, SimOp

    with open(path) as f:
        obj = json.load(f)
    ops = [SimOp(**op) for op in obj["ops"]]
    edges = [tuple(e) for e in obj["edges"]]
    steps = [tuple(s) for s in obj["steps"]]
    return SimJob(ops, edges), steps


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--trace", type=str, default="",
                    help="recorded trace JSON to replay (default: the "
                    "built-in 1x->4x->1x load-step scenario)")
    ap.add_argument("--policy", type=str, default="",
                    help="policy name (default: config autoscale.policy)")
    ap.add_argument("--json", type=str, default="",
                    help="write the full decision log to this file")
    args = ap.parse_args()

    from arroyo_tpu.autoscale import make_policy, run_scenario
    from arroyo_tpu.config import config

    cfg = config().autoscale
    policy = make_policy(args.policy or cfg.policy)
    job, steps = load_trace(args.trace) if args.trace else default_scenario()

    log = run_scenario(job, policy, cfg, steps)
    print(f"policy={args.policy or cfg.policy} "
          f"busy=[{cfg.busy_low}, {cfg.busy_high}] "
          f"hysteresis={cfg.hysteresis} cooldown={cfg.cooldown_periods} "
          f"clamp=[{cfg.min_parallelism}, {cfg.max_parallelism}]")
    print(f"{'period':>6}  {'offered/s':>10}  {'action':<12} parallelism")
    rescales = 0
    for rec in log:
        par = " ".join(f"{n}:{p}" for n, p in sorted(rec.parallelism.items()))
        mark = ""
        if rec.action == "rescale":
            rescales += 1
            mark = "  <- " + "; ".join(rec.reasons.values())
        print(f"{rec.period:>6}  {rec.offered_rate:>10.0f}  "
              f"{rec.action:<12} {par}{mark}")
    print(f"\n{rescales} rescale(s) over {len(log)} control periods; "
          f"final parallelism "
          f"{ {n: p for n, p in sorted(log[-1].parallelism.items())} }")
    if args.json:
        with open(args.json, "w") as f:
            json.dump([r.to_json() for r in log], f, indent=1)
        print(f"decision log written to {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
