"""Connectors + formats: serde roundtrips, single_file through SQL with
restore, nexmark generation + a nexmark query, filesystem sink 2PC."""

import asyncio
import json
import os

import pyarrow as pa
import pytest

from arroyo_tpu.config import update
from arroyo_tpu.engine import Engine
from arroyo_tpu.formats.de import BadDataError, Deserializer
from arroyo_tpu.formats.ser import Serializer
from arroyo_tpu.schema import StreamSchema
from arroyo_tpu.sql import plan_query


def run_plan(plan, storage_url=None, job_id="t", timeout=60.0):
    async def go():
        eng = Engine(plan.graph, job_id=job_id, storage_url=storage_url).start()
        await eng.join(timeout)
        return eng

    return asyncio.run(go())


# -- formats ------------------------------------------------------------------


def test_json_deserialize_schema_and_baddata():
    s = StreamSchema.from_fields([("a", pa.int64()), ("b", pa.string())])
    d = Deserializer(s, format="json", bad_data="drop", framing="newline")
    rows = d.deserialize_slice(b'{"a": 1, "b": "x"}\nnot json\n{"a": 2}')
    assert len(rows) == 2
    assert rows[0]["a"] == 1 and rows[0]["b"] == "x"
    assert rows[1]["b"] is None
    d_fail = Deserializer(s, format="json", bad_data="fail")
    with pytest.raises(BadDataError):
        d_fail.deserialize_slice(b"not json")


def test_json_timestamp_parsing_scales():
    s = StreamSchema.from_fields([("t", pa.timestamp("ns"))])
    d = Deserializer(s, format="json", framing="newline")
    rows = d.deserialize_slice(
        b'{"t": 1000000000}\n'  # seconds
        b'{"t": 1000000000000}\n'  # millis
        b'{"t": "2020-01-01T00:00:00Z"}',
        timestamp=0,
    )
    assert rows[0]["t"] == 1_000_000_000 * 1_000_000_000
    assert rows[1]["t"] == 1_000_000_000_000 * 1_000_000
    assert rows[2]["t"] == 1_577_836_800 * 1_000_000_000


def test_serializer_json_and_debezium():
    s = StreamSchema.from_fields([("a", pa.int64())])
    batch = pa.RecordBatch.from_arrays(
        [pa.array([1, 2]), pa.array([0, 0], type=pa.int64()).cast(pa.timestamp("ns"))],
        schema=s.schema,
    )
    recs = list(Serializer("json").serialize(batch))
    assert [json.loads(r) for r in recs] == [{"a": 1}, {"a": 2}]
    dbz = [json.loads(r) for r in Serializer("debezium_json").serialize(batch)]
    assert dbz[0]["op"] == "c" and dbz[0]["after"] == {"a": 1}


def test_avro_roundtrip():
    from arroyo_tpu.formats.avro import AvroDecoder, AvroEncoder, schema_from_arrow

    schema = pa.schema([("x", pa.int64()), ("name", pa.string()),
                        ("score", pa.float64())])
    avro_schema = json.dumps(schema_from_arrow(schema))
    enc = AvroEncoder(avro_schema, schema)
    dec = AvroDecoder(avro_schema)
    row = {"x": 42, "name": "hello", "score": 2.5}
    assert dec.decode(enc.encode(row)) == row
    assert dec.decode(enc.encode({"x": None, "name": "a", "score": 0.0}))["x"] is None


# -- single_file through SQL with checkpoint/restore --------------------------


def make_cars(path, n=200):
    import random

    random.seed(7)
    with open(path, "w") as f:
        for i in range(n):
            f.write(json.dumps({
                "timestamp": f"2023-01-01T00:00:{i % 60:02d}.{i:03d}Z",
                "driver_id": 100 + i % 5,
                "event_type": "pickup" if i % 2 else "dropoff",
            }) + "\n")


def sql_for(tmp, out_name="out.json", throttle=""):
    return f"""
    CREATE TABLE cars (
      timestamp TIMESTAMP,
      driver_id BIGINT,
      event_type TEXT
    ) WITH (
      connector = 'single_file',
      path = '{tmp}/cars.json',
      format = 'json',
      type = 'source',
      event_time_field = 'timestamp'{throttle}
    );
    CREATE TABLE out (
      driver_id BIGINT,
      cnt BIGINT
    ) WITH (
      connector = 'single_file',
      path = '{tmp}/{out_name}',
      format = 'json',
      type = 'sink'
    );
    INSERT INTO out
    SELECT driver_id, cnt FROM (
      SELECT driver_id, tumble(interval '1 minute') as w, count(*) as cnt
      FROM cars
      GROUP BY 1, 2
    );
    """


def read_output(path):
    with open(path) as f:
        return sorted(
            (json.loads(line)["driver_id"], json.loads(line)["cnt"])
            for line in f if line.strip()
        )


def test_single_file_sql_roundtrip(tmp_path):
    make_cars(tmp_path / "cars.json")
    plan = plan_query(sql_for(tmp_path))
    run_plan(plan)
    out = read_output(tmp_path / "out.json")
    assert len(out) == 5
    assert sum(c for _, c in out) == 200


def test_single_file_checkpoint_restore_same_output(tmp_path):
    make_cars(tmp_path / "cars.json")
    golden = plan_query(sql_for(tmp_path, "golden.json"))
    run_plan(golden)
    want = read_output(tmp_path / "golden.json")

    url = str(tmp_path / "ckpt")

    async def run_and_stop():
        plan = plan_query(
            sql_for(tmp_path, throttle=",\n      throttle_per_sec = '1000'")
        )
        eng = Engine(plan.graph, job_id="sfr", storage_url=url).start()
        # let some rows flow (throttled to 1k/s), checkpoint-stop mid-stream
        await asyncio.sleep(0.1)
        await eng.checkpoint_and_wait(then_stop=True)
        await eng.join(60)

    asyncio.run(run_and_stop())

    plan2 = plan_query(sql_for(tmp_path))
    run_plan(plan2, storage_url=url, job_id="sfr")
    assert read_output(tmp_path / "out.json") == want


# -- nexmark ------------------------------------------------------------------


def test_nexmark_generator_proportions():
    from arroyo_tpu.connectors.nexmark import NexmarkGenerator

    g = NexmarkGenerator()
    kinds = [g.kind_of(n) for n in range(5000)]
    assert kinds.count("person") == 100
    assert kinds.count("auction") == 300
    assert kinds.count("bid") == 4600
    # deterministic
    e1 = g.event(77, 123)
    e2 = NexmarkGenerator().event(77, 123)
    assert e1 == e2
    # bids reference existing auctions
    for n in range(4, 50):
        ev = g.event(n, 0)
        if ev["bid"]:
            assert 1000 <= ev["bid"]["auction"] <= g.last_auction_id(n)


def test_nexmark_sql_query():
    """q1-flavored query over the nexmark connector table."""
    results = []
    plan = plan_query(
        """
        CREATE TABLE nexmark WITH (
          connector = 'nexmark',
          event_rate = '100000',
          message_count = '5000',
          start_time = '0'
        );
        SELECT bid.auction as auction, bid.price * 100 as price
        FROM nexmark WHERE bid IS NOT NULL;
        """,
        preview_results=results,
    )
    run_plan(plan)
    assert len(results) == 4600
    assert all(r["price"] % 100 == 0 for r in results)


def test_nexmark_q5_shape():
    """hop-window count grouped by auction (the q5 inner query)."""
    results = []
    plan = plan_query(
        """
        CREATE TABLE nexmark WITH (
          connector = 'nexmark',
          event_rate = '1000000',
          message_count = '50000',
          start_time = '0'
        );
        SELECT auction, num FROM (
          SELECT bid.auction as auction, count(*) AS num,
                 hop(interval '10 millisecond', interval '20 millisecond') as window
          FROM nexmark WHERE bid IS NOT NULL
          GROUP BY 1, window
        );
        """,
        preview_results=results,
    )
    run_plan(plan)
    assert len(results) > 0
    total = sum(r["num"] for r in results)
    # each bid appears in width/slide = 2 windows
    assert total == 2 * 4600 * 10


# -- filesystem sink -----------------------------------------------------------


def test_filesystem_sink_parquet(tmp_path):
    out_dir = tmp_path / "fs_out"
    plan = plan_query(
        f"""
        CREATE TABLE impulse WITH (
          connector = 'impulse', event_rate = '1000000',
          message_count = '1000', start_time = '0'
        );
        CREATE TABLE out (
          counter BIGINT UNSIGNED
        ) WITH (
          connector = 'filesystem',
          path = '{out_dir}',
          format = 'parquet',
          rollover_rows = '400',
          type = 'sink'
        );
        INSERT INTO out SELECT counter FROM impulse;
        """
    )
    run_plan(plan)
    import pyarrow.parquet as pq

    files = [f for f in os.listdir(out_dir) if f.endswith(".parquet")]
    assert len(files) >= 2  # rolled at 400 rows
    total = sum(pq.read_table(out_dir / f).num_rows for f in files)
    assert total == 1000
    assert not [f for f in os.listdir(out_dir) if f.endswith(".tmp")]


def test_connector_registry_metadata():
    from arroyo_tpu.connectors import connectors

    names = {c.name for c in connectors()}
    assert {
        "kafka", "impulse", "nexmark", "single_file", "filesystem", "sse",
        "websocket", "polling_http", "webhook", "redis", "mqtt", "nats",
        "rabbitmq", "kinesis", "fluvio", "stdout", "blackhole", "preview",
        "confluent", "vec",
    } <= names
    for c in connectors():
        md = c.metadata()
        assert md["id"] and isinstance(md["config_schema"], dict)


def test_delta_sink(tmp_path):
    """Delta log written on commit: protocol + metaData at version 0, add
    actions matching the visible parquet files, stats row counts exact."""
    out_dir = tmp_path / "delta_out"
    plan = plan_query(
        f"""
        CREATE TABLE impulse WITH (
          connector = 'impulse', event_rate = '1000000',
          message_count = '1000', start_time = '0'
        );
        CREATE TABLE out (counter BIGINT UNSIGNED) WITH (
          connector = 'delta', path = '{out_dir}',
          rollover_rows = '400', type = 'sink'
        );
        INSERT INTO out SELECT counter FROM impulse;
        """
    )
    run_plan(plan)
    import pyarrow.parquet as pq

    log_dir = out_dir / "_delta_log"
    versions = sorted(log_dir.glob("*.json"))
    assert versions, "no delta log written"
    actions = []
    for v in versions:
        with open(v) as f:
            actions.extend(json.loads(l) for l in f if l.strip())
    protos = [a for a in actions if "protocol" in a]
    metas = [a for a in actions if "metaData" in a]
    adds = [a["add"] for a in actions if "add" in a]
    assert len(protos) == 1 and protos[0]["protocol"]["minReaderVersion"] == 1
    assert len(metas) == 1
    schema = json.loads(metas[0]["metaData"]["schemaString"])
    assert {f["name"] for f in schema["fields"]} == {"counter", "_timestamp"}
    assert {f["name"]: f["type"] for f in schema["fields"]}["counter"] == "long"
    # every visible parquet file is added exactly once; stats are exact
    files = {f for f in os.listdir(out_dir) if f.endswith(".parquet")}
    assert {a["path"] for a in adds} == files and len(adds) == len(files)
    assert sum(json.loads(a["stats"])["numRecords"] for a in adds) == 1000
    assert sum(pq.read_table(out_dir / f).num_rows for f in files) == 1000
    assert not [f for f in os.listdir(out_dir) if f.endswith(".tmp")]


def test_delta_sink_exactly_once_across_restart(tmp_path):
    """Stop-with-checkpoint mid-stream, restart from the checkpoint: the
    table nets exactly one add per file and no duplicated rows."""
    out_dir = tmp_path / "delta_ft"
    url = str(tmp_path / "ck")
    sql = f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '20000',
      message_count = '4000', start_time = '0', realtime = 'true'
    );
    CREATE TABLE out (counter BIGINT UNSIGNED) WITH (
      connector = 'delta', path = '{out_dir}',
      rollover_rows = '500', type = 'sink'
    );
    INSERT INTO out SELECT counter FROM impulse;
    """

    async def phase1():
        plan = plan_query(sql)
        eng = Engine(plan.graph, job_id="dft", storage_url=url).start()
        await asyncio.sleep(0.08)
        await eng.checkpoint_and_wait(then_stop=True)
        await eng.join(60)

    asyncio.run(phase1())

    async def phase2():
        plan = plan_query(sql)
        eng = Engine(plan.graph, job_id="dft", storage_url=url).start()
        await eng.join(60)

    asyncio.run(phase2())
    import pyarrow.parquet as pq

    actions = []
    for v in sorted((out_dir / "_delta_log").glob("*.json")):
        with open(v) as f:
            actions.extend(json.loads(l) for l in f if l.strip())
    adds = [a["add"] for a in actions if "add" in a]
    files = {f for f in os.listdir(out_dir) if f.endswith(".parquet")}
    assert {a["path"] for a in adds} == files
    counters = []
    for f in files:
        counters.extend(pq.read_table(out_dir / f).column("counter").to_pylist())
    assert sorted(counters) == list(range(4000))


def test_nexmark_q7_q8():
    """Canonical Nexmark q7 (per-window highest bid) and q8 (person x
    auction same-window join) plan and produce deterministic results on
    the counter-based generator."""
    from bench import QUERIES

    for name, want in [("q7", 1), ("q8", 222)]:
        res = []
        plan = plan_query(
            QUERIES[name].format(rate=5000, events=20000),
            preview_results=res,
        )
        run_plan(plan, timeout=120)
        assert len(res) == want, (name, len(res))
