CREATE TABLE impulse (
  timestamp TIMESTAMP,
  counter BIGINT UNSIGNED NOT NULL,
  subtask_index BIGINT UNSIGNED NOT NULL
) WITH (
  connector = 'single_file',
  path = '$input_dir/impulse.json',
  format = 'json',
  type = 'source',
  event_time_field = 'timestamp'
);
CREATE TABLE out (g BIGINT, v BIGINT) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
INSERT INTO out
SELECT W.g, v FROM (
  SELECT counter % 3 as g, array_agg(counter) as arr,
         tumble(interval '30 second') as w
  FROM impulse
  GROUP BY 1, w
) AS W CROSS JOIN UNNEST(W.arr) AS v;
