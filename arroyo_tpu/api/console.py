"""Web console served at /console.

A hash-routed single-page app mirroring the reference's React webui
(/root/reference/webui, router.tsx routes): pipelines list/detail with
DAG visualization, live per-operator metric graphs, checkpoint inspector
and error tail, a SQL editor with validate/preview/create, a connections
wizard generated from connector config_schema metadata, and a UDF
editor. Static assets live in arroyo_tpu/api/static/ and are served by
the API process — no build step, no framework."""

import os

STATIC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "static")


def add_console_routes(app):
    from aiohttp import web

    async def index(request):
        return web.FileResponse(os.path.join(STATIC_DIR, "index.html"))

    app.router.add_get("/", index)
    app.router.add_get("/console", index)
    app.router.add_get("/console/", index)
    # FileResponse handles content types and binary assets; new files in
    # static/ are served without a restart
    app.router.add_static("/console/", STATIC_DIR, show_index=False)
