"""Deterministic fault plans for the chaos subsystem.

A `FaultPlan` is a seeded schedule of named fault points. Seams in the
engine call `chaos.fire("<point>")` on every pass through an injectable
operation; the plan counts hits per spec (after context filtering) and
answers "does this fault fire on this hit". Firing decisions are pure
functions of the plan's specs — `at_hits` indices chosen when the plan is
built (optionally from a seed) — so the same seed over the same workload
produces the same fired-fault log, which is the reproducibility contract
the exactly-once drills assert (ISSUE 2 acceptance; SURVEY §5.3).

The registry below is the single source of truth for fault-point names:
`plan.add()` and `chaos.fire()` both reject unknown names, and
`tests/test_chaos.py` cross-checks every `chaos.fire(...)` call site in
the codebase against it, so a new seam cannot silently go unlisted in
`tools/chaos_drill.py --list`.
"""

from __future__ import annotations

import json
import random
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from ..analysis.races import guarded_by

# -- fault-point registry ----------------------------------------------------

# name -> (seam, effect description). Keep in sync with the chaos.fire()
# call sites; tests/test_chaos.py enforces the bijection.
FAULT_POINTS: Dict[str, str] = {
    # TCP data plane (engine/network.py)
    "network.connect_delay": (
        "engine/network.py RemoteEdgeSender.start — delay the outgoing "
        "data-plane connect by params.delay seconds (reconnect latency)"
    ),
    "network.drop_connection": (
        "engine/network.py RemoteEdgeSender._pump — close the socket and "
        "fail the pump mid-stream (surfaces as a data-plane task failure; "
        "the controller recovers from the latest checkpoint)"
    ),
    "network.partial_frame": (
        "engine/network.py RemoteEdgeSender._pump — write a truncated "
        "Arrow-IPC frame, then drop the connection (receiver must discard "
        "the torn frame, never deliver it)"
    ),
    # worker lifecycle (engine/worker.py)
    "worker.kill": (
        "engine/worker.py WorkerServer._heartbeat — SIGKILL-equivalent "
        "abrupt teardown of the worker (runners cancelled, servers closed, "
        "heartbeats stop; detected via heartbeat timeout)"
    ),
    "worker.heartbeat_blackout": (
        "engine/worker.py WorkerServer._heartbeat — stop heartbeating for "
        "params.duration seconds while subtasks keep running (tests the "
        "controller's liveness view vs a wedged-but-alive worker)"
    ),
    "worker.slow_barrier_ack": (
        "engine/worker.py WorkerServer.checkpoint — delay the barrier "
        "fan-out to sources by params.delay seconds (stretches barrier "
        "alignment windows)"
    ),
    # object storage (state/storage.py)
    "storage.write_fail": (
        "state/storage.py StorageProvider.put — raise a transient IOError "
        "instead of writing (checkpoint data-file write failure)"
    ),
    "storage.cas_conflict": (
        "state/storage.py StorageProvider.put_if_not_exists — raise "
        "CasConflict WITHOUT creating the key (lost CAS race; scope with "
        "match={'key': 'checkpoint-manifest'} for manifest publishes)"
    ),
    "storage.latency": (
        "state/storage.py StorageProvider.put/get — sleep params.delay "
        "seconds before the operation (slow object store)"
    ),
    # autoscaler-triggered rescale (controller/controller.py _rescale)
    "rescale.stop_delay": (
        "controller/controller.py _rescale — hold params.delay seconds "
        "between the rescale decision and the stop-with-checkpoint "
        "(widens the window in which a worker kill lands mid-rescale)"
    ),
    "rescale.reschedule_fail": (
        "controller/controller.py _rescale — fail the job after the "
        "rescale's stop checkpoint published and the parallelism "
        "overrides were applied, but before rescheduling (recovery must "
        "come back at the NEW parallelism from that checkpoint, "
        "exactly once)"
    ),
    "rescale.overlap_kill": (
        "controller/controller.py _overlap_activate — SIGKILL-equivalent "
        "teardown of a pool worker INSIDE the generation-overlap window "
        "(old generation draining its final epoch, new generation staged "
        "and restoring); the rescale must recover at the new parallelism "
        "with byte-identical output"
    ),
    # operator runner (operators/runner.py)
    "runner.stall": (
        "operators/runner.py TaskRunner._handle_input_item — hold the "
        "subtask's input loop params.delay seconds per fired hit (a "
        "wedged operator / slow UDF / stuck sink dependency: the "
        "canonical freshness-SLO failure — watermark lag grows while "
        "the job stays RUNNING). Scope with match={'job': ...} to "
        "stall ONE tenant on a multiplexed worker; the sleep is async, "
        "so co-resident jobs keep flowing. params.block=True instead "
        "sleeps BLOCKING (a CPU-bound UDF that never yields), starving "
        "the whole event loop — the starvation drill's seam"
    ),
    # conservation-ledger mutation seams (obs/audit.py): each models one
    # exactly-once violation class the auditor must flag with the exact
    # (edge, epoch); tests/test_audit_mutations.py drives them
    "audit.dup_frame": (
        "engine/network.py DataPlaneServer._handle — deliver a received "
        "data frame TWICE into the destination queue (duplicated delivery "
        "past the transport: receiver attests more rows than the sender "
        "sealed -> count_mismatch on that edge/epoch)"
    ),
    "audit.drop_batch": (
        "operators/collector.py EdgeSender._send_data — drop a batch "
        "AFTER the sender tap attested it (lost delivery / dropped flush: "
        "sender attests rows the receiver never sees -> count_mismatch)"
    ),
    "audit.rewind_epoch": (
        "engine/worker.py WorkerServer._forward — re-emit a checkpoint "
        "report for epoch - params.back (default 2), a source rewound "
        "behind committed output (the PR 15 overlap_double_emission "
        "class) -> rewind_behind_commit flagged at intake, report fenced"
    ),
    "audit.zombie_append": (
        "engine/worker.py WorkerServer._forward — append an extra report "
        "for the NEXT epoch stamped with params.gen (default: the "
        "previous incarnation of this job's data namespace), a fenced "
        "generation appending a new epoch past its fencing -> "
        "zombie_generation flagged at intake, report fenced"
    ),
    # follower read replicas (replica/manager.py)
    "replica.kill": (
        "replica/manager.py ReplicaManager._tail_one — detach the "
        "follower abruptly mid-tail (views dropped, subscription gone; "
        "the serve gateway must fail over worker-ward with zero wrong "
        "values and zero fatal reads until a reattach catches back up)"
    ),
    # checkpoint protocol (state/protocol.py)
    "protocol.fenced_zombie": (
        "state/protocol.py check_current — treat the caller's generation "
        "as superseded and raise Fenced (zombie writer resurrect: a "
        "fenced controller must not publish; recovery claims a fresh "
        "generation)"
    ),
}


class UnknownFaultPoint(KeyError):
    pass


def check_point(name: str) -> str:
    if name not in FAULT_POINTS:
        raise UnknownFaultPoint(
            f"unknown fault point {name!r}; known: {sorted(FAULT_POINTS)}"
        )
    return name


# -- specs and plans ---------------------------------------------------------


class FaultSpec:
    """One scheduled fault: fire at the given (1-based) hit indices of a
    fault point, optionally only for hits whose context matches (substring
    match per key), at most `max_fires` times."""

    def __init__(self, point: str, at_hits: Sequence[int] = (1,),
                 match: Optional[Dict[str, str]] = None,
                 params: Optional[Dict[str, Any]] = None,
                 max_fires: int = 1):
        self.point = check_point(point)
        self.at_hits = tuple(sorted(int(h) for h in at_hits))
        if not self.at_hits or self.at_hits[0] < 1:
            raise ValueError(f"at_hits must be 1-based positive: {at_hits}")
        self.match = dict(match or {})
        self.params = dict(params or {})
        self.max_fires = max_fires
        self.hits = 0      # matching hits observed
        self.fired = 0     # times this spec fired

    def matches(self, ctx: Dict[str, Any]) -> bool:
        return all(
            str(want) in str(ctx.get(key, "")) for key, want in self.match.items()
        )

    def param(self, key: str, default: Any = None) -> Any:
        return self.params.get(key, default)

    def describe(self) -> Dict[str, Any]:
        return {
            "point": self.point,
            "at_hits": list(self.at_hits),
            "match": self.match,
            "params": self.params,
            "max_fires": self.max_fires,
        }

    def __repr__(self):
        return f"FaultSpec({self.point!r}, at_hits={self.at_hits})"


# fire() appends from storage/executor threads while drill code reads
# the log from the event loop — every touch goes through _lock (RACE003)
@guarded_by("_lock", "fired_events")
class FaultPlan:
    """A seeded, deterministic schedule of faults plus the log of what
    actually fired. Thread-safe: storage seams run under to_thread."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self.specs: List[FaultSpec] = []
        self.fired_events: List[Dict[str, Any]] = []
        self._lock = threading.RLock()

    # -- construction -------------------------------------------------------

    def add(self, point: str, at_hits: Sequence[int] = (1,),
            match: Optional[Dict[str, str]] = None,
            params: Optional[Dict[str, Any]] = None,
            max_fires: int = 1) -> "FaultPlan":
        self.specs.append(FaultSpec(point, at_hits, match, params, max_fires))
        return self

    @classmethod
    def seeded(cls, seed: int, points: Sequence[str],
               hit_range: tuple = (1, 6)) -> "FaultPlan":
        """One fault per named point, each at a seed-chosen hit index.
        Points are processed in the given order so the same (seed, points)
        always builds the identical plan."""
        rng = random.Random(int(seed))
        plan = cls(seed)
        for p in points:
            plan.add(p, at_hits=(rng.randint(*hit_range),))
        return plan

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        obj = json.loads(text)
        plan = cls(obj.get("seed", 0))
        for f in obj.get("faults", []):
            plan.add(
                f["point"],
                at_hits=f.get("at_hits", (1,)),
                match=f.get("match"),
                params=f.get("params"),
                max_fires=f.get("max_fires", 1),
            )
        return plan

    def to_json(self) -> str:
        return json.dumps(
            {"seed": self.seed, "faults": [s.describe() for s in self.specs]}
        )

    # -- runtime ------------------------------------------------------------

    def fire(self, point: str, **ctx) -> Optional[FaultSpec]:
        """Count a hit of `point` against every matching spec; return the
        first spec that fires on this hit (None otherwise)."""
        check_point(point)
        with self._lock:
            for spec in self.specs:
                if spec.point != point or not spec.matches(ctx):
                    continue
                spec.hits += 1
                if spec.hits in spec.at_hits and spec.fired < spec.max_fires:
                    spec.fired += 1
                    self.fired_events.append({
                        "seq": len(self.fired_events),
                        "time": time.time(),
                        "point": point,
                        "hit": spec.hits,
                        "match": spec.match,
                        "params": spec.params,
                        "ctx": {k: str(v)[:120] for k, v in ctx.items()},
                    })
                    self._record_span_event(point, spec, ctx)
                    return spec
        return None

    @staticmethod
    def _record_span_event(point: str, spec: FaultSpec, ctx: dict) -> None:
        """Flight recorder: every fired fault lands as an instant span
        event — attached to the active trace when one is live (e.g. a CAS
        conflict inside a manifest publish), standalone otherwise — so
        drill timelines read fault -> detection -> recovery causally."""
        try:
            from .. import obs

            obs.event(
                f"chaos.fire:{point}", cat="chaos", hit=spec.hits,
                **{k: str(v)[:120] for k, v in ctx.items()},
            )
        except Exception:  # noqa: BLE001 - tracing must never fail a drill
            pass

    # -- logs ---------------------------------------------------------------

    def fired_log(self) -> List[Dict[str, Any]]:
        """Locked snapshot of the raw fired-fault log. Readers must come
        through here (or comparable_log): iterating `fired_events` bare
        races the storage-thread seams appending mid-iteration."""
        with self._lock:
            return [dict(e) for e in self.fired_events]

    def comparable_log(self) -> List[Dict[str, Any]]:
        """The reproducible view of the fired-fault log: which specs fired,
        at which configured hit, with which parameters — sorted so
        concurrency can't reorder it. Excludes wall-clock and runtime
        context, which legitimately vary between identical-seed runs."""
        return sorted(
            (
                {"point": e["point"], "hit": e["hit"], "match": e["match"],
                 "params": e["params"]}
                for e in self.fired_log()
            ),
            key=lambda e: (e["point"], e["hit"], json.dumps(e["match"],
                                                            sort_keys=True)),
        )

    def unfired(self) -> List[FaultSpec]:
        # spec counters advance under _lock in fire(); read them there too
        with self._lock:
            return [s for s in self.specs if s.fired < s.max_fires]

    def expected_log(self) -> List[Dict[str, Any]]:
        """What comparable_log() must equal when every spec fires to its
        max_fires: the deterministic schedule implied by (seed, specs)."""
        out = []
        for s in self.specs:
            for hit in s.at_hits[: s.max_fires]:
                out.append({"point": s.point, "hit": hit, "match": s.match,
                            "params": s.params})
        return sorted(
            out,
            key=lambda e: (e["point"], e["hit"], json.dumps(e["match"],
                                                            sort_keys=True)),
        )
