"""Test configuration: force JAX onto a virtual 8-device CPU platform so
sharding/collective paths are exercised without TPU hardware, per the build
environment contract. Must run before jax is imported anywhere."""

import os

# force, don't setdefault: the environment pins JAX_PLATFORMS=axon (real TPU
# tunnel) globally, and tests must never claim the real chip
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The experimental 'axon' TPU relay registers its PJRT plugin from
# sitecustomize whenever PALLAS_AXON_POOL_IPS is set, and a wedged relay
# then hangs the FIRST jax backend init in every process — even with
# JAX_PLATFORMS=cpu. Two-level neutralisation:
#  1. scrub the env so test-spawned subprocesses (workers, bench children)
#     never register the plugin at startup;
#  2. this process's sitecustomize already ran, so drop the registered
#     axon backend factory before anything initialises a backend.
for _var in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
             "AXON_POOL_SVC_OVERRIDE", "AXON_LOOPBACK_RELAY"):
    os.environ.pop(_var, None)
try:
    import jax
    import jax._src.xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    # sitecustomize's register() pins jax_platforms to 'axon' inside jax's
    # already-imported config; env alone no longer wins
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass  # jax absent or internals moved; JAX_PLATFORMS=cpu still applies

import pytest  # noqa: E402


@pytest.fixture()
def tmp_storage(tmp_path):
    return str(tmp_path / "storage")
