#!/usr/bin/env python
"""TPU relay grant-capture daemon with STAGED escalating capture.

The axon relay that fronts the single real TPU chip is intermittently
wedged: most `jax.devices()` calls hang forever inside the PJRT claim
path, but occasionally a grant lands (round 2: exactly once, ~20 s of
life; rounds 3-4: zero grants across ~70 probes). Round-2 evidence
shows grants can be SHORT — roughly one command — so the child must
convert a grant into evidence in escalating tiers, cheapest first, and
the parent must flush every tier's results to disk AS IT LANDS:

  tier kernel   (~1 XLA compile):  one tiny jitted bf16 matmul timed
                post-compile (MXU evidence in seconds), then the
                device-tier slot-assignment bench.
  tier q5small  (~6-8 compiles):   one small-event q5 through the full
                engine — the first REAL pipeline number on device.
  tier full     (reuses q5's programs where bucketed): the five-query
                bench plan at credible event counts.
  tier goldens  (correctness):     device-backend golden subset + the
                host-side assign-bench tiers for comparison.

The parent republishes TPU_GRANT.json (and BENCH_r{N}.json once any q5
number exists) after every tier completion and every RESULT line, so a
grant that dies after 30 seconds still leaves a real device number with
a truthful `partial`/`tiers_complete` record. The final publication
(child exits or deadline) adds the like-for-like CPU baseline and the
BASELINE.md appendix.

Selftest (the relay has been wedged for three straight rounds; the
staging machinery must not be dead code that first runs on the next
grant): `python tools/tpu_probe_daemon.py --selftest` runs one full
parent cycle against the CPU backend in a sandbox directory, with the
parent killing the child right after the `q5small` tier — simulating a
short grant window — then asserts the partial artifacts contain the
kernel + small-q5 numbers. tests/test_probe_staged.py wires this into
the suite.

Env knobs (all optional, used by --selftest):
  TPU_PROBE_ALLOW_PLATFORM  accept this platform besides tpu (e.g. cpu)
  TPU_PROBE_OUT_DIR         redirect ALL artifacts (grant/bench/log/
                            BASELINE appendix) into this directory
  TPU_PROBE_KILL_AFTER_TIER parent kills the child when this tier's
                            TIERDONE arrives (simulated grant loss)
  TPU_PROBE_SMALL           shrink event counts for a fast selftest

Run:  python tools/tpu_probe_daemon.py            # daemon
      python tools/tpu_probe_daemon.py --probe    # one probe child
      python tools/tpu_probe_daemon.py --once     # single parent cycle
      python tools/tpu_probe_daemon.py --selftest # staged-capture demo

Log:  tools/tpu_probe.log   (one line per probe: ts outcome detail)
"""

import json
import glob
import os
import random
import re
import signal
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT_DIR = os.environ.get("TPU_PROBE_OUT_DIR") or REPO
LOG = (os.path.join(OUT_DIR, "tpu_probe.log")
       if os.environ.get("TPU_PROBE_OUT_DIR")
       else os.path.join(REPO, "tools", "tpu_probe.log"))
GRANT_JSON = os.path.join(OUT_DIR, "TPU_GRANT.json")
PROBE_GRACE = 100.0     # child self-kill if no grant within this
PARENT_PROBE_DEADLINE = 150.0   # parent kills child if no GRANTED line
BENCH_DEADLINE = 3600.0         # after GRANTED: compiles are slow
SLEEP_BASE = 900.0              # 15 min between probes while wedged
SLEEP_AFTER_GRANT = 3600.0      # once numbers exist, probe hourly
MAX_RUNTIME = 11.5 * 3600
CPU_BASELINE_TIMEOUT = 600.0

SMALL = bool(os.environ.get("TPU_PROBE_SMALL"))

# Tier q5small: the first full-engine device number. Small on purpose —
# after the ~6-8 XLA compiles it runs in seconds, and a grant that dies
# right after still produced a real pipeline measurement.
Q5_SMALL_EVENTS = 20_000 if SMALL else 50_000

# Tier full: (query, events) — q5 is the headline; sizes keep
# post-compile runtime in seconds while being large enough for a
# credible rate.
BENCH_PLAN = ([("q5", 40_000), ("q1", 20_000)] if SMALL else
              [("q5", 500_000), ("q1", 200_000), ("q7", 200_000),
               ("q8", 200_000), ("qu", 200_000)])

# Tier goldens: re-verify on the device backend while holding the
# grant. Small on purpose: each distinct XLA program compiles through
# the relay at ~20-40 s. These four cover hop/sliding/tumbling windows,
# a windowed join (device probe forced on via device_join_min_rows=0),
# and retracting updating aggregates. session_window is deliberately
# absent: SessionWindowOperator forces the numpy backend on a single
# device, so its "device" verdict would attest the CPU path.
GOLDEN_PLAN = (["nexmark_q5"] if SMALL else
               ["nexmark_q5", "sliding_window_end", "windowed_inner_join",
                "updating_aggregate"])


def log_line(msg: str) -> None:
    ts = time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime())
    line = f"{ts} {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def git_head() -> str:
    """HEAD sha, with a '-dirty' suffix when the working tree has
    uncommitted changes: a capture of never-committed code must not pass
    the round-end strict provenance gate (bench.py compares this value
    to a clean `git rev-parse HEAD`, so '-dirty' can never match —
    conservative and honest)."""
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=REPO, capture_output=True,
            text=True, timeout=10).stdout.strip()
        dirty = subprocess.run(
            ["git", "status", "--porcelain"], cwd=REPO,
            capture_output=True, text=True, timeout=10).stdout.strip()
        return f"{sha}-dirty" if dirty else sha
    except Exception:
        return "unknown"


def next_bench_round() -> int:
    """Round number to publish under. Normally max(existing)+1, but when
    the newest BENCH_r{N}.json is this daemon's OWN earlier capture
    (device_source marks it), reuse N — so a daemon restart mid-round
    keeps overwriting the same file instead of fabricating the next
    round's artifact."""
    rounds = {}
    for p in glob.glob(os.path.join(REPO, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        if m:
            rounds[int(m.group(1))] = p
    if not rounds:
        return 1
    mx = max(rounds)
    try:
        with open(rounds[mx]) as f:
            if "probe_daemon_capture" in json.load(f).get(
                    "device_source", ""):
                return mx
    except (OSError, json.JSONDecodeError):
        pass
    return mx + 1


# Bound once at daemon start so re-captures later in the round overwrite
# the SAME BENCH_r{N}.json instead of claiming the next round's name.
ROUND = next_bench_round()


# ---------------------------------------------------------------- child

def run_kernel_tier() -> None:
    """Seconds-scale device evidence: ONE tiny jitted program (bf16
    matmul — the MXU's native shape), timed post-compile, then the
    device-tier slot-assignment bench. This is the cheapest possible
    proof-of-device; it must land before anything that takes minutes."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    n = 256 if SMALL else 1024
    a = jnp.asarray(np.random.default_rng(0).standard_normal((n, n)),
                    dtype=jnp.bfloat16)
    f = jax.jit(lambda x: x @ x)
    t0 = time.monotonic()
    f(a).block_until_ready()
    compile_s = time.monotonic() - t0
    iters = 50
    t0 = time.monotonic()
    out = None
    for _ in range(iters):
        out = f(a)
    out.block_until_ready()
    dt = time.monotonic() - t0
    us = dt / iters * 1e6
    tflops = 2 * n ** 3 * iters / dt / 1e12
    print(f"KERNEL matmul_bf16_{n} compile_s={compile_s:.1f} "
          f"us_per_iter={us:.0f} tflops={tflops:.2f}", flush=True)

    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import assign_bench
        r = assign_bench.bench("device", rows=8192, keys=20000,
                               iters=10 if SMALL else 40)
        if r is not None:
            print(f"ASSIGNBENCH device {r[0]:.0f}us/batch "
                  f"{r[1] / 1e6:.2f}Mrows/s", flush=True)
    except BaseException as e:
        print(f"ASSIGNBENCHFAIL device {type(e).__name__}: {e}",
              flush=True)


def run_device_goldens() -> None:
    """Run GOLDEN_PLAN queries with the jax backend on the held device,
    comparing against the committed golden outputs. Prints one
    'GOLDEN <name> PASS|FAIL <detail>' line each. Runs inside the probe
    child (which already holds the claim)."""
    import asyncio
    import tempfile

    sys.path.insert(0, REPO)
    sys.path.insert(0, os.path.join(REPO, "tests"))
    from arroyo_tpu.config import config
    from arroyo_tpu.engine import Engine
    from arroyo_tpu.sql import plan_query
    import test_golden as tg

    import bench

    config().tpu.enabled = True
    config().tpu.shape_buckets = (8192, 65536)
    # golden fixtures are small (hundreds of rows): drop the row floor so
    # the windowed-join golden actually exercises the device join probe
    config().tpu.device_join_min_rows = 0

    def run_one(name: str, label: str):
        qpath = os.path.join(tg.GOLDEN, "queries", f"{name}.sql")
        gpath = os.path.join(tg.GOLDEN, "golden_outputs", f"{name}.json")
        try:
            with tempfile.TemporaryDirectory() as td:
                out = os.path.join(td, "out.json")
                sql = tg.load_query(qpath, out)
                plan = plan_query(sql, parallelism=2)
                bench.force_backend(plan, "jax")

                async def go():
                    eng = Engine(plan.graph).start()
                    await eng.join(300)

                asyncio.run(go())
                got = tg.canonicalize_output(out, sql)
                want = [ln.strip() for ln in open(gpath)]
                if got == want:
                    print(f"GOLDEN {label} PASS rows={len(got)}",
                          flush=True)
                else:
                    print(f"GOLDEN {label} FAIL got={len(got)} "
                          f"want={len(want)}", flush=True)
        except BaseException as e:
            print(f"GOLDEN {label} FAIL {type(e).__name__}: {e}",
                  flush=True)

    for name in GOLDEN_PLAN:
        run_one(name, name)
    # one more pass attesting the device-resident slot directory
    # (tpu.device_directory prototype) on the real chip. The verdict is
    # only meaningful if the directory actually engaged — the swap has
    # its own gates (_device_ok, accelerator, key widths), so count
    # instantiations and fail the attestation when none happened.
    import arroyo_tpu.ops.device_directory as dd

    engaged = {"n": 0}
    orig_init = dd.DeviceSlotDirectory.__init__

    def _spy(self, *a, **k):
        engaged["n"] += 1
        return orig_init(self, *a, **k)

    config().tpu.device_directory = True
    dd.DeviceSlotDirectory.__init__ = _spy
    try:
        run_one("nexmark_q5", "nexmark_q5_device_dir")
    finally:
        dd.DeviceSlotDirectory.__init__ = orig_init
        config().tpu.device_directory = False
    if engaged["n"] == 0:
        print("GOLDEN nexmark_q5_device_dir FAIL "
              "device directory never engaged", flush=True)


def probe_child() -> None:
    """Claim the device; on grant run the escalating capture tiers while
    holding it. Every tier ends with a TIERDONE marker the parent uses
    to flush artifacts — order is strictly cheapest-first so a short
    grant still produces real device evidence."""
    granted = threading.Event()

    def watchdog():
        if not granted.wait(PROBE_GRACE):
            # jax.devices() is stuck in C inside the axon claim path —
            # no exception can unwind it; hard-exit so the parent sees a
            # clean death instead of a zombie holding half a claim.
            print("WEDGED probe watchdog fired", flush=True)
            os._exit(3)

    threading.Thread(target=watchdog, daemon=True).start()
    t0 = time.monotonic()
    import jax  # noqa: deferred heavy import
    devs = jax.devices()
    granted.set()
    kinds = ",".join(sorted({d.platform for d in devs}))
    allowed = {"tpu", os.environ.get("TPU_PROBE_ALLOW_PLATFORM", "tpu")}
    if not any(d.platform in allowed for d in devs):
        print(f"NOTTPU {kinds}", flush=True)
        os._exit(4)
    print(f"GRANTED {kinds} in {time.monotonic() - t0:.1f}s", flush=True)

    sys.path.insert(0, REPO)
    import bench

    # tier 1: seconds-scale kernel evidence
    ok = True
    try:
        run_kernel_tier()
    except BaseException as e:  # arroyolint: disable=ASY004 - tier must record-and-continue
        ok = False
        print(f"KERNELFAIL {type(e).__name__}: {e}", flush=True)
    print(f"TIERDONE kernel ok={ok}", flush=True)

    # tier 2: one small full-engine q5 — the first real pipeline number
    print(f"BENCHQ q5small {Q5_SMALL_EVENTS}", flush=True)
    ok = True
    try:
        bench.child(Q5_SMALL_EVENTS, "jax", "q5")
    except BaseException as e:  # arroyolint: disable=ASY004 - tier must record-and-continue
        ok = False
        print(f"BENCHFAIL q5small {type(e).__name__}: {e}", flush=True)
    print(f"TIERDONE q5small ok={ok}", flush=True)

    # tier 3: the full bench plan (ok when at least one query completed)
    n_ok = 0
    for query, events in BENCH_PLAN:
        print(f"BENCHQ {query} {events}", flush=True)
        try:
            bench.child(events, "jax", query)  # prints RESULT eps rows dt
            n_ok += 1
        except BaseException as e:  # arroyolint: disable=ASY004 - keep going; later queries may pass
            print(f"BENCHFAIL {query} {type(e).__name__}: {e}", flush=True)
    print(f"TIERDONE full ok={n_ok > 0}", flush=True)

    # tier 4: correctness goldens + host-side assign tiers for comparison
    ok = True
    try:
        run_device_goldens()
    except BaseException as e:  # arroyolint: disable=ASY004 - tier must record-and-continue
        ok = False
        print(f"GOLDENSUITEFAIL {type(e).__name__}: {e}", flush=True)
    sys.path.insert(0, os.path.join(REPO, "tools"))
    for kind in ("python", "native"):
        try:
            import assign_bench
            r = assign_bench.bench(kind, rows=8192, keys=20000,
                                   iters=10 if SMALL else 40)
            if r is not None:
                print(f"ASSIGNBENCH {kind} {r[0]:.0f}us/batch "
                      f"{r[1] / 1e6:.2f}Mrows/s", flush=True)
        except BaseException as e:  # arroyolint: disable=ASY004 - record-and-continue
            print(f"ASSIGNBENCHFAIL {kind} {type(e).__name__}: {e}",
                  flush=True)
    print(f"TIERDONE goldens ok={ok}", flush=True)
    print("DONE", flush=True)
    os._exit(0)


# --------------------------------------------------------- publication

class CaptureState:
    """Everything the parent has parsed from a granted child so far."""

    def __init__(self, commit: str):
        self.commit = commit
        self.platform = ""
        self.results = {}      # query -> {eps, rows, secs}
        self.events = {}       # query -> event count (from BENCHQ lines)
        self.goldens = {}
        self.kernels = {}      # name -> metrics dict
        self.assigns = {}      # tier -> raw line detail
        self.tiers_complete = []   # tiers that ran to success
        self.tiers_attempted = []  # every tier that reached its marker
        self.publishes = 0

    def best_q5(self):
        """(q5_eps_record, events) — the full q5 when present, else the
        small-tier q5; None when neither landed."""
        if "q5" in self.results:
            return self.results["q5"], self.events.get("q5")
        if "q5small" in self.results:
            return self.results["q5small"], self.events.get("q5small")
        return None, None


def publish(state: CaptureState, final: bool) -> None:
    """Flush the capture state to disk. Called after EVERY tier
    completion and result line (cheap: two small json writes), then once
    with final=True when the child exits or the deadline fires — the
    final pass adds the like-for-like CPU baseline re-measure and the
    BASELINE.md appendix."""
    state.publishes += 1
    payload = {
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "git_commit": state.commit,
        "source": "tools/tpu_probe_daemon.py staged in-process capture",
        "platform": state.platform,
        "partial": not final or "goldens" not in state.tiers_complete,
        "tiers_complete": list(state.tiers_complete),
        "tiers_attempted": list(state.tiers_attempted),
        "publishes": state.publishes,
        "events": dict(state.events),
        **{f"{q}_eps": round(r["eps"], 1)
           for q, r in state.results.items()},
        "kernels": state.kernels,
        "assign_bench": state.assigns,
        "goldens": state.goldens,
    }
    if "q5" in state.results:
        payload["q5_rows"] = state.results["q5"]["rows"]
    tmp = GRANT_JSON + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1)
    os.replace(tmp, GRANT_JSON)  # atomic: bench.py may read anytime

    q5, g_events = state.best_q5()
    if q5 is None:
        if final:
            log_line(f"GRANT partial capture (no q5 tier) -> "
                     f"TPU_GRANT.json {payload}")
        return

    baseline = None
    if final:
        log_line(f"GRANT CAPTURED -> TPU_GRANT.json {payload}")
        # like-for-like CPU baseline at the captured q5 event count;
        # pinned to the CPU platform so it can never touch (or wedge on)
        # the relay
        cpu_env = dict(os.environ)
        cpu_env["JAX_PLATFORMS"] = "cpu"
        for var in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
                    "AXON_POOL_SVC_OVERRIDE", "AXON_LOOPBACK_RELAY"):
            cpu_env.pop(var, None)
        sys.path.insert(0, REPO)
        import bench
        baseline = bench.run_child(g_events, "numpy", CPU_BASELINE_TIMEOUT,
                                   env=cpu_env)
        if baseline is None:
            log_line("capture: CPU baseline re-measure failed; "
                     "BENCH json will carry vs_baseline=null")

    bench_json = {
        "metric": "nexmark_q5_events_per_sec",
        "value": round(q5["eps"], 1),
        "unit": "events/s",
        "vs_baseline": round(q5["eps"] / baseline["eps"], 3)
        if baseline else None,
        "baseline_cpu_eps": round(baseline["eps"], 1) if baseline else None,
        "events": g_events,
        "result_rows": q5.get("rows", -1),
        "side_backend": "jax",
        "partial": payload["partial"],
        "tiers_complete": payload["tiers_complete"],
        **{f"{q}_eps": round(state.results[q]["eps"], 1)
           for q in ("q1", "q7", "q8", "qu") if q in state.results},
        "device_source": f"probe_daemon_capture@{payload['captured_at']}",
        "git_commit": state.commit,
        "goldens": state.goldens,
        "kernels": state.kernels,
    }
    bp = os.path.join(OUT_DIR, f"BENCH_r{ROUND:02d}.json")
    # never degrade: a COMPLETE capture already published this round must
    # not be overwritten by a partial flush (e.g. an hourly re-capture
    # whose grant dies early, or the daemon crashing mid-recapture)
    degrade = False
    if bench_json["partial"]:
        try:
            with open(bp) as f:
                degrade = json.load(f).get("partial") is False
        except (OSError, json.JSONDecodeError):
            pass
    if not degrade:
        tmp = bp + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bench_json, f, indent=1)
        os.replace(tmp, bp)
    if final:
        log_line(f"capture: "
                 + ("kept earlier complete "
                    if degrade else "wrote ")
                 + f"{os.path.basename(bp)} "
                 f"vs_baseline={bench_json['vs_baseline']}")
        _append_baseline_md(state, bench_json, baseline, g_events)


def _append_baseline_md(state, bench_json, baseline, g_events):
    gsum = ", ".join(f"{k}={v}"
                     for k, v in sorted(state.goldens.items())) or "none"
    ksum = ", ".join(f"{k}: {v}"
                     for k, v in sorted(state.kernels.items())) or "none"
    lines = [
        "",
        f"## TPU grant capture ({bench_json['device_source']}, "
        f"commit {state.commit[:12]})",
        "",
        "Captured automatically by `tools/tpu_probe_daemon.py` in staged",
        "tiers while the probe child held the device claim (relay grants",
        "do not survive process exit — see round-2 evidence).",
        f"Tiers completed: {', '.join(state.tiers_complete) or 'none'}.",
        "",
        "| query | device ev/s | events |",
        "|---|---|---|",
    ]
    for q in ("q5", "q5small", "q1", "q7", "q8", "qu"):
        if q in state.results:
            lines.append(f"| {q} | {state.results[q]['eps']:,.1f} "
                         f"| {state.events.get(q, 0):,} |")
    if baseline:
        lines += ["",
                  f"CPU baseline (same commit, {g_events:,} events): "
                  f"q5 {baseline['eps']:,.1f} ev/s → "
                  f"**vs_baseline {bench_json['vs_baseline']}**."]
    lines += ["", f"Kernel tier: {ksum}.",
              f"Device-backend goldens: {gsum}.", ""]
    with open(os.path.join(OUT_DIR, "BASELINE.md"), "a") as f:
        f.write("\n".join(lines))
    log_line("capture: appended section to BASELINE.md")


# -------------------------------------------------------------- parent

def run_one_probe() -> bool:
    """One parent cycle. Returns True if a grant produced numbers.
    The probe child inherits this process's environment (the selftest
    configures overrides on the whole --once process env)."""
    import queue

    cmd = [sys.executable, os.path.abspath(__file__), "--probe"]
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True,
                            stderr=subprocess.STDOUT, cwd=REPO)
    q: "queue.Queue" = queue.Queue()

    def reader():
        for ln in proc.stdout:
            q.put(ln)
        q.put(None)  # EOF

    threading.Thread(target=reader, daemon=True).start()
    deadline = time.monotonic() + PARENT_PROBE_DEADLINE
    granted = False
    state = CaptureState(git_head())
    kill_after = os.environ.get("TPU_PROBE_KILL_AFTER_TIER")
    cur_q = None
    lines = []
    try:
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError
            try:
                line = q.get(timeout=min(remaining, 5.0))
            except queue.Empty:
                continue
            if line is None:
                # child exited; if it never printed a recognized marker
                # (e.g. import jax blew up), still leave a trail
                if not granted and not any(
                        ln.startswith(("WEDGED", "NOTTPU")) for ln in lines):
                    tail = "; ".join(lines[-3:]) or "<no output>"
                    log_line(f"probe exited rc={proc.poll()} "
                             f"without grant; tail=[{tail}]")
                break
            line = line.strip()
            if not line:
                continue
            lines.append(line)
            if line.startswith("GRANTED"):
                granted = True
                state.platform = line.split()[1]
                deadline = time.monotonic() + BENCH_DEADLINE
                log_line(f"probe GRANTED ({line})")
            elif line.startswith("BENCHQ"):
                parts = line.split()
                cur_q = parts[1]
                state.events[cur_q] = int(parts[2])
            elif line.startswith("RESULT") and cur_q:
                parts = line.split()
                state.results[cur_q] = {"eps": float(parts[1]),
                                        "rows": int(parts[2]),
                                        "secs": float(parts[3])}
                publish(state, final=False)   # flush as it lands
            elif line.startswith("KERNEL "):
                parts = line.split()
                state.kernels[parts[1]] = dict(
                    p.split("=") for p in parts[2:] if "=" in p)
                log_line(f"probe: {line}")
                publish(state, final=False)
            elif line.startswith("GOLDEN "):
                parts = line.split()
                state.goldens[parts[1]] = parts[2]
                log_line(f"probe: {line}")
            elif line.startswith("ASSIGNBENCH "):
                parts = line.split()
                state.assigns[parts[1]] = " ".join(parts[2:])
                log_line(f"probe: {line}")
                publish(state, final=False)
            elif line.startswith("TIERDONE"):
                parts = line.split()
                tier = parts[1]
                ok = not any(p == "ok=False" for p in parts[2:])
                state.tiers_attempted.append(tier)
                if ok:
                    state.tiers_complete.append(tier)
                log_line(f"probe: tier {tier} "
                         f"{'complete' if ok else 'FAILED'} "
                         f"(results={sorted(state.results)})")
                publish(state, final=False)
                if kill_after == tier:
                    log_line(f"probe: simulated grant loss after "
                             f"tier {tier} (selftest)")
                    _kill(proc)
                    break
            elif line.startswith(("WEDGED", "NOTTPU", "BENCHFAIL",
                                  "KERNELFAIL", "GOLDENSUITEFAIL",
                                  "ASSIGNBENCHFAIL")):
                log_line(f"probe: {line}")
            elif line.startswith("DONE"):
                break
    except TimeoutError:
        _kill(proc)
        tail = "; ".join(lines[-3:])
        if granted:
            log_line(f"probe granted but bench DEADLINED; "
                     f"partial={sorted(state.results)} tail=[{tail}]")
        else:
            log_line("probe wedged (no grant within "
                     f"{PARENT_PROBE_DEADLINE:.0f}s)")
    finally:
        _kill(proc)

    captured = bool(state.results or state.kernels or state.goldens)
    if granted and captured:
        try:
            publish(state, final=True)
        except Exception as e:
            log_line(f"capture publication error {type(e).__name__}: {e}")
        # only a pipeline (q5) number relaxes the probe cadence: a
        # kernel-only capture is evidence but the headline is still
        # missing, so keep hunting at the fast interval
        return state.best_q5()[0] is not None
    if granted:
        log_line("grant produced no capturable results")
    return False


def _kill(proc):
    if proc.poll() is None:
        try:
            proc.send_signal(signal.SIGKILL)
            proc.wait(10)
        except Exception:
            pass


def selftest() -> int:
    """Demonstrate the staged capture machinery on the CPU backend: one
    parent cycle in a sandbox with a simulated short grant window (child
    killed right after the q5small tier), then assert the partial
    artifacts carry real numbers. Exit code 0 = staging works."""
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        env = dict(os.environ)
        env.update({
            "TPU_PROBE_OUT_DIR": td,
            "TPU_PROBE_ALLOW_PLATFORM": "cpu",
            "TPU_PROBE_KILL_AFTER_TIER": "q5small",
            "TPU_PROBE_SMALL": "1",
            "JAX_PLATFORMS": "cpu",
        })
        env.pop("PYTHONPATH", None)
        for var in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
                    "AXON_POOL_SVC_OVERRIDE", "AXON_LOOPBACK_RELAY"):
            env.pop(var, None)
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--once"],
            env=env, capture_output=True, text=True, timeout=900)
        sys.stdout.write(out.stdout)
        grant_path = os.path.join(td, "TPU_GRANT.json")
        ok = True
        try:
            with open(grant_path) as f:
                grant = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"SELFTEST FAIL no grant artifact: {e}")
            return 1
        checks = [
            ("partial flag set", grant.get("partial") is True),
            ("kernel tier complete",
             "kernel" in grant.get("tiers_complete", [])),
            ("q5small tier complete",
             "q5small" in grant.get("tiers_complete", [])),
            ("kernel number captured", bool(grant.get("kernels"))),
            ("small q5 eps captured", grant.get("q5small_eps", 0) > 0),
            ("multiple staged publishes", grant.get("publishes", 0) >= 3),
            ("platform recorded", grant.get("platform") == "cpu"),
        ]
        benches = glob.glob(os.path.join(td, "BENCH_r*.json"))
        checks.append(("bench json written from partial grant",
                       len(benches) == 1))
        if benches:
            with open(benches[0]) as f:
                bj = json.load(f)
            checks.append(("bench json flags partial",
                           bj.get("partial") is True))
            checks.append(("bench json has q5 value",
                           bj.get("value", 0) > 0))
            checks.append(("bench json has CPU baseline",
                           bj.get("vs_baseline") is not None))
        for name, passed in checks:
            print(f"SELFTEST {'PASS' if passed else 'FAIL'} {name}")
            ok = ok and passed
        print(f"SELFTEST {'OK' if ok else 'FAILED'}")
        # evidence in the real probe log: staged capture is demonstrated
        # even while the relay stays wedged
        log_line(f"SELFTEST staged-capture "
                 f"{'OK' if ok else 'FAILED'}: simulated grant loss "
                 f"after q5small; tiers={grant.get('tiers_complete')} "
                 f"q5small_eps={grant.get('q5small_eps')} "
                 f"publishes={grant.get('publishes')}")
        return 0 if ok else 1


def main():
    if "--probe" in sys.argv:
        probe_child()
        return
    if "--selftest" in sys.argv:
        sys.exit(selftest())
    once = "--once" in sys.argv
    start = time.monotonic()
    log_line(f"daemon start pid={os.getpid()} commit={git_head()[:12]} "
             f"publishing BENCH_r{ROUND:02d} (staged capture)")
    have_grant = os.path.exists(GRANT_JSON)
    while True:
        try:
            got = run_one_probe()
            have_grant = have_grant or got
        except Exception as e:
            log_line(f"daemon cycle error {type(e).__name__}: {e}")
        if once:
            break
        if time.monotonic() - start > MAX_RUNTIME:
            log_line("daemon max runtime reached; exiting")
            break
        base = SLEEP_AFTER_GRANT if have_grant else SLEEP_BASE
        time.sleep(base + random.uniform(-60, 60))


if __name__ == "__main__":
    main()
