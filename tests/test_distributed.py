"""Distributed execution: controller + workers over the gRPC control plane
and TCP data plane; embedded (in-process) and real multi-process runs;
failure recovery from checkpoints."""

import asyncio
import json
import os
import subprocess
import sys

import pytest

from arroyo_tpu.controller.controller import ControllerServer
from arroyo_tpu.controller.scheduler import EmbeddedScheduler
from arroyo_tpu.controller.state_machine import (
    IllegalTransition,
    JobState,
    check_transition,
)


def sql_pipeline(tmp, n=2000, out="out.json", throttle=None):
    throttle_opt = (
        f",\n  throttle_per_sec = '{throttle}'" if throttle else ""
    )
    return f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '1000000',
      message_count = '{n}', start_time = '0'
    );
    CREATE TABLE out (k BIGINT UNSIGNED, cnt BIGINT) WITH (
      connector = 'single_file', path = '{tmp}/{out}',
      format = 'json', type = 'sink'{throttle_opt}
    );
    INSERT INTO out
    SELECT k, cnt FROM (
      SELECT counter % 8 as k, tumble(interval '1 millisecond') as w,
             count(*) as cnt
      FROM impulse GROUP BY 1, 2
    );
    """


def read_counts(path):
    from collections import Counter

    c = Counter()
    with open(path) as f:
        for line in f:
            if line.strip():
                r = json.loads(line)
                c[r["k"]] += r["cnt"]
    return dict(c)


def test_state_machine_transitions():
    check_transition(JobState.CREATED, JobState.SCHEDULING)
    check_transition(JobState.RUNNING, JobState.RECOVERING)
    with pytest.raises(IllegalTransition):
        check_transition(JobState.STOPPED, JobState.RUNNING)
    assert JobState.FAILED.is_terminal()


def test_embedded_cluster_two_workers(tmp_path):
    """Controller + 2 embedded workers: keyed shuffle crosses the TCP data
    plane (subtasks round-robin across workers)."""

    async def go():
        c = await ControllerServer(EmbeddedScheduler()).start()
        await c.submit_job(
            "d1", sql=sql_pipeline(tmp_path), n_workers=2, parallelism=2
        )
        state = await c.wait_for_state(
            "d1", JobState.FINISHED, JobState.FAILED, timeout=60
        )
        await c.stop()
        return state

    state = asyncio.run(go())
    assert state == JobState.FINISHED
    counts = read_counts(tmp_path / "out.json")
    assert counts == {k: 250 for k in range(8)}


def test_embedded_cluster_with_checkpoints_and_stop(tmp_path):
    async def go():
        c = await ControllerServer(EmbeddedScheduler()).start()
        from arroyo_tpu.config import update

        with update(pipeline={"checkpointing": {"interval": 0.1}}):
            await c.submit_job(
                "d2",
                sql=sql_pipeline(tmp_path, n=100000, throttle=None).replace(
                    "'1000000'", "'200000'"
                ).replace("start_time = '0'",
                          "start_time = '0', realtime = 'true'"),
                storage_url=str(tmp_path / "ck"),
                n_workers=2,
                parallelism=2,
            )
            await c.wait_for_state("d2", JobState.RUNNING, timeout=30)
            # let at least one checkpoint land, then checkpoint-stop
            await asyncio.sleep(0.4)
            await c.stop_job("d2", "checkpoint")
            state = await c.wait_for_state(
                "d2", JobState.STOPPED, JobState.FAILED, timeout=60
            )
        job = c.jobs["d2"]
        await c.stop()
        return state, job.epoch

    state, epoch = asyncio.run(go())
    assert state == JobState.STOPPED
    assert epoch >= 1  # at least the stopping checkpoint published


def test_recovery_after_task_failure(tmp_path):
    """A task failure mid-run sends the job through Recovering and it
    completes from the latest checkpoint with exact output."""
    fail_flag = tmp_path / "fail_once"
    fail_flag.write_text("1")

    from arroyo_tpu.udf import udf
    import pyarrow as pa

    flag_path = str(fail_flag)

    @udf(pa.int64(), [pa.int64()], name="maybe_boom")
    def maybe_boom(xs):
        import numpy as np
        import os as _os

        if _os.path.exists(flag_path) and (xs > 60000).any():
            _os.unlink(flag_path)
            raise RuntimeError("injected failure")
        return xs

    sql = f"""
    CREATE TABLE impulse WITH (
      connector = 'impulse', event_rate = '150000',
      message_count = '100000', start_time = '0', realtime = 'true'
    );
    CREATE TABLE out (k BIGINT UNSIGNED, cnt BIGINT) WITH (
      connector = 'single_file', path = '{tmp_path}/out.json',
      format = 'json', type = 'sink'
    );
    INSERT INTO out
    SELECT k, cnt FROM (
      SELECT maybe_boom(counter) % 8 as k,
             tumble(interval '100 millisecond') as w, count(*) as cnt
      FROM impulse GROUP BY 1, 2
    );
    """

    async def go():
        from arroyo_tpu.config import update

        c = await ControllerServer(EmbeddedScheduler()).start()
        with update(pipeline={"checkpointing": {"interval": 0.1}}):
            await c.submit_job(
                "d3", sql=sql, storage_url=str(tmp_path / "ck"), n_workers=1
            )
            state = await c.wait_for_state(
                "d3", JobState.FINISHED, JobState.FAILED, timeout=120
            )
        job = c.jobs["d3"]
        await c.stop()
        return state, job.restarts

    state, restarts = asyncio.run(go())
    assert state == JobState.FINISHED
    assert restarts >= 1  # went through Recovering
    counts = read_counts(tmp_path / "out.json")
    assert sum(counts.values()) == 100000
    assert counts == {k: 12500 for k in range(8)}


@pytest.mark.slow
def test_multiprocess_cluster(tmp_path):
    """Real separate worker processes via `python -m arroyo_tpu run`."""
    sql_path = tmp_path / "q.sql"
    sql_path.write_text(sql_pipeline(tmp_path, n=4000))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTHONPATH", None)
    env["PALLAS_AXON_POOL_IPS"] = ""
    out = subprocess.run(
        [sys.executable, "-m", "arroyo_tpu", "run", str(sql_path),
         "--parallelism", "2", "--workers", "2", "--scheduler", "process"],
        cwd="/root/repo",
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert "job finished" in out.stdout, out.stdout + out.stderr
    counts = read_counts(tmp_path / "out.json")
    assert counts == {k: 500 for k in range(8)}


@pytest.mark.slow
def test_process_scheduler_kill_restore(tmp_path):
    """ROADMAP open item (PR-3 verify): process-scheduler restore after a
    worker kill reportedly failed with an IndexError reading the
    timestamp column of a restored batch (subtask 1-0). A ~25-run sweep
    (chaos kills at varied heartbeat hits, external SIGKILLs, injected
    storage latency, parallelism 1/2) could NOT reproduce it on this
    tree; this regression pins the exact scenario — worker subprocess
    killed mid-stream, job recovers from durable checkpoints, output
    stays exactly-once. If the IndexError recurs, the restore spans
    (state.restore_table events per file/stage) in the job.schedule trace
    name the failing table and stage: dump /debug/trace or re-run with
    tools/trace_report.py."""
    sql_path = tmp_path / "q.sql"
    sql_path.write_text(
        sql_pipeline(tmp_path, n=200000).replace("'1000000'", "'120000'")
        .replace("start_time = '0'", "start_time = '0', realtime = 'true'")
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTHONPATH", None)
    env["PALLAS_AXON_POOL_IPS"] = ""
    # kill the first worker subprocess ~2.5s in (heartbeat hit 25 at
    # 0.1s/beat), after several 0.15s-cadence checkpoints have landed
    env["ARROYO__CHAOS__PLAN"] = json.dumps({
        "seed": 1,
        "faults": [{"point": "worker.kill", "at_hits": [25],
                    "match": {"worker_id": "2000"}}],
    })
    env["ARROYO__PIPELINE__CHECKPOINTING__INTERVAL"] = "0.15"
    env["ARROYO__WORKER__HEARTBEAT_INTERVAL"] = "0.1"
    env["ARROYO__CONTROLLER__HEARTBEAT_TIMEOUT"] = "1.2"
    out = subprocess.run(
        [sys.executable, "-m", "arroyo_tpu", "run", str(sql_path),
         "--parallelism", "2", "--workers", "2", "--scheduler", "process",
         "--state-dir", str(tmp_path / "ck")],
        cwd="/root/repo",
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert "IndexError" not in out.stderr, out.stderr
    assert "job finished" in out.stdout, out.stdout + out.stderr
    assert "Recovering" in out.stderr  # the kill actually forced recovery
    counts = read_counts(tmp_path / "out.json")
    assert counts == {k: 25000 for k in range(8)}


def test_finish_racing_inflight_checkpoint(tmp_path):
    """A checkpoint issued just before the stream ends can never complete
    (finished tasks don't report); the controller must see the finish and
    NOT misread the cleanly-stopped worker's silence as a heartbeat
    timeout (regression: endless recover/re-finish loop)."""

    async def go():
        from arroyo_tpu.config import update

        c = await ControllerServer(EmbeddedScheduler()).start()
        # heartbeat_timeout must exceed the worker's 2s heartbeat period or
        # the timeout itself fires spuriously mid-run
        with update(pipeline={"checkpointing": {"interval": 0.01}},
                    controller={"heartbeat_timeout": 5.0}):
            await c.submit_job(
                "d5", sql=sql_pipeline(tmp_path, n=20000),
                storage_url=str(tmp_path / "ck"), n_workers=1,
            )
            state = await c.wait_for_state(
                "d5", JobState.FINISHED, JobState.FAILED, timeout=30
            )
        job = c.jobs["d5"]
        await c.stop()
        return state, job.restarts

    state, restarts = asyncio.run(go())
    assert state == JobState.FINISHED
    assert restarts == 0
    counts = read_counts(tmp_path / "out.json")
    assert sum(counts.values()) == 20000


def test_worker_leader_mode(tmp_path):
    """job_controller_mode=worker: the first worker runs the checkpoint
    cadence and manifest publish (the controller's checkpoint collection
    stays empty), checkpoint-stop is delegated to the leader, and a
    restart resumes from the leader-published manifest with exact output."""
    from arroyo_tpu.config import update

    url = str(tmp_path / "ck")
    # ~1.7s of realtime stream so the mid-run checkpoint-stop lands well
    # before the source drains
    sql = sql_pipeline(tmp_path, n=200000).replace(
        "'1000000'", "'120000'"
    ).replace("start_time = '0'", "start_time = '0', realtime = 'true'")

    async def phase1():
        c = await ControllerServer(EmbeddedScheduler()).start()
        with update(controller={"job_controller_mode": "worker"},
                    pipeline={"checkpointing": {"interval": 0.1}}):
            await c.submit_job("wl", sql=sql, storage_url=url,
                               n_workers=2, parallelism=2)
            await c.wait_for_state("wl", JobState.RUNNING, timeout=30)
            await asyncio.sleep(0.3)  # let leader checkpoints land
            await c.stop_job("wl", "checkpoint")
            state = await c.wait_for_state(
                "wl", JobState.STOPPED, JobState.FAILED, timeout=60
            )
        job = c.jobs["wl"]
        await c.stop()
        return state, job.epoch, dict(job.checkpoints)

    state, epoch, controller_ckpts = asyncio.run(phase1())
    assert state == JobState.STOPPED
    assert epoch >= 1  # leader published + reported at least one epoch
    # reports went to the leader, not the controller
    assert controller_ckpts == {}

    async def phase2():
        c = await ControllerServer(EmbeddedScheduler()).start()
        with update(controller={"job_controller_mode": "worker"},
                    pipeline={"checkpointing": {"interval": 0.1}}):
            await c.submit_job("wl", sql=sql, storage_url=url,
                               n_workers=2, parallelism=2)
            state = await c.wait_for_state(
                "wl", JobState.FINISHED, JobState.FAILED, timeout=60
            )
        await c.stop()
        return state

    assert asyncio.run(phase2()) == JobState.FINISHED
    counts = read_counts(tmp_path / "out.json")
    assert counts == {k: 25000 for k in range(8)}


def test_node_scheduler(tmp_path):
    """A node daemon offers slots; the controller's node scheduler places
    real worker subprocesses on it (reference arroyo-node + node
    scheduler)."""
    from arroyo_tpu.config import update
    from arroyo_tpu.controller.node import NodeServer
    from arroyo_tpu.controller.scheduler import NodeScheduler

    async def go():
        c = await ControllerServer(NodeScheduler()).start()
        node = await NodeServer(
            c.addr, slots=4,
            extra_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": "/root/repo",
                       "PALLAS_AXON_POOL_IPS": ""},
        ).start()
        with update(controller={"scheduler": "node"}):
            await c.submit_job(
                "nd1", sql=sql_pipeline(tmp_path, n=4000),
                n_workers=2, parallelism=2,
            )
            state = await c.wait_for_state(
                "nd1", JobState.FINISHED, JobState.FAILED, timeout=90
            )
        # stop_workers runs just after the FINISHED transition; let it land
        for _ in range(100):
            used = [n.used for n in c.nodes.values()]
            if used == [0]:
                break
            await asyncio.sleep(0.05)
        await node.stop()
        await c.stop()
        return state, used

    state, used = asyncio.run(go())
    assert state == JobState.FINISHED
    assert used == [0]  # slots returned after the job
    counts = read_counts(tmp_path / "out.json")
    assert counts == {k: 500 for k in range(8)}
