"""Stateless value operators: map / filter / key-calculation.

Capability parity with the reference's ValueExecutionOperator /
KeyExecutionOperator / ProjectionOperator
(/root/reference/crates/arroyo-worker/src/arrow/mod.rs:245-347), which run a
compiled physical sub-plan batch-at-a-time. Here the compiled form is an
expression program from arroyo_tpu.sql.expressions (vectorized pyarrow/
numpy, or a jitted JAX path for numeric-heavy projections); `py_fn` configs
allow raw python callables for hand-built graphs and tests.
"""

from __future__ import annotations

from typing import Callable, Optional

import pyarrow as pa

from ..graph.logical import OperatorName
from ..engine.construct import register_operator
from .base import Operator


class BatchMapOperator(Operator):
    """Applies fn(RecordBatch) -> RecordBatch."""

    def __init__(self, fn: Callable[[pa.RecordBatch], Optional[pa.RecordBatch]],
                 name: str = "map", out_schema=None):
        super().__init__(name)
        self.fn = fn
        self.out_schema = out_schema

    async def process_batch(self, batch, ctx, collector, input_index: int = 0):
        out = self.fn(batch)
        if out is not None and out.num_rows:
            await collector.collect(out)


@register_operator(OperatorName.ARROW_VALUE)
@register_operator(OperatorName.PROJECTION)
def _make_value(config: dict) -> Operator:
    if "py_fn" in config:
        return BatchMapOperator(config["py_fn"], config.get("name", "map"),
                                config.get("schema"))
    if "program" in config:
        from ..sql.expressions import CompiledProjection

        prog = CompiledProjection.from_config(config["program"])
        return BatchMapOperator(prog, config.get("name", "project"),
                                config.get("schema"))
    raise ValueError("value operator config needs py_fn or program")


@register_operator(OperatorName.ARROW_KEY)
def _make_key(config: dict) -> Operator:
    """Key calculation: in this engine keys are column *indices* on the edge
    schema (no separate key column materialization needed) — an ArrowKey node
    may still compute key expressions into columns before the shuffle."""
    if "py_fn" in config:
        return BatchMapOperator(config["py_fn"], "key", config.get("schema"))
    if "program" in config:
        from ..sql.expressions import CompiledProjection

        prog = CompiledProjection.from_config(config["program"])
        return BatchMapOperator(prog, "key", config.get("schema"))
    # identity: routing handled by edge schema key indices
    return BatchMapOperator(lambda b: b, "key", config.get("schema"))
