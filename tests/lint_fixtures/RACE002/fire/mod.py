"""MUST fire RACE002 (both patterns): `drive` writes back a value read
before an await (stale-local write-back — PR 9's stop-path bug shape);
`bump` computes from a pre-await read (read-modify-write spanning a
yield). ``multi_writer`` is declared and does NOT waive either."""
import asyncio

from arroyo_tpu.analysis.races import shared_state


@shared_state("stop_requested", "counter",
              multi_writer=("stop_requested", "counter"))
class Job:
    def __init__(self):
        self.stop_requested = None
        self.counter = 0


class Engine:
    async def drive(self, job):
        mode = job.stop_requested
        job.stop_requested = None
        await self.checkpoint(job)
        job.stop_requested = mode  # clobbers anything set during the await

    async def bump(self, job):
        c = job.counter
        await asyncio.sleep(0)
        job.counter = c + 1  # increment computed from a stale snapshot

    async def checkpoint(self, job):
        await asyncio.sleep(0)
