"""Deterministic input fixtures for the golden-query harness.

Run `python tests/golden/make_fixtures.py` to regenerate
tests/golden/inputs/*.json (committed; the harness only reads them).
"""

import json
import os

HERE = os.path.dirname(os.path.abspath(__file__))
INPUTS = os.path.join(HERE, "inputs")


def impulse(n=600):
    # one event per 100ms from t0; counter + subtask_index
    t0 = "2023-03-01T00:00:"
    rows = []
    for i in range(n):
        secs = i // 10
        ms = (i % 10) * 100
        ts = f"2023-03-01T00:{secs // 60:02d}:{secs % 60:02d}.{ms:03d}Z"
        rows.append({"timestamp": ts, "counter": i, "subtask_index": 0})
    return rows


def cars(n=400):
    rows = []
    for i in range(n):
        # monotone through 5 minutes with bounded (sub-watermark) disorder
        secs = (i * 300) // n + (i * 7) % 2
        ts = f"2023-03-01T01:{secs // 60:02d}:{secs % 60:02d}Z"
        rows.append(
            {
                "timestamp": ts,
                "driver_id": 100 + (i * 13) % 7,
                "event_type": "pickup" if (i * 5) % 3 else "dropoff",
                "location": ["downtown", "airport", "suburb"][(i * 11) % 3],
            }
        )
    return rows


def bids(n=2000):
    rows = []
    for i in range(n):
        # monotone through one minute with bounded disorder
        millis = i * 30 + (i * 37) % 500
        secs = millis // 1000
        ts = (
            f"2023-03-01T02:{secs // 60:02d}:{secs % 60:02d}"
            f".{millis % 1000:03d}Z"
        )
        rows.append(
            {
                "datetime": ts,
                "auction": 1000 + (i * 17) % 20,
                "bidder": 2000 + (i * 29) % 50,
                "price": 100 + (i * 71) % 9000,
            }
        )
    return rows


def main():
    os.makedirs(INPUTS, exist_ok=True)
    for name, rows in [
        ("impulse.json", impulse()),
        ("cars.json", cars()),
        ("nexmark_bids.json", bids()),
    ]:
        with open(os.path.join(INPUTS, name), "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        print(f"wrote {name}: {len(rows)} rows")


if __name__ == "__main__":
    main()
