CREATE TABLE cars (
  timestamp TIMESTAMP,
  driver_id BIGINT,
  event_type TEXT,
  location TEXT,
  WATERMARK FOR timestamp
) WITH (
  connector = 'single_file',
  path = '$input_dir/cars.json',
  format = 'json',
  type = 'source'
);
CREATE TABLE group_by_aggregate (
  timestamp TIMESTAMP,
  count BIGINT
) WITH (
  connector = 'single_file',
  path = '$output_path',
  format = 'json',
  type = 'sink'
);
CREATE VIEW group_by_view AS (
  SELECT window.end as timestamp, count
  FROM (
    SELECT tumble(interval '1 minute') as window, count(*) as count
    FROM cars
    GROUP BY 1
  )
);
INSERT INTO group_by_aggregate
SELECT timestamp, count FROM group_by_view;
