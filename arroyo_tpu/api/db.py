"""SQLite persistence for the REST API.

Capability parity with the reference's database layer
(/root/reference/crates/arroyo-api: cornucopia-generated queries over
Postgres, parallel SQLite migrations for `arroyo run`): pipelines, jobs,
udfs, connection profiles/tables. SQLite only in this build (the reference
also speaks Postgres); the schema mirrors the reference's logical model.
"""

from __future__ import annotations

import json
import sqlite3
import time
import uuid
from pathlib import Path
from typing import List, Optional

MIGRATIONS = [
    """
    CREATE TABLE IF NOT EXISTS pipelines (
        id TEXT PRIMARY KEY,
        name TEXT NOT NULL,
        query TEXT NOT NULL,
        parallelism INTEGER NOT NULL DEFAULT 1,
        state TEXT NOT NULL DEFAULT 'Created',
        graph_json TEXT,
        created_at REAL NOT NULL
    )
    """,
    """
    CREATE TABLE IF NOT EXISTS jobs (
        id TEXT PRIMARY KEY,
        pipeline_id TEXT NOT NULL REFERENCES pipelines(id),
        state TEXT NOT NULL,
        restarts INTEGER NOT NULL DEFAULT 0,
        created_at REAL NOT NULL,
        finished_at REAL
    )
    """,
    """
    CREATE TABLE IF NOT EXISTS udfs (
        id TEXT PRIMARY KEY,
        prefix TEXT,
        name TEXT NOT NULL,
        definition TEXT NOT NULL,
        language TEXT NOT NULL DEFAULT 'python',
        created_at REAL NOT NULL
    )
    """,
    """
    CREATE TABLE IF NOT EXISTS connection_profiles (
        id TEXT PRIMARY KEY,
        name TEXT NOT NULL,
        connector TEXT NOT NULL,
        config TEXT NOT NULL,
        created_at REAL NOT NULL
    )
    """,
    """
    CREATE TABLE IF NOT EXISTS connection_tables (
        id TEXT PRIMARY KEY,
        name TEXT NOT NULL,
        connector TEXT NOT NULL,
        profile_id TEXT,
        config TEXT NOT NULL,
        schema_json TEXT,
        table_type TEXT,
        created_at REAL NOT NULL
    )
    """,
]


class ApiDb:
    def __init__(self, path: str = ":memory:"):
        if path != ":memory:":
            Path(path).parent.mkdir(parents=True, exist_ok=True)
        self.conn = sqlite3.connect(path)
        self.conn.row_factory = sqlite3.Row
        for m in MIGRATIONS:
            self.conn.execute(m)
        self.conn.commit()

    # -- pipelines ----------------------------------------------------------

    def create_pipeline(self, name: str, query: str, parallelism: int,
                        graph_json: Optional[dict] = None) -> dict:
        pid = "pl_" + uuid.uuid4().hex[:12]
        self.conn.execute(
            "INSERT INTO pipelines (id, name, query, parallelism, state, "
            "graph_json, created_at) VALUES (?,?,?,?,?,?,?)",
            (pid, name, query, parallelism, "Created",
             json.dumps(graph_json) if graph_json else None, time.time()),
        )
        self.conn.commit()
        return self.get_pipeline(pid)

    def list_pipelines(self) -> List[dict]:
        rows = self.conn.execute(
            "SELECT * FROM pipelines ORDER BY created_at DESC"
        ).fetchall()
        return [self._pipeline(r) for r in rows]

    def get_pipeline(self, pid: str) -> Optional[dict]:
        r = self.conn.execute(
            "SELECT * FROM pipelines WHERE id = ?", (pid,)
        ).fetchone()
        return self._pipeline(r) if r else None

    def set_pipeline_state(self, pid: str, state: str):
        self.conn.execute(
            "UPDATE pipelines SET state = ? WHERE id = ?", (state, pid)
        )
        self.conn.commit()

    def delete_pipeline(self, pid: str):
        self.conn.execute("DELETE FROM jobs WHERE pipeline_id = ?", (pid,))
        self.conn.execute("DELETE FROM pipelines WHERE id = ?", (pid,))
        self.conn.commit()

    @staticmethod
    def _pipeline(r) -> dict:
        return {
            "id": r["id"],
            "name": r["name"],
            "query": r["query"],
            "parallelism": r["parallelism"],
            "state": r["state"],
            "created_at": r["created_at"],
        }

    # -- jobs ---------------------------------------------------------------

    def create_job(self, pipeline_id: str) -> dict:
        jid = "job_" + uuid.uuid4().hex[:12]
        self.conn.execute(
            "INSERT INTO jobs (id, pipeline_id, state, created_at) "
            "VALUES (?,?,?,?)",
            (jid, pipeline_id, "Created", time.time()),
        )
        self.conn.commit()
        return {"id": jid, "pipeline_id": pipeline_id, "state": "Created"}

    def update_job(self, jid: str, state: str,
                   restarts: Optional[int] = None):
        finished = (
            time.time()
            if state in ("Finished", "Failed", "Stopped")
            else None
        )
        self.conn.execute(
            "UPDATE jobs SET state = ?, restarts = COALESCE(?, restarts), "
            "finished_at = COALESCE(?, finished_at) WHERE id = ?",
            (state, restarts, finished, jid),
        )
        self.conn.commit()

    def jobs_for_pipeline(self, pid: str) -> List[dict]:
        rows = self.conn.execute(
            "SELECT * FROM jobs WHERE pipeline_id = ? ORDER BY created_at",
            (pid,),
        ).fetchall()
        return [dict(r) for r in rows]

    def all_jobs(self) -> List[dict]:
        return [dict(r) for r in self.conn.execute(
            "SELECT * FROM jobs ORDER BY created_at DESC"
        ).fetchall()]

    # -- udfs ---------------------------------------------------------------

    def create_udf(self, name: str, definition: str, prefix: str = "",
                   language: str = "python") -> dict:
        uid = "udf_" + uuid.uuid4().hex[:12]
        self.conn.execute(
            "INSERT INTO udfs (id, prefix, name, definition, language, "
            "created_at) VALUES (?,?,?,?,?,?)",
            (uid, prefix, name, definition, language, time.time()),
        )
        self.conn.commit()
        return {"id": uid, "name": name, "definition": definition,
                "language": language}

    def list_udfs(self) -> List[dict]:
        return [dict(r) for r in self.conn.execute(
            "SELECT * FROM udfs ORDER BY created_at"
        ).fetchall()]

    def delete_udf(self, uid: str):
        self.conn.execute("DELETE FROM udfs WHERE id = ?", (uid,))
        self.conn.commit()

    # -- connections --------------------------------------------------------

    def create_connection_profile(self, name: str, connector: str,
                                  config: dict) -> dict:
        cid = "cp_" + uuid.uuid4().hex[:12]
        self.conn.execute(
            "INSERT INTO connection_profiles (id, name, connector, config, "
            "created_at) VALUES (?,?,?,?,?)",
            (cid, name, connector, json.dumps(config), time.time()),
        )
        self.conn.commit()
        return {"id": cid, "name": name, "connector": connector,
                "config": config}

    def list_connection_profiles(self) -> List[dict]:
        out = []
        for r in self.conn.execute(
            "SELECT * FROM connection_profiles ORDER BY created_at"
        ).fetchall():
            d = dict(r)
            d["config"] = json.loads(d["config"])
            out.append(d)
        return out

    def create_connection_table(self, name: str, connector: str, config: dict,
                                schema: Optional[dict], table_type: str,
                                profile_id: Optional[str]) -> dict:
        cid = "ct_" + uuid.uuid4().hex[:12]
        self.conn.execute(
            "INSERT INTO connection_tables (id, name, connector, profile_id, "
            "config, schema_json, table_type, created_at) "
            "VALUES (?,?,?,?,?,?,?,?)",
            (cid, name, connector, profile_id, json.dumps(config),
             json.dumps(schema) if schema else None, table_type, time.time()),
        )
        self.conn.commit()
        return {"id": cid, "name": name, "connector": connector,
                "config": config, "table_type": table_type}

    def list_connection_tables(self) -> List[dict]:
        out = []
        for r in self.conn.execute(
            "SELECT * FROM connection_tables ORDER BY created_at"
        ).fetchall():
            d = dict(r)
            d["config"] = json.loads(d["config"])
            if d["schema_json"]:
                d["schema"] = json.loads(d["schema_json"])
            del d["schema_json"]
            out.append(d)
        return out

    def delete_connection_table(self, cid: str):
        self.conn.execute("DELETE FROM connection_tables WHERE id = ?", (cid,))
        self.conn.commit()
