"""Admission control + fair slot scheduling over the shared worker pool.

The multi-tenant control plane (ROADMAP item 3) schedules MANY jobs onto
one pooled worker set, so slots become a contended resource. This module
implements the Flink slot-sharing accounting (Carbone et al., 2015): one
slot hosts one subtask of EACH operator of a job, so a job's slot
requirement is its maximum operator parallelism, not its subtask count.
On top of that:

  * admission — a job enters SCHEDULING only once its slots fit the
    pool's free capacity (`admission.enabled`); a submission burst queues
    here instead of oversubscribing every worker at once;
  * per-tenant quotas — `admission.tenant_quota_slots` caps the slots
    one tenant may hold; a tenant at quota queues behind its own jobs
    while other tenants keep being admitted;
  * fair-share ordering — queued jobs are granted in ascending
    (tenant-held-slots, arrival) order, so a tenant flooding the queue
    cannot starve a light tenant (weighted fair queueing over tenants
    with equal weights, DRF-degenerate single-resource case);
  * progress guarantees — the first job always bootstraps an empty pool
    (capacity is unknown before workers register), and a single job
    larger than total capacity is admitted alone rather than wedged.

The autoscaler's arbitration (autoscale/manager.py) reads `free_slots`
to clamp scale-up decisions of jobs competing for the same saturated
pool, so DS2 targets degrade gracefully instead of thrashing rescales.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Tuple

from ..config import config
from ..utils.logging import get_logger

logger = get_logger("admission")


class _Waiter:
    __slots__ = ("seq", "job", "need", "fut", "deadline")

    def __init__(self, seq: int, job, need: int, fut: asyncio.Future,
                 deadline: float):
        self.seq = seq
        self.job = job
        self.need = need
        self.fut = fut
        self.deadline = deadline


class AdmissionController:
    def __init__(self, controller):
        self.controller = controller
        # job_id -> (tenant, granted slots)
        self.held: Dict[str, Tuple[str, int]] = {}
        self.queue: List[_Waiter] = []
        self._seq = 0

    # -- accounting ----------------------------------------------------------

    @staticmethod
    def required_slots(job) -> int:
        """Flink slot sharing: a slot hosts one subtask of each operator,
        so the requirement is the job's max operator parallelism."""
        return max(
            (n.parallelism for n in job.graph.nodes.values()), default=1
        )

    def capacity(self) -> int:
        """Total live pooled slots (dead workers don't count)."""
        c = self.controller
        return sum(
            w.slots for w in c.workers.values()
            if w.pooled and not c._worker_stale(w)
        )

    def held_slots(self) -> int:
        return sum(s for (_t, s) in self.held.values())

    def free_slots(self) -> int:
        return self.capacity() - self.held_slots()

    def tenant_held(self, tenant: str) -> int:
        return sum(s for (t, s) in self.held.values() if t == tenant)

    def tenant_at_quota(self, tenant: str) -> bool:
        """True when the tenant's held slots reached its quota — the
        serve gateway reads this (StateServe, ISSUE 12): a tenant
        saturating its COMPUTE quota gets its READ quota clamped too,
        so one hot tenant can't starve both sides of the fleet."""
        quota = int(config().admission.tenant_quota_slots or 0)
        return bool(quota) and self.tenant_held(tenant) >= quota

    def _grantable(self, tenant: str, need: int) -> bool:
        cap = self.capacity()
        if not self.held:
            # bootstrap: the pool may not be up yet (acquire precedes
            # start_workers), and a lone oversized job must still run
            return True
        quota = int(config().admission.tenant_quota_slots or 0)
        if quota and self.tenant_held(tenant) >= quota:
            # soft quota: a tenant AT quota queues; a tenant under it may
            # overshoot by at most one job (a job larger than the whole
            # quota would otherwise wedge forever)
            return False
        return self.free_slots() >= min(need, cap)

    def _grant(self, job, need: int):
        cap = self.capacity()
        self.held[job.job_id] = (job.tenant, min(need, cap) if cap else need)

    # -- the fair-share queue ------------------------------------------------

    async def acquire(self, job):
        """Block until the job's slots are granted (fair-share order).
        Idempotent across recovery reschedules: a job keeps its grant
        (its requirement is re-read in case a rescale changed the
        graph)."""
        cfg = config().admission
        if not cfg.enabled or not self.controller._pool_mode():
            return
        need = self.required_slots(job)
        if job.job_id in self.held:
            # recovery/rescale reschedule: refresh the size, keep the grant
            self.held[job.job_id] = (job.tenant, need)
            return
        if self._grantable(job.tenant, need):
            self._grant(job, need)
            return
        if len(self.queue) >= int(cfg.max_queue):
            raise RuntimeError(
                f"admission queue full ({len(self.queue)} jobs waiting)"
            )
        fut = asyncio.get_event_loop().create_future()
        deadline = time.monotonic() + float(cfg.queue_timeout)
        w = _Waiter(self._seq, job, need, fut, deadline)
        self._seq += 1
        self.queue.append(w)
        self.controller.wheel.at(deadline, fut)
        logger.info(
            "job %s queued for admission (tenant=%s need=%d free=%d)",
            job.job_id, job.tenant, need, self.free_slots(),
        )
        try:
            granted = await fut
        finally:
            if w in self.queue:
                self.queue.remove(w)
        if not granted:
            raise TimeoutError(
                f"job {job.job_id} not admitted within "
                f"{cfg.queue_timeout}s (tenant {job.tenant}, "
                f"need {need}, free {self.free_slots()})"
            )

    def release(self, job):
        """Return a terminal job's slots and admit queued jobs."""
        if self.held.pop(job.job_id, None) is not None:
            self.pump()

    def pump(self):
        """Grant queued jobs in fair-share order: ascending (tenant held
        slots, arrival seq). Called on slot release and on worker
        registration (fresh capacity)."""
        while self.queue:
            order = sorted(
                self.queue,
                key=lambda w: (self.tenant_held(w.job.tenant), w.seq),
            )
            progressed = False
            for w in order:
                if w.fut.done():
                    self.queue.remove(w)
                    progressed = True
                    break
                if self._grantable(w.job.tenant, w.need):
                    self._grant(w.job, w.need)
                    self.queue.remove(w)
                    w.fut.set_result(True)
                    progressed = True
                    break
            if not progressed:
                return

    def status(self) -> dict:
        """Admin/debug surface: capacity, per-tenant usage, queue depth."""
        tenants: Dict[str, int] = {}
        for (t, s) in self.held.values():
            tenants[t] = tenants.get(t, 0) + s
        return {
            "capacity": self.capacity(),
            "held": self.held_slots(),
            "free": self.free_slots(),
            "jobs_admitted": len(self.held),
            "queued": len(self.queue),
            "tenants": tenants,
        }
