import numpy as np
import pyarrow as pa

from arroyo_tpu.schema import TIMESTAMP_FIELD, StreamSchema


def make_batch(schema: StreamSchema, n: int, keys=None):
    rng = np.random.default_rng(7)
    arrays = []
    for f in schema.schema:
        if f.name == TIMESTAMP_FIELD:
            arrays.append(pa.array(np.arange(n, dtype="int64"), type=pa.int64()).cast(f.type))
        elif f.name == "k":
            vals = keys if keys is not None else rng.integers(0, 10, n)
            arrays.append(pa.array(np.asarray(vals, dtype="int64")))
        else:
            arrays.append(pa.array(rng.random(n)))
    return pa.RecordBatch.from_arrays(arrays, schema=schema.schema)


def test_timestamp_injected():
    s = StreamSchema.from_fields([("k", pa.int64()), ("v", pa.float64())])
    assert TIMESTAMP_FIELD in s.names
    assert s.timestamp_index == 2


def test_partition_is_complete_and_consistent():
    s = StreamSchema.from_fields([("k", pa.int64()), ("v", pa.float64())], key_names=["k"])
    batch = make_batch(s, 500)
    parts = s.partition(batch, 4)
    total = sum(p.num_rows for p in parts if p is not None)
    assert total == 500
    # same key always lands in the same partition
    key_to_part = {}
    for i, p in enumerate(parts):
        if p is None:
            continue
        for k in p.column(0).to_pylist():
            assert key_to_part.setdefault(k, i) == i


def test_partition_unkeyed_single():
    s = StreamSchema.from_fields([("v", pa.float64())])
    batch = make_batch(s, 10)
    assert s.partition(batch, 1) == [batch]


def test_hash_keys_null_handling():
    s = StreamSchema.from_fields([("k", pa.int64())], key_names=["k"])
    batch = pa.RecordBatch.from_arrays(
        [pa.array([1, None, 1], type=pa.int64()),
         pa.array([0, 0, 0], type=pa.int64()).cast(pa.timestamp("ns"))],
        schema=s.schema,
    )
    h = s.hash_keys(batch)
    assert h[0] == h[2]
    assert h[1] != h[0]
