"""Expression binding + vectorized compilation over pyarrow.compute.

This replaces the reference's DataFusion physical expressions
(/root/reference/crates/arroyo-planner/src/physical.rs): every scalar SQL
expression compiles to a closure RecordBatch -> pa.Array executed by the
stateless operators. Arrow C++ kernels keep the host path vectorized; the
device (JAX) path is reserved for keyed aggregation where the FLOPs are.
"""

from __future__ import annotations

import dataclasses
import json
import operator as _op
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from .ast import (
    Between,
    BinaryOp,
    Case,
    Cast,
    Column,
    Expr,
    FieldAccess,
    FuncCall,
    InList,
    Interval,
    IsNull,
    Literal,
    Star,
    UnaryOp,
)
from .lexer import SqlError
from .types import common_type, sql_type_to_arrow

# ---------------------------------------------------------------------------
# Name scope
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ScopeCol:
    qualifier: Optional[str]
    name: str
    index: int
    dtype: pa.DataType


class Scope:
    """Column name resolution for one relation's output schema."""

    def __init__(self):
        self.cols: List[ScopeCol] = []

    @staticmethod
    def from_schema(schema: pa.Schema, qualifier: Optional[str] = None) -> "Scope":
        s = Scope()
        for i, f in enumerate(schema):
            s.add(qualifier, f.name, i, f.type)
        return s

    def add(self, qualifier, name, index, dtype):
        self.cols.append(ScopeCol(qualifier, name, index, dtype))

    def merge(self, other: "Scope", offset: int) -> "Scope":
        out = Scope()
        out.cols = list(self.cols) + [
            ScopeCol(c.qualifier, c.name, c.index + offset, c.dtype)
            for c in other.cols
        ]
        return out

    def resolve(self, name: str, qualifier: Optional[str] = None) -> ScopeCol:
        matches = [
            c
            for c in self.cols
            if c.name == name and (qualifier is None or c.qualifier == qualifier)
        ]
        if not matches:
            raise SqlError(
                f"unknown column {qualifier + '.' if qualifier else ''}{name}"
            )
        if len({m.index for m in matches}) > 1:
            raise SqlError(f"ambiguous column {name}")
        return matches[0]

    def try_resolve(self, name, qualifier=None) -> Optional[ScopeCol]:
        try:
            return self.resolve(name, qualifier)
        except SqlError:
            return None

    def names(self) -> List[str]:
        return [c.name for c in self.cols]


# ---------------------------------------------------------------------------
# Bound (compiled) expressions
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class BoundExpr:
    fn: Callable[[pa.RecordBatch], object]  # -> pa.Array | pa.Scalar
    dtype: pa.DataType
    name: str
    # device-lowerable mirror (JaxExpr) when this expression can run
    # inside a whole-segment jitted program (engine/segments.py); None
    # keeps the expression host-only (it can still feed a segment as a
    # host-evaluated input leaf when its dtype is numeric)
    jax: Optional["JaxExpr"] = None

    def eval(self, batch: pa.RecordBatch) -> pa.Array:
        out = self.fn(batch)
        if isinstance(out, pa.Scalar):
            out = pa.array([out.as_py()] * batch.num_rows, type=self.dtype)
        elif isinstance(out, pa.ChunkedArray):
            out = out.combine_chunks()
        return out


_NANOS = pa.timestamp("ns")


# ---------------------------------------------------------------------------
# Device lowering (whole-segment jit, engine/segments.py)
#
# Numeric expressions additionally carry a JaxExpr: a closure evaluating
# the same computation over jax arrays inside ONE traced program, so a
# fused stateless segment (filter -> project -> eval) compiles to a
# single XLA executable instead of N arrow-kernel passes. Anything not
# lowerable (strings, structs, UDFs, json) either becomes a
# host-evaluated input LEAF of the segment program (numeric dtype) or
# blocks the jax tier for that segment (the composed host tier runs it
# instead) — values, not availability, are the invariant.
# ---------------------------------------------------------------------------


def jax_lowerable_type(t: pa.DataType) -> bool:
    """Types representable as a dense jax array column (timestamps and
    durations ride as int64 nanos)."""
    return (
        pa.types.is_integer(t)
        or pa.types.is_floating(t)
        or pa.types.is_boolean(t)
        or pa.types.is_timestamp(t)
        or pa.types.is_duration(t)
    )


def np_value_dtype(t: pa.DataType):
    """The numpy dtype a lowerable arrow type computes in on device."""
    if pa.types.is_timestamp(t) or pa.types.is_duration(t):
        return np.dtype("int64")
    if pa.types.is_boolean(t):
        return np.dtype("bool")
    return np.dtype(t.to_pandas_dtype())


@dataclasses.dataclass
class JaxExpr:
    """Device mirror of a BoundExpr: `fn(env)` returns the jax array for
    this expression, where `env.col(j)` resolves input column j of the
    expression's own relation and `env.host(key)` resolves a
    host-evaluated leaf. `scalar` marks literal constants (they follow
    jax weak-typing, mirroring pa.Scalar coercion on the host path).
    `leaf` marks inputs that do no device compute themselves; `strict`
    marks subtrees whose null semantics are strict propagation (null in
    -> null out), so output validity can be reconstructed host-side as
    the AND of the leaf validities — kleene AND/OR are NOT strict, and
    a segment falls back to the host tier for batches where a null
    would reach a non-strict subtree."""

    fn: Callable
    cols: frozenset = frozenset()
    hosts: tuple = ()  # BoundExpr leaves evaluated host-side (stage 0 only)
    scalar: bool = False
    leaf: bool = False
    strict: bool = True
    # bit-exact vs the arrow/numpy host kernels: True for arith, compare,
    # cast, abs, mod, sqrt (all correctly rounded / integer-identical);
    # False for transcendentals, whose libm may differ in the last ulp —
    # the segment's numpy VECTOR tier requires exact (it must stay
    # byte-identical to the unfused plan), the jax device tier does not
    exact: bool = True


def _jx_col(idx: int, dtype: pa.DataType) -> Optional[JaxExpr]:
    if not jax_lowerable_type(dtype):
        return None
    return JaxExpr(lambda env: env.col(idx), frozenset((idx,)), leaf=True)


def _jx_lit(v) -> Optional[JaxExpr]:
    if isinstance(v, (bool, int, float)):
        return JaxExpr(lambda env: v, scalar=True, leaf=True)
    return None


def _jx_cast(jx: JaxExpr, target: pa.DataType) -> JaxExpr:
    """astype mirrors the host `pc.cast(..., safe=False)` / numpy
    truncation semantics for numeric-to-numeric casts."""
    to = np_value_dtype(target)

    def fn(env, f=jx.fn):
        v = f(env)
        if hasattr(v, "astype"):
            return v.astype(to)
        return np.asarray(v, dtype=to)  # python literal (constant-folds)

    return dataclasses.replace(jx, fn=fn, scalar=False, leaf=False)


def _jx_pair(left: "BoundExpr", right: "BoundExpr"):
    """Both operands' JaxExprs with the host path's _coerce_pair type
    coercion mirrored (cast to common_type); None when either side is
    not lowered or the coercion itself is not device-representable."""
    lj, rj = left.jax, right.jax
    if lj is None or rj is None:
        return None
    lt, rt = left.dtype, right.dtype
    if pa.types.is_null(lt) or pa.types.is_null(rt):
        return None
    if not lt.equals(rt):
        # literal scalars ride jax weak typing (the host path coerces
        # the pa.Scalar the same way); real arrays get an explicit cast
        if not (lj.scalar or rj.scalar):
            t = common_type(lt, rt)
            if not jax_lowerable_type(t):
                return None
            if not lt.equals(t):
                lj = _jx_cast(lj, t)
            if not rt.equals(t):
                rj = _jx_cast(rj, t)
    return lj, rj


def _jx_combine(f: Callable, *parts: JaxExpr, op_strict: bool = True,
                op_exact: bool = True) -> JaxExpr:
    cols = frozenset().union(*(p.cols for p in parts))
    hosts = []
    for p in parts:
        for h in p.hosts:
            if not any(h is o for o in hosts):
                hosts.append(h)
    fns = tuple(p.fn for p in parts)
    return JaxExpr(
        lambda env: f(*(g(env) for g in fns)), cols, tuple(hosts),
        strict=op_strict and all(p.strict for p in parts),
        exact=op_exact and all(p.exact for p in parts),
    )


def _jnp():
    from ..ops._jax import get_jax

    return get_jax().numpy


def _anp(x):
    """Array-namespace dispatch: the composed segment closures run the
    SAME computation on numpy arrays (the host vector tier) and on jax
    tracers (the jitted device tier)."""
    return np if isinstance(x, np.ndarray) else _jnp()


def bind(expr: Expr, scope: Scope) -> BoundExpr:
    """Bind + attach the device mirror: expressions that do not lower to
    jax themselves (struct field access, string ops, UDFs, ...) but have
    a device-representable dtype become host-evaluated input LEAVES of a
    fused segment program — `bid.price * 100 / 121` ships the
    struct_field read as a leaf and multiplies on device."""
    be = _bind(expr, scope)
    if be.jax is None and jax_lowerable_type(be.dtype):
        be.jax = JaxExpr(
            lambda env, _k=id(be): env.host(_k), hosts=(be,), leaf=True
        )
    return be


def _bind(expr: Expr, scope: Scope) -> BoundExpr:
    if isinstance(expr, Column):
        if expr.table is not None:
            # `a.b` is ambiguous: qualified column OR struct field access
            # (e.g. window.start). Prefer the qualified column; fall back to
            # a struct column named `a`.
            col = scope.try_resolve(expr.name, expr.table)
            if col is None:
                base = scope.try_resolve(expr.table)
                if base is not None and pa.types.is_struct(base.dtype):
                    return bind(
                        FieldAccess(Column(expr.table), expr.name), scope
                    )
                raise SqlError(f"unknown column {expr.table}.{expr.name}")
        else:
            col = scope.resolve(expr.name)
        idx = col.index
        return BoundExpr(lambda b: b.column(idx), col.dtype, expr.name,
                         jax=_jx_col(idx, col.dtype))
    if isinstance(expr, FieldAccess):
        base = bind(expr.base, scope)
        if not pa.types.is_struct(base.dtype):
            raise SqlError(f"{base.name} is not a struct; cannot access "
                           f".{expr.field}")
        fidx = base.dtype.get_field_index(expr.field)
        if fidx < 0:
            raise SqlError(f"struct {base.name} has no field {expr.field}")
        ftype = base.dtype.field(fidx).type
        return BoundExpr(
            lambda b: pc.struct_field(base.eval(b), expr.field),
            ftype,
            expr.field,
        )
    if isinstance(expr, Literal):
        v = expr.value
        if v is None:
            return BoundExpr(lambda b: pa.scalar(None, pa.null()), pa.null(), "NULL")
        t = _literal_type(v)
        return BoundExpr(lambda b: pa.scalar(v, t), t, str(v), jax=_jx_lit(v))
    if isinstance(expr, Interval):
        nanos = expr.nanos
        return BoundExpr(
            lambda b: pa.scalar(nanos, pa.int64()), pa.duration("ns"),
            "interval", jax=_jx_lit(nanos),
        )
    if isinstance(expr, BinaryOp):
        return _bind_binary(expr, scope)
    if isinstance(expr, UnaryOp):
        operand = bind(expr.operand, scope)
        if expr.op == "NOT":
            jx = (
                _jx_combine(_op.invert, operand.jax)
                if operand.jax is not None
                and pa.types.is_boolean(operand.dtype) else None
            )
            return BoundExpr(
                lambda b: pc.invert(operand.eval(b)), pa.bool_(),
                f"NOT {operand.name}", jax=jx,
            )
        jx = (
            _jx_combine(_op.neg, operand.jax)
            if operand.jax is not None
            and not pa.types.is_boolean(operand.dtype) else None
        )
        return BoundExpr(
            lambda b: pc.negate(operand.eval(b)), operand.dtype,
            f"-{operand.name}", jax=jx,
        )
    if isinstance(expr, Cast):
        operand = bind(expr.operand, scope)
        target = sql_type_to_arrow(expr.type_name)
        jx = (
            _jx_cast(operand.jax, target)
            if operand.jax is not None
            and jax_lowerable_type(operand.dtype)
            and jax_lowerable_type(target) else None
        )
        return BoundExpr(
            lambda b: _cast(operand.eval(b), target), target, operand.name,
            jax=jx,
        )
    if isinstance(expr, IsNull):
        operand = bind(expr.operand, scope)
        if expr.negated:
            return BoundExpr(
                lambda b: pc.is_valid(operand.eval(b)), pa.bool_(), "is_not_null"
            )
        return BoundExpr(
            lambda b: pc.is_null(operand.eval(b)), pa.bool_(), "is_null"
        )
    if isinstance(expr, InList):
        operand = bind(expr.operand, scope)
        values = [it.value for it in expr.items if isinstance(it, Literal)]
        if len(values) != len(expr.items):
            raise SqlError("IN list items must be literals")
        vset = pa.array(values, type=operand.dtype if not pa.types.is_null(
            operand.dtype) else None)

        def in_fn(b):
            out = pc.is_in(operand.eval(b), value_set=vset)
            return pc.invert(out) if expr.negated else out

        return BoundExpr(in_fn, pa.bool_(), "in")
    if isinstance(expr, Between):
        operand = bind(expr.operand, scope)
        lo = bind(expr.low, scope)
        hi = bind(expr.high, scope)

        def between_fn(b):
            v = operand.eval(b)
            out = pc.and_kleene(
                pc.greater_equal(v, lo.fn(b)), pc.less_equal(v, hi.fn(b))
            )
            return pc.invert(out) if expr.negated else out

        jx = None
        plo, phi = _jx_pair(operand, lo), _jx_pair(operand, hi)
        if plo is not None and phi is not None:
            jx = _jx_combine(
                lambda v1, l1, v2, h1: (v1 >= l1) & (v2 <= h1),
                plo[0], plo[1], phi[0], phi[1],
            )
            if expr.negated:
                jx = _jx_combine(_op.invert, jx)
        return BoundExpr(between_fn, pa.bool_(), "between", jax=jx)
    if isinstance(expr, Case):
        return _bind_case(expr, scope)
    if isinstance(expr, FuncCall):
        return bind_scalar_function(expr, scope)
    if isinstance(expr, Star):
        raise SqlError("* is only valid directly in a SELECT list")
    raise SqlError(f"unsupported expression {expr!r}")


def _literal_type(v) -> pa.DataType:
    if isinstance(v, bool):
        return pa.bool_()
    if isinstance(v, int):
        return pa.int64()
    if isinstance(v, float):
        return pa.float64()
    if isinstance(v, str):
        return pa.string()
    raise SqlError(f"unsupported literal {v!r}")


def _cast(arr, target: pa.DataType):
    if isinstance(arr, pa.Scalar):
        return pa.scalar(arr.as_py(), target)
    if pa.types.is_string(target) and pa.types.is_timestamp(arr.type):
        return pc.strftime(arr, format="%Y-%m-%dT%H:%M:%S.%f")
    if pa.types.is_timestamp(target) and pa.types.is_string(arr.type):
        # tolerant ISO8601 parse
        return pc.cast(arr, target)
    return pc.cast(arr, target, safe=False)


_ARITH = {"+": pc.add, "-": pc.subtract, "*": pc.multiply, "/": pc.divide}
_CMP = {
    "=": pc.equal,
    "!=": pc.not_equal,
    "<": pc.less,
    "<=": pc.less_equal,
    ">": pc.greater,
    ">=": pc.greater_equal,
}


_JAX_CMP = {
    "=": _op.eq, "!=": _op.ne, "<": _op.lt, "<=": _op.le,
    ">": _op.gt, ">=": _op.ge,
}
_JAX_ARITH = {"+": _op.add, "-": _op.sub, "*": _op.mul, "/": _op.truediv}


def _bind_binary(expr: BinaryOp, scope: Scope) -> BoundExpr:
    left = bind(expr.left, scope)
    right = bind(expr.right, scope)
    op = expr.op
    name = f"{left.name}{op}{right.name}"
    if op in ("AND", "OR"):
        f = pc.and_kleene if op == "AND" else pc.or_kleene
        jx = None
        if (left.jax is not None and right.jax is not None
                and pa.types.is_boolean(left.dtype)
                and pa.types.is_boolean(right.dtype)):
            # kleene and/or are not strictly null-propagating (true OR
            # null = true): nulls reaching this subtree force the
            # segment's host tier for that batch
            jx = _jx_combine(_op.and_ if op == "AND" else _op.or_,
                             left.jax, right.jax, op_strict=False)
        return BoundExpr(lambda b: f(left.eval(b), right.eval(b)), pa.bool_(),
                         name, jax=jx)
    if op in _CMP:
        if pa.types.is_struct(left.dtype) and pa.types.is_struct(right.dtype):
            if op != "=":
                raise SqlError("structs only support equality comparison")
            fields = [f.name for f in left.dtype]

            def struct_eq(b):
                lv, rv = left.eval(b), right.eval(b)
                out = None
                for fname in fields:
                    e = pc.equal(pc.struct_field(lv, fname),
                                 pc.struct_field(rv, fname))
                    out = e if out is None else pc.and_kleene(out, e)
                return out

            return BoundExpr(struct_eq, pa.bool_(), name)
        f = _CMP[op]
        pair = _jx_pair(left, right)
        jx = (
            _jx_combine(_JAX_CMP[op], pair[0], pair[1])
            if pair is not None else None
        )
        return BoundExpr(
            lambda b: f(*_coerce_pair(left, right, b)), pa.bool_(), name,
            jax=jx,
        )
    if op == "||":
        return BoundExpr(
            lambda b: pc.binary_join_element_wise(
                _to_str(left.eval(b)), _to_str(right.eval(b)), ""
            ),
            pa.string(),
            name,
        )
    if op in ("->", "->>"):
        return _bind_json_access(left, right, op)
    if op in _ARITH:
        return _bind_arith(left, right, op, name)
    if op == "%":
        def mod_fn(b):
            lv, rv = _coerce_pair(left, right, b)
            return _numpy_binary(np.mod, lv, rv)

        pair = _jx_pair(left, right)
        jx = (
            _jx_combine(lambda a, c: _anp(a).mod(a, c), pair[0], pair[1])
            if pair is not None else None
        )
        return BoundExpr(mod_fn, common_type(_num(left.dtype), _num(right.dtype)),
                         name, jax=jx)
    raise SqlError(f"unsupported operator {op}")


def _num(t: pa.DataType) -> pa.DataType:
    return pa.int64() if pa.types.is_null(t) else t


def _bind_arith(left: BoundExpr, right: BoundExpr, op: str, name: str) -> BoundExpr:
    lt, rt = left.dtype, right.dtype

    def _pair_jax(f):
        pair = _jx_pair(left, right)
        return _jx_combine(f, pair[0], pair[1]) if pair is not None else None

    # timestamp +- interval arithmetic in int64 nanos
    if pa.types.is_timestamp(lt) and pa.types.is_duration(rt):
        f = pc.add if op == "+" else pc.subtract

        def ts_fn(b):
            lv = pc.cast(left.eval(b), pa.int64())
            return pc.cast(f(lv, right.fn(b)), _NANOS)

        return BoundExpr(ts_fn, _NANOS, name,
                         jax=_pair_jax(_op.add if op == "+" else _op.sub))
    if pa.types.is_duration(lt) and pa.types.is_timestamp(rt) and op == "+":
        def ts_fn2(b):
            rv = pc.cast(right.eval(b), pa.int64())
            return pc.cast(pc.add(rv, left.fn(b)), _NANOS)

        return BoundExpr(ts_fn2, _NANOS, name, jax=_pair_jax(_op.add))
    if pa.types.is_timestamp(lt) and pa.types.is_timestamp(rt) and op == "-":
        def diff_fn(b):
            return pc.subtract(
                pc.cast(left.eval(b), pa.int64()), pc.cast(right.eval(b), pa.int64())
            )

        return BoundExpr(diff_fn, pa.duration("ns"), name,
                         jax=_pair_jax(_op.sub))
    out_t = common_type(_num(lt), _num(rt))
    if op == "/" and pa.types.is_integer(out_t):
        # SQL integer division truncates
        def idiv(b):
            lv, rv = _coerce_pair(left, right, b)
            return _numpy_binary(
                lambda a, c: (a // c).astype(np.int64), lv, rv
            )

        return BoundExpr(
            idiv, out_t, name,
            jax=_pair_jax(lambda a, c: (a // c).astype(np.int64)),
        )
    f = _ARITH[op]
    return BoundExpr(lambda b: f(*_coerce_pair(left, right, b)), out_t, name,
                     jax=_pair_jax(_JAX_ARITH[op]))


def _coerce_pair(left: BoundExpr, right: BoundExpr, b) -> Tuple:
    lv = left.fn(b)
    rv = right.fn(b)
    if isinstance(lv, pa.ChunkedArray):
        lv = lv.combine_chunks()
    if isinstance(rv, pa.ChunkedArray):
        rv = rv.combine_chunks()
    lt, rt = left.dtype, right.dtype
    if pa.types.is_null(lt) or pa.types.is_null(rt):
        return lv, rv
    if not lt.equals(rt):
        t = common_type(lt, rt)
        if not lt.equals(t):
            lv = _cast_any(lv, t)
        if not rt.equals(t):
            rv = _cast_any(rv, t)
    return lv, rv


def _cast_any(v, t):
    if isinstance(v, pa.Scalar):
        return pa.scalar(v.as_py(), t)
    return pc.cast(v, t, safe=False)


def _numpy_binary(f, lv, rv):
    la = lv.as_py() if isinstance(lv, pa.Scalar) else np.asarray(
        lv.to_numpy(zero_copy_only=False))
    ra = rv.as_py() if isinstance(rv, pa.Scalar) else np.asarray(
        rv.to_numpy(zero_copy_only=False))
    return pa.array(f(la, ra))


def _to_str(v):
    t = v.type if not isinstance(v, pa.Scalar) else v.type
    if pa.types.is_string(t):
        return v
    return _cast_any(v, pa.string())


def _bind_json_access(left: BoundExpr, right: BoundExpr, op: str) -> BoundExpr:
    """Postgres-style json access over string columns (python fallback)."""

    def fn(b):
        docs = left.eval(b).to_pylist()
        key = right.fn(b)
        key = key.as_py() if isinstance(key, pa.Scalar) else None
        out = []
        for d in docs:
            try:
                obj = json.loads(d) if isinstance(d, str) else d
                v = obj[key] if not isinstance(key, int) else obj[key]
            except Exception:
                v = None
            if op == "->":
                out.append(json.dumps(v) if v is not None else None)
            else:
                out.append(
                    v if isinstance(v, str) or v is None else json.dumps(v)
                )
        return pa.array(out, type=pa.string())

    return BoundExpr(fn, pa.string(), "json_access")


def _bind_case(expr: Case, scope: Scope) -> BoundExpr:
    branches = []
    for when, then in expr.branches:
        if expr.operand is not None:
            cond = bind(BinaryOp("=", expr.operand, when), scope)
        else:
            cond = bind(when, scope)
        branches.append((cond, bind(then, scope)))
    else_b = bind(expr.else_, scope) if expr.else_ is not None else None
    out_t = branches[0][1].dtype
    for _, t in branches[1:]:
        if not pa.types.is_null(t.dtype):
            out_t = t.dtype if pa.types.is_null(out_t) else common_type(out_t, t.dtype)
    if else_b is not None and not pa.types.is_null(else_b.dtype):
        out_t = else_b.dtype if pa.types.is_null(out_t) else common_type(
            out_t, else_b.dtype)

    def fn(b):
        n = b.num_rows
        result = (
            _cast_any(else_b.eval(b), out_t)
            if else_b is not None
            else pa.array([None] * n, type=out_t)
        )
        for cond, then in reversed(branches):
            c = cond.eval(b)
            result = pc.if_else(c, _cast_any(then.eval(b), out_t), result)
        return result

    return BoundExpr(fn, out_t, "case")


# ---------------------------------------------------------------------------
# Scalar functions
# ---------------------------------------------------------------------------

_SIMPLE_FUNCS: Dict[str, Tuple[Callable, Optional[pa.DataType]]] = {
    # name -> (pc function, fixed output type or None=same as input)
    "abs": (pc.abs, None),
    "ceil": (pc.ceil, None),
    "floor": (pc.floor, None),
    "sqrt": (pc.sqrt, pa.float64()),
    "exp": (pc.exp, pa.float64()),
    "ln": (pc.ln, pa.float64()),
    "log10": (pc.log10, pa.float64()),
    "log2": (pc.log2, pa.float64()),
    "sin": (pc.sin, pa.float64()),
    "cos": (pc.cos, pa.float64()),
    "tan": (pc.tan, pa.float64()),
    "asin": (pc.asin, pa.float64()),
    "acos": (pc.acos, pa.float64()),
    "atan": (pc.atan, pa.float64()),
    "upper": (pc.utf8_upper, pa.string()),
    "lower": (pc.utf8_lower, pa.string()),
    "length": (pc.utf8_length, pa.int64()),
    "char_length": (pc.utf8_length, pa.int64()),
    "character_length": (pc.utf8_length, pa.int64()),
    "trim": (pc.utf8_trim_whitespace, pa.string()),
    "ltrim": (pc.utf8_ltrim_whitespace, pa.string()),
    "rtrim": (pc.utf8_rtrim_whitespace, pa.string()),
    "reverse": (pc.utf8_reverse, pa.string()),
}

_EXTRACT_FUNCS = {
    "year": pc.year,
    "month": pc.month,
    "day": pc.day,
    "hour": pc.hour,
    "minute": pc.minute,
    "second": pc.second,
    "millisecond": pc.millisecond,
    "dow": pc.day_of_week,
    "doy": pc.day_of_year,
    "week": pc.iso_week,
    "quarter": pc.quarter,
    "epoch": None,  # special-cased
}


# jnp mirrors for the float64-exact math subset (host kernels and XLA
# agree bit-for-bit on these elementwise f64 ops); ceil/floor only lower
# for floats (pc.ceil keeps ints integral, jnp.ceil would promote)
_JAX_FLOAT_FUNCS = {
    "ceil": "ceil", "floor": "floor", "sqrt": "sqrt", "exp": "exp",
    "ln": "log", "log10": "log10", "log2": "log2", "sin": "sin",
    "cos": "cos", "tan": "tan", "asin": "arcsin", "acos": "arccos",
    "atan": "arctan",
}


def _jx_func(name: str, a: BoundExpr) -> Optional[JaxExpr]:
    if a.jax is None:
        return None
    if name == "abs" and (pa.types.is_integer(a.dtype)
                          or pa.types.is_floating(a.dtype)):
        return _jx_combine(_op.abs, a.jax)
    jname = _JAX_FLOAT_FUNCS.get(name)
    if jname is not None and pa.types.is_float64(a.dtype):
        return _jx_combine(
            lambda v, _j=jname: getattr(_anp(v), _j)(v), a.jax,
            # sqrt is IEEE correctly-rounded everywhere; the other libm
            # functions may differ in the last ulp between kernels, so
            # only the jax tier (not the byte-identical vector tier)
            # may run them
            op_exact=(jname == "sqrt"),
        )
    return None


def bind_scalar_function(expr: FuncCall, scope: Scope) -> BoundExpr:
    from ..udf import registry as udf_registry

    name = expr.name
    args = [bind(a, scope) for a in expr.args]
    if name in _SIMPLE_FUNCS:
        f, out_t = _SIMPLE_FUNCS[name]
        a = args[0]
        return BoundExpr(lambda b: f(a.eval(b)), out_t or a.dtype, name,
                         jax=_jx_func(name, a))
    if name in ("power", "pow"):
        return BoundExpr(
            lambda b: pc.power(args[0].eval(b), args[1].fn(b)), pa.float64(), name
        )
    if name == "round":
        nd = 0
        if len(args) > 1:
            nd_expr = expr.args[1]
            nd = nd_expr.value if isinstance(nd_expr, Literal) else 0
        a = args[0]
        return BoundExpr(
            lambda b: pc.round(a.eval(b), ndigits=nd), a.dtype, name
        )
    if name == "coalesce":
        out_t = next(
            (a.dtype for a in args if not pa.types.is_null(a.dtype)), pa.null()
        )

        def coalesce_fn(b):
            result = _cast_any(args[-1].eval(b), out_t)
            for a in reversed(args[:-1]):
                v = _cast_any(a.eval(b), out_t)
                result = pc.if_else(pc.is_valid(v), v, result)
            return result

        return BoundExpr(coalesce_fn, out_t, name)
    if name == "nullif":
        a, c = args[0], args[1]
        return BoundExpr(
            lambda b: pc.if_else(
                pc.equal(a.eval(b), c.fn(b)),
                pa.scalar(None, a.dtype),
                a.eval(b),
            ),
            a.dtype,
            name,
        )
    if name == "concat":
        def concat_fn(b):
            parts = [_to_str(a.eval(b)) for a in args]
            return pc.binary_join_element_wise(*parts, "")

        return BoundExpr(concat_fn, pa.string(), name)
    if name in ("substr", "substring"):
        a = args[0]

        def substr_fn(b):
            start = args[1].fn(b)
            start_v = start.as_py() if isinstance(start, pa.Scalar) else 1
            length = None
            if len(args) > 2:
                lv = args[2].fn(b)
                length = lv.as_py() if isinstance(lv, pa.Scalar) else None
            stop = (start_v - 1 + length) if length is not None else None
            return pc.utf8_slice_codeunits(
                a.eval(b), start=start_v - 1, stop=stop
            )

        return BoundExpr(substr_fn, pa.string(), name)
    if name == "replace":
        a = args[0]

        def replace_fn(b):
            pat = args[1].fn(b).as_py()
            rep = args[2].fn(b).as_py()
            return pc.replace_substring(a.eval(b), pattern=pat, replacement=rep)

        return BoundExpr(replace_fn, pa.string(), name)
    if name == "like":
        a = args[0]

        def like_fn(b):
            pat = args[1].fn(b)
            return pc.match_like(a.eval(b), pat.as_py())

        return BoundExpr(like_fn, pa.bool_(), name)
    if name == "extract" or name == "date_part":
        part = expr.args[0].value if isinstance(expr.args[0], Literal) else None
        a = args[1]
        if part == "epoch":
            return BoundExpr(
                lambda b: pc.divide(
                    pc.cast(a.eval(b), pa.int64()), pa.scalar(1_000_000_000)
                ),
                pa.int64(),
                name,
            )
        if part not in _EXTRACT_FUNCS:
            raise SqlError(f"unsupported extract part {part!r}")
        f = _EXTRACT_FUNCS[part]
        return BoundExpr(lambda b: pc.cast(f(a.eval(b)), pa.int64()),
                         pa.int64(), name)
    if name == "date_trunc":
        unit = expr.args[0].value if isinstance(expr.args[0], Literal) else "day"
        a = args[1]
        return BoundExpr(
            lambda b: pc.floor_temporal(a.eval(b), unit=unit), a.dtype, name
        )
    if name == "to_timestamp":
        a = args[0]
        if pa.types.is_string(a.dtype):
            return BoundExpr(lambda b: pc.cast(a.eval(b), _NANOS), _NANOS, name)
        # numeric epoch seconds
        return BoundExpr(
            lambda b: pc.cast(
                pc.multiply(pc.cast(a.eval(b), pa.int64()),
                            pa.scalar(1_000_000_000)),
                _NANOS,
            ),
            _NANOS,
            name,
        )
    if name == "md5":
        a = args[0]

        def md5_fn(b):
            import hashlib

            return pa.array(
                [
                    hashlib.md5(str(v).encode()).hexdigest() if v is not None
                    else None
                    for v in a.eval(b).to_pylist()
                ],
                type=pa.string(),
            )

        return BoundExpr(md5_fn, pa.string(), name)
    if name == "array_element":
        a, idx = args[0], args[1]
        if not pa.types.is_list(a.dtype):
            raise SqlError("array_element requires a list operand")
        vt = a.dtype.value_type

        def elem_fn(b):
            i = idx.fn(b)
            i_v = i.as_py() if isinstance(i, pa.Scalar) else 1
            return pc.list_element(a.eval(b), i_v - 1)  # SQL is 1-indexed

        return BoundExpr(elem_fn, vt, name)
    if name == "cardinality":
        a = args[0]
        return BoundExpr(
            lambda b: pc.cast(pc.list_value_length(a.eval(b)), pa.int64()),
            pa.int64(),
            name,
        )
    # window TVFs leak here only if misused
    if name in ("tumble", "hop", "session"):
        raise SqlError(
            f"{name}() is a window function and may only appear in GROUP BY "
            "(and as a SELECT alias of that group)"
        )
    udf = udf_registry.get(name)
    if udf is not None:
        return udf.bind(args)
    raise SqlError(f"unknown function {name!r}")


# ---------------------------------------------------------------------------
# Compiled programs used by the stateless operators
# ---------------------------------------------------------------------------


class _LazyFilteredBatch:
    """Duck-typed RecordBatch view whose columns are filtered ON DEMAND.

    CompiledProjection predicates used to filter the whole batch before
    projecting — paying the filter kernel for every column, including
    wide struct columns the projection never reads (nexmark batches
    carry person+auction+bid structs; q5/q1 read only `bid`). This view
    exposes just the surface bound expressions use (column(i)/num_rows/
    schema) and filters each accessed column once, lazily."""

    __slots__ = ("_batch", "_mask", "_cols", "num_rows", "schema")

    def __init__(self, batch: pa.RecordBatch, mask, num_rows: int):
        self._batch = batch
        self._mask = mask
        self._cols = {}
        self.num_rows = num_rows
        self.schema = batch.schema

    def column(self, i: int):
        c = self._cols.get(i)
        if c is None:
            c = self._batch.column(i).filter(self._mask)
            self._cols[i] = c
        return c

    def __getattr__(self, name):
        # duck-typing guard: a BoundExpr reaching for any other
        # RecordBatch attribute would otherwise fail only on the
        # partially-filtered path with an anonymous error (zero-pass /
        # all-pass predicates never build this view)
        raise AttributeError(
            f"_LazyFilteredBatch (the lazy predicate-filtered RecordBatch "
            f"view) exposes only column()/num_rows/schema, not {name!r}; "
            f"teach the view that attribute or filter eagerly in "
            f"CompiledProjection"
        )


class CompiledProjection:
    """Projection (+ optional pre-filter): the runtime form handed to
    ARROW_VALUE operators."""

    def __init__(self, exprs: List[BoundExpr], out_schema: pa.Schema,
                 predicate: Optional[BoundExpr] = None):
        self.exprs = exprs
        self.out_schema = out_schema
        self.predicate = predicate

    def __call__(self, batch: pa.RecordBatch) -> Optional[pa.RecordBatch]:
        if self.predicate is not None:
            mask = pc.fill_null(self.predicate.eval(batch), False)
            kept = pc.sum(mask).as_py() or 0
            if kept == 0:
                return None
            if kept < batch.num_rows:
                batch = _LazyFilteredBatch(batch, mask, kept)
        arrays = []
        for e, f in zip(self.exprs, self.out_schema):
            arr = e.eval(batch)
            if not arr.type.equals(f.type):
                arr = _cast(arr, f.type)
            arrays.append(arr)
        return pa.RecordBatch.from_arrays(arrays, schema=self.out_schema)

    @staticmethod
    def from_config(config: dict) -> "CompiledProjection":
        """Rebuild from a serialized config (cross-process path): exprs are
        re-bound from SQL text against the carried schema."""
        from .parser import parse_expr_text

        in_schema = config["in_schema"]
        scope = Scope.from_schema(
            in_schema.schema if hasattr(in_schema, "schema") else in_schema
        )
        exprs = [bind(parse_expr_text(s), scope) for s in config["exprs"]]
        pred = (
            bind(parse_expr_text(config["predicate"]), scope)
            if config.get("predicate")
            else None
        )
        out = config["out_schema"]
        return CompiledProjection(
            exprs, out.schema if hasattr(out, "schema") else out, pred
        )


class CompiledPredicate:
    def __init__(self, expr: BoundExpr):
        self.expr = expr

    def __call__(self, batch: pa.RecordBatch) -> Optional[pa.RecordBatch]:
        mask = self.expr.eval(batch)
        out = batch.filter(mask)
        return out if out.num_rows else None
