"""Metrics registry with Prometheus text exposition.

Capability parity with the reference's `arroyo-metrics` crate +
TaskCounters (/root/reference/crates/arroyo-operator/src/context.rs):
per-task messages/batches/bytes rx-tx counters, per-queue occupancy gauges,
and UI-facing 5-minute rate windows (computed in engine.job_metrics).

The flight-recorder layer (arroyo_tpu/obs) adds a histogram kind
(`Registry.histogram` → `.labels(...).observe(v)`) with standard
`_bucket`/`_sum`/`_count` exposition, feeding per-subtask batch-processing
latency, data-plane exchange latency, storage op latency and checkpoint
phase durations, plus watermark-lag and barrier-alignment gauges.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import defaultdict, deque
from typing import Dict, Optional, Tuple

LabelSet = Tuple[Tuple[str, str], ...]

# latency buckets (seconds): 1ms .. 10s, roughly log-spaced — covers the
# data plane (sub-ms frames) through checkpoint flushes (seconds)
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)


def _escape_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class _Hist:
    """Per-labelset histogram state: bucket counts + running sum/count."""

    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float, buckets: Tuple[float, ...]):
        i = bisect.bisect_left(buckets, value)
        if i < len(self.counts):
            self.counts[i] += 1
        self.sum += value
        self.count += 1

    def cumulative(self) -> list:
        out = []
        cum = 0
        for c in self.counts:
            cum += c
            out.append(cum)
        return out


class _Metric:
    def __init__(self, name: str, help_: str, kind: str,
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.kind = kind
        self.buckets = tuple(buckets)
        self.values: Dict[LabelSet, float] = defaultdict(float)
        self.hists: Dict[LabelSet, _Hist] = {}
        # scrape-time refreshers: key -> zero-arg callable returning the
        # current value (or None to keep the stored sample). Gauges whose
        # producer only updates on its own hot path (e.g. backpressure,
        # sampled every N collect() calls) register one so a quiesced
        # stream can't pin a stale value into every future scrape.
        self.refreshers: Dict[LabelSet, object] = {}
        self.lock = threading.Lock()

    def labels(self, **labels: str) -> "_Handle":
        key = tuple(sorted(labels.items()))
        return _Handle(self, key)

    def observe(self, key: LabelSet, value: float):
        with self.lock:
            h = self.hists.get(key)
            if h is None:
                h = self.hists[key] = _Hist(len(self.buckets))
            h.observe(value, self.buckets)

    def _refresh(self):
        """Run registered refreshers (lock held), dropping dead ones."""
        if not self.refreshers:
            return
        dead = []
        for key, fn in self.refreshers.items():
            try:
                v = fn()
            except Exception:  # noqa: BLE001 - producer gone mid-scrape
                v = None
            if v is None:
                dead.append(key)
            else:
                self.values[key] = v
        for key in dead:
            del self.refreshers[key]

    @staticmethod
    def _label_str(key: LabelSet, extra: str = "") -> str:
        parts = [f'{k}="{_escape_label(v)}"' for k, v in key]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def expose(self) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.kind}"]
        with self.lock:
            if self.kind == "histogram":
                for key, h in self.hists.items():
                    cum = h.cumulative()
                    for le, c in zip(self.buckets, cum):
                        le_label = f'le="{le}"'
                        lines.append(
                            f"{self.name}_bucket"
                            f"{self._label_str(key, le_label)} {c}"
                        )
                    inf_label = 'le="+Inf"'
                    lines.append(
                        f"{self.name}_bucket"
                        f"{self._label_str(key, inf_label)} {h.count}"
                    )
                    lines.append(f"{self.name}_sum{self._label_str(key)} {h.sum}")
                    lines.append(f"{self.name}_count{self._label_str(key)} {h.count}")
                return "\n".join(lines)
            self._refresh()
            for key, val in self.values.items():
                if key:
                    label_s = ",".join(
                        f'{k}="{_escape_label(v)}"' for k, v in key)
                    lines.append(f"{self.name}{{{label_s}}} {val}")
                else:
                    lines.append(f"{self.name} {val}")
        return "\n".join(lines)


class _Handle:
    __slots__ = ("metric", "key")

    def __init__(self, metric: _Metric, key: LabelSet):
        self.metric = metric
        self.key = key

    def inc(self, amount: float = 1.0):
        with self.metric.lock:
            self.metric.values[self.key] += amount

    def set(self, value: float):
        with self.metric.lock:
            self.metric.values[self.key] = value

    def observe(self, value: float):
        """Histogram observation (seconds for the latency families)."""
        self.metric.observe(self.key, value)

    def set_refresher(self, fn):
        """Register a scrape-time refresher: `fn()` is called under the
        metric lock at expose/snapshot and must return the current value,
        or None to unregister itself (producer gone)."""
        with self.metric.lock:
            self.metric.refreshers[self.key] = fn

    def get(self) -> float:
        with self.metric.lock:
            return self.metric.values[self.key]

    def get_hist(self) -> Optional[dict]:
        """Structured view of this labelset's histogram state."""
        with self.metric.lock:
            h = self.metric.hists.get(self.key)
            if h is None:
                return None
            return _hist_dict(h, self.metric.buckets)


def _hist_dict(h: _Hist, buckets: Tuple[float, ...]) -> dict:
    out = {str(le): c for le, c in zip(buckets, h.cumulative())}
    out["+Inf"] = h.count
    return {"sum": h.sum, "count": h.count, "buckets": out}


def hist_quantiles(snapshot: Optional[dict],
                   qs: Tuple[float, ...] = (0.5, 0.95, 0.99)) -> dict:
    """Estimate quantiles from a histogram snapshot's cumulative buckets
    (the {"sum", "count", "buckets": {le: cumulative}} shape _hist_dict /
    Registry.snapshot produce) by linear interpolation within the bucket
    that crosses the target rank — the same estimator Prometheus's
    histogram_quantile() applies server-side. The +Inf bucket has no upper
    edge, so ranks landing there report the highest finite edge (a floor,
    like Prometheus). Returns {"p50": v, ...}; empty dict for a missing or
    empty snapshot."""
    if not snapshot or not snapshot.get("count"):
        return {}
    edges = sorted(
        (float(le), c) for le, c in snapshot["buckets"].items()
        if le != "+Inf"
    )
    total = snapshot["count"]
    out = {}
    for q in qs:
        rank = q * total
        val = edges[-1][0] if edges else 0.0
        prev_edge, prev_cum = 0.0, 0
        for edge, cum in edges:
            if cum >= rank:
                if cum > prev_cum:
                    frac = (rank - prev_cum) / (cum - prev_cum)
                    val = prev_edge + (edge - prev_edge) * frac
                else:
                    val = edge
                break
            prev_edge, prev_cum = edge, cum
        out[f"p{int(q * 100)}"] = val
    return out


class Registry:
    def __init__(self):
        self.metrics: Dict[str, _Metric] = {}
        self.lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> _Metric:
        return self._get(name, help_, "counter")

    def gauge(self, name: str, help_: str = "") -> _Metric:
        return self._get(name, help_, "gauge")

    def histogram(self, name: str, help_: str = "",
                  buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> _Metric:
        return self._get(name, help_, "histogram", buckets)

    def _get(self, name: str, help_: str, kind: str,
             buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> _Metric:
        with self.lock:
            if name not in self.metrics:
                self.metrics[name] = _Metric(name, help_, kind, buckets)
            return self.metrics[name]

    def expose(self) -> str:
        with self.lock:
            metrics = list(self.metrics.values())
        return "\n".join(m.expose() for m in metrics) + "\n"

    def snapshot(self) -> Dict[str, list]:
        """{metric name: [(labels dict, value)]} for structured consumers
        (the API's operator metric groups). Histogram entries carry a
        {"sum", "count", "buckets": {le: cumulative}} dict as the value."""
        with self.lock:
            metrics = list(self.metrics.items())
        out: Dict[str, list] = {}
        for name, m in metrics:
            with m.lock:
                if m.kind == "histogram":
                    out[name] = [
                        (dict(k), _hist_dict(h, m.buckets))
                        for k, h in m.hists.items()
                    ]
                    continue
                m._refresh()
                out[name] = [(dict(k), v) for k, v in m.values.items()]
        return out

    def reset(self):
        """Clear every metric's samples IN PLACE. The _Metric objects stay
        registered: module-level families (MESSAGES_RECV etc.) hand out
        handles bound to those objects, and dropping them from the registry
        would orphan the handles — increments would land in objects no
        longer visible to expose()/snapshot() and silently vanish."""
        with self.lock:
            for m in self.metrics.values():
                with m.lock:
                    m.values.clear()
                    m.hists.clear()
                    m.refreshers.clear()

    def drop_job(self, job_id: str) -> int:
        """Cardinality GC: remove every label set carrying job=job_id
        (values, histograms, refreshers) across all families. Without
        this, a 1000-job churn run grows /metrics exposition unboundedly
        — per-subtask counters, queue gauges and latency histograms of
        stopped jobs would be scraped forever. Handles held by a live
        producer of the dropped job recreate a zeroed entry on their next
        write, which is the counter-restart shape every consumer already
        tolerates. Returns the number of label sets removed."""
        match = ("job", job_id)
        dropped = 0
        with self.lock:
            metrics = list(self.metrics.values())
        for m in metrics:
            with m.lock:
                for store in (m.values, m.hists, m.refreshers):
                    stale = [k for k in store if match in k]
                    for k in stale:
                        del store[k]
                    dropped += len(stale)
        return dropped


REGISTRY = Registry()

# Task-level counters, one label-set per subtask (reference TaskCounters).
MESSAGES_RECV = REGISTRY.counter(
    "arroyo_worker_messages_recv", "messages received by a subtask")
MESSAGES_SENT = REGISTRY.counter(
    "arroyo_worker_messages_sent", "messages sent by a subtask")
BATCHES_RECV = REGISTRY.counter(
    "arroyo_worker_batches_recv", "batches received by a subtask")
BATCHES_SENT = REGISTRY.counter(
    "arroyo_worker_batches_sent", "batches sent by a subtask")
BYTES_RECV = REGISTRY.counter(
    "arroyo_worker_bytes_recv", "bytes received by a subtask")
BYTES_SENT = REGISTRY.counter(
    "arroyo_worker_bytes_sent", "bytes sent by a subtask")
ERRORS = REGISTRY.counter(
    "arroyo_worker_errors", "deserialization/user errors in a subtask")
BACKPRESSURE = REGISTRY.gauge(
    "arroyo_worker_backpressure",
    "fullness (0..1) of a subtask's most-loaded output queue — the "
    "reference derives its backpressure gauge from tx queue occupancy "
    "the same way (job_metrics.rs)")
QUEUE_SIZE = REGISTRY.gauge(
    "arroyo_worker_queue_size", "occupancy of an edge queue (batches)")
QUEUE_BYTES = REGISTRY.gauge(
    "arroyo_worker_queue_bytes", "occupancy of an edge queue (bytes)")
TPU_KERNEL_MILLIS = REGISTRY.counter(
    "arroyo_tpu_kernel_millis", "wall millis spent inside device kernels")
BUSY_SECONDS = REGISTRY.counter(
    "arroyo_worker_busy_seconds",
    "wall seconds a subtask spent doing useful work (processing input "
    "batches, watermark-driven emission, ticks) — excludes time idle on "
    "queue reads or blocked on backpressure. The autoscaler's DS2-style "
    "true-rate estimate is rows / busy-seconds (Kalavri et al., OSDI '18)")

# Flight-recorder latency families (ISSUE 4): histograms in seconds.
BATCH_PROCESSING_SECONDS = REGISTRY.histogram(
    "arroyo_worker_batch_processing_seconds",
    "per-subtask wall time processing one input batch through the "
    "operator chain")
EXCHANGE_FRAME_SECONDS = REGISTRY.histogram(
    "arroyo_exchange_frame_seconds",
    "data-plane frame latency: send-timestamp (frame header) to receive "
    "on the destination worker, per destination subtask")
STORAGE_OP_SECONDS = REGISTRY.histogram(
    "arroyo_storage_op_seconds",
    "object-storage operation latency by op (put/get/cas)")
CHECKPOINT_PHASE_SECONDS = REGISTRY.histogram(
    "arroyo_checkpoint_phase_seconds",
    "checkpoint phase durations per subtask (phase=align|capture|flush)")
# Device-tier observatory (ISSUE 6): end-to-end latency markers +
# XLA compile/dispatch telemetry.
LATENCY_MARKER_SECONDS = REGISTRY.histogram(
    "arroyo_worker_latency_marker_seconds",
    "latency-marker transit time source->this subtask (Flink-style "
    "markers stamped at the sources; per-operator record latency)")
E2E_LATENCY_SECONDS = REGISTRY.histogram(
    "arroyo_worker_e2e_latency_seconds",
    "latency-marker transit time source->sink: the pipeline's "
    "end-to-end record latency, recorded at terminal subtasks")
# XLA compiles run tens of ms (CPU) to tens of seconds (TPU relay):
# latency-shaped DEFAULT_BUCKETS top out at 10s, so compile histograms
# get their own ladder
COMPILE_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                   10.0, 30.0, 60.0, 120.0)
XLA_COMPILES = REGISTRY.counter(
    "arroyo_xla_compiles_total",
    "XLA compilations per jitted program (a new shape signature "
    "specializes a fresh executable)")
XLA_COMPILE_CACHE = REGISTRY.counter(
    "arroyo_xla_compile_cache_total",
    "per-program compile-cache outcomes by result=hit|miss (hit = this "
    "process already traced the call's shape signature)")
XLA_COMPILE_SECONDS = REGISTRY.histogram(
    "arroyo_xla_compile_seconds",
    "wall time of calls that triggered an XLA compilation, per program "
    "(includes the compiled executable's first dispatch)",
    buckets=COMPILE_BUCKETS)
DEVICE_DISPATCH_SECONDS = REGISTRY.histogram(
    "arroyo_device_dispatch_seconds",
    "steady-state dispatch wall time of already-compiled jitted "
    "programs, per program")
DEVICE_EXCHANGE_SECONDS = REGISTRY.histogram(
    "arroyo_device_exchange_seconds",
    "per-dispatch wall time of the mesh EXCHANGE programs only (the "
    "keyed shuffle: device-routed all_to_all route+scatter steps and "
    "the host-fed packed-transfer steps), excluding emission/reset — "
    "the collective cost the mesh tier pays per micro-batch flush")
DEVICE_PADDING_WASTE = REGISTRY.gauge(
    "arroyo_device_padding_waste",
    "fraction (0..1) of rows shipped to the device that were neutral "
    "padding filler, per program and packing rung (shape bucket)")
# fused segment runtime (engine/segments.py): one dispatch per segment
# per batch instead of one per operator — these families are what the
# bench's dispatches_per_batch ratio and the per-segment ledger read
SEGMENT_DISPATCH_SECONDS = REGISTRY.histogram(
    "arroyo_segment_dispatch_seconds",
    "per-batch execution wall time of fused stateless segments, per "
    "segment program and tier (tier=jax: one jitted XLA program for the "
    "whole chain; tier=host: the composed arrow/numpy program)")
SEGMENT_FUSED_OPS = REGISTRY.gauge(
    "arroyo_segment_fused_ops",
    "operators fused into each segment program (the dispatches a batch "
    "no longer pays individually)")
SEGMENT_DISPATCHES = REGISTRY.counter(
    "arroyo_segment_dispatches_total",
    "stateless-chain dispatches by job/task and fused=1|0 — fused "
    "segments count one per batch, unfused members of a planned run "
    "count one per operator per batch (the A/B numerator of the bench's "
    "dispatches_per_batch)")
SEGMENT_BATCHES = REGISTRY.counter(
    "arroyo_segment_batches_total",
    "batches entering a planned stateless run (fused or not) by "
    "job/task — the denominator of dispatches_per_batch")
WATERMARK_LAG_SECONDS = REGISTRY.gauge(
    "arroyo_worker_watermark_lag_seconds",
    "wall-clock seconds the subtask's effective watermark trails now "
    "(refreshed at scrape time)")
BARRIER_ALIGNMENT_SECONDS = REGISTRY.gauge(
    "arroyo_worker_barrier_alignment_seconds",
    "seconds the subtask's last checkpoint barrier spent aligning "
    "(first barrier arrival to all live inputs barriered)")
# State-at-scale observability (ROADMAP item 4): per-(table, kind) sizes
# refreshed at scrape time via weakref refreshers registered by each
# subtask's TableManager — the rebase/spill knobs are tuned from these.
STATE_BYTES = REGISTRY.gauge(
    "arroyo_state_bytes",
    "approximate bytes held by a state table per (task, table, kind): "
    "global tables report their last serialized size, time-key tables "
    "in-memory + spilled batch bytes (refreshed at scrape time)")
STATE_ROWS = REGISTRY.gauge(
    "arroyo_state_rows",
    "live entries per state table: KV entries for global tables, "
    "buffered rows for time-key tables (refreshed at scrape time)")
STATE_SPILLED_BYTES = REGISTRY.gauge(
    "arroyo_state_spilled_bytes",
    "bytes a time-key table currently holds in local Arrow-IPC spill "
    "files (cold batches beyond state.memory_budget_bytes)")
STATE_CHAIN_LEN = REGISTRY.gauge(
    "arroyo_state_delta_chain_len",
    "incremental global-table blob-chain length (base + deltas) per "
    "(task, table); the rebase policy (state.rebase_epochs / "
    "state.rebase_bytes_factor) bounds it")
# Fleet observatory (ISSUE 11): per-job cost attribution on multiplexed
# workers. Every family carries a `job` label so Registry.drop_job GCs a
# terminal job's series with the rest; values are rolled up from the
# job-id contextvar accounting (obs/attribution.py) by the per-worker
# pump, so shared-worker usage sums to the worker's measured busy time
# and fair-share grants can be audited against actual consumption.
JOB_ATTR_BUSY_SECONDS = REGISTRY.counter(
    "arroyo_job_attributed_busy_seconds",
    "wall seconds of useful work attributed to a job via the ambient "
    "job-id context (batch processing, watermark-driven emission, "
    "ticks) — sums across co-resident jobs to a multiplexed worker's "
    "arroyo_worker_busy_seconds total")
JOB_ATTR_CPU_SECONDS = REGISTRY.counter(
    "arroyo_job_attributed_cpu_seconds",
    "process CPU seconds apportioned to a job by the accounting pump "
    "(each flush splits the interval's process-CPU delta across jobs "
    "proportional to their attributed busy time in that interval)")
JOB_ATTR_DEVICE_SECONDS = REGISTRY.counter(
    "arroyo_job_attributed_device_seconds",
    "wall seconds inside jitted device programs (compiles + dispatches) "
    "attributed to a job — the per-job dimension of the shared-program "
    "XLA telemetry (programs are cached process-wide across jobs, so "
    "the per-program families cannot carry a job label themselves)")
JOB_ATTR_DISPATCHES = REGISTRY.counter(
    "arroyo_job_attributed_dispatches",
    "device program invocations (compile or dispatch) attributed to a "
    "job via the ambient job-id context")
JOB_ATTR_BYTES = REGISTRY.counter(
    "arroyo_job_attributed_bytes",
    "data-plane bytes (batches received by the job's subtasks) "
    "attributed to a job via the ambient job-id context")
JOB_ATTR_PHASE_SECONDS = REGISTRY.counter(
    "arroyo_job_attributed_phase_seconds",
    "wall seconds per batch-pipeline phase (phase=decode|process|"
    "dispatch|exchange|emit|flush|watermark) attributed to a job — the "
    "metric rollup of the timeline profiler's phase ledger")
# StateServe (ISSUE 12): the queryable-state serving tier. Every family
# carries a `job` label so Registry.drop_job GCs a stopped job's serve
# series with the rest of its metrics; the tenant label on the request
# counter is what per-tenant QPS dashboards and the noisy-neighbor
# wiring read.
SERVE_REQUEST_SECONDS = REGISTRY.histogram(
    "arroyo_serve_request_seconds",
    "gateway wall time serving one state read request (routing + cache "
    "+ worker fan-out + merge), per job")
SERVE_REQUESTS = REGISTRY.counter(
    "arroyo_serve_requests_total",
    "state read requests through the gateway per (job, tenant, outcome="
    "ok|partial|throttled|stale_route|error) — the per-tenant QPS "
    "series read quotas are audited against")
SERVE_KEYS = REGISTRY.counter(
    "arroyo_serve_keys_total",
    "individual key lookups served per job (a bulk read counts each "
    "key; the fleet harness's lookups/s gate reads this)")
SERVE_CACHE_HITS = REGISTRY.counter(
    "arroyo_serve_cache_hits_total",
    "reads answered from the controller-side read-through cache "
    "(entry's published epoch and schedule incarnation both matched)")
SERVE_CACHE_MISSES = REGISTRY.counter(
    "arroyo_serve_cache_misses_total",
    "reads that fanned out to a worker (cold key, epoch-invalidated "
    "entry, or cache disabled)")
SERVE_WORKER_RPCS = REGISTRY.counter(
    "arroyo_serve_worker_rpcs_total",
    "QueryState RPCs the gateway issued to workers per job — the "
    "follower tier's headline win: ~0 for durable jobs once followers "
    "are caught up (the fleet harness asserts it)")
# Follower read replicas (ISSUE 20): controller-hosted serving tier off
# the checkpoint stream. Every family is job-labeled so Registry.
# drop_job GCs a stopped job's replica series with the rest (the fleet
# churn test asserts it); staleness is the replica_staleness SLO input.
REPLICA_TAILS = REGISTRY.counter(
    "arroyo_replica_tails_total",
    "delta-chain suffix tails applied by followers per job (one per "
    "published epoch caught up, per mounted job)")
REPLICA_SERVED_EPOCH = REGISTRY.gauge(
    "arroyo_replica_served_epoch",
    "the epoch a job's follower currently serves at (its last fully "
    "tailed published manifest)")
REPLICA_LAG_EPOCHS = REGISTRY.gauge(
    "arroyo_replica_lag_epochs",
    "published_epoch - follower served epoch per job: 0 when caught "
    "up, transiently 1 while a tail is in flight; > max_lag_epochs "
    "routes reads worker-ward and feeds the replica_staleness SLO")
REPLICA_LOOKUPS = REGISTRY.counter(
    "arroyo_replica_lookups_total",
    "individual key lookups answered from follower views per job (the "
    "fleet harness's serve_follower_lookup_eps reads this)")
REPLICA_SUBSCRIBES = REGISTRY.counter(
    "arroyo_replica_subscribes_total",
    "follower (re)attach restores per job — 1 at mount, +1 per "
    "post-death reattach (each re-resolves latest.json from storage; "
    "see the follower_serves_unpublished_epoch model mutant)")
# Watchtower (ISSUE 13): retained history + per-job SLO engine. The
# alert counter is job-labeled (drop_job GCs it); published-epoch is the
# gauge the checkpoint-age SLO watches for stalls; the trace-drop
# counter makes flight-recorder ring overflow visible without catching
# /debug/trace at the right moment.
TRACE_DROPPED_SPANS = REGISTRY.counter(
    "arroyo_trace_dropped_spans_total",
    "flight-recorder spans dropped because the per-process ring buffer "
    "(obs.trace_buffer_spans) was full — sustained drops mean the "
    "recording of the next incident is incomplete; the watchtower's "
    "trace_drops rule alerts on the windowed drop rate")
JOB_PUBLISHED_EPOCH = REGISTRY.gauge(
    "arroyo_job_published_epoch",
    "the job's last PUBLISHED checkpoint epoch (set by the controller "
    "watchtower each sample) — the checkpoint-age SLO fires when this "
    "stops advancing on a durable job")
WATCH_ALERTS = REGISTRY.counter(
    "arroyo_watch_alerts_total",
    "watchtower alert transitions per (job, rule, event=firing|cleared)")
# Conservation ledger (ISSUE 19): per-edge epoch attestation auditing.
# Every family carries a `job` label so Registry.drop_job GCs a terminal
# job's audit series with the rest; the breach counter additionally
# carries the breach kind (digest_mismatch|count_mismatch|flow_violation|
# rewind_behind_commit|zombie_generation) and is what the watchtower's
# `conservation` SLO rule and the retained-history allowlist read.
AUDIT_EPOCHS = REGISTRY.counter(
    "arroyo_audit_epochs_reconciled_total",
    "checkpoint epochs whose per-edge attestations the controller "
    "reconciler joined at manifest publish, per job")
AUDIT_EDGES_VERIFIED = REGISTRY.counter(
    "arroyo_audit_edges_verified_total",
    "per-epoch edge attestations that matched on both sides (sender "
    "row count + commutative digest == receiver's), per job")
AUDIT_BREACHES = REGISTRY.counter(
    "arroyo_audit_breaches_total",
    "conservation breaches flagged by the reconciler per (job, kind): "
    "attestation joins that diverged, flow-consistency violations, and "
    "recovery-conservation breaches (rewind-behind-commit / "
    "zombie-generation append) — each names its exact (edge, epoch)")
LOOP_LAG_SECONDS = REGISTRY.histogram(
    "arroyo_worker_loop_lag_seconds",
    "event-loop scheduling lag sampled by the accounting pump (sleep-"
    "overshoot of a loop_lag_interval timer): how long a ready task "
    "waits for the multiplexed worker loop — the noisy-neighbor signal")


class RateWindow:
    """Fixed 5-minute window of (t, value) samples for UI rates
    (reference: job_metrics.rs:188-265). Backed by a deque — the old
    list + pop(0) trim was O(n) per add on long-running jobs — and
    hard-capped at MAX_SAMPLES so a hot producer can't grow it without
    bound inside the time window."""

    WINDOW = 300.0
    MAX_SAMPLES = 4096

    def __init__(self):
        self.samples: deque[tuple[float, float]] = deque()

    def add(self, value: float, now: float | None = None):
        now = time.monotonic() if now is None else now
        self.samples.append((now, value))
        cutoff = now - self.WINDOW
        while self.samples and self.samples[0][0] < cutoff:
            self.samples.popleft()
        while len(self.samples) > self.MAX_SAMPLES:
            self.samples.popleft()

    def rate(self) -> float:
        if len(self.samples) < 2:
            return 0.0
        (t0, v0), (t1, v1) = self.samples[0], self.samples[-1]
        return (v1 - v0) / (t1 - t0) if t1 > t0 else 0.0
